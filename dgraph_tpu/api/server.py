"""The embedded server node: Query / Mutate / Alter / CommitOrAbort.

Reference semantics: edgraph/server.go — Query (:373), Mutate (:267), Alter
(:213), CommitOrAbort (:462); parseMutationObject (:528). The reference runs
this behind gRPC with a separate Zero process; here the node embeds its Zero
(coord/zero.py) in-process — the same embedded single-process cluster mode
the reference's own tests use (query/query_test.go TestMain, SURVEY.md §4).

Read path: a query leases a read_ts from the oracle and executes against an
immutable GraphSnapshot (storage/csr_build.py) — the TPU-first stance: the
device only ever sees committed snapshot CSRs; MVCC stays host-side.
Snapshots are cached per effective read_ts (bounded LRU), so repeated reads
between commits reuse the same device arrays.

Write path: Mutate buffers edges under start_ts (uncommitted posting layers
+ index/reverse/count maintenance), the oracle tracks conflict-key
fingerprints, and commit runs the SSI check, assigns commit_ts, and promotes
the layers — first-committer-wins snapshot isolation
(dgraph/cmd/zero/oracle.go:71-83).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from dgraph_tpu.coord.zero import TxnConflict, Zero
from dgraph_tpu.obs import costs, otrace
from dgraph_tpu.obs.slowlog import SlowQueryLog
from dgraph_tpu.query import dql, rdf
from dgraph_tpu.query import mutation as mut
from dgraph_tpu.query import qcache
from dgraph_tpu.query import upsert as ups
from dgraph_tpu.query.engine import Executor
from dgraph_tpu.storage import index as idx
from dgraph_tpu.storage import keys as K
from dgraph_tpu.storage.csr_build import (GraphSnapshot, PredData,
                                          SnapshotAssembler, build_pred,
                                          build_snapshot)
from dgraph_tpu.storage.postings import Op
from dgraph_tpu.storage.store import Store
from dgraph_tpu.parallel.scheduler import Scheduler
from dgraph_tpu import tenancy as tnc
from dgraph_tpu.utils import deadline as dl
from dgraph_tpu.utils import metrics
from dgraph_tpu.utils.schema import parse_schema



@dataclass
class TxnContext:
    """Reference: api.TxnContext (start/commit ts + conflict keys)."""

    start_ts: int
    commit_ts: int = 0
    aborted: bool = False
    keys: list[bytes] = field(default_factory=list)       # all touched
    conflict_keys: list[bytes] = field(default_factory=list)
    preds: set[str] = field(default_factory=set)
    version: int = 0                       # bumped per mutate (overlay cache)
    overlay: tuple[int, dict] | None = None  # (version, {attr: PredData})
    inflight: int = 0          # mutations mid-apply; commit/abort wait on 0
    finishing: bool = False    # commit/abort started: reject new mutations
    last_active: float = field(default_factory=time.monotonic)


@dataclass
class MutationResult:
    uids: dict[str, int]          # blank-node name -> assigned uid
    context: TxnContext


class Node:
    """One embedded server (store + zero + snapshot cache)."""

    def __init__(self, dirpath: str | None = None, n_groups: int = 1,
                 trace_fraction: float = 1.0,
                 memory_mb: int | None = None,
                 plan_cache_size: int = 256,
                 task_cache_mb: int = 64,
                 result_cache_mb: int = 32,
                 dispatch_width: int = 4,
                 overlay: bool = True,
                 overlay_max_keys: int | None = None,
                 overlay_max_age_s: float | None = None,
                 background_rollup: bool = True,
                 fold_workers: int | None = None,
                 planner: bool = True,
                 stats_top_k: int = 8,
                 span_sample: float = 0.01,
                 trace_rng=None,
                 slow_query_ms: float = 0.0,
                 slow_query_log: str | None = None,
                 mesh_devices: int = 0,
                 mesh_min_edges: int | None = None,
                 default_timeout_ms: float = 0.0,
                 vector_nprobe: int = 0,
                 vector_centroids: int = -1,
                 vector_ivf_min_rows: int = 0,
                 batching: bool = True,
                 batch_window_ms: float = 2.0,
                 batch_max: int = 16,
                 write_batch: bool = True,
                 write_window_ms: float = 2.0,
                 write_batch_max: int = 64,
                 device_budget_mb: int = 0,
                 residency_pin: str = "",
                 cost_ledger: bool = True,
                 cost_regression_factor: float = 4.0,
                 lazy_folds: bool = True,
                 delta_journal_max_keys: int | None = None,
                 live_queue_max: int = 256,
                 live_idle_timeout_s: float = 300.0,
                 live_heartbeat_s: float = 15.0,
                 devprof: bool = True,
                 qos: bool = True,
                 tenants=None) -> None:
        # memory_mb enables the PAGED store: snapshot mmap'd, lists
        # materialize lazily, clean entries evict under the budget
        self.store = Store(dirpath,
                           memory_budget=(memory_mb * (1 << 20))
                           if memory_mb else None,
                           max_delta_keys=delta_journal_max_keys)
        self.zero = Zero(n_groups)
        self.metrics = metrics.Registry()
        # checkpoint/ingest gauges (peak transient bytes etc.) land in this
        # node's registry — they show on /metrics next to the query tiers
        self.store.metrics = self.metrics
        # HBM working-set manager (ISSUE 11, storage/residency.py): owns
        # the node's device-byte budget and the HBM ↔ host ↔ paged tiers.
        # Folded tablets attach at build_pred/stamp_pred (store.residency),
        # device uploads admit against the budget (evicting colder tablets
        # by the same rate×log2(size) score the placement controller
        # uses), and COLD tablets (footprint > budget) serve through the
        # host-cutover machinery. budget 0 = unbounded: accounting only —
        # fully-resident traffic pays no admission/eviction work.
        from dgraph_tpu.storage.residency import ResidencyManager

        pins = residency_pin
        if isinstance(pins, str):
            pins = tuple(p.strip() for p in pins.split(",") if p.strip())
        self.residency = ResidencyManager(
            budget_bytes=int(device_budget_mb) << 20,
            metrics=self.metrics, pins=tuple(pins))
        self.store.residency = self.residency
        self.traces = metrics.TraceStore(fraction=trace_fraction,
                                         rng=trace_rng)
        # span tracing + device profiling (obs/otrace.py): root spans start
        # at query/mutate/alter, children attach via contextvar down to the
        # device kernels; completed traces export as Chrome trace JSON at
        # /debug/traces/<id>. slow_query_ms > 0 arms the slow-query log.
        self.slow_log = SlowQueryLog(slow_query_ms, path=slow_query_log)
        self.tracer = otrace.Tracer(fraction=span_sample, proc="node",
                                    rng=trace_rng, slowlog=self.slow_log)
        # round-6 serving tier: parsed-plan cache, snapshot-keyed task
        # result LRU (+ singleflight), bounded device-dispatch gate.
        # Size 0 disables a tier (bench.py's cold-cache mode).
        self.plan_cache = (qcache.PlanCache(plan_cache_size, self.metrics)
                           if plan_cache_size > 0 else None)
        self.task_cache = (qcache.TaskResultCache(task_cache_mb << 20,
                                                  self.metrics)
                           if task_cache_mb > 0 else None)
        self.result_cache = (qcache.ResultCache(result_cache_mb << 20,
                                                self.metrics)
                             if result_cache_mb > 0 else None)
        self.dispatch_gate = qcache.DispatchGate(dispatch_width,
                                                 self.metrics)
        # device-dispatch batcher (ISSUE 9, query/batch.py): concurrent
        # compatible device-class tasks — same predicate CSR object (which
        # pins the snapshot), same kernel class — pack into ONE batched
        # kernel launch, amortizing the fixed dispatch+sync that otherwise
        # serializes through the gate. --no_batch / batching=False
        # restores exact per-task dispatch.
        self.batcher = None
        if batching and batch_max > 1:
            from dgraph_tpu.query.batch import DeviceBatcher

            self.batcher = DeviceBatcher(self.dispatch_gate, self.metrics,
                                         window_ms=batch_window_ms,
                                         max_batch=batch_max)
        # group-commit write window (ISSUE 16, storage/writebatch.py):
        # concurrent committing txns form ONE batched oracle conflict
        # pass, ONE contiguous WAL append with ONE fsync, and ONE
        # store-lock apply advancing the window's union watermarks.
        # --no_write_batch / write_batch=False restores the exact
        # per-commit path.
        self.write_batcher = None
        if write_batch and write_batch_max > 1:
            from dgraph_tpu.storage.writebatch import WriteBatcher

            self.write_batcher = WriteBatcher(
                self.zero.oracle, self.store, self.metrics,
                window_ms=write_window_ms, max_batch=write_batch_max)
        # cost-based planner (query/planner.py) over the live cardinality
        # stats (storage/stats.py). Order decisions only — disabling it
        # (--no_planner) restores exact parse-order execution.
        self.planner_enabled = planner
        self.stats_top_k = int(stats_top_k)
        # request lifelines (ISSUE 7): a per-request deadline budget
        # (query/mutate timeout_ms arg, HTTP ?timeoutMs=, --default_
        # timeout_ms flag) consumed at the dispatch gate + task seams;
        # overruns are typed DeadlineExceeded, overload sheds typed
        # ResourceExhausted — never a hang. 0 = unbudgeted.
        self.default_timeout_ms = float(default_timeout_ms)
        self._txns: dict[int, TxnContext] = {}
        self._lock = threading.RLock()       # commit/read linearization
        self._inflight_cv = threading.Condition(self._lock)
        self._sched = Scheduler()            # conflict-keyed mutation apply
        # incremental per-predicate snapshot reuse (shared with the worker
        # wire service and follower readers): a commit touching one
        # predicate STAMPS a delta overlay on one predicate (storage/
        # delta.py) — or re-folds it when the journal can't prove the delta
        self._assembler = SnapshotAssembler(
            self.store,
            on_pred_build=lambda attr: self.metrics.counter(
                "dgraph_posting_reads_total").inc(
                    len(self.store.by_pred.get(
                        (int(K.KeyKind.DATA), attr), ()))),
            metrics=self.metrics,
            overlay_enabled=overlay,
            overlay_max_keys=overlay_max_keys,
            overlay_max_age_s=overlay_max_age_s,
            fold_workers=fold_workers,
            lazy_folds=lazy_folds)
        # cold-open / first-query gauges (ISSUE 15): wall from node birth
        # to the first completed query — the number lazy folds move
        self._birth = time.perf_counter()
        self._first_query_done = False
        # background rollup: overlays past the size/age threshold fold back
        # into fresh bases OFF the query path (posting-list rollups one
        # level up); started lazily on the first stamped overlay
        self.background_rollup = background_rollup
        self._rollup_stop = threading.Event()
        self._rollup_started = False
        if self.store.max_seen_commit_ts:
            # recover the ts sequence past everything the WAL replayed
            self.zero.oracle.timestamps(self.store.max_seen_commit_ts)
        maxuid = self._max_uid_in_store()
        if maxuid:
            self.zero.uids.assign(maxuid)
        self.memory_budget = 0          # 0 = unbounded
        self._enforcer_started = False
        # mesh deployment mode (ISSUE 6 / ROADMAP item 1): at snapshot
        # assembly, large uid tablets are placed across a jax.sharding.Mesh
        # as row-range-sharded NamedSharding arrays and multi-hop
        # traversals fuse into ONE device dispatch whose per-hop frontier
        # exchange rides ICI (parallel/mesh_exec.py). 0 = off, -1 = every
        # visible device, N = first N devices. The classic per-task path
        # (and the gRPC wire path on a cluster) remains the fallback for
        # shapes the fused programs do not cover.
        # vector-index IVF knobs (--vector_nprobe / --vector_centroids /
        # --vector_ivf_min_rows): per-node — they ride this node's Store
        # into the fold (storage/vecindex.py), so embedding a second Node
        # in the same process never inherits them
        if vector_nprobe or vector_centroids >= 0 or vector_ivf_min_rows:
            from dgraph_tpu.storage.vecindex import VectorKnobs

            self.store.vector_knobs = VectorKnobs(
                nprobe=vector_nprobe,
                centroids=vector_centroids,
                ivf_min_rows=vector_ivf_min_rows)
        self.mesh_exec = None
        if mesh_devices:
            from dgraph_tpu.parallel.mesh_exec import MeshExecutor

            self.mesh_exec = MeshExecutor(
                n_devices=None if mesh_devices < 0 else mesh_devices,
                metrics=self.metrics, shard_min_edges=mesh_min_edges,
                residency=self.residency)
        # per-tablet load counters (coord/placement.py TabletLoadBook):
        # every dispatched task and applied edge counts toward the
        # dgraph_tablet_load{pred,group,stat} series on /metrics and the
        # /debug/metrics tablet_load section — the placement controller's
        # scoring inputs, inspectable on the embedded node too
        from dgraph_tpu.coord.placement import TabletLoadBook

        self.tablet_book = TabletLoadBook(self.metrics, group=0)
        # per-request cost ledger + /debug/top profiler (ISSUE 13,
        # obs/costs.py): every query assembles one resource cost record
        # (device-kernel ms, transfer bytes, traversed edges, cache/batch/
        # shed outcomes, per-predicate breakdown) which feeds the
        # aggregatable dgraph_query_cost_* histograms (with trace
        # exemplars) and the CostBook's sliding /debug/top window with
        # per-shape EWMA regression baselines. --no_cost_ledger restores
        # the unmeasured path (bench `obs` gates the armed overhead <2%).
        self.cost_ledger = bool(cost_ledger)
        self.cost_book = costs.CostBook(
            regression_factor=cost_regression_factor)
        # live queries (ISSUE 18, dgraph_tpu/live/): standing subscriptions
        # re-derived O(Δ) per commit window. Re-evals run read-only at the
        # window's watermark through the normal query path — same caches,
        # same DeviceBatcher — ranked under endpoint="live" in /debug/top.
        from dgraph_tpu.live import LiveManager

        self.live = LiveManager(
            eval_fn=lambda q, v, ts, subs=(): self.query(
                q, v, start_ts=ts, read_only=True,
                _cost_endpoint="live", _cost_subs=subs)[0],
            watermark_fn=lambda: self.store.max_seen_commit_ts,
            parse_fn=self._parse,
            stores=[self.store],
            metrics=self.metrics,
            queue_max=live_queue_max,
            idle_timeout_s=live_idle_timeout_s,
            heartbeat_s=live_heartbeat_s,
            batcher=self.batcher)
        self.store.on_delta_overflow = self.live.on_journal_overflow
        # multi-tenant QoS (ISSUE 20, dgraph_tpu/tenancy/): namespaces are
        # ALWAYS active for a non-default tenant (they are correctness —
        # every request resolves predicates in its caller's namespace via
        # NamespacedSnapshot/NamespacedSchema views); quota admission and
        # weighted-fair device scheduling arm only when qos=True AND a
        # tenants config is installed (serve --tenants / POST
        # /admin/tenant). --no_qos keeps every serving seam reading one
        # None attribute — single-tenant deployments stay byte-identical.
        self.qos_enabled = bool(qos)
        self.tenancy = tnc.TenantRegistry(self.metrics)
        from collections import OrderedDict

        # tenant snapshot views, cached per (tenant, base snapshot token)
        # so engine-side attrs cached ON the snapshot object (known-uid
        # sets) survive across requests within one base snapshot
        self._ns_views: OrderedDict = OrderedDict()
        self._ns_lock = threading.Lock()
        self.zero.tenants = self.tenancy
        if tenants:
            self.configure_tenants(tenants)
        # device-runtime observatory (ISSUE 19, obs/devprof.py): XLA
        # compile/retrace tracking, HBM telemetry, and the dispatch
        # timeline, attached at the gate/mesh seams plus the module
        # fan-out for process-global build sites. --no_devprof never
        # constructs it — the seams read one None attribute / one empty
        # tuple, so the disarmed path is byte-identical to pre-19.
        self._device_budget_bytes = int(device_budget_mb) << 20
        self.devprof = None
        if devprof:
            self._arm_devprof()

    def _arm_devprof(self) -> None:
        from dgraph_tpu.obs import devprof as devprof_mod
        from dgraph_tpu.obs.devprof import DevProfiler

        prof = DevProfiler(self.metrics, slow_log=self.slow_log,
                           budget_bytes=self._device_budget_bytes,
                           residency=self.residency)
        prof.add_cache_probe("mesh.programs",
                             lambda: len(self.mesh_exec._progs)
                             if self.mesh_exec is not None else 0)

        def dist_caches():
            import sys

            d = sys.modules.get("dgraph_tpu.parallel.dist")
            if d is None:
                return {}
            return {"dist.expand":
                    d._expand_program.cache_info().currsize,
                    "dist.k_hop":
                    d._k_hop_program.cache_info().currsize}

        def ops_jit_caches():
            # only modules ALREADY imported by an executed path — the
            # probe must not pull jax kernels in on a scrape
            import sys

            out = {}
            for name in ("segments", "vector", "pallas_bfs",
                         "traversal"):
                m = sys.modules.get(f"dgraph_tpu.ops.{name}")
                for fam, fn in getattr(m, "JIT_PROGRAMS", {}).items():
                    size = getattr(fn, "_cache_size", None)
                    out[fam] = size() if size is not None else -1
            return out

        prof.add_cache_probe("dist", dist_caches)
        prof.add_cache_probe("ops.jit", ops_jit_caches)
        self.devprof = prof
        self.dispatch_gate.profiler = prof
        if self.mesh_exec is not None:
            self.mesh_exec._prof = prof
        devprof_mod.register(prof)

    def set_devprof(self, on: bool) -> None:
        """Arm/disarm the device-runtime observatory live (bench.py's
        armed-vs-disarmed A/B runs toggle this between battery passes)."""
        from dgraph_tpu.obs import devprof as devprof_mod

        if on and self.devprof is None:
            self._arm_devprof()
        elif not on and self.devprof is not None:
            devprof_mod.unregister(self.devprof)
            self.dispatch_gate.profiler = None
            if self.mesh_exec is not None:
                self.mesh_exec._prof = None
            self.devprof = None

    # -- multi-tenant QoS (ISSUE 20) -----------------------------------------

    _NS_VIEW_CAP = 32

    def configure_tenants(self, cfg, replace: bool = False) -> dict:
        """Install/merge the tenant table (serve --tenants flag and the
        POST /admin/tenant hot reload). `cfg` is a {"tenants": {...}} (or
        bare name->spec) dict, a JSON string, or a path to a JSON file.
        Arms quota admission + fair scheduling when qos is enabled."""
        if isinstance(cfg, str):
            import json as _json
            import os

            if os.path.exists(cfg):
                with open(cfg, encoding="utf-8") as f:
                    cfg = _json.load(f)
            else:
                cfg = _json.loads(cfg)
        table = self.tenancy.configure(cfg, replace=replace)
        self._arm_qos()
        return table

    def _arm_qos(self) -> None:
        """Attach the fair scheduler + write-window caps + live-query caps
        once qos is on and a tenant table exists. Idempotent; reconfigs
        keep the armed scheduler's virtual clocks (weights re-read live
        through weight_fn)."""
        if not (self.qos_enabled and self.tenancy.configured):
            return
        gate = self.dispatch_gate
        if gate.fair is None:
            gate.fair = tnc.FairScheduler(weight_fn=self.tenancy.weight,
                                          metrics=self.metrics)
            gate.tenant_fn = tnc.current
        wb = self.write_batcher
        if wb is not None and wb.tenant_fn is None:
            wb.tenant_fn = tnc.current
            wb.tenant_cap_fn = lambda t: self.tenancy.window_share(
                t, wb.max_batch)
        self.live.registry = self.tenancy

    def _ns_view(self, snap, tenant: str):
        """The tenant's view of one snapshot, cached per (tenant, base
        cache token): token equality implies identical committed content,
        so one view object can serve every request of that (tenant,
        snapshot) pair — and attrs the engine caches on the snapshot
        object (known-uid sets) stay warm across them."""
        key = (tenant, qcache.snapshot_token(snap))
        with self._ns_lock:
            v = self._ns_views.get(key)
            if v is not None:
                self._ns_views.move_to_end(key)
                return v
        v = tnc.NamespacedSnapshot(snap, tenant)
        with self._ns_lock:
            self._ns_views[key] = v
            self._ns_views.move_to_end(key)
            while len(self._ns_views) > self._NS_VIEW_CAP:
                self._ns_views.popitem(last=False)
        return v

    def _schema_view(self):
        """The caller's schema: the raw SchemaState for the default
        namespace, a translating NamespacedSchema view for a tenant."""
        t = tnc.current()
        if t:
            return tnc.NamespacedSchema(self.store.schema, t)
        return self.store.schema

    def _admit_tenant(self, tenant: str) -> None:
        """API-edge quota admission (PR 7 shed discipline): over-quota
        tenants get typed ResourceExhausted before any device work —
        never a queue slot. Disarmed = one boolean check."""
        if self.qos_enabled and self.tenancy.configured:
            self.tenancy.admit(tenant)

    def set_memory_budget(self, budget_bytes: int) -> None:
        """Install/retarget the memory budget and ensure the background
        enforcement loop is running (admin.go live memory_mb reconfig —
        the loop re-reads the budget each tick, so later changes stick)."""
        self.memory_budget = int(budget_bytes)
        if self._enforcer_started or budget_bytes <= 0:
            return
        self._enforcer_started = True

        def loop():
            while True:
                time.sleep(10)
                try:
                    if self.memory_budget > 0:
                        self.enforce_memory(self.memory_budget)
                # dgraph: allow(except-seam) bg maintenance tick: next
                # tick retries; a dead enforcer must not kill the loop
                except Exception:
                    pass
        # dgraph: allow(ctxvar-copy) detached memory-enforcer bg loop
        threading.Thread(target=loop, daemon=True).start()

    # value-posting slots (lang/value fingerprints) carry the 1<<60 / 1<<61
    # tag bits (storage/postings.py lang_uid/value_fingerprint) and must never
    # be mistaken for uids when recovering the lease
    _SLOT_BITS = 1 << 60

    def _max_uid_in_store(self) -> int:
        ts = self.store.max_seen_commit_ts
        m = 0
        if self.store.paged:
            # segment-backed keys never enter by_pred: recover their max
            # from packed metadata without materializing any list
            def _uid_typed(attr):
                e = self.store.schema.get(attr)
                return e is None or e.type_id.name in ("UID", "DEFAULT")

            m = self.store.segment_max_uid(_uid_typed, self._SLOT_BITS)
        for (kind, attr), keys in self.store.by_pred.items():
            if kind not in (int(K.KeyKind.DATA), int(K.KeyKind.REVERSE)):
                continue
            entry = self.store.schema.get(attr)
            uid_typed = entry is None or entry.type_id.name == "UID" or \
                entry.type_id.name == "DEFAULT"
            for kb in keys:
                m = max(m, K.uid_of(kb))
                pl = self.store.lists.get(kb)
                if pl is None or kind != int(K.KeyKind.DATA) or not uid_typed:
                    continue
                bp = pl.base_packed
                if not pl.layers and not pl.uncommitted:
                    # packed metadata already carries the max object uid —
                    # decoding every list made cold-open O(edges). Slot-tagged
                    # values (>= _SLOT_BITS) force the slow path: the max
                    # REAL uid hides below them.
                    if not bp.nblocks:
                        continue
                    last = int(bp.block_last[-1])
                    if last < self._SLOT_BITS:
                        m = max(m, last)
                        continue
                u = pl.uids(max(ts, pl.base_ts))
                u = u[u < self._SLOT_BITS]
                if len(u):
                    m = max(m, int(u[-1]))
        return m

    # -- transactions --------------------------------------------------------

    # abandoned query-only txns (opened lazily by the gRPC surface, never
    # committed/discarded) are reaped once this many accumulate, else they
    # pin the oracle's conflict-GC watermark forever
    MAX_IDLE_TXNS = 1024
    # a pristine txn younger than this is never reaped: a slow-but-live
    # client that opened via a query and mutates later must not get
    # "unknown txn" just because 1024 other txns arrived in between
    IDLE_TXN_GRACE_S = 60.0

    def new_txn(self) -> TxnContext:
        st = self.zero.oracle.new_txn()
        ctx = TxnContext(start_ts=st.start_ts)
        with self._lock:
            self._txns[st.start_ts] = ctx
            if len(self._txns) > self.MAX_IDLE_TXNS:
                # pristine txns (no buffered writes) past the grace period
                # abort harmlessly, oldest-activity first: a later commit on
                # one returns "unknown txn", same as the reference's
                # expired-txn behavior
                cutoff = time.monotonic() - self.IDLE_TXN_GRACE_S
                pristine = sorted(
                    (ts for ts, c in self._txns.items()
                     if not c.keys and not c.inflight and ts != st.start_ts),
                    key=lambda ts: self._txns[ts].last_active)
                idle = [ts for ts in pristine
                        if self._txns[ts].last_active < cutoff]
                if not idle and len(self._txns) > 4 * self.MAX_IDLE_TXNS:
                    # burst pressure: >4x the soft bound opened inside one
                    # grace window — the bound (it protects the oracle's
                    # conflict-GC watermark) beats the grace period
                    idle = pristine
                for ts in idle[: max(len(idle) // 2, 1)]:
                    del self._txns[ts]
                    self.zero.oracle.abort(ts)
        return ctx

    def _drain_inflight(self, ctx, clamped: bool = True) -> None:
        """Wait out this txn's in-flight mutation applies, clamped to the
        caller's deadline — the lifeline contract: a budgeted commit or
        read never hangs behind a wedged apply (unbudgeted callers keep
        the exact old blocking wait). abort() drains UNclamped: it is the
        cleanup that unpins the oracle's conflict-GC watermark, and
        bailing on an expired budget would leak the keyed txn forever
        (the janitor only reaps pristine txns). Caller holds self._lock;
        the condition releases it while waiting."""
        while ctx.inflight:
            if not self._inflight_cv.wait(
                    dl.clamp(None) if clamped else None):
                dl.check("txn inflight drain")

    def commit(self, start_ts: int) -> int:
        """CommitOrAbort (edgraph/server.go:462). Returns commit_ts; raises
        TxnConflict after aborting the txn's buffered layers on conflict."""
        t0 = time.perf_counter()
        with self._span("commit", start_ts=int(start_ts)):
            with self._lock:
                ctx = self._txns.get(start_ts)
                if ctx is None:
                    raise mut.MutationError(f"unknown txn {start_ts}")
                # cut off new mutations first, then drain in-flight applies
                # — otherwise a steady write stream could starve this wait
                # and late mutations would silently ride the commit
                ctx.finishing = True
                self._drain_inflight(ctx)
                if self._txns.pop(start_ts, None) is None:
                    # a concurrent commit/abort won the race while we waited
                    raise mut.MutationError(f"unknown txn {start_ts}")
            # node lock RELEASED before the write window: the group-commit
            # batcher parks followers on events, and a follower parked
            # while holding the node lock would stall every other
            # committer's prep (defeating the window) and every reader.
            # Visibility stays exact: an in-flight commit is invisible
            # until the group apply advances the store watermarks, and
            # the ack below returns only after that apply — so a
            # committer's next read always observes its own write.
            try:
                wb = self.write_batcher
                if wb is None:
                    with self._lock:   # exact pre-window path
                        commit_ts = self._commit_solo(start_ts, ctx)
                else:
                    # dgraph: allow(ctxvar-copy) synchronous same-thread
                    # call (the window batcher, not an executor) — the
                    # caller's deadline/ledger ride into the entry itself
                    commit_ts = wb.submit(
                        start_ts, ctx.keys,
                        solo=lambda: self._commit_solo(start_ts, ctx))
            except TxnConflict:
                ctx.aborted = True
                self.metrics.counter("dgraph_num_aborts_total").inc()
                raise
            ctx.commit_ts = commit_ts
            # live-query wake (ISSUE 18): outside every lock, after the
            # apply is visible. One truthiness check when nobody subscribes.
            live = self.live
            if live is not None and live.active:
                live.notify_commit(commit_ts, ctx.preds)
            self.metrics.counter("dgraph_num_commits_total").inc()
            self.metrics.histogram("dgraph_commit_latency_s").observe(
                time.perf_counter() - t0)
            return commit_ts

    def _commit_solo(self, start_ts: int, ctx) -> int:
        """The exact per-commit path: one oracle decision, one per-commit
        WAL record with its own fsync. Runs for --no_write_batch, deadline
        bypasses, and write windows of one — unaccompanied traffic
        produces byte-identical logs to the pre-16 write path."""
        try:
            with otrace.span("zero:commit"):
                commit_ts = self.zero.oracle.commit(start_ts)
        except TxnConflict:
            self.store.abort(start_ts, ctx.keys)
            raise
        self.store.commit(start_ts, commit_ts, ctx.keys)
        return commit_ts

    def abort(self, start_ts: int) -> None:
        with self._lock:
            ctx = self._txns.get(start_ts)
            if ctx is not None:
                ctx.finishing = True
                self._drain_inflight(ctx, clamped=False)
            ctx = self._txns.pop(start_ts, None)
            self.zero.oracle.abort(start_ts)
            if ctx is not None:
                self.store.abort(start_ts, ctx.keys)

    # -- snapshots ----------------------------------------------------------

    def snapshot(self, read_ts: int | None = None) -> GraphSnapshot:
        with self._lock:
            if read_ts is None:
                read_ts = self.zero.oracle.read_ts()
            snap = self._assembler.snapshot(read_ts)
            if self.background_rollup and not self._rollup_started and \
                    self._assembler._overlays:
                self._start_rollup_loop()
            if self.mesh_exec is not None:
                # mesh placement at snapshot assembly — identity-cached at
                # the snapshot AND PredData level, so repeated reads keep
                # their qcache tokens and delta-overlay predicates keep
                # serving host-side until compaction folds a fresh base
                snap = self.mesh_exec.place_snapshot(snap)
            return snap

    # overlays older than this many seconds (or deeper than the stamp
    # ceiling) compact on the next tick
    ROLLUP_TICK_S = 1.0

    def _start_rollup_loop(self) -> None:
        self._rollup_started = True

        def loop():
            while not self._rollup_stop.wait(self.ROLLUP_TICK_S):
                try:
                    if self._assembler.compact_candidates():
                        self._assembler.compact(self._lock)
                # dgraph: allow(except-seam) next tick retries; queries
                # are unaffected by a failed compaction attempt
                except Exception:
                    pass
        # dgraph: allow(ctxvar-copy) detached compaction bg loop
        threading.Thread(target=loop, daemon=True,
                         name="dgt-rollup").start()

    def _invalidate_snapshots(self) -> None:
        with self._lock:
            self._assembler.invalidate()
        # schema/drop changes don't always mint a new read_ts, but they DO
        # mint new snapshot objects (fresh cache tokens), so stale task
        # results can never be served — clearing just releases the bytes
        if self.task_cache is not None:
            self.task_cache.clear()
        if self.result_cache is not None:
            self.result_cache.clear()

    # -- parsing --------------------------------------------------------------

    def _span(self, name: str, **attrs):
        """Root span when nothing is active on this execution context
        (direct API / HTTP entry — the sampling decision happens here);
        child span when nested (upsert inside query, commit inside
        mutate). An armed slow-query log force-samples every root: a slow
        query can only be identified AFTER it ran, so the threshold can
        never be honored from a 1% sample."""
        cur = otrace.current()
        if cur is not None:
            return self.tracer.start(name, parent=cur, attrs=attrs)
        return self.tracer.root(name, attrs=attrs,
                                force=self.slow_log.enabled)

    def _parse(self, q: str, variables: dict | None = None) -> dql.ParsedRequest:
        """Parse through the plan cache: hot query shapes skip the lexer +
        recursive-descent parser entirely. Parsed trees are read-only
        during execution (engine only builds NEW GraphQuery nodes), so one
        AST serves every replay."""
        if self.plan_cache is not None:
            return self.plan_cache.parse(q, variables,
                                         ns=tnc.current())
        return dql.parse(q, variables)

    # -- Query ---------------------------------------------------------------

    def _read_view(self, start_ts: int | None) -> tuple[int, GraphSnapshot]:
        """Snapshot for a read: committed state at read_ts, with an open
        txn's own uncommitted layers overlaid when start_ts names one
        (posting/list.go:528 — StartTs == readTs visibility)."""
        if start_ts is not None:
            read_ts = start_ts
        else:
            with otrace.span("zero:read_ts"):
                read_ts = self.zero.oracle.read_ts()
        with self._lock:
            # only an EXPLICIT startTs continues an open txn: a fresh read's
            # ts may numerically equal a pending txn's start_ts and must not
            # see its uncommitted writes
            ctx = self._txns.get(start_ts) if start_ts is not None else None
            if ctx is not None:
                ctx.last_active = time.monotonic()
                # drain this txn's in-flight applies: the overlay build reads
                # the uncommitted layer dicts a concurrent apply mutates
                self._drain_inflight(ctx)
            if ctx is not None and ctx.preds:
                base = self.snapshot(read_ts)
                snap = GraphSnapshot(read_ts)
                # lazy base (ISSUE 15): share the pending fold-thunks —
                # dict(base.preds) would drop them via the CPython dict
                # fast path and untouched predicates would read as absent
                copier = getattr(base.preds, "lazy_copy", None)
                snap.preds = copier() if copier is not None \
                    else dict(base.preds)
                snap.metrics = getattr(base, "metrics", None)
                if ctx.overlay is not None and ctx.overlay[0] == ctx.version:
                    snap.preds.update(ctx.overlay[1])
                else:
                    built = {attr: build_pred(self.store, attr, read_ts,
                                              own_start_ts=read_ts)
                             for attr in sorted(ctx.preds)}
                    ctx.overlay = (ctx.version, built)
                    snap.preds.update(built)
                # overlay views are cacheable WITHIN one txn version: the
                # per-mutate version bump rotates the token, so a buffered
                # write can never be served from a pre-write cache entry
                snap.cache_token = ("txn", ctx.start_ts, ctx.version,
                                    qcache.snapshot_token(base))
            else:
                snap = self.snapshot(read_ts)
        return read_ts, snap

    def _deadline_scope(self, timeout_ms: float | None):
        """Deadline scope for one request: explicit timeout_ms beats the
        node default; 0/None = unbudgeted (a no-op scope)."""
        from dgraph_tpu.utils import deadline as dl

        ms = self.default_timeout_ms if timeout_ms is None \
            else float(timeout_ms)
        return dl.scope(ms / 1000.0 if ms and ms > 0 else None)

    def _count_task(self, tq, res, dt: float) -> None:
        """Executor on_task hook: per-tablet read accounting — feeds BOTH
        the placement controller's load book and the residency manager's
        admission/eviction scores (the same rate×log2(size) signal)."""
        attr = tq.attr[1:] if tq.attr.startswith("~") else tq.attr
        # tablet accounting keys on STORAGE attrs: a tenant's task carries
        # its bare name, so translate before the load book / residency
        # touch (no-op for the default namespace)
        attr = tnc.prefix(tnc.current(), attr)
        out_bytes = 0.0
        if getattr(res, "dest_uids", None) is not None:
            out_bytes = 8.0 * len(res.dest_uids)
        self.tablet_book.record_read(attr, out_bytes=out_bytes, serve_s=dt)
        self.residency.touch(attr)

    def query(self, q: str, variables: dict | None = None,
              start_ts: int | None = None,
              read_only: bool = False,
              edge_limit: int | None = None,
              explain: bool = False,
              timeout_ms: float | None = None,
              _cost_endpoint: str = "query",
              _cost_subs: tuple = ()) -> tuple[dict, TxnContext]:
        """Parse + execute a DQL request (edgraph/server.go:373).

        read_only treats start_ts purely as a snapshot timestamp: it never
        joins an open txn's uncommitted overlay even if some pending txn
        happens to carry the same start_ts (read ts values come from the same
        oracle counter, so numeric collision is possible).

        edge_limit overrides the process-default traversed-edge budget for
        THIS request only (the --query_edge_limit flag, now per-request).

        explain=True adds an "explain" key to the returned dict: the
        physical plan tree with estimated vs actual cardinality per step
        (the ?explain=true HTTP surface). Explain requests bypass the
        whole-query result cache so the actuals are real."""
        qtitle = q.strip().splitlines()[0][:120] if q.strip() else ""
        tr = self.traces.start("query", qtitle)
        sp = self._span("query", query=qtitle)
        m = self.metrics
        m.counter("dgraph_num_queries_total").inc()
        m.counter("dgraph_pending_queries_total").inc()
        m.meter("query").mark()
        t0 = time.perf_counter()
        err = ""
        # per-request cost ledger: the plan-shape key is the DQL text —
        # exactly what qcache.plan_key keys on — so /debug/top aggregates
        # replays of one shape across variable bindings
        # _cost_endpoint="live" tags standing-subscription re-evals so
        # /debug/top?endpoint=live ranks them next to foreground shapes
        tenant = tnc.current()
        lg = costs.CostLedger(endpoint=_cost_endpoint, shape=q,
                              tenant=tenant) \
            if self.cost_ledger else None
        if lg is not None and _cost_subs:
            # per-subscription attribution (ISSUE 19): the live manager
            # passes the ids of every subscription a coalesced re-eval
            # serves; /debug/top?group=sub apportions the record's cost
            # equally among them
            lg.subs = tuple(_cost_subs)
        try:
          with sp, self._deadline_scope(timeout_ms), costs.scope(lg):
            self._admit_tenant(tenant)
            req = self._parse(q, variables)
            tr.printf("parsed: %d query blocks", len(req.queries))
            if req.upsert is not None:
                # implicit txn commits; an explicit one stays open for the
                # client's own commit/abort
                out, _uids, ctx = self.upsert(
                    req.upsert["query"], req.upsert["mutations"],
                    start_ts=start_ts, commit_now=start_ts is None)
                return out, ctx
            if req.schema_request is not None:
                return {"schema": self._schema_json(req.schema_request)}, \
                    TxnContext(start_ts=0)
            if read_only and start_ts is not None:
                read_ts, snap = start_ts, self.snapshot(start_ts)
            else:
                read_ts, snap = self._read_view(start_ts)
            if tenant:
                # namespace seam: the executor, planner, caches, and
                # batcher all run on the tenant's unprefixed vocabulary
                # while reading only the tenant's storage tablets
                snap = self._ns_view(snap, tenant)
            schema = self._schema_view()
            sp.set(read_ts=int(read_ts))
            tr.printf("snapshot at ts %d (%d preds)", read_ts, len(snap.preds))
            pf_attrs = None
            if not req.mutations:
                # plan-driven FOLD prefetch (ISSUE 15): pending lazy folds
                # of the plan's read set resolve on the shared fold pool
                # BEFORE the result-token computation, so the cache-key
                # walk below JOINS in-flight folds instead of folding
                # serially. Issued only when something is actually pending
                # — a warm result-cache hit must stay free of prefetch
                # work (the upload leg runs after the cache miss, below)
                pf_attrs = qcache.plan_attrs(req)
                is_pending = getattr(snap.preds, "is_pending", None)
                if pf_attrs and is_pending is not None:
                    # ONLY the pending attrs: the early call must not run
                    # the upload leg for folded tablets a cache hit never
                    # needs (and the miss-path call below would re-submit)
                    pend = [a for a in pf_attrs if is_pending(a)]
                    if pend:
                        self.residency.prefetch(pend, snap)
            # whole-query result tier: keyed on (plan key, per-predicate
            # token tuple of the plan's read set, edge budget). A commit to
            # predicate P rotates only P's PredData token, so replays that
            # never read P keep their cache heat; plans whose read set
            # isn't statically derivable (explicit uids, expand, shortest)
            # key on the snapshot object and rotate on every commit /
            # alter / drop / txn-overlay version bump as before
            rkey = None
            if self.result_cache is not None and not req.mutations \
                    and not explain:
                pk = qcache.plan_key(q, variables, tenant)
                if pk is not None:
                    # the EFFECTIVE budget is part of the key: a shrunk
                    # budget (per-request or via set_query_edge_limit) must
                    # re-execute, not serve a result computed under a
                    # larger one (and vice versa)
                    from dgraph_tpu.query import engine as _eng

                    eff = edge_limit if edge_limit is not None \
                        else _eng.MAX_QUERY_EDGES
                    rkey = (pk, qcache.result_token(req, snap), eff)
                    cached = self.result_cache.get(rkey)
                    if cached is not None:
                        tr.printf("result cache hit")
                        sp.set(result_cache="hit")
                        costs.note("result_cache_hit")
                        return cached, TxnContext(start_ts=read_ts)
            # cost-based plan (order decisions only): cached alongside the
            # AST, keyed on the per-predicate stats tokens of the plan's
            # read set — a commit to P rebuilds only plans that read P
            plan = None
            recorder = {} if explain else None
            if self.planner_enabled and not req.mutations:
                from dgraph_tpu.query import planner as plmod

                def build():
                    return plmod.build_plan(req, snap, schema,
                                            metrics=self.metrics,
                                            top_k=self.stats_top_k,
                                            trace=tr)
                try:
                    plan = (self.plan_cache.plan(q, variables, req, snap,
                                                 build, ns=tenant)
                            if self.plan_cache is not None else build())
                except Exception:
                    # stats/planner trouble must never fail a query —
                    # parse-order execution is always available
                    self.metrics.counter(
                        "dgraph_planner_fallbacks_total").inc()
                    plan = None
                if plan is not None and sp:
                    # compact decision summary for the slow-query log;
                    # per-step est-vs-actual rides Plan.record span events
                    sp.set(plan={
                        "root_swaps": len(plan.root_swap),
                        "filter_reorders": len(plan.and_order),
                        "sibling_reorders": len(plan.child_order),
                        "cutover_overrides": len(plan.cutover)})
            if self.residency.enabled and pf_attrs:
                # warm→HBM UPLOAD prefetch (ISSUE 11): after the result
                # cache missed, start async uploads for the read set so
                # the transfer overlaps the preceding host work / device
                # step — exactly the pre-lazy call site, so cache hits
                # never paid for it
                self.residency.prefetch(pf_attrs, snap)
            out = Executor(snap, schema,
                           cache=self.task_cache, gate=self.dispatch_gate,
                           edge_limit=edge_limit, plan=plan,
                           explain=recorder,
                           mesh=self.mesh_exec,
                           batcher=self.batcher,
                           on_task=self._count_task).execute(req)
            tr.printf("executed")
            if rkey is not None:
                self.result_cache.put(rkey, out)
            if explain:
                from dgraph_tpu.query import planner as plmod

                out = dict(out)
                out["explain"] = (plmod.render_explain(plan, recorder)
                                  if plan is not None
                                  else {"planner": "off"})
            return out, TxnContext(start_ts=read_ts)
        except BaseException as e:
            # EVERY failure shape finishes the breadcrumb trace with its
            # error, exactly once, via the finally below — including
            # TxnConflict from the upsert path and non-Exception bases
            err = str(e) or type(e).__name__
            from dgraph_tpu.utils.deadline import DeadlineExceeded

            if isinstance(e, DeadlineExceeded):
                m.counter("dgraph_deadline_exceeded_total").inc()
            raise
        finally:
            m.counter("dgraph_pending_queries_total").dec()
            m.histogram("dgraph_query_latency_s").observe(
                time.perf_counter() - t0,
                exemplar=sp.trace_id or None)
            if not self._first_query_done and not err:
                self._first_query_done = True
                m.counter("dgraph_first_query_ms").set(
                    (time.perf_counter() - self._birth) * 1e3)
            self._finish_cost(lg, sp)
            self.traces.finish(tr, error=err)

    def _finish_cost(self, lg, sp) -> None:
        """Close one request's cost ledger: observe the aggregatable
        dgraph_query_cost_* histograms (exemplar = the request's sampled
        trace id, resolvable at /debug/traces/<id>), admit the record to
        the /debug/top window, and route a flagged cost regression into
        the slow-query ring — even when the query finished UNDER
        --slow_query_ms (that is the point: a shape that regressed from
        2ms to 40ms never crosses a 500ms threshold)."""
        if lg is None:
            return
        m = self.metrics
        if not lg.tasks and lg.device_ms == 0.0 and not lg.groups:
            # trivial record (whole-result cache hit, schema request,
            # parse error): nothing executed — skip record assembly and
            # the cost observations entirely. This keeps the armed warm
            # path within the <2% bench `obs` gate AND keeps zero-cost
            # replays from diluting the cost distributions and the
            # per-shape EWMA baselines into flagging every real
            # execution as a regression.
            return
        # counted AFTER the trivial skip: the counter means "records
        # admitted to the cost surfaces", matching /debug/metrics
        m.counter("dgraph_cost_records_total").inc()
        lg.finish()
        rec = lg.to_dict()
        total = rec["total"]
        # per-tenant attribution + quota debit (ISSUE 20): every admitted
        # record's ledger units debit its tenant's buckets and advance
        # the dgraph_tenant_* labeled series. Cache hits are trivial
        # records (skipped above): they consumed no device resources, so
        # they cost nothing — admission still gated them.
        if lg.tenant or self.tenancy.configured:
            self.tenancy.debit(
                lg.tenant,
                device_ms=float(total["device_ms"]),
                edges=float(total["edges"]),
                bytes_=float(total["h2d"] + total["d2h"]))
        tid = sp.trace_id if sp else ""
        ex = tid or None
        m.histogram("dgraph_query_cost_device_ms").observe(
            float(total["device_ms"]), exemplar=ex)
        m.histogram("dgraph_query_cost_edges").observe(
            float(total["edges"]), exemplar=ex)
        m.histogram("dgraph_query_cost_bytes").observe(
            float(total["h2d"] + total["d2h"]), exemplar=ex)
        flag = self.cost_book.record(lg.shape, lg.endpoint, tid, rec)
        if flag is not None:
            m.counter("dgraph_cost_regressions_total").inc()
            self.slow_log.record({
                "ts": time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime()),
                "root": "cost_regression",
                "trace_id": tid,
                "query": lg.shape[:2000],
                "elapsed_ms": total["wall_ms"],
                **flag})

    def analytics(self, kind: str, pred: str, *, damping: float = 0.85,
                  tol: float = 1e-6, max_iters: int = 100, top: int = 20,
                  timeout_ms: float | None = None,
                  start_ts: int | None = None) -> dict:
        """Whole-graph analytics over one uid predicate's tablet
        (query/analytics.py): PageRank / connected components / triangle
        count as device-resident while_loop programs on the mesh, host
        oracle fallback when the tablet is overlay/residency-deferred or
        the node runs without a mesh. Same request discipline as query():
        span + deadline scope + cost ledger + DispatchGate."""
        from dgraph_tpu.query import analytics as an

        sp = self._span("analytics", kind=kind, pred=pred)
        m = self.metrics
        m.meter("analytics").mark()
        t0 = time.perf_counter()
        tenant = tnc.current()
        lg = costs.CostLedger(endpoint="analytics",
                              shape=f"analytics:{kind}:{pred}",
                              tenant=tenant) \
            if self.cost_ledger else None
        try:
            with sp, self._deadline_scope(timeout_ms), costs.scope(lg):
                self._admit_tenant(tenant)
                read_ts, snap = self._read_view(start_ts)
                if tenant:
                    snap = self._ns_view(snap, tenant)
                sp.set(read_ts=int(read_ts))
                rev = pred.startswith("~")
                pd = snap.pred(pred[1:] if rev else pred)
                csr = (pd.rev_csr if rev else pd.csr) \
                    if pd is not None else None
                if csr is None:
                    raise ValueError(
                        f"analytics: predicate {pred!r} has no uid "
                        f"edges")
                if self.residency.enabled:
                    self.residency.prefetch(
                        [pred[1:] if rev else pred], snap)
                lga = costs.current()
                if lga is not None:
                    lga.add_task(pred[1:] if rev else pred, 0)
                out = an.run(kind, csr, mesh=self.mesh_exec,
                             gate=self.dispatch_gate, metrics=m,
                             damping=damping, tol=tol,
                             max_iters=max_iters, top=top)
                out["pred"] = pred
                sp.set(device=out["device"], nodes=out["nodes"],
                       edges=out["edges"])
                return out
        finally:
            m.histogram("dgraph_analytics_latency_s").observe(
                time.perf_counter() - t0,
                exemplar=sp.trace_id or None)
            self._finish_cost(lg, sp)

    def upsert(self, q: str, mutations: list[dict],
               variables: dict | None = None, start_ts: int | None = None,
               commit_now: bool = False) -> tuple[dict, dict, TxnContext]:
        """Query-then-conditionally-mutate in one txn (edgraph/server.go
        doQueryInUpsert + gql/upsert.go). `mutations` entries carry any of
        cond / set / delete / set_json / delete_json (text cond is the inside
        of @if(...)). Returns (query json, assigned uids, ctx)."""
        self.metrics.counter("dgraph_num_upserts_total").inc()
        own_txn = start_ts is None
        with self._lock:
            if own_txn:
                ctx = self.new_txn()
            else:
                ctx = self._txns.get(start_ts)
                if ctx is None:
                    raise mut.MutationError(f"unknown txn {start_ts}")
        with self._span("upsert", mutations=len(mutations)):
            try:
                out: dict = {}
                vars_map: dict = {}
                if q.strip():
                    _, snap = self._read_view(ctx.start_ts)
                    tenant = tnc.current()
                    if tenant:
                        snap = self._ns_view(snap, tenant)
                    ex = Executor(snap, self._schema_view(),
                                  cache=self.task_cache,
                                  gate=self.dispatch_gate,
                                  mesh=self.mesh_exec,
                                  batcher=self.batcher,
                                  on_task=self._count_task)
                    out = ex.execute(self._parse(q, variables))
                    vars_map = ex.vars
                uid_map: dict = {}
                for m in mutations:
                    cond = m.get("cond", "")
                    if cond and not ups.eval_cond(cond, vars_map):
                        continue
                    nq_set = ups.expand(rdf.parse(m.get("set", "")), vars_map)
                    nq_del = ups.expand(rdf.parse(m.get("delete", "")),
                                        vars_map)
                    if m.get("set_json") is not None:
                        nq_set += mut.nquads_from_json(
                            m["set_json"], Op.SET,
                            schema=self._schema_view())
                    if m.get("delete_json") is not None:
                        nq_del += mut.nquads_from_json(
                            m["delete_json"], Op.DEL,
                            schema=self._schema_view())
                    if not nq_set and not nq_del:
                        continue   # cond met but every quad's var was empty
                    res = self.mutate_quads(nq_set, nq_del, commit_now=False,
                                            start_ts=ctx.start_ts)
                    uid_map.update(res.uids)
            except BaseException:
                if own_txn:
                    # don't leak the implicit txn (it would pin the oracle's
                    # conflict-GC watermark); an explicit txn stays open for
                    # the client to retry or abort
                    self.abort(ctx.start_ts)
                raise
            if commit_now:
                self.commit(ctx.start_ts)
            return out, uid_map, ctx

    def _schema_json(self, preds: list[str]) -> list[dict]:
        from dgraph_tpu.utils.schema import schema_json

        # the tenant's schema view lists + strips its own entries, so a
        # schema{} response never leaks another namespace (or the prefix)
        return schema_json(self._schema_view(), preds)

    # -- Mutate --------------------------------------------------------------

    def mutate(self, set_nquads: str = "", del_nquads: str = "",
               set_json=None, delete_json=None, commit_now: bool = False,
               start_ts: int | None = None,
               timeout_ms: float | None = None) -> MutationResult:
        """Buffer (and optionally commit) one mutation (server.go:267)."""
        nquads_set = rdf.parse(set_nquads) if set_nquads else []
        nquads_del = rdf.parse(del_nquads) if del_nquads else []
        if set_json is not None:
            nquads_set += mut.nquads_from_json(set_json, Op.SET,
                                               schema=self._schema_view())
        if delete_json is not None:
            nquads_del += mut.nquads_from_json(delete_json, Op.DEL,
                                               schema=self._schema_view())
        return self.mutate_quads(nquads_set, nquads_del,
                                 commit_now=commit_now, start_ts=start_ts,
                                 timeout_ms=timeout_ms)

    def mutate_quads(self, nquads_set, nquads_del=(), *,
                     commit_now: bool = False,
                     start_ts: int | None = None,
                     timeout_ms: float | None = None) -> MutationResult:
        """Mutate with pre-parsed NQuads (the loaders' entry — skips text
        parsing; dgraph/cmd/live/batch.go feeds api.Mutation.Set directly)."""
        nquads_set = list(nquads_set)
        nquads_del = list(nquads_del)
        if not nquads_set and not nquads_del:
            raise mut.MutationError("empty mutation")
        tenant = tnc.current()
        if tenant:
            # namespace seam for writes: the tenant's quads land on its
            # own storage attrs. "S * *" wildcard deletion reads the
            # store to learn its footprint — a tenant must not discover
            # (or delete) predicates outside its namespace, so it gets
            # the typed error instead.
            self._admit_tenant(tenant)
            for nq in nquads_set + nquads_del:
                if nq.predicate == "*":
                    raise tnc.NamespaceError(
                        "wildcard predicate deletion (S * *) is not "
                        "available inside a tenant namespace")
                nq.predicate = tnc.prefix(tenant, nq.predicate)
        tr = self.traces.start(
            "mutate", f"{len(nquads_set)} set / {len(nquads_del)} del")
        sp = self._span("mutate", set=len(nquads_set),
                        delete=len(nquads_del))
        m = self.metrics
        m.counter("dgraph_num_mutations_total").inc()
        m.counter("dgraph_active_mutations_total").inc()
        m.meter("mutate").mark()
        t0 = time.perf_counter()
        err = ""
        try:
          with sp, self._deadline_scope(timeout_ms):
            with self._lock:
                if start_ts is None:
                    ctx = self.new_txn()
                else:
                    ctx = self._txns.get(start_ts)
                    if ctx is None or ctx.finishing:
                        raise mut.MutationError(f"unknown txn {start_ts}")
                # inflight pins the txn: commit/abort of this start_ts wait
                # until apply completes, so they can't interleave mid-apply
                # and orphan uncommitted layers (advisor r2 invariant, now
                # kept WITHOUT serializing all mutations behind one lock)
                ctx.inflight += 1
                ctx.last_active = time.monotonic()
            applied = False
            try:
                uid_map = mut.assign_uids(nquads_set + nquads_del,
                                          self.zero.uids)
                edges = mut.to_edges(nquads_set, uid_map, Op.SET) + \
                    mut.to_edges(nquads_del, uid_map, Op.DEL)
                # conflict-keyed parallel apply (worker/scheduler.go:34-95):
                # disjoint (attr, uid) footprints run concurrently; shared
                # footprints serialize in arrival order. Objects of uid edges
                # are in the footprint too (reverse/count maintenance does
                # read-modify-write on the object side). `S * *` deletes
                # only learn their footprint by reading the store at apply
                # time, so they take the scheduler exclusively.
                exclusive = any(e.attr == "*" for e in edges)
                skeys: set[int] = set()
                if not exclusive:
                    for e in edges:
                        skeys.add(hash((e.attr, e.subject)))
                        if e.object_uid:
                            skeys.add(hash((e.attr, e.object_uid)))
                touched, conflict, preds = self._sched.run(
                    skeys, lambda: mut.apply_mutations(
                        self.store, edges, ctx.start_ts),
                    exclusive=exclusive)
                applied = True
            finally:
                with self._lock:
                    try:
                        if applied:
                            ctx.keys += touched
                            ctx.conflict_keys += conflict
                            ctx.preds |= preds
                            ctx.version += 1
                            self.zero.oracle.track(ctx.start_ts, conflict,
                                                   sorted(preds))
                            m.counter("dgraph_posting_writes_total").inc(
                                len(touched))
                    finally:
                        # unconditional: a parked commit/abort must wake even
                        # if oracle bookkeeping above raised
                        ctx.inflight -= 1
                        self._inflight_cv.notify_all()
            from collections import Counter

            edge_counts = Counter(e.attr for e in edges)
            for p in preds:
                self.zero.should_serve(p)
                self.tablet_book.record_write(p, n=edge_counts[p] or 1)
            res = MutationResult(uids=uid_map, context=ctx)
            if commit_now:
                self.commit(ctx.start_ts)
            return res
        except BaseException as e:
            err = str(e) or type(e).__name__
            raise
        finally:
            m.counter("dgraph_active_mutations_total").dec()
            m.histogram("dgraph_mutation_latency_s").observe(
                time.perf_counter() - t0,
                exemplar=sp.trace_id or None)
            self.traces.finish(tr, error=err)

    def run_request(self, q: str, variables: dict | None = None,
                    commit_now: bool = True) -> tuple[dict, MutationResult | None]:
        """One combined DQL request: query blocks and/or mutation blocks
        through the same entry (the `{set {...}}` surface)."""
        req = self._parse(q, variables)
        mres = None
        if req.mutations:
            sets, dels = [], []
            for m in req.mutations:
                (sets if m["op"] == "set" else dels).append(m["rdf"])
            mres = self.mutate(set_nquads="\n".join(sets),
                               del_nquads="\n".join(dels),
                               commit_now=commit_now)
        out = {}
        if req.queries:
            out, _ = self.query(q, variables)
        return out, mres

    # -- Alter ---------------------------------------------------------------

    def alter(self, schema_text: str = "", drop_attr: str = "",
              drop_all: bool = False) -> None:
        """Schema mutations + drops (server.go:213), with the reindex
        pipeline (worker/mutation.go:97 runSchemaMutation)."""
        self.metrics.counter("dgraph_num_alters_total").inc()
        title = ("drop_all" if drop_all else
                 f"drop {drop_attr}" if drop_attr else
                 (schema_text.strip().splitlines() or [""])[0][:120])
        tr = self.traces.start("alter", title)
        err = ""
        try:
          with self._span("alter", op=title):
            self._alter_locked(schema_text, drop_attr, drop_all)
        except BaseException as e:
            err = str(e) or type(e).__name__
            raise
        finally:
            self.traces.finish(tr, error=err)

    def _alter_locked(self, schema_text: str, drop_attr: str,
                      drop_all: bool) -> None:
        tenant = tnc.current()
        with self._lock:
            if drop_all:
                attrs = set(self.store.predicates()) | \
                    set(self.store.schema.predicates())
                if tenant:
                    # a tenant's drop_all empties ITS namespace only; the
                    # default (admin) namespace keeps the whole-store drop
                    attrs = {a for a in attrs
                             if tnc.split(a)[0] == tenant}
                for attr in attrs:
                    self.store.delete_predicate(attr)
                self._invalidate_snapshots()
                return
            if drop_attr:
                self.store.delete_predicate(tnc.prefix(tenant, drop_attr))
                self._invalidate_snapshots()
                return
            for e in parse_schema(schema_text):
                if tenant:
                    e.predicate = tnc.prefix(tenant, e.predicate)
                old = self.store.schema.get(e.predicate)
                self.store.set_schema(e)
                if idx.needs_reindex(old, e):
                    read_ts = self.zero.oracle.read_ts()
                    commit_ts = self.zero.oracle.timestamps(1)
                    idx.rebuild_index(self.store, e.predicate, read_ts, commit_ts)
                    idx.rebuild_reverse(self.store, e.predicate, read_ts, commit_ts)
                    idx.rebuild_count(self.store, e.predicate, read_ts, commit_ts)
            self._invalidate_snapshots()

    # -- memory management ---------------------------------------------------

    def enforce_memory(self, budget_bytes: int) -> dict:
        """Bring host posting-list memory under budget (the --memory_mb
        contract; reference posting/lists.go:123-180 periodic commit +
        LRU eviction under AllottedMemory).

        Levers, cheapest first:
        1. roll up the layer-heaviest lists below the min-pending watermark
           (folds Python layer dicts into the packed numpy base — the same
           compaction the reference's periodic commit achieves);
        2. drop task-result cache entries (pure recompute cost, no
           correctness state);
        3. drop cached device snapshots and the predicate build cache
           (rebuilt read-through on the next query).
        Never touches uncommitted layers or layers a live txn could read.
        """
        stats = self.store.memory_stats()
        rolled = 0
        if stats["bytes"] > budget_bytes and stats["layers"]:
            pend = self.zero.oracle.min_pending()
            upto = self.store.max_seen_commit_ts if pend is None \
                else min(pend - 1, self.store.max_seen_commit_ts)
            if upto > 0:
                with self.store._lock:
                    pls = list(self.store.lists.values())
                pls.sort(key=lambda p: p.layer_count(), reverse=True)
                for pl in pls:
                    if pl.layer_count() == 0:
                        break
                    pl.rollup(upto)
                    rolled += 1
                    if rolled % 256 == 0 and \
                            self.store.memory_stats()["bytes"] <= budget_bytes:
                        break
                stats = self.store.memory_stats()
        cache_evicted = 0
        cache_bytes = (self.task_cache.bytes if self.task_cache else 0) + \
            (self.result_cache.bytes if self.result_cache else 0)
        if cache_bytes and stats["bytes"] + cache_bytes > budget_bytes:
            over = stats["bytes"] + cache_bytes - budget_bytes
            if self.result_cache is not None:
                cache_evicted += self.result_cache.evict_to(
                    max(0, self.result_cache.bytes - over))
                over = stats["bytes"] + \
                    (self.task_cache.bytes if self.task_cache else 0) - \
                    budget_bytes
            if self.task_cache is not None and over > 0:
                cache_evicted += self.task_cache.evict_to(
                    max(0, self.task_cache.bytes - over))
        # overlay rows are pure acceleration state: force-compact them back
        # into folded bases before the invalidate hammer (keeps cache heat)
        compacted = 0
        overlay_bytes = self._assembler.overlay_bytes()
        if overlay_bytes and stats["bytes"] + overlay_bytes > budget_bytes:
            compacted = self._assembler.compact(self._lock, force=True)
            overlay_bytes = self._assembler.overlay_bytes()
        # device-byte accounting routes through the ResidencyManager
        # (ISSUE 11 satellite): fold_bytes is the HOST footprint of every
        # live folded PredData — CSR columns, value tables, token indexes,
        # AND vector embedding matrices, which the old accounting never
        # saw (a vector-heavy snapshot silently blew the budget). The
        # manager also re-enforces its own device budget here.
        fold_bytes = self.residency.host_bytes()
        res_evicted = 0
        if self.residency.enabled:
            res_evicted = self.residency.evict_to(self.residency.budget)
        dropped_snaps = 0
        if stats["bytes"] + fold_bytes > budget_bytes:
            with self._lock:
                dropped_snaps = self._assembler.invalidate()
            # dropped PredData frees its device buffers too (weakref
            # entries unregister as the folds are collected); make any
            # survivors' device bytes visible immediately. fold_bytes
            # stays the MEASURED value — the number that triggered the
            # drop, not the post-drop remainder.
            self.residency.usage()
        self.metrics.counter("dgraph_memory_bytes").set(stats["bytes"])
        return {"bytes": stats["bytes"], "lists": stats["lists"],
                "layers": stats["layers"], "rolled_up": rolled,
                "dropped_caches": dropped_snaps,
                "task_cache_evicted": cache_evicted,
                "overlay_bytes": overlay_bytes,
                "overlays_compacted": compacted,
                "fold_bytes": fold_bytes,
                "residency_evicted": res_evicted,
                "residency": self.residency.usage()}

    # -- live queries (ISSUE 18) --------------------------------------------

    def subscribe(self, q: str, variables: dict | None = None, *,
                  cursor: int | None = None, queue_max: int | None = None):
        """Register a standing query (the gRPC/embedded surface): returns a
        live.Subscription iterator whose first event is init (full result
        at its watermark), ack (reconnect cursor proven unchanged by the
        delta journal), or a typed resync; subsequent events are diffs at
        the commit watermark they reflect. See docs/query-language.md."""
        return self.live.subscribe(q, variables, cursor=cursor,
                                   queue_max=queue_max)

    # -- ops -----------------------------------------------------------------

    def health(self) -> dict:
        return {"status": "healthy", "version": "dgraph-tpu",
                "maxAssigned": self.zero.oracle.max_assigned}

    def state(self) -> dict:
        return self.zero.state()

    def close(self) -> None:
        live = getattr(self, "live", None)
        if live is not None:
            live.close()
        if getattr(self, "devprof", None) is not None:
            from dgraph_tpu.obs import devprof as devprof_mod

            devprof_mod.unregister(self.devprof)
        self._rollup_stop.set()
        self.slow_log.close()
        self.residency.close()
        self.store.close()
