"""CLI: `python -m dgraph_tpu.analysis [paths] [--rule R] [--format F]`.

Exit status: 0 clean, 1 findings, 2 usage error — so CI can gate on it
(contrib/scripts/smoke_lint.sh does). `--format=json` emits a machine-
readable finding list; `--list-rules` prints every rule with its doc.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

from .runner import RULES, analyze_paths


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dgraph_tpu.analysis",
        description="dgraph-tpu project-invariant static analysis")
    ap.add_argument("paths", nargs="*",
                    help="files/directories to analyze (default: the "
                         "installed dgraph_tpu package)")
    ap.add_argument("--rule", action="append", dest="rules",
                    metavar="RULE",
                    help="run only this rule (repeatable)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for name in sorted(RULES):
            print(f"{name:24s} {RULES[name]().doc}")
        return 0

    paths = [Path(p) for p in args.paths] or \
        [Path(__file__).resolve().parent.parent]
    for p in paths:
        if not p.exists():
            print(f"error: no such path {p}", file=sys.stderr)
            return 2
    t0 = time.perf_counter()
    try:
        findings = analyze_paths(paths, args.rules)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    dt = time.perf_counter() - t0
    if args.format == "json":
        print(json.dumps({"findings": [f.to_dict() for f in findings],
                          "elapsed_s": round(dt, 3)}, indent=2))
    else:
        for f in findings:
            print(f.format())
        print(f"{len(findings)} finding(s) "
              f"[{', '.join(sorted(args.rules or RULES))}] "
              f"in {dt:.2f}s", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
