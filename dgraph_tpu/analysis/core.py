"""Checker framework core: parsed-file model, findings, suppressions.

Parsing happens ONCE per file (ast.parse + a line scan for suppression
comments); every checker walks the same tree. Checkers come in two
shapes: per-file (`check(sf)` yields findings) and project-wide
(`collect(sf)` per file, then `finalize()` once — for invariants that
only hold across the whole tree, like the metric-registration and
fault-point cross-checks).

No imports of jax/numpy/grpc here or in any checker: the analyzer must
start fast (`python -m dgraph_tpu.analysis` budget is 10s including the
interpreter) and run anywhere, including boxes without the accelerator
stack.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path

# `# dgraph: allow(rule-a, rule-b) optional free-text rationale`
_ALLOW_RE = re.compile(r"#\s*dgraph:\s*allow\(([a-z0-9_\-, ]+)\)")


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str
    line: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message}


class SourceFile:
    """One parsed module + its suppression map.

    `rel` is the path relative to the analysis root (scoped rules match
    on its parts: a file under query/ or parallel/ is request-path
    code). `allow` maps line number -> set of suppressed rule names; a
    finding on line L is suppressed by a comment on L or on L-1."""

    def __init__(self, path: Path, rel: str, text: str) -> None:
        self.path = path
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text)
        self.allow: dict[int, set[str]] = {}
        for i, ln in enumerate(self.lines, start=1):
            if "dgraph:" not in ln:          # cheap pre-filter
                continue
            m = _ALLOW_RE.search(ln)
            if m:
                rules = {r.strip() for r in m.group(1).split(",")
                         if r.strip()}
                self.allow.setdefault(i, set()).update(rules)

    @classmethod
    def load(cls, path: Path, root: Path) -> "SourceFile":
        try:
            rel = str(path.relative_to(root))
        except ValueError:
            rel = str(path)
        return cls(path, rel, path.read_text())

    def suppressed(self, rule: str, line: int) -> bool:
        """A finding on `line` is suppressed by an allow() on the line
        itself or anywhere in the contiguous comment block directly
        above it (multi-line rationales are encouraged)."""
        def hit(ln: int) -> bool:
            rules = self.allow.get(ln)
            return bool(rules and (rule in rules or "all" in rules))

        if hit(line):
            return True
        ln = line - 1
        while ln >= 1 and self.lines[ln - 1].lstrip().startswith("#"):
            if hit(ln):
                return True
            ln -= 1
        return False

    def in_dirs(self, names: tuple[str, ...]) -> bool:
        """True when a directory segment (or the filename stem) matches —
        how scoped rules decide a file is request-path / seam code.
        Besides the analysis-root-relative segments, the ENCLOSING
        PACKAGE chain counts (directories with __init__.py walking up
        from the file): a single-file run roots at the file's parent and
        rel alone would drop the very segments the scoped rules key on.
        Only package dirs qualify — matching the raw absolute path would
        make the verdict depend on where the repo happens to be cloned
        (a checkout under /home/ci/api/… must not put the whole tree in
        seam scope)."""
        parts = set(Path(self.rel).parts[:-1])
        parts.add(Path(self.rel).stem)
        try:
            d = self.path.resolve().parent
            while (d / "__init__.py").exists() and d != d.parent:
                parts.add(d.name)
                d = d.parent
        except OSError:
            pass
        return any(p in names for p in parts)

    def src(self, node: ast.AST) -> str:
        """Source text of a node ('' when unavailable). Hand-rolled
        against the cached line list: ast.get_source_segment re-splits
        the whole file per call, which alone blew the analyzer's 10s
        budget across ~100 files."""
        try:
            lo = node.lineno - 1
            hi = node.end_lineno - 1
            if lo == hi:
                return self.lines[lo][node.col_offset:node.end_col_offset]
            parts = [self.lines[lo][node.col_offset:]]
            parts.extend(self.lines[lo + 1:hi])
            parts.append(self.lines[hi][:node.end_col_offset])
            return "\n".join(parts)
        except (AttributeError, IndexError, TypeError):
            return ""


@dataclass
class Checker:
    """Base: per-file checker. Subclasses set `rule`/`doc` and override
    `check`."""

    rule: str = ""
    doc: str = ""

    def check(self, sf: SourceFile) -> list[Finding]:
        raise NotImplementedError

    def run(self, sf: SourceFile) -> list[Finding]:
        return [f for f in self.check(sf)
                if not sf.suppressed(f.rule, f.line)]


@dataclass
class ProjectChecker(Checker):
    """Cross-file checker: `collect` per file, `finalize` once. The
    collected state lives on the instance — the runner constructs a
    fresh instance per analysis run."""

    _files: list[SourceFile] = field(default_factory=list)

    def collect(self, sf: SourceFile) -> None:
        self._files.append(sf)

    def finalize(self) -> list[Finding]:
        raise NotImplementedError

    def finalize_run(self) -> list[Finding]:
        by_path = {sf.rel: sf for sf in self._files}
        out = []
        for f in self.finalize():
            sf = by_path.get(f.path)
            if sf is not None and sf.suppressed(f.rule, f.line):
                continue
            out.append(f)
        return out


# -- shared AST helpers ------------------------------------------------------

def call_name(node: ast.Call) -> str:
    """Dotted name of the called object ('' for computed callees):
    `time.sleep` -> "time.sleep", `Thread` -> "Thread"."""
    return dotted(node.func)


def dotted(node: ast.AST) -> str:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif parts:
        parts.append("")                 # computed base: "<x>.attr"
    return ".".join(reversed(parts))


def const_str(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def kw(node: ast.Call, name: str) -> ast.AST | None:
    for k in node.keywords:
        if k.arg == name:
            return k.value
    return None


def enclosing_functions(tree: ast.Module) -> dict[int, ast.AST]:
    """Map every node id to its nearest enclosing FunctionDef (or the
    module). Built once per file by checkers that need scope context."""
    owner: dict[int, ast.AST] = {}

    def walk(node: ast.AST, fn: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            nfn = child if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef)) else fn
            owner[id(child)] = nfn
            walk(child, nfn)

    owner[id(tree)] = tree
    walk(tree, tree)
    return owner
