"""Analysis driver: load files once, run every (selected) checker.

Skips generated protobuf modules (*_pb2.py) and anything that does not
parse as the running interpreter's Python. Findings come back sorted by
(path, line, rule) so output is stable across runs.
"""

from __future__ import annotations

from pathlib import Path

from .checkers import ALL_CHECKERS
from .core import Checker, Finding, ProjectChecker, SourceFile

RULES: dict[str, type] = {cls().rule: cls for cls in ALL_CHECKERS}

_SKIP_SUFFIXES = ("_pb2.py",)


def iter_sources(paths: list[Path]) -> list[SourceFile]:
    files: list[SourceFile] = []
    for p in paths:
        p = Path(p)
        root = p if p.is_dir() else p.parent
        targets = sorted(p.rglob("*.py")) if p.is_dir() else [p]
        for py in targets:
            if any(py.name.endswith(s) for s in _SKIP_SUFFIXES):
                continue
            if "__pycache__" in py.parts:
                continue
            try:
                files.append(SourceFile.load(py, root))
            except (SyntaxError, UnicodeDecodeError):
                continue
    return files


def analyze_paths(paths: list[Path | str],
                  rules: list[str] | None = None) -> list[Finding]:
    """Run the selected checkers (default: all) over every .py under
    `paths`; suppressions already applied."""
    selected = list(RULES) if rules is None else list(rules)
    unknown = [r for r in selected if r not in RULES]
    if unknown:
        raise ValueError(
            f"unknown rule(s) {unknown}; known: {sorted(RULES)}")
    files = iter_sources([Path(p) for p in paths])
    findings: list[Finding] = []
    checkers: list[Checker] = [RULES[r]() for r in selected]
    for sf in files:
        for c in checkers:
            if isinstance(c, ProjectChecker):
                c.collect(sf)
            else:
                findings.extend(c.run(sf))
    for c in checkers:
        if isinstance(c, ProjectChecker):
            findings.extend(c.finalize_run())
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
