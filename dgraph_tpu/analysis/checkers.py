"""The ~8 project checkers (ISSUE 14), one per re-litigated invariant.

Each checker names the review that motivated it; docs/dev.md "Project
invariants" is the operator-facing companion. Heuristics are deliberate:
this is a project linter for THIS codebase's idioms, not a general
soundness tool — anything it cannot see (cross-function lock nesting,
dynamically-built metric names) is covered by the runtime halves
(utils/locks.py lockdep, the fresh-node /metrics audit in
tests/test_costs.py, which consumes this module's collector).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path

from .core import (Checker, Finding, ProjectChecker, SourceFile, call_name,
                   const_str, dotted, enclosing_functions, kw)

_PKG_ROOT = Path(__file__).resolve().parent.parent


# ---------------------------------------------------------------------------
# 1. metric-registration (PR 13's runtime audit, now static + shared)
# ---------------------------------------------------------------------------

# f-string placeholders used at metric call sites, expanded mechanically;
# a NEW placeholder must be added here or the checker flags the site as
# unexpandable (the invariant stays mechanical, never hand-maintained)
METRIC_PLACEHOLDERS: dict[str, tuple[str, ...]] = {
    "prefix": ("task", "result"),
    "ep": ("query", "mutate", "commit", "abort", "alter"),
}

_METRIC_METHODS = ("counter", "histogram", "keyed")


def _metric_templates(sf: SourceFile):
    """(template, lineno) for every dgraph_* name passed to a metric
    constructor method. f-strings come back as '{placeholder}'
    templates."""
    for node in ast.walk(sf.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _METRIC_METHODS and node.args):
            continue
        arg = node.args[0]
        s = const_str(arg)
        if s is not None:
            if s.startswith("dgraph_"):
                yield s, node.lineno
            continue
        if isinstance(arg, ast.JoinedStr):
            parts = []
            for v in arg.values:
                if isinstance(v, ast.Constant):
                    parts.append(str(v.value))
                elif isinstance(v, ast.FormattedValue):
                    parts.append("{%s}" % (dotted(v.value) or "?"))
            tpl = "".join(parts)
            if tpl.startswith("dgraph_"):
                yield tpl, node.lineno


def expand_metric_template(tpl: str) -> list[str] | None:
    """Expand {placeholder}s via METRIC_PLACEHOLDERS; None when a
    placeholder is unknown (the checker flags that site)."""
    m = re.search(r"\{([^{}]*)\}", tpl)
    if m is None:
        return [tpl]
    key = m.group(1)
    vals = METRIC_PLACEHOLDERS.get(key)
    if vals is None:
        return None
    out: list[str] = []
    for v in vals:
        sub = expand_metric_template(tpl.replace("{%s}" % key, v, 1))
        if sub is None:
            return None
        out.extend(sub)
    return out


def registered_metric_names(metrics_path: Path | None = None) -> set[str]:
    """Every dgraph_* literal inside utils/metrics.Registry.__init__ —
    the statically-extracted pre-registration set."""
    path = metrics_path or (_PKG_ROOT / "utils" / "metrics.py")
    tree = ast.parse(path.read_text())
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "Registry":
            for fn in node.body:
                if isinstance(fn, ast.FunctionDef) and \
                        fn.name == "__init__":
                    for c in ast.walk(fn):
                        s = const_str(c)
                        if s and s.startswith("dgraph_"):
                            out.add(s)
    return out


def collect_metric_names(root: Path) -> set[str]:
    """Every expanded dgraph_* name constructed anywhere under `root` —
    the shared collector tests/test_costs.py's runtime audit consumes
    (one implementation, two consumers)."""
    names: set[str] = set()
    for py in sorted(Path(root).rglob("*.py")):
        if py.name.endswith("_pb2.py"):
            continue
        try:
            sf = SourceFile.load(py, Path(root))
        except SyntaxError:
            continue
        for tpl, _ in _metric_templates(sf):
            names.update(expand_metric_template(tpl) or ())
    return names


@dataclass
class MetricRegistrationChecker(ProjectChecker):
    rule: str = "metric-registration"
    doc: str = ("every dgraph_* metric name constructed anywhere must be "
                "pre-registered in utils/metrics.Registry.__init__ (a "
                "fresh node's /metrics must expose it at 0)")

    @staticmethod
    def _is_registry_file(sf: SourceFile) -> bool:
        """Exactly utils/metrics.py — a future obs/fleet_metrics.py must
        be checked like any other file, never exempted or (worse) let to
        shadow the real pre-registration set."""
        p = Path(sf.rel)
        return p.name == "metrics.py" and p.parent.name == "utils"

    def finalize(self) -> list[Finding]:
        registered: set[str] | None = None
        for sf in self._files:
            if self._is_registry_file(sf) and any(
                    isinstance(n, ast.ClassDef) and n.name == "Registry"
                    for n in sf.tree.body):
                registered = registered_metric_names(sf.path)
        if registered is None:        # subset/fixture run: canonical set
            registered = registered_metric_names()
        out = []
        for sf in self._files:
            if self._is_registry_file(sf):
                continue              # Registry itself + its docstrings
            for tpl, line in _metric_templates(sf):
                names = expand_metric_template(tpl)
                if names is None:
                    out.append(Finding(
                        self.rule, sf.rel, line,
                        f"metric name {tpl!r} uses a placeholder not in "
                        f"analysis.checkers.METRIC_PLACEHOLDERS — add its "
                        f"expansion so the audit stays mechanical"))
                    continue
                for name in names:
                    if name not in registered:
                        out.append(Finding(
                            self.rule, sf.rel, line,
                            f"metric {name!r} is constructed here but "
                            f"not pre-registered in utils/metrics."
                            f"Registry.__init__ — a fresh node's "
                            f"/metrics would omit it"))
        return out


# ---------------------------------------------------------------------------
# 2. ctxvar-copy (HedgedReplicas PR 4 / batcher PR 9 lesson)
# ---------------------------------------------------------------------------

@dataclass
class CtxvarChecker(Checker):
    rule: str = "ctxvar-copy"
    doc: str = ("ThreadPoolExecutor.submit / Thread(target=) must carry "
                "contextvars (submit(ctx.run, fn, ...)) or annotate the "
                "task as deliberately detached — deadlines, trace spans, "
                "and cost ledgers all ride contextvars")

    def check(self, sf: SourceFile) -> list[Finding]:
        out = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name.split(".")[-1] == "submit" and "." in name:
                if node.args and isinstance(node.args[0], ast.Attribute) \
                        and node.args[0].attr == "run":
                    continue          # pool.submit(ctx.run, fn, ...)
                out.append(Finding(
                    self.rule, sf.rel, node.lineno,
                    "pool.submit() without a contextvars copy — request "
                    "context (deadline/trace/cost ledger) is lost across "
                    "the thread seam; submit(contextvars.copy_context()"
                    ".run, fn, ...) or annotate a detached task"))
            elif name.split(".")[-1] == "Thread":
                tgt = kw(node, "target")
                if tgt is None or (isinstance(tgt, ast.Attribute)
                                   and tgt.attr == "run"):
                    continue
                out.append(Finding(
                    self.rule, sf.rel, node.lineno,
                    "Thread(target=) without a contextvars copy — use "
                    "target=contextvars.copy_context().run or annotate "
                    "a deliberately-detached background thread"))
        return out


# ---------------------------------------------------------------------------
# 3. deadline-wait (the PR 7 lifeline contract at every wait point)
# ---------------------------------------------------------------------------

_DEADLINE_MARKERS = re.compile(
    r"clamp\(|remaining|deadline|expires|budget")
_WAIT_SCOPE = ("query", "parallel", "api", "coord")


def _name_resolves_to_deadline(name: str, assigns: dict[str, list[str]],
                               depth: int = 3,
                               seen: set[str] | None = None) -> bool:
    """One-level-at-a-time dataflow: does `name`'s assignment chain in
    this function reach a deadline-derived expression? `seen` caps the
    walk so mutually-referencing assignments cannot recurse forever."""
    if depth <= 0:
        return False
    seen = seen if seen is not None else set()
    if name in seen:
        return False
    seen.add(name)
    for rhs in assigns.get(name, ()):
        if _DEADLINE_MARKERS.search(rhs):
            return True
        for ref in set(re.findall(r"[A-Za-z_]\w*", rhs)):
            if ref != name and ref in assigns and \
                    _name_resolves_to_deadline(ref, assigns,
                                               depth - 1, seen):
                return True
    return False


@dataclass
class DeadlineWaitChecker(Checker):
    rule: str = "deadline-wait"
    doc: str = ("blocking waits (Condition/Event.wait, Queue.get, "
                "time.sleep, lock acquires) on request paths must consult "
                "the utils/deadline scope — clamp the timeout or check "
                "the budget; a budgeted request must never hang")

    def check(self, sf: SourceFile) -> list[Finding]:
        if not sf.in_dirs(_WAIT_SCOPE):
            return []
        owner = enclosing_functions(sf.tree)
        # per-function Name -> [RHS source] for the dataflow heuristic
        fn_assigns: dict[int, dict[str, list[str]]] = {}
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                fn = owner.get(id(node))
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if isinstance(t, ast.Name) and node.value is not None:
                        fn_assigns.setdefault(id(fn), {}).setdefault(
                            t.id, []).append(sf.src(node.value))
        out = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call) or \
                    not isinstance(node.func, ast.Attribute):
                continue
            attr = node.func.attr
            recv = dotted(node.func.value).lower()
            if attr == "sleep":
                if recv not in ("time", ""):
                    continue
            elif attr in ("wait", "wait_for"):
                if "stop" in recv:     # background-loop stop events
                    continue
            elif attr == "acquire":
                blocking = kw(node, "blocking") or (
                    node.args[0] if node.args else None)
                if isinstance(blocking, ast.Constant) and \
                        blocking.value is False:
                    continue           # non-blocking probe
            elif attr == "get":
                if "queue" not in recv:
                    continue
            else:
                continue
            if self._compliant(sf, node, fn_assigns.get(
                    id(owner.get(id(node))), {})):
                continue
            out.append(Finding(
                self.rule, sf.rel, node.lineno,
                f"blocking {recv or 'call'}.{attr}() on a request path "
                f"without consulting the deadline scope — clamp the "
                f"timeout (utils/deadline.clamp) or bound the loop by "
                f"the remaining budget"))
        return out

    def _compliant(self, sf: SourceFile, node: ast.Call,
                   assigns: dict[str, list[str]]) -> bool:
        exprs = list(node.args) + [k.value for k in node.keywords]
        for e in exprs:
            src = sf.src(e)
            if src and _DEADLINE_MARKERS.search(src):
                return True
            if isinstance(e, ast.Name) and \
                    _name_resolves_to_deadline(e.id, assigns):
                return True
        # context window: a deadline-bounded loop or a pre-checked budget
        # right above the wait (`while ... monotonic() < deadline:` /
        # `if pause >= dl.remaining(): raise`)
        lo = max(node.lineno - 8, 1)
        ctx = "\n".join(sf.lines[lo - 1:node.lineno])
        return bool(_DEADLINE_MARKERS.search(ctx))


# ---------------------------------------------------------------------------
# 4. except-seam (silent swallows at dispatch/wire seams)
# ---------------------------------------------------------------------------

_SEAM_SCOPE = ("api", "parallel", "zero_service")


@dataclass
class ExceptSeamChecker(Checker):
    rule: str = "except-seam"
    doc: str = ("bare `except:`/`except Exception:` handlers that "
                "silently swallow (pass/continue-only bodies) are banned "
                "at dispatch/wire seams — narrow to transport-shaped "
                "types, record the failure, or annotate why dropping it "
                "is correct")

    def check(self, sf: SourceFile) -> list[Finding]:
        if not sf.in_dirs(_SEAM_SCOPE):
            return []
        out = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            t = node.type
            broad = t is None or (isinstance(t, ast.Name)
                                  and t.id in ("Exception", "BaseException"))
            if not broad:
                continue
            if all(isinstance(s, (ast.Pass, ast.Continue)) or
                   (isinstance(s, ast.Expr)
                    and isinstance(s.value, ast.Constant))
                   for s in node.body):
                out.append(Finding(
                    self.rule, sf.rel, node.lineno,
                    "broad except silently swallows at a wire/dispatch "
                    "seam — narrow to transport-shaped types "
                    "(ConnectionError/OSError/grpc.RpcError), count or "
                    "log it, or annotate why dropping is correct"))
        return out


# ---------------------------------------------------------------------------
# 5. rpc-error-taxonomy (typed errors at RPC boundaries)
# ---------------------------------------------------------------------------

@dataclass
class TypedErrorChecker(Checker):
    rule: str = "rpc-error-taxonomy"
    doc: str = ("RPC-boundary failures must raise the typed taxonomy "
                "(utils/errors.Unavailable/FailedPrecondition, "
                "utils/deadline.DeadlineExceeded/ResourceExhausted), "
                "never bare Exception/RuntimeError strings — retry "
                "policy, breakers, and HTTP status mapping match on type")

    def check(self, sf: SourceFile) -> list[Finding]:
        if not sf.in_dirs(_SEAM_SCOPE):
            return []
        out = []
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Raise)
                    and isinstance(node.exc, ast.Call)
                    and isinstance(node.exc.func, ast.Name)
                    and node.exc.func.id in ("Exception", "RuntimeError")):
                continue
            out.append(Finding(
                self.rule, sf.rel, node.lineno,
                f"raise {node.exc.func.id} at an RPC boundary — use the "
                f"typed seam taxonomy (utils/errors.Unavailable / "
                f"FailedPrecondition / deadline.DeadlineExceeded / "
                f"ResourceExhausted) so callers can match on type"))
        return out


# ---------------------------------------------------------------------------
# 6. jax-purity (+ donated-buffer discipline)
# ---------------------------------------------------------------------------

_DEVICE_ORCHESTRATORS = ("while_loop", "scan", "fori_loop", "cond",
                         "shard_map", "jit", "pallas_call", "switch")
_IMPURE_CALLS = re.compile(
    r"^(time\.(time|monotonic|perf_counter|sleep|time_ns)"
    r"|random\.\w+|np\.random\.\w+|numpy\.random\.\w+"
    r"|datetime\.(now|utcnow)|print)$")


@dataclass
class JaxPurityChecker(Checker):
    rule: str = "jax-purity"
    doc: str = ("no Python RNG/clock/print inside jit/shard_map/"
                "lax.* loop bodies (they freeze at trace time), and a "
                "buffer passed at a donate_argnums position must never "
                "be read after the donating call")

    def check(self, sf: SourceFile) -> list[Finding]:
        out = []
        device_fns = self._device_fns(sf)
        for fn in device_fns:
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) and \
                        _IMPURE_CALLS.match(call_name(node) or ""):
                    out.append(Finding(
                        self.rule, sf.rel, node.lineno,
                        f"impure call {call_name(node)}() inside a "
                        f"traced/device function — it runs ONCE at trace "
                        f"time, not per step; thread values in as "
                        f"operands instead"))
        out.extend(self._donation(sf))
        return out

    def _device_fns(self, sf: SourceFile) -> list[ast.AST]:
        """FunctionDefs/Lambdas that trace to device code: jit-decorated,
        or passed by name into a lax/shard_map orchestrator."""
        by_name: dict[str, list[ast.FunctionDef]] = {}
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.FunctionDef):
                by_name.setdefault(node.name, []).append(node)
        fns: list[ast.AST] = []
        seen: set[int] = set()

        def add(fn: ast.AST) -> None:
            if id(fn) not in seen:
                seen.add(id(fn))
                fns.append(fn)

        for node in ast.walk(sf.tree):
            if isinstance(node, ast.FunctionDef):
                for dec in node.decorator_list:
                    src = sf.src(dec)
                    if "jit" in src or "shard_map" in src or \
                            "pallas_call" in src:
                        add(node)
            if isinstance(node, ast.Call):
                callee = call_name(node)
                if callee.split(".")[-1] not in _DEVICE_ORCHESTRATORS:
                    continue
                for arg in node.args:
                    if isinstance(arg, ast.Lambda):
                        add(arg)
                    elif isinstance(arg, ast.Name):
                        for fn in by_name.get(arg.id, ()):
                            add(fn)
        return fns

    def _donation(self, sf: SourceFile) -> list[Finding]:
        """X = jax.jit(f, donate_argnums=...) call sites: a Name passed
        at a donated position must not be loaded again after the call
        (without an intervening rebind) in the same function."""
        donors: dict[str, tuple[int, ...]] = {}
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    "jit" in call_name(node.value):
                d = kw(node.value, "donate_argnums")
                if d is None:
                    continue
                nums: list[int] = []
                for c in ast.walk(d):
                    if isinstance(c, ast.Constant) and \
                            isinstance(c.value, int):
                        nums.append(c.value)
                for t in node.targets:
                    if isinstance(t, ast.Name) and nums:
                        donors[t.id] = tuple(nums)
        if not donors:
            return []
        out = []
        owner = enclosing_functions(sf.tree)
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in donors):
                continue
            fn = owner.get(id(node))
            for pos in donors[node.func.id]:
                if pos >= len(node.args) or \
                        not isinstance(node.args[pos], ast.Name):
                    continue
                donated = node.args[pos].id
                stores = sorted(
                    n.lineno for n in ast.walk(fn)
                    if isinstance(n, ast.Name) and n.id == donated
                    and isinstance(n.ctx, ast.Store))
                for load in ast.walk(fn):
                    if isinstance(load, ast.Name) and \
                            load.id == donated and \
                            isinstance(load.ctx, ast.Load) and \
                            load.lineno > node.lineno:
                        if any(node.lineno <= s <= load.lineno
                               for s in stores):
                            continue   # rebound before this read
                        out.append(Finding(
                            self.rule, sf.rel, load.lineno,
                            f"{donated!r} was donated to "
                            f"{node.func.id}() on line {node.lineno} "
                            f"(donate_argnums) and is read here — the "
                            f"buffer may already be aliased/freed"))
                        break          # one finding per donated arg
        return out


# ---------------------------------------------------------------------------
# 7. fault-points (registry <-> code cross-check)
# ---------------------------------------------------------------------------

@dataclass
class FaultPointChecker(ProjectChecker):
    rule: str = "fault-points"
    doc: str = ("utils/faults.POINTS and faults.fire(...) sites must "
                "agree both ways: every wired point is declared (ops "
                "runbook lists POINTS), every declared point is wired "
                "somewhere (no dead registry entries)")

    def finalize(self) -> list[Finding]:
        declared: dict[str, tuple[str, int]] = {}
        declared_rel = None
        fired: list[tuple[str, str, int]] = []
        for sf in self._files:
            is_faults = Path(sf.rel).name == "faults.py"
            if is_faults:
                for node in sf.tree.body:
                    if isinstance(node, ast.Assign) and any(
                            isinstance(t, ast.Name) and t.id == "POINTS"
                            for t in node.targets):
                        declared_rel = sf.rel
                        for c in ast.walk(node.value):
                            s = const_str(c)
                            if s:
                                declared[s] = (sf.rel, c.lineno)
                continue               # fire() defined here, not wired
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                callee = call_name(node)
                if callee.split(".")[-1] != "fire":
                    continue
                s = const_str(node.args[0])
                if s:
                    fired.append((s, sf.rel, node.lineno))
        if declared_rel is None:       # subset run: canonical declaration
            for name, line in self._canonical_points():
                declared[name] = ("utils/faults.py", line)
        out = []
        for name, rel, line in fired:
            if name not in declared:
                out.append(Finding(
                    self.rule, rel, line,
                    f"fault point {name!r} is fired here but not "
                    f"declared in utils/faults.POINTS — declare it so "
                    f"the ops runbook and chaos schedules can see it"))
        if declared_rel is not None:
            fired_names = {n for n, _, _ in fired}
            for name, (rel, line) in sorted(declared.items()):
                if name not in fired_names:
                    out.append(Finding(
                        self.rule, rel, line,
                        f"fault point {name!r} is declared in POINTS but "
                        f"never fired anywhere — dead registry entry "
                        f"(or the wiring was removed)"))
        return out

    @staticmethod
    def _canonical_points() -> list[tuple[str, int]]:
        path = _PKG_ROOT / "utils" / "faults.py"
        tree = ast.parse(path.read_text())
        out = []
        for node in tree.body:
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "POINTS"
                    for t in node.targets):
                for c in ast.walk(node.value):
                    s = const_str(c)
                    if s:
                        out.append((s, c.lineno))
        return out


# ---------------------------------------------------------------------------
# 8. lock-order (static sibling of utils/locks.py lockdep)
# ---------------------------------------------------------------------------

_LOCKISH = re.compile(r"lock|_cv$|_mutex")


@dataclass
class LockOrderChecker(ProjectChecker):
    rule: str = "lock-order"
    doc: str = ("`with <lock>` nesting across the tree must form an "
                "acyclic order graph — a static A->B in one function and "
                "B->A in another is a deadlock schedule even if no run "
                "has hit it yet (runtime sibling: utils/locks.py)")

    edges: dict[tuple[str, str], tuple[str, int]] = field(
        default_factory=dict)

    def collect(self, sf: SourceFile) -> None:
        super().collect(sf)
        mod = Path(sf.rel).stem

        def lock_key(expr: ast.AST, cls: str | None) -> str | None:
            if isinstance(expr, ast.Attribute) and \
                    isinstance(expr.value, ast.Name) and \
                    expr.value.id == "self" and \
                    _LOCKISH.search(expr.attr):
                return f"{mod}.{cls or '?'}.{expr.attr}"
            if isinstance(expr, ast.Name) and _LOCKISH.search(expr.id):
                return f"{mod}.{expr.id}"
            return None

        def walk(node: ast.AST, cls: str | None,
                 stack: list[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    walk(child, child.name, [])
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef, ast.Lambda)):
                    # a nested def's body is NOT dynamically inside the
                    # enclosing with-block — fresh stack
                    walk(child, cls, [])
                elif isinstance(child, ast.With):
                    keys = []
                    for item in child.items:
                        k = lock_key(item.context_expr, cls)
                        if k is not None:
                            keys.append(k)
                    held = list(stack)
                    for k in keys:
                        for h in held:
                            if h != k and (h, k) not in self.edges:
                                self.edges[(h, k)] = (sf.rel,
                                                      child.lineno)
                        held.append(k)
                    walk(child, cls, held)
                else:
                    walk(child, cls, stack)

        walk(sf.tree, None, [])

    def finalize(self) -> list[Finding]:
        graph: dict[str, set[str]] = {}
        for a, b in self.edges:
            graph.setdefault(a, set()).add(b)

        def path(src: str, dst: str) -> list[str] | None:
            stack, seen = [(src, [src])], {src}
            while stack:
                n, p = stack.pop()
                if n == dst:
                    return p
                for nx in graph.get(n, ()):
                    if nx not in seen:
                        seen.add(nx)
                        stack.append((nx, p + [nx]))
            return None

        out, reported = [], set()
        for (a, b), (rel, line) in sorted(self.edges.items()):
            back = path(b, a)
            if back is None:
                continue
            cyc = frozenset(back)
            if cyc in reported:
                continue
            reported.add(cyc)
            out.append(Finding(
                self.rule, rel, line,
                f"lock-order cycle: {a} -> {b} here, but "
                f"{' -> '.join(back)} elsewhere — two threads "
                f"interleaving these orders deadlock"))
        return out


# ---------------------------------------------------------------------------

ALL_CHECKERS = (MetricRegistrationChecker, CtxvarChecker,
                DeadlineWaitChecker, ExceptSeamChecker, TypedErrorChecker,
                JaxPurityChecker, FaultPointChecker, LockOrderChecker)
