"""dgraph-analyze: project-invariant static analysis (ISSUE 14).

An AST-walking checker framework encoding the invariants this codebase's
review rounds kept re-litigating by hand — metric pre-registration,
contextvar discipline across thread seams, deadline discipline at
blocking waits, the seam error taxonomy, JAX purity/donation rules, the
fault-point registry cross-check, and static lock-order extraction (the
compile-time sibling of utils/locks.py lockdep).

Run it:

    python -m dgraph_tpu.analysis dgraph_tpu/          # whole package
    python -m dgraph_tpu.analysis --rule deadline-wait path/to/file.py
    python -m dgraph_tpu.analysis --format=json dgraph_tpu/

Suppress a finding where the flagged code is deliberate:

    pool.submit(self._loop)   # dgraph: allow(ctxvar-copy) detached bg loop

(the comment goes on the flagged line or the line directly above; the
rationale after the closing paren is free text, but write one). The
analyzer runs as a tier-1 test over the whole package and must come up
clean — docs/dev.md "Project invariants" documents every rule.
"""

from .core import Finding, SourceFile
from .runner import RULES, analyze_paths

__all__ = ["Finding", "SourceFile", "RULES", "analyze_paths"]
