"""Canonical result serialization + JSON diffs for live queries (ISSUE 18).

A live notification must be verifiable: "byte-identical to re-running the
query at the carried watermark" is only testable if both sides serialize
the same way. `canon()` is that one serialization — sorted keys, no
whitespace — used by the manager for change detection, by the SSE surface
for the wire bytes, and by the correctness gates in tests/smoke.

Diffs are computed per top-level query block (the root keys of a DQL
result). Entries that carry a `uid` are matched BY uid — an entry whose
uid persists but whose body changed reports as `changed` — while uid-less
entries (aggregates, @groupby buckets, var blocks) are matched as a
multiset of canonical encodings: those rows have no identity, so a
modification is an add+remove pair. This mirrors what a feed consumer
actually wants: patch-by-key when keys exist, replace-by-value when not.
"""

from __future__ import annotations

import json
from collections import Counter


def canon(obj) -> str:
    """THE canonical encoding of a query result. Every byte-identity
    check in the subsystem compares exactly this."""
    return json.dumps(obj, sort_keys=True, separators=(",", ":"),
                      default=str)


def _entry_uid(e):
    if isinstance(e, dict):
        return e.get("uid")
    return None


def _block_diff(old: list, new: list) -> dict | None:
    """added/removed/changed for one result block's entry list."""
    old_by_uid: dict = {}
    new_by_uid: dict = {}
    old_anon: Counter = Counter()
    new_anon: Counter = Counter()
    for e in old:
        u = _entry_uid(e)
        if u is not None:
            old_by_uid[u] = e
        else:
            old_anon[canon(e)] += 1
    for e in new:
        u = _entry_uid(e)
        if u is not None:
            new_by_uid[u] = e
        else:
            new_anon[canon(e)] += 1
    added, removed, changed = [], [], []
    for u, e in new_by_uid.items():
        o = old_by_uid.get(u)
        if o is None:
            added.append(e)
        elif canon(o) != canon(e):
            changed.append(e)
    for u, e in old_by_uid.items():
        if u not in new_by_uid:
            removed.append(e)
    for c, n in (new_anon - old_anon).items():
        added.extend([json.loads(c)] * n)
    for c, n in (old_anon - new_anon).items():
        removed.extend([json.loads(c)] * n)
    if not (added or removed or changed):
        return None
    return {"added": added, "removed": removed, "changed": changed}


def result_diff(old: dict | None, new: dict) -> dict | None:
    """Per-block diff of two query results; None when nothing changed.
    Non-list block values (explain payloads are rejected at subscribe
    time, but schema-ish scalars could appear) diff as whole-value
    `changed` entries."""
    old = old or {}
    out: dict = {}
    for block in sorted(set(old) | set(new)):
        ov, nv = old.get(block), new.get(block)
        if isinstance(ov, list) or isinstance(nv, list):
            d = _block_diff(ov if isinstance(ov, list) else [],
                            nv if isinstance(nv, list) else [])
        elif canon(ov) != canon(nv):
            d = {"added": [], "removed": [], "changed": [nv]}
        else:
            d = None
        if d is not None:
            out[block] = d
    return out or None
