"""Live queries: standing subscriptions re-derived O(Δ) per commit window."""

from .diff import canon, result_diff
from .manager import LiveManager, Subscription

__all__ = ["LiveManager", "Subscription", "canon", "result_diff"]
