"""Live queries: standing subscriptions with O(Δ) re-evaluation (ISSUE 18).

The reference streams its commit log to consumers; here the analogous seam
is the per-predicate delta journal + the commit window. A subscription is
a registered DQL read evaluated ONCE at registration, then re-derived only
when a commit window actually touches its read set:

  * the touch test IS qcache.plan_attrs — the same static read-set
    derivation the per-predicate result-cache tokens key on. A commit
    batch carrying predicates P wakes only subscriptions whose attr set
    intersects P; plans whose read set is not statically derivable
    (explicit uids, expand(), shortest) wake on every window, exactly as
    they key on the whole snapshot in the result cache.
  * wakes are COALESCED per commit window: the notifier drains every
    pending commit event in one sweep, dedupes woken subscriptions by
    (query, variables) so 10k standing copies of one feed cost ONE
    re-execution, and evaluates the distinct shapes concurrently so the
    DeviceBatcher packs their device steps like foreground reads.
  * freshness is exact, never best-effort: every notification carries the
    commit watermark `at` it reflects, and its `result` is byte-identical
    (diff.canon) to re-running the query read-only at that watermark —
    the tested correctness gate.
  * clients receive JSON diffs (added/removed/changed per block) against
    the last delivered result, with a typed full-result `resync` event
    whenever the diff chain cannot be trusted end-to-end: delta-journal
    overflow on a subscribed predicate, slow-consumer shedding, reconnect
    with a stale cursor, or a re-evaluation error after retry.

Flow control: per-subscription bounded queues. A full queue sheds by
REPLACING the queued backlog with one resync event (bounded memory, and
the client converges from any gap); a queue that stays blocked past the
idle timeout expires the subscription so a vanished consumer cannot pin
its cursor — and therefore the journal retention floor — forever.

The manager is engine-agnostic: Node and the embedded multi-group Cluster
both drive it through three callables (eval at a watermark, current
watermark, parse) plus their store list for journal pinning.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque

from ..utils import locks
from ..utils.deadline import ResourceExhausted
from ..utils.errors import FailedPrecondition
from .diff import canon, result_diff

_BACKOFF_MIN_S = 0.05
_BACKOFF_MAX_S = 1.0


def _loads_memo(c: str, memo: dict | None):
    """json.loads with a per-window cache: one parse per distinct canon
    string no matter how many subscribers share it (str hashes are cached
    by the interpreter, so repeat lookups are cheap)."""
    if memo is None:
        return json.loads(c)
    obj = memo.get(c)
    if obj is None:
        obj = memo[c] = json.loads(c)
    return obj


class Subscription:
    """One standing query: registration state + the client event queue.

    Iterate it (`for ev in sub:`) or poll `next(timeout)`; events are
    dicts with a `type` of init / ack / diff / resync / expire. `cancel()`
    tears it down from the client side."""

    def __init__(self, mgr: "LiveManager", sid: str, q: str,
                 variables: dict | None, attrs: frozenset | None,
                 queue_max: int, tenant: str = "") -> None:
        self.id = sid
        self.q = q
        self.variables = dict(variables) if variables else None
        self.attrs = attrs               # None = wake on every window
        self.tenant = tenant             # registering namespace (ISSUE 20)
        self.queue_max = max(int(queue_max), 1)
        self.queue: deque = deque()
        self._mgr = mgr
        self.cv = threading.Condition(mgr._lock)
        self.last_canon: str | None = None
        self.cursor = 0                  # watermark of the last delivery
        self.ready = False               # initial evaluation done
        self.pending_wake = False
        self.needs_resync: str | None = None
        self.closed = False
        self.blocked_since: float | None = None   # queue-full monotonic
        self.waiting = 0                 # threads blocked in next()
        self.delivered = 0
        self.sheds = 0
        self.resyncs = 0

    # -- client surface ------------------------------------------------------

    def next(self, timeout: float | None = None) -> dict | None:
        """Block for the next event; None on timeout (the SSE heartbeat
        pacing); StopIteration once cancelled/expired and drained."""
        deadline = None if timeout is None \
            else time.monotonic() + float(timeout)
        with self.cv:
            while not self.queue:
                if self.closed:
                    raise StopIteration
                rem = None if deadline is None \
                    else deadline - time.monotonic()
                if rem is not None and rem <= 0:
                    return None
                self.waiting += 1
                try:
                    self.cv.wait(rem)
                finally:
                    self.waiting -= 1
            ev = self.queue.popleft()
            self.blocked_since = None
            self.delivered += 1
            return ev

    def __iter__(self):
        return self

    def __next__(self) -> dict:
        ev = self.next(None)
        if ev is None:                   # unreachable without timeout
            raise StopIteration
        return ev

    def cancel(self) -> bool:
        return self._mgr.cancel(self.id)

    def snapshot(self) -> dict:
        out = {"id": self.id, "attrs": sorted(self.attrs)
               if self.attrs is not None else None,
               "cursor": self.cursor, "queued": len(self.queue),
               "delivered": self.delivered, "sheds": self.sheds,
               "resyncs": self.resyncs, "closed": self.closed}
        if self.tenant:
            out["tenant"] = self.tenant
        return out


class LiveManager:
    """Registry + notifier for standing subscriptions.

    eval_fn(q, variables, at_ts) -> result dict at exactly `at_ts`
    watermark_fn() -> the newest committed watermark
    parse_fn(q, variables) -> dql.ParsedRequest (for the touch test)
    stores -> journal pinning + cursor provability (delta_since)
    """

    def __init__(self, *, eval_fn, watermark_fn, parse_fn, stores,
                 metrics=None, queue_max: int = 256,
                 idle_timeout_s: float = 300.0, heartbeat_s: float = 15.0,
                 batcher=None, eval_workers: int = 4) -> None:
        self._eval = eval_fn
        # per-subscription cost attribution (ISSUE 19): an eval_fn that
        # accepts a 4th `subs` argument (Node's does) gets the ids of
        # every subscription the evaluation serves, so the cost ledger
        # can rank standing load in /debug/top?group=sub. Detected once
        # here — 3-arg engines (older embedders) keep working unchanged.
        import inspect

        try:
            params = inspect.signature(eval_fn).parameters
            self._eval_takes_subs = len(params) >= 4 or \
                "subs" in params
        except (TypeError, ValueError):
            self._eval_takes_subs = False
        self._watermark = watermark_fn
        self._parse = parse_fn
        self._stores = list(stores)
        self._m = metrics
        self.queue_max = int(queue_max)
        self.idle_timeout_s = float(idle_timeout_s)
        self.heartbeat_s = float(heartbeat_s)
        self._batcher = batcher
        # multi-tenant QoS (ISSUE 20): Node injects its TenantRegistry so
        # subscribe() can enforce per-tenant standing-subscription caps
        # (typed ResourceExhausted at the edge) and clamp notify-queue
        # bounds. None (the default, and --no_qos) = uncapped.
        self.registry = None
        self._eval_workers = max(int(eval_workers), 1)
        self._lock = locks.Lock("live.LiveManager._lock")
        self._cv = threading.Condition(self._lock)
        self._subs: dict[str, Subscription] = {}
        self._by_attr: dict[str, set[str]] = {}
        self._wildcard: set[str] = set()
        self._dirty: set[str] = set()
        # commit events: (commit_ts, preds tuple, arrival perf_counter).
        # Guarded by _lock; the overflow feed is a lock-free deque because
        # the store calls it from INSIDE its commit critical section — an
        # edge store._lock -> live lock there would cycle against the
        # notifier's eval path (live -> snapshot -> store._lock).
        self._events: deque = deque()
        self._overflow: deque = deque()
        self._seq = 1
        self._thread: threading.Thread | None = None
        self._stop = False
        self._closed = False
        self._pool = None
        self._backoff = 0.0
        self._retry_at = 0.0
        self._last_pin: int | None = None
        self._pin_raise_at = 0.0         # next amortised min-scan allowed
        self.windows = 0                 # processed commit windows
        self.registered = 0
        # the fan-out hot path runs once per subscriber per window: cache
        # the two metric objects instead of a registry name-lookup each
        self._c_notifs = None if metrics is None else \
            metrics.counter("dgraph_subs_notifications_total")
        self._h_latency = None if metrics is None else \
            metrics.histogram("dgraph_subs_notify_latency_s")

    def _eval_at(self, q, variables, ts, subs: tuple = ()):
        if self._eval_takes_subs:
            return self._eval(q, variables, ts, subs)
        return self._eval(q, variables, ts)

    # -- metrics plumbing ----------------------------------------------------

    def _count(self, name: str, n: int = 1) -> None:
        if self._m is not None:
            self._m.counter(name).inc(n)

    def _gauge(self, name: str, v: int) -> None:
        if self._m is not None:
            self._m.counter(name).set(v)

    # -- registration --------------------------------------------------------

    def subscription_attrs(self, q: str,
                           variables: dict | None = None) -> frozenset | None:
        """The touch-test read set for one query text (None = wake on
        every window). Exposed for tests and the wire surfaces."""
        from ..query import qcache

        return qcache.subscription_attrs(self._parse(q, variables))

    def subscribe(self, q: str, variables: dict | None = None, *,
                  cursor: int | None = None,
                  queue_max: int | None = None) -> Subscription:
        """Register a standing read: validates the query, evaluates it
        once at the current watermark, and returns the Subscription whose
        first queued event is `init` (fresh), `ack` (reconnect cursor and
        the journal PROVES nothing it reads changed since), or a typed
        `resync` (reconnect cursor, change possible)."""
        req = self._parse(q, variables)
        if getattr(req, "mutations", None) or \
                getattr(req, "upsert", None) is not None:
            raise ValueError("subscriptions must be read-only queries")
        if getattr(req, "schema_request", None) is not None:
            raise ValueError("schema requests are not subscribable")
        from ..query import qcache

        attrs = qcache.subscription_attrs(req)
        from .. import tenancy

        tenant = tenancy.current()
        if tenant and attrs is not None:
            # the touch test compares against COMMITTED storage attrs,
            # which carry the namespace prefix — translate the read set
            # once at registration, not per window
            attrs = tenancy.prefix_attrs(tenant, attrs)
        reg = self.registry
        qmax = queue_max or self.queue_max
        if reg is not None:
            cap_q = reg.sub_queue_max(tenant)
            if cap_q is not None:
                qmax = min(qmax, max(int(cap_q), 1))
        with self._cv:
            if self._closed:
                raise FailedPrecondition("live manager is closed")
            cap = reg.max_subs(tenant) if reg is not None else None
            if cap is not None and sum(
                    1 for s in self._subs.values()
                    if s.tenant == tenant) >= cap:
                reg.note_shed(tenant)
                raise ResourceExhausted(
                    f"tenant {tenant or 'default'!r} at max standing "
                    f"subscriptions ({cap})")
            sid = f"s{self._seq}"
            self._seq += 1
            sub = Subscription(self, sid, q, variables, attrs,
                               qmax, tenant)
            self._subs[sid] = sub
            if attrs is None:
                self._wildcard.add(sid)
            else:
                for a in attrs:
                    self._by_attr.setdefault(a, set()).add(sid)
            self.registered += 1
            self._count("dgraph_subs_registered_total")
            self._count("dgraph_subs_active")
            self._ensure_thread_locked()
        try:
            w0 = self._watermark()
            c = canon(self._eval_at(q, variables, w0, (sid,)))
        except BaseException:
            self.cancel(sid)
            raise
        first = "init"
        if cursor is not None:
            first = "ack" if self._cursor_unchanged(attrs, int(cursor)) \
                else "cursor"
        with self._cv:
            sub.last_canon = c
            sub.cursor = w0
            sub.ready = True
            if first == "ack":
                ev = {"type": "ack", "sub": sid, "at": w0}
            elif first == "cursor":
                sub.resyncs += 1
                self._count("dgraph_subs_resyncs_total")
                ev = {"type": "resync", "reason": "cursor", "sub": sid,
                      "at": w0, "result": json.loads(c)}
            else:
                ev = {"type": "init", "sub": sid, "at": w0,
                      "result": json.loads(c)}
            self._enqueue_locked(sub, ev)
            if self._c_notifs is not None:
                self._c_notifs.inc()
            if sub.pending_wake:
                self._cv.notify()        # commits landed during the eval
            # a new cursor sits at the watermark: it can only lower the
            # pin when it's the first one (or a cursor raced below the
            # floor) — the O(subs) min-scan on every subscribe turned 10k
            # registrations into an O(n^2) stall otherwise
            if self._last_pin is None or sub.cursor < self._last_pin:
                self._update_pin_locked()
        return sub

    def _cursor_unchanged(self, attrs: frozenset | None,
                          cursor: int) -> bool:
        """True only when the delta journal PROVES no subscribed predicate
        changed after `cursor` (floor at/below it AND no newer entries) —
        the cheap-ack reconnect path. None attrs can never prove."""
        if attrs is None:
            return False
        for st in self._stores:
            for a in attrs:
                if st.delta_since(a, cursor) != {}:
                    return False
        return True

    def cancel(self, sid: str) -> bool:
        with self._cv:
            return self._close_sub_locked(sid, None)

    def reap(self, sid: str) -> bool:
        """A dead wire client (write failed / socket gone): same teardown
        as cancel, counted separately — it unpins the cursor a vanished
        subscriber would otherwise hold forever."""
        ok = self.cancel(sid)
        if ok:
            self._count("dgraph_subs_reaped_total")
        return ok

    def _close_sub_locked(self, sid: str, final_ev: dict | None) -> bool:
        sub = self._subs.pop(sid, None)
        if sub is None:
            return False
        self._wildcard.discard(sid)
        if sub.attrs is not None:
            for a in sub.attrs:
                peers = self._by_attr.get(a)
                if peers is not None:
                    peers.discard(sid)
                    if not peers:
                        del self._by_attr[a]
        self._dirty.discard(sid)
        if final_ev is not None:
            sub.queue.clear()
            sub.queue.append(final_ev)
        sub.closed = True
        sub.cv.notify_all()
        self._count("dgraph_subs_active", -1)
        # removing a sub can only RAISE the floor, and only when it was
        # the one holding it — skip the min-scan otherwise (and amortise
        # it even then: a mass-cancel of same-cursor subs would turn an
        # immediate rescan into O(n^2))
        if self._last_pin is not None and sub.cursor <= self._last_pin:
            self._maybe_raise_pin_locked()
        return True

    # -- commit feed ---------------------------------------------------------

    @property
    def active(self) -> bool:
        return bool(self._subs)

    def notify_commit(self, commit_ts: int, preds) -> None:
        """Called by the engine right after a commit window applies.
        Cheap when nobody subscribes (one truthiness check)."""
        if not self._subs:
            return
        with self._cv:
            self._events.append((int(commit_ts), tuple(preds),
                                 time.perf_counter()))
            self._cv.notify()

    def on_journal_overflow(self, attr: str) -> None:
        """Store callback from INSIDE the commit critical section: the
        journal dropped completeness for `attr`, so affected diff chains
        must resync. Lock-free append only (see _overflow above)."""
        self._overflow.append(attr)

    # -- notifier ------------------------------------------------------------

    def _ensure_thread_locked(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        # dgraph: allow(ctxvar-copy) detached notifier loop — deadlines
        # and cost ledgers are minted per re-evaluation, not inherited
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="live-notifier")
        self._thread.start()

    def _ensure_pool(self):
        if self._pool is None and self._eval_workers > 1:
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(
                max_workers=self._eval_workers,
                thread_name_prefix="live-eval")
        return self._pool

    def _runnable_locked(self) -> bool:
        if self._stop or self._events or self._overflow:
            return True
        return bool(self._dirty) and (
            not self._retry_at or time.monotonic() >= self._retry_at)

    def _run(self) -> None:
        while True:
            with self._cv:
                if not self._runnable_locked():
                    self._cv.wait(0.5)
                if self._stop:
                    return
                window = self._collect_locked()
            if window is not None:
                self._process(window)

    def _collect_locked(self):
        """Drain every pending commit event + overflow mark into ONE
        coalesced window; returns (watermark, first-arrival, groups) or
        None. Also the expiry sweep (a blocked queue past the idle
        timeout = a vanished client)."""
        now_m = time.monotonic()
        for sid in [s.id for s in self._subs.values()
                    if s.blocked_since is not None
                    and now_m - s.blocked_since > self.idle_timeout_s]:
            if self._close_sub_locked(sid, {"type": "expire", "sub": sid,
                                            "reason": "idle"}):
                self._count("dgraph_subs_expired_total")
        while self._overflow:
            attr = self._overflow.popleft()
            for sid in set(self._by_attr.get(attr, ())) | self._wildcard:
                sub = self._subs.get(sid)
                if sub is not None:
                    sub.needs_resync = sub.needs_resync or "overflow"
                    self._mark_locked(sub)
        w = 0
        t_first = None
        preds: set[str] = set()
        had_events = bool(self._events)
        while self._events:
            ts, ps, t = self._events.popleft()
            w = max(w, ts)
            preds.update(ps)
            t_first = t if t_first is None else min(t_first, t)
        for attr in preds:
            for sid in self._by_attr.get(attr, ()):
                sub = self._subs.get(sid)
                if sub is not None:
                    self._mark_locked(sub)
        if had_events:
            for sid in list(self._wildcard):
                sub = self._subs.get(sid)
                if sub is not None:
                    self._mark_locked(sub)
        if self._retry_at and time.monotonic() < self._retry_at \
                and not had_events:
            return None
        ready = [self._subs[sid] for sid in self._dirty
                 if sid in self._subs and self._subs[sid].ready]
        if not ready:
            return None
        groups: dict[tuple, tuple] = {}
        for sub in ready:
            # the tenant is part of the coalescing identity: two tenants'
            # byte-identical DQL reads DIFFERENT tablets, so sharing one
            # re-evaluation would leak namespace A's result into B
            key = (sub.q, canon(sub.variables or {}), sub.tenant)
            if key in groups:
                groups[key][1].append(sub)
            else:
                groups[key] = (sub.variables, [sub])
        if w == 0:
            w = self._watermark()
        if t_first is None:
            t_first = time.perf_counter()
        return (w, t_first, groups)

    def _mark_locked(self, sub: Subscription) -> None:
        if not sub.pending_wake:
            sub.pending_wake = True
            self._dirty.add(sub.id)

    def _process(self, window) -> None:
        """Re-execute each distinct woken (query, variables) ONCE at the
        window watermark — concurrently, so the DeviceBatcher packs the
        device steps — then fan the per-subscription diffs out."""
        w, t_first, groups = window
        items = list(groups.items())
        self.windows += 1
        self._count("dgraph_subs_windows_total")
        self._count("dgraph_subs_wakeups_total",
                    sum(len(subs) for _v, subs in groups.values()))
        self._count("dgraph_subs_evals_total", len(items))
        if self._batcher is not None and len(items) > 1:
            hint = getattr(self._batcher, "hint_burst", None)
            if hint is not None:
                hint()

        def run_one(q, variables, sub_ids, tenant):
            from .. import tenancy

            try:
                # the notifier thread carries no request context: install
                # the group's tenant so the engine resolves its namespace
                with tenancy.scope(tenant):
                    return (True, canon(
                        self._eval_at(q, variables, w, sub_ids)))
            except Exception as e:       # retried with backoff, then resync
                return (False, f"{type(e).__name__}: {e}")

        results: dict[tuple, tuple] = {}
        pool = self._ensure_pool() if len(items) > 1 else None
        if pool is not None:
            # dgraph: allow(ctxvar-copy) re-evals mint their own ledgers/
            # deadlines; nothing context-bound crosses into the pool
            futs = {key: pool.submit(run_one, key[0], variables,
                                     tuple(s.id for s in subs), key[2])
                    for key, (variables, subs) in items}
            for key, fut in futs.items():
                results[key] = fut.result()
        else:
            for key, (variables, subs) in items:
                results[key] = run_one(key[0], variables,
                                       tuple(s.id for s in subs), key[2])
        now_p = time.perf_counter()
        latency_s = max(now_p - t_first, 0.0)
        with self._cv:
            any_fail = False
            memo: dict = {}              # per-window parse/diff sharing
            for key, (_variables, subs) in items:
                ok, val = results[key]
                delivered = 0
                done: list[str] = []
                for sub in subs:
                    if sub.closed or sub.id not in self._subs:
                        continue
                    if not ok:
                        any_fail = True
                        sub.needs_resync = sub.needs_resync or "error"
                        continue         # stays dirty; retried next round
                    sub.pending_wake = False
                    done.append(sub.id)
                    if self._deliver_locked(sub, val, w, memo):
                        delivered += 1
                # fan-out bookkeeping is batched per GROUP, not per
                # subscriber: one dirty-set update, one counter add, and
                # one latency observation (every subscriber of the group
                # shares the window's single latency value) — at 10k
                # standing subs the per-sub variants dominated the
                # notifier's CPU and taxed foreground readers
                self._dirty.difference_update(done)
                if delivered:
                    if self._c_notifs is not None:
                        self._c_notifs.inc(delivered)
                    if self._h_latency is not None:
                        self._h_latency.observe(latency_s)
            if any_fail:
                self._backoff = min(max(self._backoff * 2, _BACKOFF_MIN_S),
                                    _BACKOFF_MAX_S)
                self._retry_at = time.monotonic() + self._backoff
            else:
                self._backoff = 0.0
                self._retry_at = 0.0
            self._maybe_raise_pin_locked()

    def _deliver_locked(self, sub: Subscription, c: str, w: int,
                        memo: dict | None = None) -> bool:
        """One subscription's outcome for one window: a typed resync when
        the diff chain broke, a diff when the result changed, or a silent
        cursor advance when the wake was a false positive (the commit
        touched the read set without changing this result).

        `memo` shares parsed results, diffs, AND whole event objects
        across the window's subscribers: every sub of a coalesced group
        carries the same (old, new) canon pair, so the O(result-size)
        work — and the event dict itself — happens once per GROUP, not
        once per subscription. Window events (diff, window resync) are
        therefore STREAM-SCOPED: they carry no `sub` field (the
        subscription is implied by the channel that delivers them —
        one SSE connection / one iterator per subscription) and must be
        treated as read-only shared objects (the SSE path serializes
        them immediately; embedded consumers get the same contract).
        Registration replies (init/ack/cursor resync) and expire keep
        their `sub` field: they answer a specific registration."""
        if sub.needs_resync:
            ev = {"type": "resync", "reason": sub.needs_resync,
                  "at": w, "result": _loads_memo(c, memo)}
            sub.needs_resync = None
            sub.resyncs += 1
            self._count("dgraph_subs_resyncs_total")
        elif c != sub.last_canon:
            if memo is not None and ("ev", sub.last_canon, c) in memo:
                ev = memo[("ev", sub.last_canon, c)]
            else:
                d = result_diff(json.loads(sub.last_canon)
                                if sub.last_canon is not None else None,
                                _loads_memo(c, memo))
                ev = {"type": "diff", "at": w, "diff": d,
                      "result": _loads_memo(c, memo)}
                if memo is not None:
                    memo[("ev", sub.last_canon, c)] = ev
        else:
            sub.cursor = w
            sub.last_canon = c
            return False
        sub.last_canon = c
        sub.cursor = w
        self._enqueue_locked(sub, ev)
        return True

    def _enqueue_locked(self, sub: Subscription, ev: dict) -> None:
        """Bounded enqueue with slow-consumer shedding: a full queue is
        REPLACED by one resync carrying the current result — the client
        converges from any number of missed diffs, and memory stays
        bounded no matter how far behind it is."""
        if len(sub.queue) >= sub.queue_max:
            sub.queue.clear()
            sub.sheds += 1
            self._count("dgraph_subs_sheds_total")
            if ev.get("type") != "resync" and "result" in ev:
                ev = {"type": "resync", "reason": "shed",
                      "at": ev["at"], "result": ev["result"]}
                sub.resyncs += 1
                self._count("dgraph_subs_resyncs_total")
            if sub.blocked_since is None:
                sub.blocked_since = time.monotonic()
        # the notifications counter is the CALLER's job: _process batches
        # one add per group, subscribe counts its single reply event
        sub.queue.append(ev)
        if sub.waiting:                  # skip the wakeup scan when no
            sub.cv.notify_all()          # consumer is parked (the common
                                         # standing-subscription case)

    # -- journal retention ---------------------------------------------------

    _PIN_RAISE_S = 1.0

    def _maybe_raise_pin_locked(self) -> None:
        """Amortised pin maintenance for the hot paths (per window, per
        cancel). RAISING the floor is a retention optimisation, never a
        correctness edge: cursors only advance, and a floor that lags the
        true minimum merely retains a sliver of extra journal — so the
        O(subs) min-scan runs at most once per _PIN_RAISE_S. Lowering
        (first subscriber) and releasing (last one gone) stay immediate
        at their call sites."""
        now = time.monotonic()
        if self._subs and now < self._pin_raise_at:
            return
        self._pin_raise_at = now + self._PIN_RAISE_S
        self._update_pin_locked()

    def _update_pin_locked(self) -> None:
        """Pin every store's delta-journal floor at the oldest active
        cursor, so a reconnect-with-cursor stays provable (cheap ack) as
        long as retention allows; no subscribers = no pin."""
        cur = min((s.cursor for s in self._subs.values() if s.ready),
                  default=None)
        if cur == self._last_pin:
            return
        self._last_pin = cur
        for st in self._stores:
            st.pin_delta_floor(cur)

    # -- introspection / lifecycle ------------------------------------------

    def stats(self) -> dict:
        with self._cv:
            out = {
                "active": len(self._subs),
                "registered": self.registered,
                "windows": self.windows,
                "wildcard": len(self._wildcard),
                "attrs_indexed": len(self._by_attr),
                "queued": sum(len(s.queue) for s in self._subs.values()),
                "pinned_cursor": self._last_pin,
                "pending": len(self._dirty),
            }
            by_tenant: dict[str, int] = {}
            for s in self._subs.values():
                if s.tenant:
                    by_tenant[s.tenant] = by_tenant.get(s.tenant, 0) + 1
            if by_tenant:
                out["tenants"] = by_tenant
            return out

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._stop = True
            for sid in list(self._subs):
                self._close_sub_locked(sid, None)
            self._cv.notify_all()
        th = self._thread
        if th is not None:
            th.join(timeout=5)
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None
