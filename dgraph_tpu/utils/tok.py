"""Tokenizers: value → index terms.

Reference semantics: tok/tok.go — registry keyed by a 1-byte identifier that
prefixes every index term (so one index posting space can hold many tokenizer
families, tok/tok.go:34-60); IsSortable drives index-ordered sort
(worker/sort.go sortWithIndex), IsLossy forces post-filter re-checks of
candidates against actual values (worker/task.go:837-919). Full-text uses
per-language stemming + stopwords (tok/fts.go, Bleve); ours is a self-contained
Porter stemmer + English stopword list. Custom tokenizers: the reference loads
Go plugin .so files (tok/tok.go:92-109); here a custom tokenizer is a Python
module registered via register_custom / --custom_tokenizers.

Term bytes returned by tokenize() are exactly what lands in INDEX keys
(storage/keys.py index_key) and therefore define index-bucket sort order:
int/float/datetime tokens are big-endian order-preserving encodings so walking
index buckets in key order IS the sorted order (the sortWithIndex contract).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Callable

from dgraph_tpu.utils.types import TypeID, Val, convert


@dataclass(frozen=True)
class Tokenizer:
    name: str
    ident: int           # 1-byte term prefix
    type_id: TypeID      # value type it accepts
    sortable: bool
    lossy: bool
    fn: Callable[[Val], list[bytes]]

    def tokens(self, v: Val) -> list[bytes]:
        prefix = bytes([self.ident])
        return [prefix + t for t in self.fn(v)]


_REGISTRY: dict[str, Tokenizer] = {}


def register(t: Tokenizer) -> None:
    if t.name in _REGISTRY:
        raise ValueError(f"duplicate tokenizer {t.name}")
    for existing in _REGISTRY.values():
        if existing.ident == t.ident:
            raise ValueError(f"duplicate tokenizer ident 0x{t.ident:x}")
    _REGISTRY[t.name] = t


def get(name: str) -> Tokenizer:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise ValueError(f"unknown tokenizer {name!r}") from None


def has(name: str) -> bool:
    return name in _REGISTRY


def default_tokenizer(tid: TypeID) -> Tokenizer:
    """Tokenizer used when @index has no argument (reference schema/parse.go)."""
    return get({
        TypeID.INT: "int", TypeID.FLOAT: "float", TypeID.BOOL: "bool",
        TypeID.DATETIME: "year", TypeID.GEO: "geo",
        TypeID.STRING: "term", TypeID.DEFAULT: "term",
    }[tid])


# ---------------------------------------------------------------------------
# Scalar encodings (order-preserving big-endian; sortable indexes)
# ---------------------------------------------------------------------------

def _enc_int(v: int) -> bytes:
    if not (-(1 << 63) <= v < (1 << 63)):
        raise ValueError(f"int value {v} outside int64 range")
    return struct.pack(">Q", v + (1 << 63))  # bias: preserves order across sign


def _enc_float(f: float) -> bytes:
    bits = struct.unpack(">Q", struct.pack(">d", f))[0]
    bits = bits ^ ((1 << 63) if bits >> 63 == 0 else 0xFFFFFFFFFFFFFFFF)
    return struct.pack(">Q", bits)


def _int_tokens(v: Val) -> list[bytes]:
    return [_enc_int(int(convert(v, TypeID.INT).value))]


def _float_tokens(v: Val) -> list[bytes]:
    return [_enc_float(float(convert(v, TypeID.FLOAT).value))]


def _bool_tokens(v: Val) -> list[bytes]:
    return [b"\x01" if convert(v, TypeID.BOOL).value else b"\x00"]


def _dt_part(part: str):
    def fn(v: Val) -> list[bytes]:
        dt = convert(v, TypeID.DATETIME).value
        out = struct.pack(">h", dt.year)
        if part in ("month", "day", "hour"):
            out += bytes([dt.month])
        if part in ("day", "hour"):
            out += bytes([dt.day])
        if part == "hour":
            out += bytes([dt.hour])
        return [out]

    return fn


# ---------------------------------------------------------------------------
# String tokenizers
# ---------------------------------------------------------------------------

def _normalize(s: str) -> str:
    import unicodedata

    s = unicodedata.normalize("NFKD", s)
    return "".join(c for c in s if not unicodedata.combining(c)).lower()


def _term_tokens(v: Val) -> list[bytes]:
    words = "".join(c if c.isalnum() else " " for c in _normalize(str(v.value))).split()
    return sorted({w.encode("utf-8") for w in words})


def _exact_tokens(v: Val) -> list[bytes]:
    return [str(v.value).encode("utf-8")]


def _hash_tokens(v: Val) -> list[bytes]:
    import hashlib

    return [hashlib.blake2b(str(v.value).encode("utf-8"), digest_size=8).digest()]


def _trigram_tokens(v: Val) -> list[bytes]:
    s = str(v.value)
    return sorted({s[i : i + 3].encode("utf-8") for i in range(len(s) - 2)}) if len(s) >= 3 else []


_STOPWORDS = frozenset(
    """a an and are as at be but by for if in into is it no not of on or such that
    the their then there these they this to was will with""".split()
)


def _is_cons(w: str, i: int) -> bool:
    c = w[i]
    if c in "aeiou":
        return False
    if c == "y":
        return i == 0 or not _is_cons(w, i - 1)
    return True


def _measure(w: str) -> int:
    """Porter's m: number of VC sequences."""
    m, i, n = 0, 0, len(w)
    while i < n and _is_cons(w, i):
        i += 1
    while i < n:
        while i < n and not _is_cons(w, i):
            i += 1
        if i >= n:
            break
        m += 1
        while i < n and _is_cons(w, i):
            i += 1
    return m


def _ends_cvc(w: str) -> bool:
    n = len(w)
    if n < 3:
        return False
    return (_is_cons(w, n - 3) and not _is_cons(w, n - 2)
            and _is_cons(w, n - 1) and w[-1] not in "wxy")


def porter_stem(w: str) -> str:
    """Compact Porter stemmer (steps 1a/1b/1c + common suffix strips) —
    enough to make full-text matching insensitive to plurals/verb forms, the
    property the reference gets from Bleve's English stemmer. The 1b cleanup
    (re-add 'e' on short CVC stems, undouble consonants) keeps inflections
    and their base form on the SAME token: hiking/hike → hike, not hik/hike."""
    if len(w) <= 3:
        return w
    for suf, rep in (("sses", "ss"), ("ies", "i"), ("ss", "ss"), ("s", "")):
        if w.endswith(suf):
            w = w[: len(w) - len(suf)] + rep
            break
    matched = ""
    if w.endswith("eed"):                   # Porter 1b: (m>0) EED -> EE
        if _measure(w[:-3]) > 0:
            w = w[:-1]
        return w
    for suf in ("ational", "tional", "ization", "fulness", "ousness", "iveness",
                "biliti", "entli", "ousli", "ing", "edly", "ed", "ly", "ment", "ness"):
        if w.endswith(suf) and len(w) - len(suf) >= 3:
            w = w[: len(w) - len(suf)]
            matched = suf
            break
    if matched in ("ing", "ed", "edly"):
        if w.endswith(("at", "bl", "iz")):
            w += "e"
        elif len(w) >= 2 and w[-1] == w[-2] and _is_cons(w, len(w) - 1) \
                and w[-1] not in "lsz":
            w = w[:-1]                      # hopping -> hopp -> hop
        elif _measure(w) == 1 and _ends_cvc(w):
            w += "e"                        # hiking -> hik -> hike
    if len(w) > 2 and w.endswith("y") and any(
            not _is_cons(w, i) for i in range(len(w) - 1)):
        w = w[:-1] + "i"                    # pony/ponies both -> poni
    return w


# per-language full-text analysis (reference tok/fts.go: Bleve analyzers
# selected by the value's lang tag). English keeps the Porter stemmer;
# other supported languages use light suffix-stripping stemmers — the
# contract is CONSISTENCY (index and query tokenize identically under the
# same lang), which is what makes alloftext(pred@ru, ...) match inflected
# forms. Unknown languages analyze without stemming or stopwords.

_LANG_STOPWORDS: dict[str, frozenset] = {
    "ru": frozenset("и в во не что он на я с со как а то все она так его но да"
                    " ты к у же вы за бы по ее мне было вот от меня еще нет о"
                    " из ему был него до вас они ни мы этот того потому этого"
                    " какой ей этом мой тем чтобы есть надо ней для их нее уже"
                    " или вам сказал себя под будет при об это кто".split()),
    "de": frozenset("der die das und oder aber ein eine einen einem einer in"
                    " im an am auf aus bei mit nach seit von zu zum zur ist"
                    " sind war waren wird werden nicht auch als wie für den"
                    " des dem es ich du er sie wir ihr man sich".split()),
    "fr": frozenset("le la les un une des du de au aux et ou mais dans par"
                    " pour sur avec sans sous est sont était ce cette ces il"
                    " elle ils elles je tu nous vous se ne pas plus que qui"
                    " quoi dont où".split()),
    "es": frozenset("el la los las un una unos unas y o pero en de del al con"
                    " por para sin sobre es son era eran este esta estos estas"
                    " yo tú él ella nosotros ellos se no sí que quien como".split()),
    "it": frozenset("il lo la i gli le un uno una e o ma in di del della al"
                    " alla con per su da è sono era erano questo questa io tu"
                    " lui lei noi voi loro si non che chi come".split()),
}
# tokens are compared AFTER _normalize (NFKD + strip combining marks +
# lower), so the tables must hold normalized forms — 'était' arrives as
# 'etait', 'für' as 'fur'
_LANG_STOPWORDS = {k: frozenset(_normalize(w) for w in v)
                   for k, v in _LANG_STOPWORDS.items()}

_LANG_SUFFIXES: dict[str, list[str]] = {
    # longest-first light stemmers; endings chosen to fold the common
    # number/case/verb inflections onto one token
    "ru": ["иями", "ями", "ами", "ием", "иях", "иям", "ется",
           "ого", "его", "ому", "ему", "ыми", "ими",
           "ают", "яют", "уют", "юют", "ает", "яет", "ует",
           "ют", "ешь", "ишь", "ить", "ать", "ять", "еть", "ов", "ев",
           "ий", "ый", "ой", "ей", "ом", "ем", "ам", "ям", "ах", "ях",
           "ла", "ло", "ли", "ть", "ы", "и", "а", "я", "о", "е", "у",
           "ю", "ь"],
    "de": ["ungen", "ung", "heit", "keit", "lich", "isch", "ern", "en",
           "er", "es", "em", "e", "n", "s"],
    "fr": ["issements", "issement", "issantes", "issant", "emment",
           "ement", "ments", "ment", "euses", "euse", "eaux", "eux",
           "ives", "ive", "ées", "ée", "és", "é", "er", "es", "e", "s"],
    "es": ["amientos", "amiento", "aciones", "ación", "adores", "ador",
           "ancias", "ancia", "mente", "idades", "idad", "ando", "iendo",
           "arse", "ar", "er", "ir", "as", "os", "es", "a", "o", "e", "s"],
    "it": ["azioni", "azione", "amenti", "amento", "mente", "ando",
           "endo", "are", "ere", "ire", "i", "e", "a", "o"],
}
_LANG_SUFFIXES = {k: [_normalize(s) for s in v]
                  for k, v in _LANG_SUFFIXES.items()}


def lang_stem(w: str, code: str) -> str:
    """Stemmer for a 2-letter language code: Porter for English, light
    suffix stripping for the other supported languages, identity else."""
    if code == "en":
        return porter_stem(w)
    rules = _LANG_SUFFIXES.get(code)
    if rules is None:
        return w
    for suf in rules:
        if w.endswith(suf) and len(w) - len(suf) >= 3:
            return w[: len(w) - len(suf)]
    return w


def fulltext_tokens(text: str, lang: str = "") -> list[bytes]:
    """Language-aware full-text terms (unprefixed). The lang tag's primary
    subtag picks the analyzer; untagged text analyzes as English (the
    reference's default analyzer)."""
    code = (lang or "en").split("-")[0].lower()
    stop = _STOPWORDS if code == "en" else _LANG_STOPWORDS.get(
        code, frozenset())
    words = "".join(c if c.isalnum() else " "
                    for c in _normalize(text)).split()
    return sorted({lang_stem(w, code).encode("utf-8")
                   for w in words if w not in stop})


def _fulltext_tokens(v: Val) -> list[bytes]:
    return fulltext_tokens(str(v.value))


def _geo_tokens(v: Val) -> list[bytes]:
    from dgraph_tpu.utils import geo as geomod

    g = v.value if not isinstance(v.value, (str, bytes, dict)) else geomod.parse_geojson(v.value)
    return [t.encode("ascii") for t in geomod.index_tokens(g)]


# ---------------------------------------------------------------------------
# Registry population (idents mirror the reference's 1-byte space,
# tok/tok.go registry :76-133)
# ---------------------------------------------------------------------------

register(Tokenizer("term", 0x01, TypeID.STRING, sortable=False, lossy=True, fn=_term_tokens))
register(Tokenizer("exact", 0x02, TypeID.STRING, sortable=True, lossy=False, fn=_exact_tokens))
register(Tokenizer("year", 0x04, TypeID.DATETIME, sortable=True, lossy=True, fn=_dt_part("year")))
register(Tokenizer("month", 0x41, TypeID.DATETIME, sortable=True, lossy=True, fn=_dt_part("month")))
register(Tokenizer("day", 0x42, TypeID.DATETIME, sortable=True, lossy=True, fn=_dt_part("day")))
register(Tokenizer("hour", 0x43, TypeID.DATETIME, sortable=True, lossy=True, fn=_dt_part("hour")))
register(Tokenizer("geo", 0x05, TypeID.GEO, sortable=False, lossy=True, fn=_geo_tokens))
register(Tokenizer("int", 0x06, TypeID.INT, sortable=True, lossy=False, fn=_int_tokens))
register(Tokenizer("float", 0x07, TypeID.FLOAT, sortable=True, lossy=True, fn=_float_tokens))
register(Tokenizer("fulltext", 0x08, TypeID.STRING, sortable=False, lossy=True, fn=_fulltext_tokens))
register(Tokenizer("bool", 0x09, TypeID.BOOL, sortable=False, lossy=False, fn=_bool_tokens))
register(Tokenizer("trigram", 0x0A, TypeID.STRING, sortable=False, lossy=True, fn=_trigram_tokens))
register(Tokenizer("hash", 0x0B, TypeID.STRING, sortable=False, lossy=True, fn=_hash_tokens))


def register_custom(name: str, fn: Callable[[Val], list[bytes]],
                    type_id: TypeID = TypeID.STRING, sortable: bool = False,
                    lossy: bool = True) -> None:
    """Custom tokenizer (reference: Go plugin LoadCustomTokenizer, tok/tok.go:92).
    Custom idents live in 0x80+ to never collide with built-ins."""
    ident = 0x80 + (sum(name.encode()) % 0x70)
    taken = {t.ident for t in _REGISTRY.values()}
    while ident in taken:
        ident = 0x80 + ((ident + 1 - 0x80) % 0x70)
    register(Tokenizer(name, ident, type_id, sortable, lossy, fn))


def load_custom_module(spec: str) -> None:
    """Load custom tokenizers from 'module.path' exposing TOKENIZERS =
    [(name, fn, type_id, sortable, lossy), ...] — the plugin mechanism."""
    import importlib

    mod = importlib.import_module(spec)
    for name, fn, tid, sortable, lossy in getattr(mod, "TOKENIZERS", []):
        register_custom(name, fn, tid, sortable, lossy)
