"""lockdep: runtime lock-ordering verification (ISSUE 14).

Reference analog: the reference runs its whole CI under `go test -race`;
Go's runtime cannot prove lock-ORDER safety, but the Linux kernel's
lockdep can — and this is that idea for the Python side of this codebase.
Every instrumented lock belongs to a named CLASS (striped locks share an
index-suffixed family name); each acquisition while other classes are
held records directed edges held-class -> new-class into one
process-global order graph. The first acquisition that closes a cycle in
that graph is a provable deadlock SCHEDULE (two threads interleaving the
two witness stacks wedge forever), reported with both witness sites —
even though this particular run never deadlocked. That is the whole
point: chaos runs detect inversions without having to lose the race.

Arming contract (near-zero overhead, byte-identical when disarmed):

  * `Lock(name)` / `RLock(name)` are FACTORIES. Disarmed (the default)
    they return the raw `threading.Lock()` / `threading.RLock()` object —
    the production binary runs the exact same primitives it always did,
    zero wrappers, zero overhead.
  * `arm()` (or env DGRAPH_TPU_LOCKDEP=1) must run BEFORE the locks are
    constructed; tests arm in a fixture, then build their nodes. Armed
    factories return instrumented wrappers that feed the global state.
  * Violations raise `LockOrderError` at the acquisition that closed the
    cycle when `arm(raise_on_cycle=True)` (the test default), and are
    always appended to `violations()` so harnesses can assert emptiness.

Reentrant acquisition of the SAME instance (RLock) is not an ordering
and records nothing. Two DIFFERENT instances of the same class nested
(e.g. two stripes of a striped lock family) are reported as
`same-class-nesting`: stripe order is hash-derived, so any nesting is a
latent ABBA unless the call site sorts stripes first.

Adopted by: storage store (via utils/sync.SafeLock), the residency
manager + its striped upload locks, the dispatch gate, the device
batcher, and the placement controller. The static half of this invariant
is dgraph_tpu/analysis (rule lock-order) over `with` nesting.
"""

from __future__ import annotations

import os
import sys
import threading


class LockOrderError(AssertionError):
    """A lock acquisition closed a cycle in the global order graph."""


# wrapper modules whose frames are never the interesting witness site:
# this module itself and utils/sync.py (SafeLock forwards acquire here —
# without the skip every store-lock witness would print sync.py:<n>)
_WRAPPER_FILES = ("locks.py", "sync.py")


def _site(depth: int = 3) -> str:
    """filename:lineno of the acquiring frame (cheap: no stack object).
    Walks past wrapper frames so witness sites name the REAL caller."""
    try:
        f = sys._getframe(depth)
        while f is not None and \
                os.path.basename(f.f_code.co_filename) in _WRAPPER_FILES:
            f = f.f_back
        if f is None:
            return "?"
        return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"
    except Exception:
        return "?"


class _State:
    """Process-global order graph + per-thread held stacks."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        # a -> {b: (witness site holding a, witness site acquiring b)}
        self.graph: dict[str, dict[str, tuple[str, str]]] = {}
        self.same_class_seen: set[str] = set()
        self.violations: list[dict] = []
        self.raise_on_cycle = True
        self.tls = threading.local()
        # bumped by reset(): a background thread still holding an
        # instrumented lock across a reset/re-arm boundary (daemon loops
        # outliving one test into the next) must not inject its stale
        # held entries as edges into the fresh graph
        self.epoch = 0

    def held(self) -> list:
        """This thread's stack of (class key, instance id, site, epoch)."""
        h = getattr(self.tls, "held", None)
        if h is None:
            h = self.tls.held = []
        return h

    def _path(self, src: str, dst: str) -> list[str] | None:
        """DFS: a path src -> ... -> dst in the order graph, or None."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self.graph.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def _report(self, kind: str, key: str, cycle: list[str],
                site: str, witness: tuple[str, str] | None) -> None:
        v = {"kind": kind, "key": key, "cycle": cycle, "site": site,
             "witness": witness}
        self.violations.append(v)
        if self.raise_on_cycle:
            wtxt = f" (forward order first seen at {witness[0]} -> " \
                   f"{witness[1]})" if witness else ""
            raise LockOrderError(
                f"lock-order {kind}: acquiring {key!r} at {site} closes "
                f"the cycle {' -> '.join(cycle)}{wtxt}")

    def acquired(self, key: str, inst: int, site: str) -> None:
        """Record one successful acquisition by this thread. MUST run
        after the real acquire succeeded (the lock is held while we
        mutate the graph under self.lock — lockdep's own lock is a leaf:
        nothing is acquired while holding it)."""
        held = self.held()
        epoch = self.epoch
        # stale-epoch entries (held across a reset()) are invisible: they
        # belong to a graph that no longer exists
        live = [e for e in held if e[3] == epoch]
        if any(k == key and i == inst for k, i, _, _ in live):
            held.append((key, inst, site, epoch))  # reentrant: no ordering
            return
        new_edges = []
        for hk, hi, hsite, _ep in live:
            if hk == key:
                # a second INSTANCE of a held class: hash-ordered stripes
                # nesting each other are a latent ABBA by construction
                with self.lock:
                    if key not in self.same_class_seen:
                        self.same_class_seen.add(key)
                        self._report("same-class-nesting", key,
                                     [key, key], site, (hsite, site))
                continue
            new_edges.append((hk, hsite))
        with self.lock:
            for hk, hsite in new_edges:
                row = self.graph.setdefault(hk, {})
                if key not in row:
                    row[key] = (hsite, site)
                    back = self._path(key, hk)
                    if back is not None:
                        self._report("inversion", key, back + [key],
                                     site, self.graph[hk][key])
        held.append((key, inst, site, epoch))

    def released(self, key: str, inst: int) -> None:
        held = self.held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == key and held[i][1] == inst:
                del held[i]
                return


_STATE = _State()
_armed = False


def armed() -> bool:
    return _armed


def arm(raise_on_cycle: bool = True) -> None:
    """Arm lockdep for locks constructed FROM NOW ON. Tests call this in
    a fixture before building nodes; `reset()` first for a clean graph."""
    global _armed
    _STATE.raise_on_cycle = bool(raise_on_cycle)
    _armed = True


def disarm() -> None:
    global _armed
    _armed = False


def reset() -> None:
    """Drop the recorded graph + violations (between tests). Bumps the
    epoch so locks still held by surviving background threads cannot
    leak pre-reset orderings into the fresh graph."""
    with _STATE.lock:
        _STATE.graph.clear()
        _STATE.same_class_seen.clear()
        _STATE.violations.clear()
        _STATE.epoch += 1


def violations() -> list[dict]:
    with _STATE.lock:
        return list(_STATE.violations)


def edges() -> dict[str, list[str]]:
    """The observed order graph (for debugging / assertions)."""
    with _STATE.lock:
        return {a: sorted(b) for a, b in _STATE.graph.items()}


class _DepBase:
    """Shared wrapper plumbing over a real threading primitive."""

    __slots__ = ("_lk", "name")

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lk.acquire(blocking, timeout)
        if ok and _armed:
            try:
                _STATE.acquired(self.name, id(self), _site(2))
            except BaseException:
                self._lk.release()     # never leave the real lock wedged
                raise
        return ok

    def release(self) -> None:
        self._lk.release()
        _STATE.released(self.name, id(self))

    def __enter__(self) -> bool:
        ok = self._lk.acquire()
        if _armed:
            try:
                _STATE.acquired(self.name, id(self), _site(2))
            except BaseException:
                self._lk.release()
                raise
        return ok

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lk.locked()

    def __repr__(self) -> str:
        return f"<lockdep {type(self).__name__} {self.name!r} " \
               f"wrapping {self._lk!r}>"


class _DepLock(_DepBase):
    __slots__ = ()

    def __init__(self, name: str) -> None:
        self._lk = threading.Lock()
        self.name = name


class _DepRLock(_DepBase):
    __slots__ = ()

    def __init__(self, name: str) -> None:
        self._lk = threading.RLock()
        self.name = name

    def locked(self) -> bool:                    # RLock has no .locked()
        if self._lk.acquire(blocking=False):
            self._lk.release()
            return False
        return True


def Lock(name: str):
    """A named mutex: raw `threading.Lock` disarmed, instrumented armed."""
    if _armed:
        return _DepLock(name)
    return threading.Lock()


def RLock(name: str):
    """A named reentrant mutex: raw `threading.RLock` disarmed,
    instrumented armed (reentrant re-acquisition records no ordering)."""
    if _armed:
        return _DepRLock(name)
    return threading.RLock()


if os.environ.get("DGRAPH_TPU_LOCKDEP", "") not in ("", "0"):
    arm(raise_on_cycle=os.environ.get(
        "DGRAPH_TPU_LOCKDEP_RAISE", "1") not in ("", "0"))
