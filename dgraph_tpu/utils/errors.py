"""Typed error taxonomy for RPC/dispatch boundaries (ISSUE 14).

The lifeline layer (PR 7) made deadline/overload failures typed
(utils/deadline.DeadlineExceeded / ResourceExhausted, each carrying a
`code` that maps onto a gRPC status); the remaining seam failures were
still bare `RuntimeError("...")` strings — un-matchable by retry policy,
breaker classification, or HTTP status mapping. These classes close that
gap. They deliberately SUBCLASS RuntimeError: every existing
`except RuntimeError` catch keeps working, the analyzer's
rpc-error-taxonomy rule is satisfied, and new code can match on type or
on `code`.

Taxonomy (mirrors the reference's gRPC status usage, SURVEY §API):

  Unavailable        nobody can serve this right now (no live leader, no
                     connection to the owning group, quorum lost, listener
                     bind failure) — retriable against another replica.
  FailedPrecondition the request is well-formed but the system state
                     refuses it (tablet mid-move, standby zero asked to
                     lead) — retry AFTER refreshing routing/leadership.

DeadlineExceeded / ResourceExhausted stay in utils/deadline (they are
budget semantics, not wire semantics); FaultError stays in utils/faults
(transport-shaped by design).
"""

from __future__ import annotations


class WireError(RuntimeError):
    """Base for typed seam failures; `code` is the gRPC status name."""

    code = "UNKNOWN"


class Unavailable(WireError):
    """No live peer can serve the request (dead leader, unreachable
    group, lost quorum, un-bindable listener)."""

    code = "UNAVAILABLE"


class FailedPrecondition(WireError):
    """System state refuses the request until the caller refreshes its
    view (predicate mid-move fence, non-leader zero)."""

    code = "FAILED_PRECONDITION"
