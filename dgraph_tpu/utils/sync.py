"""Concurrency helpers: the race-detection story (SURVEY §5).

The reference leans on `go test -race` plus x.SafeMutex's AssertLock
(x/lock.go) and liberal x.AssertTrue invariants. Python has no data-race
sanitizer, so the strategy here is:
  1. SafeLock.assert_held() guards on internal methods that REQUIRE the
     caller to hold the lock (misuse fails fast instead of corrupting);
  2. invariant-checking multithreaded stress tests (tests/test_stress.py,
     scaled up via DGRAPH_TPU_STRESS=1) covering the scheduler, the txn
     pipeline, and replication;
  3. single-writer disciplines documented at the structure (e.g. packed
     bases are immutable — mutation replaces, never edits).
"""

from __future__ import annotations

import threading

from . import locks


class SafeLock:
    """RLock that can assert 'the current thread holds me'
    (x/lock.go SafeMutex.AssertLock analog). `name` is the lockdep
    class (utils/locks.py): armed runs record this lock's orderings in
    the global order graph; disarmed it is a raw threading.RLock."""

    def __init__(self, name: str = "sync.SafeLock") -> None:
        self._lock = locks.RLock(name)
        self._owner: int | None = None
        self._depth = 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            self._owner = threading.get_ident()
            self._depth += 1
        return ok

    def release(self) -> None:
        if not self.held_by_me():
            # non-owner misuse: let RLock raise its canonical error without
            # touching the true owner's tracking state
            self._lock.release()
            raise AssertionError("unreachable: RLock.release must raise")
        # mutate tracking while still holding the lock (releasing first
        # would race a new owner's acquire against our owner-clear)
        self._depth -= 1
        if self._depth == 0:
            self._owner = None
        self._lock.release()

    __enter__ = acquire

    def __exit__(self, *exc) -> None:
        self.release()

    def held_by_me(self) -> bool:
        return self._owner == threading.get_ident()

    def assert_held(self) -> None:
        if not self.held_by_me():
            raise AssertionError(
                "lock-discipline violation: caller must hold the lock")
