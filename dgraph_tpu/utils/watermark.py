"""WaterMark: minimum-unfinished-index tracker.

Reference semantics: x/watermark.go:66-213 — Begin(k)/Done(k) mark an index
pending/finished; DoneUntil() is the highest index such that every index at
or below it is finished; WaitForMark(k) blocks until DoneUntil >= k. The
reference runs a goroutine over a channel of marks; here a heap under a
condition variable gives the same contract synchronously (no event loop to
leak in embedded nodes).

Used for applied/synced watermarks: e.g. "all WAL records up to index k are
applied" gates snapshotting and follower catch-up the same way the
reference gates reads on the applied watermark.
"""

from __future__ import annotations

import heapq
import threading
from collections import Counter


class WaterMark:
    def __init__(self, name: str = "") -> None:
        self.name = name
        self._cv = threading.Condition()
        self._pending: Counter[int] = Counter()   # index -> open begins
        self._heap: list[int] = []                # candidate minimums
        self._done_until = 0
        self._last_index = 0

    def begin(self, index: int) -> None:
        with self._cv:
            self._last_index = max(self._last_index, index)
            if self._pending[index] == 0:
                heapq.heappush(self._heap, index)
            self._pending[index] += 1

    def done(self, index: int) -> None:
        with self._cv:
            if self._pending.get(index, 0) <= 0:
                raise ValueError(f"done({index}) without begin")
            self._pending[index] -= 1
            if self._pending[index] == 0:
                del self._pending[index]
            self._advance_locked()

    def _advance_locked(self) -> None:
        moved = False
        while self._heap and self._pending.get(self._heap[0], 0) == 0:
            idx = heapq.heappop(self._heap)
            if idx > self._done_until:
                self._done_until = idx
                moved = True
        if not self._heap and self._last_index > self._done_until \
                and not self._pending:
            # everything begun has finished
            self._done_until = self._last_index
            moved = True
        if moved:
            self._cv.notify_all()

    def set_done_until(self, index: int) -> None:
        """Jump the watermark (reference SetDoneUntil — only valid when not
        interleaved with begin/done)."""
        with self._cv:
            if self._pending:
                raise ValueError("set_done_until with marks pending")
            self._done_until = max(self._done_until, index)
            self._last_index = max(self._last_index, index)
            self._cv.notify_all()

    def done_until(self) -> int:
        with self._cv:
            return self._done_until

    def last_index(self) -> int:
        with self._cv:
            return self._last_index

    def wait_for_mark(self, index: int, timeout: float | None = None) -> bool:
        """Block until done_until >= index; returns False on timeout."""
        with self._cv:
            return self._cv.wait_for(lambda: self._done_until >= index,
                                     timeout=timeout)
