"""Geo values: GeoJSON parse, geohash covers, spatial predicates.

Reference semantics: types/geo.go (go-geom GeoJSON values), types/s2index.go
(S2 cell covers as index tokens, ~6 levels), types/geofilter.go (near / within
/ contains / intersects query filters with index-cover candidate generation +
exact post-filter).

Redesign: covers use standard geohash cells (base-32, precision 1-9) instead
of S2. The contract is identical — a *lossy* cell→uid index generates
candidates and an exact host-side geometry test post-filters them (the
reference does the same: worker/task.go:921 filterGeoFunction) — only the cell
decomposition differs. Geometry math is self-contained (haversine,
point-in-polygon winding) so no external geo deps are needed.
"""

from __future__ import annotations

import json
import math
from dataclasses import dataclass
from typing import Iterable

EARTH_RADIUS_M = 6_371_000.0
_BASE32 = "0123456789bcdefghjkmnpqrstuvwxyz"

# Index precisions: ~5000km .. ~150m cells (analog of the reference's S2
# min/max level loop in types/s2index.go indexCells). Precision 1 has only 32
# cells globally, so a bbox cover always succeeds at some precision >= 1 and
# query covers never silently drop candidate cells.
MIN_PRECISION = 1
MAX_PRECISION = 6


@dataclass(frozen=True)
class Geom:
    """A geometry: kind in {Point, Polygon, MultiPolygon}; coords per GeoJSON."""

    kind: str
    coords: tuple

    def points(self) -> Iterable[tuple[float, float]]:
        if self.kind == "Point":
            yield self.coords
        elif self.kind == "Polygon":
            for ring in self.coords:
                yield from ring
        elif self.kind == "MultiPolygon":
            for poly in self.coords:
                for ring in poly:
                    yield from ring


def _to_tuple(x):
    return tuple(_to_tuple(i) for i in x) if isinstance(x, (list, tuple)) else float(x)


def parse_geojson(s) -> Geom:
    obj = json.loads(s) if isinstance(s, (str, bytes)) else s
    kind = obj.get("type")
    if kind not in ("Point", "Polygon", "MultiPolygon"):
        raise ValueError(f"unsupported geometry type {kind!r}")
    return Geom(kind, _to_tuple(obj["coordinates"]))


def to_geojson(g: Geom) -> str:
    def unroll(x):
        return [unroll(i) for i in x] if isinstance(x, tuple) else x

    return json.dumps({"type": g.kind, "coordinates": unroll(g.coords)})


# ---------------------------------------------------------------------------
# Geohash
# ---------------------------------------------------------------------------

def geohash(lng: float, lat: float, precision: int) -> str:
    lat_rng, lng_rng = [-90.0, 90.0], [-180.0, 180.0]
    bits, even, ch, out = 0, True, 0, []
    while len(out) < precision:
        rng, v = (lng_rng, lng) if even else (lat_rng, lat)
        mid = (rng[0] + rng[1]) / 2
        ch <<= 1
        if v >= mid:
            ch |= 1
            rng[0] = mid
        else:
            rng[1] = mid
        even = not even
        bits += 1
        if bits == 5:
            out.append(_BASE32[ch])
            bits, ch = 0, 0
    return "".join(out)


def geohash_bounds(h: str) -> tuple[float, float, float, float]:
    """(min_lng, min_lat, max_lng, max_lat) of a geohash cell."""
    lat_rng, lng_rng = [-90.0, 90.0], [-180.0, 180.0]
    even = True
    for c in h:
        cd = _BASE32.index(c)
        for shift in range(4, -1, -1):
            rng = lng_rng if even else lat_rng
            mid = (rng[0] + rng[1]) / 2
            if (cd >> shift) & 1:
                rng[0] = mid
            else:
                rng[1] = mid
            even = not even
    return lng_rng[0], lat_rng[0], lng_rng[1], lat_rng[1]


def _cells_covering_bbox(min_lng, min_lat, max_lng, max_lat, precision: int, limit=64):
    """Geohash cells at `precision` overlapping a bbox (grid walk)."""
    cells: list[str] = []
    h0 = geohash(min_lng, min_lat, precision)
    lng0, lat0, lng1, lat1 = geohash_bounds(h0)
    dlng, dlat = lng1 - lng0, lat1 - lat0
    lat = lat0
    while lat < max_lat + dlat / 2:
        lng = lng0
        while lng < max_lng + dlng / 2:
            cells.append(geohash(min(max(lng, -180 + 1e-9), 180 - 1e-9),
                                 min(max(lat, -90 + 1e-9), 90 - 1e-9), precision))
            if len(cells) > limit:
                return None  # too many cells at this precision
            lng += dlng
        lat += dlat
    return sorted(set(cells))


def index_tokens(g: Geom) -> list[str]:
    """Cover tokens written to the geo index for a stored geometry.

    A point is indexed at every precision (so queries at any scale hit it);
    a polygon is indexed by its bbox cover at the coarsest precision that
    keeps the cover small.
    """
    if g.kind == "Point":
        lng, lat = g.coords
        return [geohash(lng, lat, p) for p in range(MIN_PRECISION, MAX_PRECISION + 1)]
    pts = list(g.points())
    lngs = [p[0] for p in pts]
    lats = [p[1] for p in pts]
    toks: list[str] = []
    for p in range(MAX_PRECISION, MIN_PRECISION - 1, -1):
        cover = _cells_covering_bbox(min(lngs), min(lats), max(lngs), max(lats), p)
        if cover is not None:
            # index the cover cells AND their coarser prefixes' points queries
            toks = cover
            break
    else:
        toks = [geohash(lngs[0], lats[0], MIN_PRECISION)]
    # also index coarser ancestors so coarse query covers match
    anc = {t[:p] for t in toks for p in range(MIN_PRECISION, len(t))}
    return sorted(set(toks) | anc)


def query_tokens(g: Geom, radius_m: float | None = None) -> list[str]:
    """Cover tokens probed by a geo query (near/within/intersects candidates)."""
    if g.kind == "Point" and radius_m is not None:
        lng, lat = g.coords
        dlat = math.degrees(radius_m / EARTH_RADIUS_M)
        dlng = dlat / max(math.cos(math.radians(lat)), 1e-6)
        for p in range(MAX_PRECISION, 0, -1):
            cover = _cells_covering_bbox(lng - dlng, lat - dlat, lng + dlng, lat + dlat, p)
            if cover is not None:
                return cover
        return _ALL_P1_CELLS
    if g.kind == "Point":
        lng, lat = g.coords
        return [geohash(lng, lat, p) for p in range(MIN_PRECISION, MAX_PRECISION + 1)]
    pts = list(g.points())
    lngs = [p[0] for p in pts]
    lats = [p[1] for p in pts]
    for p in range(MAX_PRECISION, 0, -1):
        cover = _cells_covering_bbox(min(lngs), min(lats), max(lngs), max(lats), p)
        if cover is not None:
            return cover
    return _ALL_P1_CELLS


# every precision-1 cell (worst-case query cover: whole-globe candidates)
_ALL_P1_CELLS = sorted(_BASE32)


# ---------------------------------------------------------------------------
# Exact predicates (post-filters; reference types/geofilter.go)
# ---------------------------------------------------------------------------

def haversine_m(a: tuple[float, float], b: tuple[float, float]) -> float:
    lng1, lat1, lng2, lat2 = map(math.radians, (*a, *b))
    dlat, dlng = lat2 - lat1, lng2 - lng1
    h = math.sin(dlat / 2) ** 2 + math.cos(lat1) * math.cos(lat2) * math.sin(dlng / 2) ** 2
    return 2 * EARTH_RADIUS_M * math.asin(math.sqrt(h))


def _point_in_ring(pt, ring) -> bool:
    x, y = pt
    inside = False
    for i in range(len(ring) - 1):
        x1, y1 = ring[i][:2]
        x2, y2 = ring[i + 1][:2]
        if (y1 > y) != (y2 > y) and x < (x2 - x1) * (y - y1) / (y2 - y1) + x1:
            inside = not inside
    return inside


def _point_in_polygon(pt, poly) -> bool:
    if not poly or not _point_in_ring(pt, poly[0]):
        return False
    return not any(_point_in_ring(pt, hole) for hole in poly[1:])


def contains(g: Geom, pt: Geom) -> bool:
    """Polygon g contains point pt."""
    if pt.kind != "Point":
        pt = Geom("Point", next(iter(pt.points())))
    if g.kind == "Polygon":
        return _point_in_polygon(pt.coords, g.coords)
    if g.kind == "MultiPolygon":
        return any(_point_in_polygon(pt.coords, poly) for poly in g.coords)
    return g.kind == "Point" and g.coords == pt.coords


def within(g: Geom, region: Geom) -> bool:
    """Geometry g lies within region (vertex containment, as the reference's
    Loop.Contains over loop vertices)."""
    return all(contains(region, Geom("Point", p)) for p in g.points())


def near(g: Geom, center: tuple[float, float], radius_m: float) -> bool:
    return any(haversine_m(p, center) <= radius_m for p in g.points())


def intersects(a: Geom, b: Geom) -> bool:
    if a.kind == "Point":
        return contains(b, a) if b.kind != "Point" else a.coords == b.coords
    if b.kind == "Point":
        return contains(a, b)
    # polygon-polygon: any vertex containment either way (candidate-level test)
    return any(contains(a, Geom("Point", p)) for p in b.points()) or any(
        contains(b, Geom("Point", p)) for p in a.points()
    )
