"""Structured logging: one process-wide sink, text or JSON lines.

Reference: the reference logs through glog; this port previously used 21
bare print() call sites in the CLI. get_logger() gives each component a
named logger; --log_json (or configure(json_mode=True)) switches every
line to single-line JSON ({"ts","level","component","event",...fields}),
the shape log shippers ingest without a parse rule. Text mode keeps the
human-readable "<event> key=value" form on stderr-free stdout, flushed
per line (the CLI's print(..., flush=True) contract)."""

from __future__ import annotations

import json
import sys
import threading
import time

_lock = threading.Lock()
_json_mode = False
_stream = None          # None = sys.stdout at call time (testable)


def configure(json_mode: bool = False, stream=None) -> None:
    """Install process-wide output mode (the --log_json flag's target)."""
    global _json_mode, _stream
    _json_mode = bool(json_mode)
    _stream = stream


def json_mode() -> bool:
    return _json_mode


class Logger:
    __slots__ = ("component",)

    def __init__(self, component: str) -> None:
        self.component = component

    def _emit(self, level: str, event: str, fields: dict) -> None:
        out = _stream if _stream is not None else sys.stdout
        if _json_mode:
            rec = {"ts": round(time.time(), 3), "level": level,
                   "component": self.component, "event": event}
            rec.update(fields)
            line = json.dumps(rec, default=str, separators=(",", ":"))
        else:
            kv = " ".join(f"{k}={v}" for k, v in fields.items())
            line = f"{event} {kv}" if kv else event
        with _lock:
            try:
                out.write(line + "\n")
                out.flush()
            except (ValueError, OSError):
                pass     # closed stream at shutdown: logging never raises

    def info(self, event: str, **fields) -> None:
        self._emit("info", event, fields)

    def warn(self, event: str, **fields) -> None:
        self._emit("warn", event, fields)

    def error(self, event: str, **fields) -> None:
        self._emit("error", event, fields)


def get_logger(component: str) -> Logger:
    return Logger(component)
