"""Value type system: scalar types, conversion matrix, comparison.

Reference semantics: types/ — 10 scalar types (types/scalar_types.go:35-44),
full conversion matrix incl. binary marshaling (types/conversion.go), ordering
(types/compare.go, types/sort.go). Geo here is lat/lng points + geohash cells
(the reference uses S2; see utils/geo.py for the cover logic).

Device mapping: int/float/datetime/bool values are mirrored into HBM arrays
aligned with each predicate's subject table (storage/csr_build.py) so compare
functions run on the VPU; string/geo/password stay host-side behind token
indexes, exactly as the reference keeps them behind index posting lists.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from datetime import datetime, timezone
from enum import IntEnum
from typing import Any


class TypeID(IntEnum):
    DEFAULT = 0
    BINARY = 1
    INT = 2
    FLOAT = 3
    BOOL = 4
    DATETIME = 5
    STRING = 6
    GEO = 7
    UID = 8
    PASSWORD = 9
    VECTOR = 10          # float32vector: dense embedding (tuple of floats)

    @classmethod
    def from_name(cls, name: str) -> "TypeID":
        try:
            return _NAME_TO_TYPE[name.lower()]
        except KeyError:
            raise ValueError(f"unknown type {name!r}") from None


_NAME_TO_TYPE = {
    "default": TypeID.DEFAULT,
    "binary": TypeID.BINARY,
    "int": TypeID.INT,
    "float": TypeID.FLOAT,
    "bool": TypeID.BOOL,
    "datetime": TypeID.DATETIME,
    "string": TypeID.STRING,
    "geo": TypeID.GEO,
    "uid": TypeID.UID,
    "password": TypeID.PASSWORD,
    "float32vector": TypeID.VECTOR,
}

TYPE_NAMES = {v: k for k, v in _NAME_TO_TYPE.items()}


@dataclass(frozen=True)
class Val:
    """A typed value."""

    tid: TypeID
    value: Any

    def __repr__(self) -> str:
        return f"Val({TYPE_NAMES[self.tid]}, {self.value!r})"


# ---------------------------------------------------------------------------
# Parsing / conversion (reference: types/conversion.go Convert)
# ---------------------------------------------------------------------------

_RFC3339_FORMATS = (
    "%Y-%m-%dT%H:%M:%S.%f%z", "%Y-%m-%dT%H:%M:%S%z",
    "%Y-%m-%dT%H:%M:%S.%f", "%Y-%m-%dT%H:%M:%S",
    "%Y-%m-%dT%H:%M", "%Y-%m-%d", "%Y-%m", "%Y",
)


def parse_datetime(s: str) -> datetime:
    for fmt in _RFC3339_FORMATS:
        try:
            dt = datetime.strptime(s, fmt)
            if dt.tzinfo is None:
                dt = dt.replace(tzinfo=timezone.utc)
            return dt
        except ValueError:
            continue
    raise ValueError(f"cannot parse datetime {s!r}")


def _check_int64(v: int) -> int:
    if not (-(1 << 63) <= v < (1 << 63)):
        raise ValueError(f"int value {v} outside int64 range")
    return v


def parse_vector(raw) -> tuple[float, ...]:
    """Parse a float32vector literal: a `"[0.1, 0.2, ...]"` string or a
    JSON array of numbers. Values are snapped to float32 (the storage and
    device precision) so WAL/snapshot round-trips are bit-exact; NaN/Inf
    components reject the value — a NaN row would poison every similarity
    score it touches."""
    import math

    if isinstance(raw, str):
        s = raw.strip()
        if not (s.startswith("[") and s.endswith("]")):
            raise ValueError(f"vector literal must be [v1, v2, ...]: {raw!r}")
        body = s[1:-1].strip()
        parts = [p for p in body.split(",") if p.strip()] if body else []
        try:
            xs = [float(p) for p in parts]
        except ValueError:
            raise ValueError(f"bad vector component in {raw!r}") from None
    elif isinstance(raw, (list, tuple)):
        xs = []
        for x in raw:
            if isinstance(x, bool) or not isinstance(x, (int, float)):
                raise ValueError(f"vector component {x!r} is not a number")
            xs.append(float(x))
    else:
        raise ValueError(f"cannot parse vector from {type(raw).__name__}")
    if not xs:
        raise ValueError("empty vector")
    if any(not math.isfinite(x) for x in xs):
        raise ValueError("vector contains NaN/Inf components")
    import numpy as _np

    return tuple(float(x) for x in _np.asarray(xs, dtype=_np.float32))


def vector_str(v: tuple[float, ...]) -> str:
    """Canonical string form of a vector value (repr round-trips float32
    exactly through parse_vector)."""
    return "[" + ", ".join(repr(float(x)) for x in v) + "]"


def convert(src: Val, to: TypeID) -> Val:
    """Convert a value between scalar types; raises ValueError when undefined.

    Mirrors the reference's conversion matrix (types/conversion.go): any type
    converts from its string form and to its string form; numeric types
    interconvert; datetime <-> int (unix seconds) / float.
    """
    if src.tid == to:
        return src
    v = src.value
    try:
        if src.tid in (TypeID.STRING, TypeID.DEFAULT):
            s = str(v)
            if to in (TypeID.STRING, TypeID.DEFAULT):
                return Val(to, s)
            if to == TypeID.INT:
                return Val(to, _check_int64(int(s)))
            if to == TypeID.FLOAT:
                return Val(to, float(s))
            if to == TypeID.BOOL:
                if s.lower() in ("true", "1"):
                    return Val(to, True)
                if s.lower() in ("false", "0"):
                    return Val(to, False)
                raise ValueError(s)
            if to == TypeID.DATETIME:
                return Val(to, parse_datetime(s))
            if to == TypeID.BINARY:
                return Val(to, s.encode("utf-8"))
            if to == TypeID.PASSWORD:
                return Val(to, hash_password(s))
            if to == TypeID.GEO:
                from dgraph_tpu.utils import geo as geomod

                return Val(to, geomod.parse_geojson(s))
            if to == TypeID.VECTOR:
                return Val(to, parse_vector(s))
        elif src.tid == TypeID.INT:
            if to == TypeID.FLOAT:
                return Val(to, float(v))
            if to == TypeID.BOOL:
                return Val(to, bool(v))
            if to in (TypeID.STRING, TypeID.DEFAULT):
                return Val(to, str(v))
            if to == TypeID.DATETIME:
                return Val(to, datetime.fromtimestamp(v, tz=timezone.utc))
        elif src.tid == TypeID.FLOAT:
            if to == TypeID.INT:
                return Val(to, _check_int64(int(v)))
            if to == TypeID.BOOL:
                return Val(to, bool(v))
            if to in (TypeID.STRING, TypeID.DEFAULT):
                return Val(to, repr(v) if isinstance(v, float) else str(v))
            if to == TypeID.DATETIME:
                return Val(to, datetime.fromtimestamp(v, tz=timezone.utc))
        elif src.tid == TypeID.BOOL:
            if to == TypeID.INT:
                return Val(to, int(v))
            if to == TypeID.FLOAT:
                return Val(to, float(v))
            if to in (TypeID.STRING, TypeID.DEFAULT):
                return Val(to, "true" if v else "false")
        elif src.tid == TypeID.DATETIME:
            if to in (TypeID.STRING, TypeID.DEFAULT):
                return Val(to, v.isoformat())
            if to == TypeID.INT:
                return Val(to, int(v.timestamp()))
            if to == TypeID.FLOAT:
                return Val(to, v.timestamp())
        elif src.tid == TypeID.BINARY:
            if to in (TypeID.STRING, TypeID.DEFAULT):
                return Val(to, v.decode("utf-8"))
        elif src.tid == TypeID.GEO:
            if to in (TypeID.STRING, TypeID.DEFAULT):
                from dgraph_tpu.utils import geo as geomod

                return Val(to, geomod.to_geojson(v))
        elif src.tid == TypeID.VECTOR:
            if to in (TypeID.STRING, TypeID.DEFAULT):
                return Val(to, vector_str(v))
    except (ValueError, TypeError, OverflowError) as e:
        raise ValueError(f"cannot convert {src!r} to {TYPE_NAMES[to]}: {e}") from None
    raise ValueError(f"no conversion from {TYPE_NAMES[src.tid]} to {TYPE_NAMES[to]}")


# ---------------------------------------------------------------------------
# Comparison / sort keys (reference: types/compare.go CompareVals)
# ---------------------------------------------------------------------------

def compare_vals(op: str, a: Val, b: Val) -> bool:
    """Apply a comparison operator (lt/le/gt/ge/eq/ne) between same-type values."""
    if a.tid != b.tid:
        try:
            b = convert(b, a.tid)
        except ValueError:
            return False
    av, bv = a.value, b.value
    if a.tid == TypeID.DATETIME:
        av, bv = av.timestamp(), bv.timestamp()
    return {
        "lt": lambda: av < bv,
        "le": lambda: av <= bv,
        "gt": lambda: av > bv,
        "ge": lambda: av >= bv,
        "eq": lambda: av == bv,
        "ne": lambda: av != bv,
    }[op]()


def sort_key(v: Val):
    """Total-order sort key within one type."""
    if v.tid == TypeID.DATETIME:
        return v.value.timestamp()
    return v.value


# ---------------------------------------------------------------------------
# Device mirroring: numeric encode (storage/csr_build.py uploads these)
# ---------------------------------------------------------------------------

def to_device_scalar(v: Val) -> float | int | None:
    """Encode a value for the HBM value table (int64/float64 lattice), or None
    if the type only exists behind host-side indexes (string/geo/password)."""
    if v.tid == TypeID.INT:
        return int(v.value)
    if v.tid == TypeID.FLOAT:
        return float(v.value)
    if v.tid == TypeID.BOOL:
        return int(bool(v.value))
    if v.tid == TypeID.DATETIME:
        return float(v.value.timestamp())
    return None


# ---------------------------------------------------------------------------
# Passwords (reference: types/password.go, bcrypt)
# ---------------------------------------------------------------------------

def hash_password(pw: str) -> str:
    """Salted PBKDF2-HMAC-SHA256 (stdlib; the reference vendors bcrypt)."""
    import hashlib
    import os

    if len(pw) < 6:
        raise ValueError("password too short, i.e. should have at least 6 chars")
    salt = os.urandom(16)
    dk = hashlib.pbkdf2_hmac("sha256", pw.encode("utf-8"), salt, 100_000)
    return "pbkdf2$" + salt.hex() + "$" + dk.hex()


def verify_password(pw: str, stored: str) -> bool:
    import hashlib
    import hmac

    try:
        scheme, salt_hex, dk_hex = stored.split("$")
        if scheme != "pbkdf2":
            return False
        dk = hashlib.pbkdf2_hmac("sha256", pw.encode("utf-8"), bytes.fromhex(salt_hex), 100_000)
        return hmac.compare_digest(dk.hex(), dk_hex)
    except ValueError:
        return False


# ---------------------------------------------------------------------------
# Binary marshaling for the persistent store (reference: types binary Marshal)
# ---------------------------------------------------------------------------

def marshal(v: Val) -> bytes:
    tid = v.tid
    if tid in (TypeID.STRING, TypeID.DEFAULT, TypeID.PASSWORD):
        return str(v.value).encode("utf-8")
    if tid == TypeID.BINARY:
        return bytes(v.value)
    if tid == TypeID.INT:
        return struct.pack("<q", int(v.value))
    if tid == TypeID.FLOAT:
        return struct.pack("<d", float(v.value))
    if tid == TypeID.BOOL:
        return b"\x01" if v.value else b"\x00"
    if tid == TypeID.DATETIME:
        return struct.pack("<d", v.value.timestamp())
    if tid == TypeID.GEO:
        from dgraph_tpu.utils import geo as geomod

        return geomod.to_geojson(v.value).encode("utf-8")
    if tid == TypeID.UID:
        return struct.pack("<Q", int(v.value))
    if tid == TypeID.VECTOR:
        xs = v.value
        return struct.pack(f"<{len(xs)}f", *xs)
    raise ValueError(f"cannot marshal {v!r}")


def unmarshal(tid: TypeID, b: bytes) -> Val:
    if tid in (TypeID.STRING, TypeID.DEFAULT, TypeID.PASSWORD):
        return Val(tid, b.decode("utf-8"))
    if tid == TypeID.BINARY:
        return Val(tid, b)
    if tid == TypeID.INT:
        return Val(tid, struct.unpack("<q", b)[0])
    if tid == TypeID.FLOAT:
        return Val(tid, struct.unpack("<d", b)[0])
    if tid == TypeID.BOOL:
        return Val(tid, b == b"\x01")
    if tid == TypeID.DATETIME:
        return Val(tid, datetime.fromtimestamp(struct.unpack("<d", b)[0], tz=timezone.utc))
    if tid == TypeID.GEO:
        from dgraph_tpu.utils import geo as geomod

        return Val(tid, geomod.parse_geojson(b.decode("utf-8")))
    if tid == TypeID.UID:
        return Val(tid, struct.unpack("<Q", b)[0])
    if tid == TypeID.VECTOR:
        n = len(b) // 4
        return Val(tid, tuple(float(x) for x in struct.unpack(f"<{n}f", b)))
    raise ValueError(f"cannot unmarshal type {tid}")
