"""Metrics + request tracing (reference: x/metrics.go expvar counters at
/debug/vars, golang.org/x/net/trace request traces at /debug/requests with
sampled LazyPrintf breadcrumbs, edgraph/server.go:289,388).

Design: one Registry per server Node (tests run many embedded nodes — a
process-global expvar table like the reference's would bleed counts between
them). Counters take the GIL-side lock only on read-modify-write; histograms
keep a bounded ring of recent samples and compute percentiles on demand
rather than maintaining buckets (the /debug surface is low-QPS)."""

from __future__ import annotations

import bisect
import random
import threading
import time
from collections import deque


class Counter:
    __slots__ = ("_v", "_lock")

    def __init__(self) -> None:
        self._v = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._v += n

    def dec(self, n: int = 1) -> None:
        self.inc(-n)

    def set(self, v: int) -> None:
        """Gauge-style overwrite (dgraph_memory_bytes etc.)."""
        with self._lock:
            self._v = v

    @property
    def value(self) -> int:
        return self._v


def exp_buckets(start: float, factor: float, count: int) -> tuple:
    """Exponential bucket upper bounds: start * factor**i, i in [0, count).
    FIXED bounds are the whole point (ISSUE 13): histograms with identical
    bounds merge EXACTLY across nodes and over time — sum the per-bucket
    counts, _sum, and _count — which ring-sample quantiles never can."""
    out = []
    v = float(start)
    for _ in range(max(int(count), 1)):
        out.append(v)
        v *= factor
    return tuple(out)


# shared default bucket schemes, picked by metric-name suffix so every
# node exposes the same bounds for the same metric (merge exactness)
BUCKETS_SECONDS = exp_buckets(0.0005, 2.0, 16)       # 0.5ms .. ~16s
BUCKETS_MS = exp_buckets(0.05, 2.0, 18)              # 0.05ms .. ~6.5s
BUCKETS_BYTES = exp_buckets(256, 4.0, 14)            # 256B .. ~17GB
BUCKETS_COUNT = exp_buckets(1, 4.0, 16)              # 1 .. ~1e9


def default_buckets(name: str) -> tuple:
    if name.endswith("_s"):
        return BUCKETS_SECONDS
    if name.endswith("_ms"):
        return BUCKETS_MS
    if name.endswith("_bytes"):
        return BUCKETS_BYTES
    return BUCKETS_COUNT


class Histogram:
    """Fixed-bucket cumulative histogram + a bounded ring of recent
    samples.

    The buckets (`le` upper bounds, +Inf implicit) are the Prometheus
    exposition and the fleet-merge unit: identical bounds merge exactly
    across nodes (obs/prom.py renders them, Registry.export ships them).
    The ring keeps the /debug/metrics percentile readout (quantiles are
    NOT on /metrics anymore — they cannot be aggregated).

    Each bucket keeps at most one trace EXEMPLAR — the most recent
    observation that carried a sampled trace id — rendered in OpenMetrics
    `# {trace_id="..."} value ts` syntax so an operator can jump from a
    latency bucket straight to the trace that landed in it."""

    __slots__ = ("_ring", "_lock", "count", "total", "bounds",
                 "bucket_counts", "exemplars")

    def __init__(self, cap: int = 2048, buckets: tuple | None = None) -> None:
        self._ring: deque[float] = deque(maxlen=cap)
        self._lock = threading.Lock()
        self.count = 0
        self.total = 0.0
        self.bounds: tuple = tuple(buckets) if buckets else BUCKETS_COUNT
        self.bucket_counts = [0] * (len(self.bounds) + 1)
        # per-bucket (trace_id, value, unix_ts) — newest sampled wins
        self.exemplars: list[tuple | None] = [None] * (len(self.bounds) + 1)

    def _bucket_of(self, v: float) -> int:
        return bisect.bisect_left(self.bounds, v)

    def observe(self, v: float, exemplar: str | None = None) -> None:
        b = self._bucket_of(v)
        with self._lock:
            self._ring.append(v)
            self.count += 1
            self.total += v
            self.bucket_counts[b] += 1
            if exemplar:
                self.exemplars[b] = (exemplar, v, time.time())

    def snapshot(self) -> dict:
        """count is lifetime; mean and percentiles all describe the same
        recent window (the ring) so the distribution is self-consistent."""
        with self._lock:
            vals = sorted(self._ring)
            count = self.count
        if not vals:
            return {"count": count, "mean": 0.0}
        pick = lambda q: vals[min(len(vals) - 1, int(q * len(vals)))]
        return {"count": count,
                "mean": round(sum(vals) / len(vals), 6),
                "p50": pick(0.50), "p95": pick(0.95), "p99": pick(0.99),
                "max": vals[-1]}

    def export(self) -> dict:
        """Mergeable state: bounds + per-bucket counts + sum/count (+ the
        exemplars, which a merge keeps newest-first per bucket)."""
        with self._lock:
            return {"bounds": list(self.bounds),
                    "counts": list(self.bucket_counts),
                    "sum": self.total, "count": self.count,
                    "exemplars": [list(e) if e else None
                                  for e in self.exemplars]}


class Meter:
    """Sliding-window event rate (per-endpoint QPS for /debug/metrics).
    Marks keep a bounded timestamp ring; rate() PRUNES timestamps older
    than the retention window from the left (they can never count again)
    instead of rescanning the full ring per call — O(expired + recent),
    not O(cap). The ring bounds memory, so a sustained burst beyond `cap`
    events/window under-reports — `dropped` counts every mark that
    evicted a STILL-LIVE timestamp (one inside the retention window), so
    the QPS readout says when it is lying (snapshot())."""

    __slots__ = ("_ring", "_lock", "window", "dropped")

    def __init__(self, window: float = 10.0, cap: int = 8192) -> None:
        self.window = window
        self._ring: deque[float] = deque(maxlen=cap)
        self._lock = threading.Lock()
        self.dropped = 0

    def mark(self) -> None:
        now = time.monotonic()
        with self._lock:
            ring = self._ring
            if len(ring) == ring.maxlen and ring[0] >= now - self.window:
                # the append below evicts a mark the window still needs:
                # the rate is about to under-report
                self.dropped += 1
            ring.append(now)

    def snapshot(self) -> dict:
        """Rate plus its honesty bit: dropped > 0 means the window
        overflowed the ring and the qps number is a floor, not a rate."""
        return {"qps": self.rate(), "dropped": self.dropped}

    def rate(self, window: float | None = None) -> float:
        """Events/sec over the trailing `window` seconds, clamped to the
        meter's retention window: pruning discards marks older than
        self.window, so a wider request would silently undercount — it
        gets the full-retention rate instead."""
        w = min(window or self.window, self.window)
        now = time.monotonic()
        with self._lock:
            ring = self._ring
            # retention is the DEFAULT window: a narrower custom window
            # must not discard marks the next default-window call needs
            retain = now - self.window
            while ring and ring[0] < retain:
                ring.popleft()
            if w >= self.window:
                n = len(ring)
            else:
                cut = now - w
                n = 0
                for t in reversed(ring):   # recent marks sit at the right
                    if t < cut:
                        break
                    n += 1
        return round(n / w, 3)


class KeyedGauge:
    """Per-key integer gauges under one metric name (Prometheus labeled
    gauge shape) — per-predicate overlay depth, per-tablet sizes. Zero
    values drop their key so an idle predicate doesn't grow the map.

    `labels` names multi-dimensional keys: when set, keys are the label
    VALUES joined with '|' (e.g. labels=("pred", "group"), key
    "follows|2") and obs/prom.py renders them as separate Prometheus
    labels instead of the default key="..."."""

    __slots__ = ("_vals", "_lock", "labels")

    def __init__(self, labels: tuple[str, ...] | None = None) -> None:
        self._vals: dict[str, int] = {}
        self._lock = threading.Lock()
        self.labels = labels

    def set(self, key: str, v: int) -> None:
        with self._lock:
            if v:
                self._vals[key] = v
            else:
                self._vals.pop(key, None)

    def inc(self, key: str, n: int = 1) -> None:
        with self._lock:
            v = self._vals.get(key, 0) + n
            if v:
                self._vals[key] = v
            else:
                self._vals.pop(key, None)

    def get(self, key: str) -> int:
        # dict reads race dict writes in free-threaded builds, and even on
        # the GIL a concurrent resize can surface torn iteration states —
        # reads take the same lock the writers do
        with self._lock:
            return self._vals.get(key, 0)

    def snapshot(self) -> dict[str, int]:
        with self._lock:
            return dict(self._vals)


class Registry:
    """Named metrics with the reference's dgraph_* vocabulary pre-registered
    (x/metrics.go:27-76), plus the round-6 serving-layer counters (plan /
    task caches, singleflight, dispatch gate)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.counters: dict[str, Counter] = {}
        self.histograms: dict[str, Histogram] = {}
        self.meters: dict[str, Meter] = {}
        self.keyed_gauges: dict[str, KeyedGauge] = {}
        for name in ("dgraph_num_queries_total", "dgraph_num_mutations_total",
                     "dgraph_num_commits_total", "dgraph_num_aborts_total",
                     "dgraph_posting_reads_total",
                     "dgraph_posting_writes_total",
                     "dgraph_pending_queries_total",
                     "dgraph_active_mutations_total",
                     "dgraph_num_upserts_total", "dgraph_num_alters_total",
                     "dgraph_plan_cache_hits_total",
                     "dgraph_plan_cache_misses_total",
                     "dgraph_task_cache_hits_total",
                     "dgraph_task_cache_misses_total",
                     "dgraph_task_cache_evicted_total",
                     "dgraph_task_cache_inflight_waits_total",
                     "dgraph_task_cache_bytes",
                     "dgraph_result_cache_hits_total",
                     "dgraph_result_cache_misses_total",
                     "dgraph_result_cache_evicted_total",
                     "dgraph_result_cache_bytes",
                     "dgraph_dispatch_inflight",
                     "dgraph_dispatch_waits_total",
                     # delta-overlay maintenance tier (storage/delta.py)
                     "dgraph_overlay_stamps_total",
                     "dgraph_overlay_fold_fallbacks_total",
                     "dgraph_compactions_total",
                     "dgraph_cache_invalidations_avoided_total",
                     "dgraph_parallel_folds_total",
                     "dgraph_fold_pool_width",
                     # cost-based planner (query/planner.py) + live
                     # cardinality stats (storage/stats.py)
                     "dgraph_planner_plans_total",
                     "dgraph_planner_root_swaps_total",
                     "dgraph_planner_filter_reorders_total",
                     "dgraph_planner_child_reorders_total",
                     "dgraph_planner_host_expands_total",
                     "dgraph_planner_device_expands_total",
                     "dgraph_planner_cache_hits_total",
                     "dgraph_planner_cache_misses_total",
                     "dgraph_planner_fallbacks_total",
                     "dgraph_stats_builds_total",
                     "dgraph_stats_delta_updates_total",
                     # out-of-core ingest tier (ingest/, loader/)
                     "dgraph_ingest_spill_bytes_total",
                     "dgraph_ingest_spill_runs_total",
                     "dgraph_ingest_merge_fanin",
                     "dgraph_xidmap_lookups_total",
                     "dgraph_xidmap_shard_loads_total",
                     "dgraph_xidmap_evictions_total",
                     "dgraph_checkpoint_peak_transient_bytes",
                     # request lifelines (utils/deadline, utils/retry,
                     # utils/faults; ISSUE 7): retries, overload sheds,
                     # budget overruns, hedges, breaker trips, degraded
                     # reads, injected faults
                     "dgraph_retry_total",
                     "dgraph_shed_total",
                     "dgraph_deadline_exceeded_total",
                     "dgraph_hedge_fired_total",
                     "dgraph_breaker_open_total",
                     "dgraph_degraded_reads_total",
                     "dgraph_fault_injected_total",
                     # vector similarity index (storage/vecindex.py,
                     # ops/vector.py; ISSUE 8)
                     "dgraph_vector_searches_total",
                     "dgraph_vector_ivf_probes_total",
                     "dgraph_vector_fused_pipelines_total",
                     "dgraph_vector_mesh_dispatches_total",
                     # self-driving shard placement (coord/placement.py;
                     # ISSUE 10): controller ticks, actions, replica
                     # freshness ships, and the replica read/fallback
                     # counters on the query router
                     "dgraph_placement_ticks_total",
                     "dgraph_placement_moves_total",
                     "dgraph_placement_replicas_added_total",
                     "dgraph_placement_replicas_dropped_total",
                     "dgraph_placement_delta_ships_total",
                     "dgraph_placement_resyncs_total",
                     "dgraph_placement_cooldown_skips_total",
                     "dgraph_placement_errors_total",
                     "dgraph_replica_reads_total",
                     "dgraph_replica_fallbacks_total",
                     # batched multi-query dispatch (query/batch.py;
                     # ISSUE 9) — counters created by the batcher too,
                     # but a node with batching OFF must still expose
                     # them at 0 (the pre-registration invariant the
                     # audit test enforces mechanically, ISSUE 13)
                     "dgraph_batch_formed_total",
                     "dgraph_batch_tasks_total",
                     "dgraph_batch_window_waits_total",
                     "dgraph_batch_deadline_bypass_total",
                     # group-commit write window (storage/writebatch.py;
                     # ISSUE 16) — created by the WriteBatcher too, but a
                     # node with write batching OFF must still expose
                     # them at 0 (the same pre-registration invariant)
                     "dgraph_write_batch_formed_total",
                     "dgraph_write_batch_commits_total",
                     "dgraph_write_batch_fsyncs_total",
                     "dgraph_write_batch_window_waits_total",
                     "dgraph_write_batch_deadline_bypass_total",
                     "dgraph_write_batch_conflict_aborts_total",
                     # per-tenant window slot cap (ISSUE 20): commits a
                     # window-hogging tenant ran solo instead of batching
                     "dgraph_write_batch_tenant_solo_total",
                     # mesh deployment mode (parallel/mesh_exec.py;
                     # ISSUES 6 + 12)
                     "dgraph_mesh_dispatches_total",
                     "dgraph_mesh_fused_hops_total",
                     "dgraph_mesh_traversed_edges_total",
                     "dgraph_mesh_program_builds_total",
                     "dgraph_mesh_devices",
                     "dgraph_mesh_sharded_tablets",
                     "dgraph_mesh_replicated_tablets",
                     "dgraph_mesh_residency_deferred_total",
                     "dgraph_mesh_fused_queries_total",
                     "dgraph_mesh_unfused_queries_total",
                     "dgraph_mesh_replay_divergence_total",
                     # HBM working-set manager (storage/residency.py;
                     # ISSUE 11)
                     "dgraph_residency_hbm_bytes",
                     "dgraph_residency_host_bytes",
                     "dgraph_residency_admissions_total",
                     "dgraph_residency_evictions_total",
                     "dgraph_residency_prefetch_hits_total",
                     "dgraph_residency_prefetch_wasted_total",
                     "dgraph_residency_thrash_total",
                     "dgraph_residency_cold_serves_total",
                     "dgraph_residency_upload_failures_total",
                     "dgraph_residency_host_fallbacks_total",
                     "dgraph_residency_budget_overruns_total",
                     # host posting-list memory (Node.enforce_memory)
                     "dgraph_memory_bytes",
                     # query cost ledger (obs/costs.py; ISSUE 13)
                     "dgraph_cost_records_total",
                     "dgraph_cost_regressions_total",
                     "dgraph_cost_ship_failures_total",
                     # lazy on-demand snapshot folds (storage/csr_build
                     # LazyPreds/_FoldThunk; ISSUE 15): per-trigger fold
                     # counters plus the cold-open / first-query gauges
                     # the scale runbook reads
                     "dgraph_fold_lazy_total",
                     "dgraph_fold_eager_total",
                     "dgraph_fold_prefetch_total",
                     "dgraph_fold_inline_total",
                     "dgraph_fold_pending_tablets",
                     "dgraph_cold_open_ms",
                     "dgraph_first_query_ms",
                     # device aggregation + whole-graph analytics
                     # (ops/segments.py, query/groupby.py,
                     # query/analytics.py; ISSUE 17)
                     "dgraph_agg_device_reduces_total",
                     "dgraph_agg_host_reduces_total",
                     "dgraph_agg_terminal_ops_total",
                     "dgraph_analytics_runs_total",
                     "dgraph_analytics_host_fallbacks_total",
                     "dgraph_analytics_iterations_total",
                     "dgraph_analytics_edges_total",
                     # delta-journal retention (storage/store.py; ISSUE 18):
                     # keys/pinned_floor are gauges refreshed on scrape
                     "dgraph_delta_journal_keys",
                     "dgraph_delta_journal_overflows",
                     "dgraph_delta_journal_pinned_floor",
                     # live queries (live/manager.py, api/http.py; ISSUE 18)
                     "dgraph_subs_active",
                     "dgraph_subs_registered_total",
                     "dgraph_subs_notifications_total",
                     "dgraph_subs_wakeups_total",
                     "dgraph_subs_evals_total",
                     "dgraph_subs_windows_total",
                     "dgraph_subs_sheds_total",
                     "dgraph_subs_resyncs_total",
                     "dgraph_subs_expired_total",
                     "dgraph_subs_reaped_total",
                     "dgraph_subs_heartbeats_total",
                     # device-runtime observatory (obs/devprof.py;
                     # ISSUE 19) — created by the profiler too, but a
                     # node with --no_devprof must still expose them at
                     # 0 (the pre-registration invariant)
                     "dgraph_xla_compiles_total",
                     "dgraph_xla_retrace_storms_total",
                     "dgraph_devprof_dispatches_total",
                     "dgraph_devprof_hbm_pressure_total",
                     "dgraph_device_utilization",
                     "dgraph_devprof_hbm_budget_bytes"):
            self.counters[name] = Counter()
        # per-endpoint breaker state (0 closed / 1 half-open / 2 open)
        self.keyed_gauges["dgraph_breaker_state"] = KeyedGauge()
        # per-tablet live load counters (the placement controller's
        # inputs): key "<pred>|<group>|<stat>" renders as labeled series
        # dgraph_tablet_load{pred=,group=,stat=} with stat one of
        # reads/writes/bytes/serve_ms
        self.keyed_gauges["dgraph_tablet_load"] = KeyedGauge(
            labels=("pred", "group", "stat"))
        self.keyed_gauges["dgraph_mesh_fallbacks_total"] = KeyedGauge(
            labels=("reason",))
        self.keyed_gauges["dgraph_batch_incompatible"] = KeyedGauge()
        self.keyed_gauges["dgraph_overlay_depth"] = KeyedGauge()
        self.keyed_gauges["dgraph_residency_tier_bytes"] = KeyedGauge(
            labels=("tier",))
        self.keyed_gauges["dgraph_devprof_hbm_highwater_bytes"] = \
            KeyedGauge(labels=("tier",))
        # multi-tenant QoS (dgraph_tpu/tenancy/; ISSUE 20): per-tenant
        # cost attribution in cost-ledger units plus the shed counter —
        # labeled series so one Grafana row ranks tenants. Values are
        # integer floors of the registry's float accumulators (KeyedGauge
        # is integer; TenantRegistry keeps the exact floats).
        self.keyed_gauges["dgraph_tenant_device_ms_total"] = KeyedGauge(
            labels=("tenant",))
        self.keyed_gauges["dgraph_tenant_edges_total"] = KeyedGauge(
            labels=("tenant",))
        self.keyed_gauges["dgraph_tenant_bytes_total"] = KeyedGauge(
            labels=("tenant",))
        self.keyed_gauges["dgraph_tenant_shed_total"] = KeyedGauge(
            labels=("tenant",))
        for name in ("dgraph_query_latency_s", "dgraph_mutation_latency_s",
                     "dgraph_commit_latency_s", "dgraph_compaction_s",
                     "dgraph_planner_est_error_log2",
                     "dgraph_batch_occupancy",
                     "dgraph_write_batch_occupancy",
                     # per-request cost distributions off the ledger
                     # (obs/costs.py): aggregatable le-bucket histograms
                     # with trace exemplars, NOT ring quantiles
                     "dgraph_query_cost_device_ms",
                     "dgraph_query_cost_edges",
                     "dgraph_query_cost_bytes",
                     # per-tablet fold wall time (lazy/eager/prefetch/
                     # inline triggers alike; ISSUE 15)
                     "dgraph_fold_ms",
                     # per-endpoint HTTP latency (api/http.py observes
                     # these; pre-registered so a fresh node scrapes 0s)
                     "dgraph_http_query_latency_s",
                     "dgraph_http_mutate_latency_s",
                     "dgraph_http_commit_latency_s",
                     "dgraph_http_abort_latency_s",
                     "dgraph_http_alter_latency_s",
                     "dgraph_analytics_latency_s",
                     "dgraph_http_analytics_latency_s",
                     # live queries (ISSUE 18): commit-to-notify latency +
                     # subscribe registration time (SSE setup to first ack)
                     "dgraph_subs_notify_latency_s",
                     "dgraph_http_subscribe_latency_s",
                     # device-runtime observatory (obs/devprof.py;
                     # ISSUE 19): real XLA compile wall ms, gate
                     # queue-entry-to-launch gap, fenced dispatch ms
                     "dgraph_xla_compile_ms",
                     "dgraph_device_queue_gap_ms",
                     "dgraph_device_dispatch_ms"):
            self.histograms[name] = Histogram(
                buckets=default_buckets(name))

    def counter(self, name: str) -> Counter:
        with self._lock:
            return self.counters.setdefault(name, Counter())

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            h = self.histograms.get(name)
            if h is None:
                h = self.histograms[name] = Histogram(
                    buckets=default_buckets(name))
            return h

    def meter(self, name: str) -> Meter:
        with self._lock:
            return self.meters.setdefault(name, Meter())

    def keyed(self, name: str,
              labels: tuple[str, ...] | None = None) -> KeyedGauge:
        with self._lock:
            return self.keyed_gauges.setdefault(name, KeyedGauge(labels))

    def to_dict(self) -> dict:
        """expvar-style dump for /debug/vars."""
        out: dict = {c: m.value for c, m in sorted(self.counters.items())}
        out.update({h: m.snapshot() for h, m in sorted(self.histograms.items())})
        out.update({f"{n}_qps": m.rate() for n, m in sorted(self.meters.items())})
        out.update({f"{n}_meter_dropped": m.dropped
                    for n, m in sorted(self.meters.items()) if m.dropped})
        out.update({n: g.snapshot()
                    for n, g in sorted(self.keyed_gauges.items())})
        return out

    def export(self) -> dict:
        """Compact mergeable snapshot of the whole registry — the payload
        workers ship on the Status/load-report path (StatusResponse.
        metrics_json) and Zero's fleet aggregator merges. Counters and
        keyed gauges sum; fixed-bucket histograms merge EXACTLY because
        every node uses the same bounds per metric name."""
        with self._lock:
            counters = dict(self.counters)
            histograms = dict(self.histograms)
            keyed = dict(self.keyed_gauges)
        return {"counters": {n: c.value for n, c in counters.items()},
                "histograms": {n: h.export()
                               for n, h in histograms.items()},
                "keyed": {n: {"labels": list(g.labels) if g.labels else
                              None, "vals": g.snapshot()}
                          for n, g in keyed.items()}}


def merge_exports(snaps: list[dict]) -> dict:
    """Sum/merge per-node Registry.export() snapshots into one fleet
    view: counters and keyed-gauge values sum; histograms merge
    bucket-by-bucket (bounds must match — a mismatch drops the straggler
    series rather than producing a silently-wrong merge); exemplars keep
    the newest per bucket."""
    out = {"counters": {}, "histograms": {}, "keyed": {}}
    for snap in snaps:
        for n, v in snap.get("counters", {}).items():
            out["counters"][n] = out["counters"].get(n, 0) + int(v)
        for n, h in snap.get("histograms", {}).items():
            cur = out["histograms"].get(n)
            if cur is None:
                out["histograms"][n] = {
                    "bounds": list(h.get("bounds", [])),
                    "counts": list(h.get("counts", [])),
                    "sum": float(h.get("sum", 0.0)),
                    "count": int(h.get("count", 0)),
                    "exemplars": [list(e) if e else None
                                  for e in h.get("exemplars", [])]}
                continue
            if cur["bounds"] != list(h.get("bounds", [])):
                continue             # never merge mismatched bucket schemes
            cur["counts"] = [a + b for a, b in
                             zip(cur["counts"], h.get("counts", []))]
            cur["sum"] += float(h.get("sum", 0.0))
            cur["count"] += int(h.get("count", 0))
            for i, e in enumerate(h.get("exemplars", [])):
                if e and (i >= len(cur["exemplars"])
                          or cur["exemplars"][i] is None
                          or e[2] > cur["exemplars"][i][2]):
                    if i < len(cur["exemplars"]):
                        cur["exemplars"][i] = list(e)
        for n, g in snap.get("keyed", {}).items():
            cur = out["keyed"].setdefault(
                n, {"labels": g.get("labels"), "vals": {}})
            for k, v in g.get("vals", {}).items():
                cur["vals"][k] = cur["vals"].get(k, 0) + int(v)
    return out


class Trace:
    """One request's breadcrumb trail (net/trace analog)."""

    __slots__ = ("kind", "title", "t0", "events", "error", "elapsed")

    def __init__(self, kind: str, title: str) -> None:
        self.kind = kind
        self.title = title
        self.t0 = time.perf_counter()
        self.events: list[tuple[float, str]] = []
        self.error = ""
        self.elapsed = 0.0            # frozen by TraceStore.finish

    def printf(self, msg: str, *args) -> None:
        self.events.append((time.perf_counter() - self.t0,
                            msg % args if args else msg))

    def to_dict(self) -> dict:
        return {"kind": self.kind, "title": self.title,
                "elapsed_s": round(self.elapsed, 6),
                "error": self.error,
                "events": [{"t": round(t, 6), "msg": m}
                           for t, m in self.events]}


class _NullTrace:
    """Unsampled requests get a no-op trace — zero overhead breadcrumbs."""

    def printf(self, msg: str, *args) -> None:
        pass

    error = ""


NULL_TRACE = _NullTrace()


class TraceStore:
    """Sampled request traces, newest-first ring (reference: --trace fraction
    gating tr.New, /debug/requests rendering).

    rng is injectable (anything with .random()) so tests drive the
    sampling decision deterministically instead of flaking on the global
    unseeded generator."""

    def __init__(self, fraction: float = 1.0, keep: int = 64,
                 rng=None) -> None:
        self.fraction = fraction
        self.rng = rng if rng is not None else random
        self._ring: deque[Trace] = deque(maxlen=keep)
        self._lock = threading.Lock()

    def start(self, kind: str, title: str):
        if self.fraction <= 0 or \
                (self.fraction < 1.0 and self.rng.random() >= self.fraction):
            return NULL_TRACE
        return Trace(kind, title)

    def finish(self, tr, error: str = "") -> None:
        if tr is NULL_TRACE:
            return
        tr.error = error
        tr.elapsed = time.perf_counter() - tr.t0
        with self._lock:
            self._ring.appendleft(tr)

    def recent(self, n: int = 32) -> list[dict]:
        with self._lock:
            return [t.to_dict() for i, t in enumerate(self._ring) if i < n]
