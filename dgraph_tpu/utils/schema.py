"""Predicate schema: state, text parser, directives.

Reference semantics: schema/ — per-predicate SchemaEntry (type + directives
@index(tokenizers) / @reverse / @count / @upsert / @lang / list) held in an
in-memory map backed by SCHEMA keys in the store (schema/schema.go:44-56,
accessors :114-233; text parser schema/parse.go).

Schema text:   pred: type .            pred: [type] .        (list)
               pred: string @index(term, exact) @count @upsert .
               friend: uid @reverse @count .
"""

from __future__ import annotations

import re
import threading
from dataclasses import dataclass, field

from dgraph_tpu.utils import tok
from dgraph_tpu.utils.types import TypeID


VECTOR_METRICS = ("cosine", "l2", "dot")


@dataclass(frozen=True)
class VectorSpec:
    """@index(vector(dim: D[, metric: cosine|l2|dot])) — the TPU-native
    index type (ROADMAP item 4): a dense-embedding similarity index whose
    probe is a segmented matmul + top-k (storage/vecindex.py)."""

    dim: int
    metric: str = "cosine"

    def __str__(self) -> str:
        return f"vector(dim: {self.dim}, metric: {self.metric})"


@dataclass
class SchemaEntry:
    predicate: str
    type_id: TypeID = TypeID.DEFAULT
    is_list: bool = False
    tokenizers: list[str] = field(default_factory=list)  # @index(...)
    reverse: bool = False                                # @reverse
    count: bool = False                                  # @count
    upsert: bool = False                                 # @upsert
    lang: bool = False                                   # @lang
    vector: VectorSpec | None = None                     # @index(vector(...))

    @property
    def indexed(self) -> bool:
        return bool(self.tokenizers)

    def directives_str(self) -> str:
        parts = []
        if self.tokenizers:
            parts.append("@index(" + ", ".join(self.tokenizers) + ")")
        if self.vector is not None:
            parts.append(f"@index({self.vector})")
        if self.reverse:
            parts.append("@reverse")
        if self.count:
            parts.append("@count")
        if self.upsert:
            parts.append("@upsert")
        if self.lang:
            parts.append("@lang")
        return " ".join(parts)

    def __str__(self) -> str:
        from dgraph_tpu.utils.types import TYPE_NAMES

        t = TYPE_NAMES[self.type_id]
        if self.is_list:
            t = f"[{t}]"
        d = self.directives_str()
        return f"{self.predicate}: {t} {d + ' ' if d else ''}."


_LINE_RE = re.compile(
    r"^\s*(?P<pred>[^\s:]+)\s*:\s*(?P<list>\[)?\s*(?P<type>\w+)\s*\]?\s*(?P<dirs>[^.]*)\.\s*$"
)
_DIR_RE = re.compile(r"@(?P<name>\w+)(?:\((?P<args>[^)]*)\))?")
# the vector index form nests parens (@index(vector(dim: 8))), which the
# flat _DIR_RE cannot express — extracted separately before the flat scan
_VEC_RE = re.compile(r"@index\(\s*vector\s*\((?P<args>[^)]*)\)\s*\)")


def _parse_vector_spec(args: str, e: "SchemaEntry") -> VectorSpec:
    if e.type_id != TypeID.VECTOR:
        raise ValueError(
            f"@index(vector) needs float32vector type ({e.predicate})")
    if e.is_list:
        raise ValueError(
            f"@index(vector) on [float32vector] is unsupported ({e.predicate})")
    dim, metric = 0, "cosine"
    for part in args.split(","):
        part = part.strip()
        if not part:
            continue
        k, _, v = part.partition(":")
        k, v = k.strip(), v.strip()
        if k == "dim":
            try:
                dim = int(v)
            except ValueError:
                raise ValueError(
                    f"vector index dim must be an int ({e.predicate})") from None
        elif k == "metric":
            metric = v.strip("\"'").lower()
        else:
            raise ValueError(f"unknown vector index arg {k!r} ({e.predicate})")
    if dim < 1:
        raise ValueError(f"vector index needs dim >= 1 ({e.predicate})")
    if metric not in VECTOR_METRICS:
        raise ValueError(
            f"vector metric must be one of {VECTOR_METRICS} ({e.predicate})")
    return VectorSpec(dim=dim, metric=metric)


def parse_schema(text: str) -> list[SchemaEntry]:
    """Parse schema text into entries; validates tokenizer/type compatibility
    (reference: schema/parse.go)."""
    entries = []
    for raw in text.splitlines():
        line = raw.split("#", 1)[0].strip()
        if not line:
            continue
        m = _LINE_RE.match(line)
        if not m:
            raise ValueError(f"invalid schema line: {raw!r}")
        e = SchemaEntry(m.group("pred"))
        e.type_id = TypeID.from_name(m.group("type"))
        e.is_list = m.group("list") is not None
        dirs = m.group("dirs") or ""
        vm = _VEC_RE.search(dirs)
        if vm is not None:
            e.vector = _parse_vector_spec(vm.group("args"), e)
            dirs = dirs[: vm.start()] + dirs[vm.end():]
        for d in _DIR_RE.finditer(dirs):
            name, args = d.group("name"), d.group("args")
            if name == "index":
                toks = [a.strip() for a in (args or "").split(",") if a.strip()]
                if not toks:
                    toks = [tok.default_tokenizer(e.type_id).name]
                for t in toks:
                    tz = tok.get(t)
                    want = e.type_id if e.type_id != TypeID.DEFAULT else tz.type_id
                    if tz.type_id != want:
                        raise ValueError(
                            f"tokenizer {t!r} is for type {tz.type_id.name}, "
                            f"not {e.type_id.name} ({e.predicate})")
                e.tokenizers = toks
            elif name == "reverse":
                if e.type_id != TypeID.UID:
                    raise ValueError(f"@reverse needs uid type ({e.predicate})")
                e.reverse = True
            elif name == "count":
                e.count = True
            elif name == "upsert":
                e.upsert = True
            elif name == "lang":
                e.lang = True
            else:
                raise ValueError(f"unknown directive @{name} ({e.predicate})")
        if e.upsert and not e.indexed:
            raise ValueError(f"@upsert needs @index ({e.predicate})")
        entries.append(e)
    return entries


class SchemaState:
    """Mutable predicate→SchemaEntry map with mutation-time auto-population.

    Reference: schema/schema.go State() singleton; unknown predicates get a
    type inferred from the first mutation's value (schema.go:? mutation path),
    which we mirror in ensure().
    """

    def __init__(self) -> None:
        self._m: dict[str, SchemaEntry] = {}
        self._lock = threading.RLock()

    def set(self, e: SchemaEntry) -> None:
        with self._lock:
            self._m[e.predicate] = e

    def get(self, pred: str) -> SchemaEntry | None:
        with self._lock:
            return self._m.get(pred)

    def ensure(self, pred: str, tid: TypeID, is_list: bool = False) -> SchemaEntry:
        with self._lock:
            e = self._m.get(pred)
            if e is None:
                e = SchemaEntry(pred, tid, is_list=is_list)
                self._m[pred] = e
            elif e.type_id == TypeID.DEFAULT and tid != TypeID.DEFAULT:
                e.type_id = tid
            return e

    def delete(self, pred: str) -> None:
        with self._lock:
            self._m.pop(pred, None)

    def predicates(self) -> list[str]:
        with self._lock:
            return sorted(self._m)

    def entries(self) -> list[SchemaEntry]:
        with self._lock:
            return [self._m[p] for p in sorted(self._m)]

    def type_of(self, pred: str) -> TypeID:
        e = self.get(pred)
        return e.type_id if e else TypeID.DEFAULT

    def is_indexed(self, pred: str) -> bool:
        e = self.get(pred)
        return bool(e and e.tokenizers)

    def is_reversed(self, pred: str) -> bool:
        e = self.get(pred)
        return bool(e and e.reverse)

    def has_count(self, pred: str) -> bool:
        e = self.get(pred)
        return bool(e and e.count)

    def is_list(self, pred: str) -> bool:
        e = self.get(pred)
        return bool(e and e.is_list)

    def tokenizer_names(self, pred: str) -> list[str]:
        e = self.get(pred)
        return list(e.tokenizers) if e else []

    def vector_spec(self, pred: str) -> VectorSpec | None:
        e = self.get(pred)
        return e.vector if e else None

    def to_text(self) -> str:
        return "\n".join(str(e) for e in self.entries())


def schema_json(state: "SchemaState", preds: list[str] | None = None) -> list[dict]:
    """`schema {}` response entries (the reference's schema-query JSON
    shape, edgraph/server.go schema handling). Shared by the embedded
    server and the cluster client so the two surfaces cannot drift."""
    out = []
    for attr in (preds or state.predicates()):
        e = state.get(attr)
        if e is None:
            continue
        d: dict = {"predicate": e.predicate, "type": e.type_id.name.lower()}
        if e.indexed:
            d["index"] = True
            d["tokenizer"] = list(e.tokenizers)
        if e.vector is not None:
            d["index"] = True
            d["vector"] = {"dim": e.vector.dim, "metric": e.vector.metric}
        for flag in ("reverse", "count", "upsert", "lang"):
            if getattr(e, flag, False):
                d[flag] = True
        if e.is_list:
            d["list"] = True
        out.append(d)
    return out
