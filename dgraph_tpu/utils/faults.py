"""Faultline: seeded fault injection at the system's real seams.

Reference analog: the reference proves its failure handling with systest
clusters that kill/partition real processes; conn/pool.go Echo failures
and Raft CheckQuorum are the detection side. This registry is the
injection side for one process: named fault POINTS installed at the RPC
serve/send seams (worker serve_task, zero RPC send), disk I/O
(store WAL write, ingest spill), and the device-dispatch seam
(qcache.DispatchGate), each firing with a configured probability from a
DETERMINISTIC per-registry PRNG — the same seed replays the same fault
schedule, so chaos runs are debuggable, not flaky.

Modes:
  error  — raise FaultError (a ConnectionError: transport-shaped, so the
           retry/breaker machinery treats it like a real network fault)
  delay  — sleep `delay_s` then proceed (slow disk / slow peer)
  drop   — sleep `delay_s` (default 0) then raise FaultError — a request
           that disappeared; with a delay it models a blackholed send
           that only the caller's deadline bounds.

Activation:
  * env:  DGRAPH_TPU_FAULTS="worker.serve_task:error:0.1,disk.wal_write:
          delay:1.0:0.05"  (name:mode:p[:delay_s][:count]) and
          DGRAPH_TPU_FAULTS_SEED=42 — parsed at import for every process.
  * flag: `serve --faults ... --faults_seed N` (dgraph_tpu/__main__.py).
  * HTTP: POST /debug/faults {"install": {...}} / {"clear": true} — the
          chaos harness drives live processes through this.
  * code: faults.GLOBAL.install(...) in tests.

Fire sites pass their node's metrics registry so injections show as
dgraph_fault_injected_total on that node's /metrics. The disabled fast
path is one truthiness check of an empty dict — free on hot paths.
"""

from __future__ import annotations

import os
import random
import threading
import time


class FaultError(ConnectionError):
    """An injected transport-shaped failure."""


# fault-point names wired into the codebase (docs/ops.md runbook lists
# these; installing an unknown name is allowed but never fires)
POINTS = (
    "worker.serve_task",    # RPC serve seam: group task server
    "worker.mutate",        # RPC serve seam: group mutation apply
    "zero.rpc",             # RPC send seam: any ZeroClient call
    "rpc.send",             # RPC send seam: RemoteWorker.process_task
    "disk.wal_write",       # store WAL append/commit records
    "disk.fsync",           # the sync-write durability seam only (the
    # fsync a commit pays); a delay here emulates durable-disk sync cost
    # (bench_write's sync sweep — loopback-fs fsync is unrepresentative)
    "disk.spill",           # out-of-core ingest spill-run writes
    "device.dispatch",      # device-dispatch gate critical section
    "device.step",          # inside a held gate slot: slow device program
    # placement subsystem (coord/placement.py)
    "zero.rebalance_decide",  # controller tick, before acting on a pick
    "move.chunk_ship",      # per-chunk in the tablet move/replica stream
    "replica.delta_ship",   # replica freshness delta ship
    # device working-set manager (storage/residency.py): the H2D upload
    # seam every warm->hbm promotion crosses; query paths catch the
    # injected error and serve the byte-identical host gather
    "residency.h2d_upload",
)


class _Point:
    __slots__ = ("name", "mode", "p", "delay_s", "count", "fired")

    def __init__(self, name: str, mode: str, p: float,
                 delay_s: float, count: int | None) -> None:
        if mode not in ("error", "delay", "drop"):
            raise ValueError(f"unknown fault mode {mode!r}")
        self.name = name
        self.mode = mode
        self.p = float(p)
        self.delay_s = float(delay_s)
        self.count = count                 # remaining fires (None = forever)
        self.fired = 0

    def snapshot(self) -> dict:
        return {"mode": self.mode, "p": self.p, "delay_s": self.delay_s,
                "remaining": self.count, "fired": self.fired}


class FaultRegistry:
    """Named fault points with one seeded PRNG. The registry is usually
    the module GLOBAL (one per process, like the env the reference's
    systest kills operate on); tests may build private instances."""

    def __init__(self, seed: int | None = None) -> None:
        self._lock = threading.Lock()
        self._points: dict[str, _Point] = {}
        self._rng = random.Random(seed)
        self.seed = seed

    def reseed(self, seed: int | None) -> None:
        with self._lock:
            self._rng = random.Random(seed)
            self.seed = seed

    def install(self, name: str, mode: str = "error", p: float = 1.0,
                delay_s: float = 0.0, count: int | None = None) -> None:
        pt = _Point(name, mode, p, delay_s, count)
        with self._lock:
            self._points[name] = pt

    def clear(self, name: str | None = None) -> None:
        with self._lock:
            if name is None:
                self._points.clear()
            else:
                self._points.pop(name, None)

    def configure(self, spec: str) -> None:
        """Parse 'name:mode:p[:delay_s][:count]' entries separated by
        commas (the env/flag format)."""
        for item in (spec or "").split(","):
            item = item.strip()
            if not item:
                continue
            parts = item.split(":")
            if len(parts) < 2:
                raise ValueError(f"bad fault spec {item!r} "
                                 "(want name:mode[:p[:delay_s[:count]]])")
            name, mode = parts[0], parts[1]
            # empty optional fields keep their defaults ("a:error::0.5"
            # sets delay without restating p)
            p = float(parts[2]) if len(parts) > 2 and parts[2] else 1.0
            delay_s = float(parts[3]) if len(parts) > 3 and parts[3] \
                else 0.0
            count = int(parts[4]) if len(parts) > 4 and parts[4] else None
            self.install(name, mode, p, delay_s, count)

    def snapshot(self) -> dict:
        with self._lock:
            return {"seed": self.seed,
                    "points": {n: p.snapshot()
                               for n, p in self._points.items()}}

    def fire(self, name: str, m=None) -> None:
        """Evaluate one fault point. Fast no-op when nothing is installed.
        `m` is the local metrics Registry (dgraph_fault_injected_total)."""
        if not self._points:
            return
        with self._lock:
            pt = self._points.get(name)
            if pt is None:
                return
            if pt.count is not None and pt.count <= 0:
                return
            if pt.p < 1.0 and self._rng.random() >= pt.p:
                return
            pt.fired += 1
            if pt.count is not None:
                pt.count -= 1
            mode, delay_s = pt.mode, pt.delay_s
        if m is not None:
            try:
                m.counter("dgraph_fault_injected_total").inc()
            except Exception:
                pass
        from ..obs import otrace
        from . import deadline as dl

        otrace.event("fault_injected", point=name, mode=mode)
        if delay_s > 0:
            # an in-process delay is synchronous on the request thread, so
            # the deadline cannot preempt it — clamp the injected sleep to
            # the caller's remaining budget (+ a hair past it, so the next
            # wait point sees the budget as spent), the way a real slow
            # step is bounded by the RPC timeout across the wire
            rem = dl.remaining()
            if rem is not None:
                delay_s = min(delay_s, max(rem, 0.0) + 0.005)
            time.sleep(delay_s)
        if mode in ("error", "drop"):
            raise FaultError(f"injected fault at {name} ({mode})")


GLOBAL = FaultRegistry()


def fire(name: str, m=None) -> None:
    """Evaluate `name` against the process-global registry."""
    GLOBAL.fire(name, m)


def init_from_env() -> None:
    """Arm the global registry from DGRAPH_TPU_FAULTS[_SEED] (called at
    import so every subcommand/process honors the env contract)."""
    seed = os.environ.get("DGRAPH_TPU_FAULTS_SEED")
    if seed is not None:
        try:
            GLOBAL.reseed(int(seed))
        except ValueError:
            pass
    spec = os.environ.get("DGRAPH_TPU_FAULTS")
    if spec:
        GLOBAL.configure(spec)


init_from_env()
