"""Unified retry policy + per-endpoint circuit breaking.

Reference semantics: the reference client retries aborted txns and failed
RPCs with backoff (x/x.go RetryUntilSuccess-shape loops, conn/pool.go
reconnect backoff) and routes around unhealthy peers via Echo health
state. This module replaces the repo's ad-hoc loops (parallel/client.py
mutate's bare `except Exception` + fixed 0.1s sleep, coord/zero_service
ZeroClient._rpc's fixed 0.2s rotation sleep) with one policy:

  * RetryPolicy — exponential backoff with FULL jitter (AWS-style:
    sleep = uniform(0, min(cap, base * 2^attempt))), a per-request retry
    budget, deadline awareness (never sleeps past the active deadline,
    never retries DeadlineExceeded), and an explicit retryable-error
    contract: by default only transport-shaped failures retry — a
    programming error propagates on the first throw.
  * CircuitBreaker — closed / open / half-open per endpoint, fed by the
    same error/latency signals the hedger sees. A flapping replica trips
    open after `fail_threshold` consecutive transport failures; while
    open, routing skips it instead of paying its timeout per request;
    after `open_s` one half-open probe is admitted and its outcome closes
    or re-opens the breaker.
  * CommitAmbiguous — a txn whose commit decision cannot be known (the
    commit RPC timed out in flight, or the Decide fan-out failed after a
    successful commit). NEVER retried: re-running the txn could apply it
    twice (blank nodes would mint fresh uids).
"""

from __future__ import annotations

import random
import threading
import time

from .deadline import DeadlineExceeded
from . import deadline as dl_mod


class CommitAmbiguous(Exception):
    """The commit decision's outcome is unknown (in-flight timeout) or a
    committed txn's Decide fan-out failed. Not retryable by design."""

    code = "COMMIT_AMBIGUOUS"


def transport_errors() -> tuple:
    """The transport-shaped error classes a retry may assume were not a
    programming error: connection loss, RPC failure, and the replication
    layer's quorum loss. RuntimeError is included for the repo's
    'no live leader' / 'no connection to group' routing errors."""
    from ..parallel.remote import NoQuorum

    errs: list[type] = [ConnectionError, OSError, TimeoutError,
                        NoQuorum, RuntimeError]
    try:
        import grpc

        errs.append(grpc.RpcError)
    except ImportError:                       # pragma: no cover
        pass
    return tuple(errs)


def backoff_s(attempt: int, base_s: float = 0.05, cap_s: float = 1.0,
              rng=None) -> float:
    """Full-jitter exponential backoff for the given 0-based attempt."""
    ceiling = min(cap_s, base_s * (2 ** attempt))
    return (rng or random).uniform(0, ceiling)


class RetryPolicy:
    """One request's retry discipline. Stateless across calls (safe to
    share); the per-request budget is tracked inside run()."""

    def __init__(self, max_attempts: int = 4, base_s: float = 0.05,
                 cap_s: float = 1.0, budget_s: float | None = None,
                 rng=None, metrics=None, name: str = "") -> None:
        self.max_attempts = max(1, int(max_attempts))
        self.base_s = float(base_s)
        self.cap_s = float(cap_s)
        self.budget_s = budget_s          # total sleep budget across retries
        self.rng = rng or random
        self.metrics = metrics
        self.name = name

    def run(self, fn, retryable: tuple | None = None,
            abort_on: tuple = (), on_retry=None):
        """Call fn() with retries. `retryable` errors (default: transport
        shapes) back off and retry; `abort_on` errors — and DeadlineExceeded
        / CommitAmbiguous, always — propagate immediately. on_retry(exc) is
        invoked before each re-attempt (cache invalidation hooks)."""
        if retryable is None:
            retryable = transport_errors()
        never = (DeadlineExceeded, CommitAmbiguous) + tuple(abort_on)
        slept = 0.0
        last: BaseException | None = None
        for attempt in range(self.max_attempts):
            try:
                return fn()
            except never:
                raise
            except retryable as e:
                last = e
                if attempt == self.max_attempts - 1:
                    raise
                pause = backoff_s(attempt, self.base_s, self.cap_s, self.rng)
                if self.budget_s is not None and \
                        slept + pause > self.budget_s:
                    raise
                rem = dl_mod.remaining()
                if rem is not None and pause >= rem:
                    # sleeping would blow the deadline: surface the cause
                    raise
                if self.metrics is not None:
                    self.metrics.counter("dgraph_retry_total").inc()
                from ..obs import costs, otrace

                costs.note("retries")
                otrace.event("retry", op=self.name or "call",
                             attempt=attempt + 1,
                             error=type(e).__name__, backoff_ms=
                             round(pause * 1000.0, 1))
                if on_retry is not None:
                    on_retry(e)
                time.sleep(pause)
                slept += pause
        raise last if last else RuntimeError("retry exhausted")


class CircuitBreaker:
    """Per-endpoint closed/open/half-open breaker.

    State values match the dgraph_breaker_state gauge: 0 = closed,
    1 = half-open, 2 = open. Latency feeds in as a soft failure when
    `latency_threshold_s` is set (the hedger's slow-replica signal);
    transport errors are hard failures. Thread-safe; `clock` is
    injectable for tests."""

    CLOSED, HALF_OPEN, OPEN = 0, 1, 2

    def __init__(self, fail_threshold: int = 5, open_s: float = 5.0,
                 latency_threshold_s: float | None = None,
                 clock=time.monotonic) -> None:
        self.fail_threshold = max(1, int(fail_threshold))
        self.open_s = float(open_s)
        self.latency_threshold_s = latency_threshold_s
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._fails = 0
        self._opened_at = 0.0
        self._probing = False
        self._probe_at = 0.0

    @property
    def state(self) -> int:
        with self._lock:
            self._maybe_half_open_locked()
            return self._state

    def _maybe_half_open_locked(self) -> None:
        if self._state == self.OPEN and \
                self._clock() - self._opened_at >= self.open_s:
            self._state = self.HALF_OPEN
            self._probing = False

    def allow(self) -> bool:
        """May a request be routed to this endpoint right now? Open:
        no. Half-open: exactly one in-flight probe — granting consumes
        the probe token; record() (either outcome) releases it, and a
        token whose request never reported back expires after open_s so
        a dropped probe cannot wedge the breaker half-open forever."""
        with self._lock:
            self._maybe_half_open_locked()
            if self._state == self.CLOSED:
                return True
            if self._state != self.HALF_OPEN:
                return False
            if self._probing and \
                    self._clock() - self._probe_at >= self.open_s:
                self._probing = False       # stale probe: token expired
            if not self._probing:
                self._probing = True
                self._probe_at = self._clock()
                return True
            return False

    def record(self, ok: bool, latency_s: float | None = None) -> None:
        """Feed one outcome. A success that was slower than the latency
        threshold counts as a (soft) failure — a consistently slow replica
        trips the breaker the same way a failing one does."""
        if ok and latency_s is not None and \
                self.latency_threshold_s is not None and \
                latency_s > self.latency_threshold_s:
            ok = False
        with self._lock:
            if ok:
                self._state = self.CLOSED
                self._fails = 0
                self._probing = False
                return
            self._fails += 1
            self._probing = False
            if self._state == self.HALF_OPEN or \
                    self._fails >= self.fail_threshold:
                self._state = self.OPEN
                self._opened_at = self._clock()
