"""Shared election driver: the mechanical half of a Raft-style ballot.

Both replication planes run the same failure-detector loop — leaders ping
on a fixed cadence, followers campaign after a randomized silence window —
while their vote/grant rules and promotion effects differ (workers grant on
the (max_commit_ts, log_len) up-to-date rule and install WAL shipping;
zeros grant on the shipped state sequence and reload Zero from replicated
state). This module owns the LOOP; the planes own the RPCs.

Reference: conn/node.go:47-105 (etcd-raft tick/election loop, CheckQuorum),
redesigned as one reusable driver for `parallel/remote.WorkerService` and
`coord/zero_service.ZeroReplica` (review finding: the two hand-rolled
copies had already diverged once).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable


class BallotLoop:
    """Run `send_pings()` every ping_s while `is_leader()`; otherwise run
    `campaign()` once `leader_contact()` has been silent longer than a
    randomized timeout (re-randomized per round, Raft's split-vote
    avoidance). `campaign` may raise — the loop must survive anything."""

    def __init__(self, *, is_leader: Callable[[], bool],
                 send_pings: Callable[[], None],
                 campaign: Callable[[], None],
                 leader_contact: Callable[[], float],
                 touch_contact: Callable[[], None],
                 ping_s: float, timeout_range: tuple[float, float],
                 tick_s: float = 0.1,
                 stop_event: threading.Event | None = None) -> None:
        self._is_leader = is_leader
        self._send_pings = send_pings
        self._campaign = campaign
        self._leader_contact = leader_contact
        self._touch_contact = touch_contact
        self._ping_s = ping_s
        self._timeout_range = timeout_range
        self._tick_s = tick_s
        # an externally-owned event makes stop-before-start safe: a loop
        # constructed after the event was set exits on its first tick
        self._stop = stop_event if stop_event is not None \
            else threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        if self._thread is None:
            # dgraph: allow(ctxvar-copy) detached ballot tick bg loop
            self._thread = threading.Thread(target=self._run, daemon=True)
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        timeout = random.uniform(*self._timeout_range)
        last_ping = 0.0
        while not self._stop.wait(self._tick_s):
            now = time.monotonic()
            if self._is_leader():
                if now - last_ping >= self._ping_s:
                    last_ping = now
                    try:
                        self._send_pings()
                    except Exception:
                        pass
                continue
            if now - self._leader_contact() > timeout:
                try:
                    self._campaign()
                except Exception:
                    pass
                timeout = random.uniform(*self._timeout_range)
                self._touch_contact()


def tally(votes_granted: int, member_count: int) -> bool:
    """Majority of the FULL member set (dead members count against)."""
    return votes_granted >= member_count // 2 + 1
