"""End-to-end request deadlines (the request-lifeline contract).

Reference semantics: every gRPC call in the reference carries a
context.Context deadline — ProcessTaskOverNetwork, Zero oracle calls, and
the applied-watermark waits all give up when the caller's budget runs out
(x/x.go timeouts, worker/task.go ctx plumbing). Python has no ambient
context, so the budget rides a contextvar in-process and a gRPC invocation
metadata key (`WIRE_KEY`, milliseconds remaining) across process
boundaries — exactly like obs/otrace span propagation.

Contract: a request that exceeds its budget returns a typed
DeadlineExceeded (or the gRPC DEADLINE_EXCEEDED status over the wire),
never a hang. Every wait point — dispatch-gate acquisition, hedged-replica
grace, RPC timeouts, applied-watermark waits, Zero failover backoff —
clamps to the remaining budget via `clamp()`/`check()`.

Overload shedding raises the sibling ResourceExhausted: the request was
rejected *before* consuming device time because its remaining budget could
not cover the expected step (query/qcache.DispatchGate).
"""

from __future__ import annotations

import contextvars
import time


class DeadlineExceeded(Exception):
    """The request's end-to-end budget ran out. Typed — callers must not
    blind-retry it (the budget is gone) and the retry layer never does."""

    code = "DEADLINE_EXCEEDED"


class ResourceExhausted(Exception):
    """Shed under overload: the remaining budget cannot cover the expected
    work (or the queue is full), so the request is rejected up front
    instead of wasting device time it cannot finish in."""

    code = "RESOURCE_EXHAUSTED"


# gRPC invocation metadata key: remaining budget in ms at send time (keys
# must be lowercase ASCII; -bin suffix is reserved for binary values)
WIRE_KEY = "dgt-deadline-ms"


class Deadline:
    """One request's absolute expiry on the monotonic clock."""

    __slots__ = ("expires", "budget_s")

    def __init__(self, budget_s: float) -> None:
        self.budget_s = float(budget_s)
        self.expires = time.monotonic() + self.budget_s

    @classmethod
    def after_ms(cls, ms: float) -> "Deadline":
        return cls(float(ms) / 1000.0)

    def remaining(self) -> float:
        """Seconds left (may be <= 0)."""
        return self.expires - time.monotonic()

    def expired(self) -> bool:
        return self.remaining() <= 0

    def check(self, what: str = "") -> None:
        rem = self.remaining()
        if rem <= 0:
            raise DeadlineExceeded(
                f"deadline exceeded{f' at {what}' if what else ''} "
                f"(budget {self.budget_s * 1000:.0f}ms, "
                f"over by {-rem * 1000:.0f}ms)")

    def clamp(self, timeout: float | None) -> float:
        """min(timeout, remaining), floored at 0 — the per-wait timeout a
        budgeted request may spend at one wait point."""
        rem = max(self.remaining(), 0.0)
        if timeout is None:
            return rem
        return min(float(timeout), rem)


_current: contextvars.ContextVar[Deadline | None] = \
    contextvars.ContextVar("dgt_deadline", default=None)


def current() -> Deadline | None:
    return _current.get()


def remaining() -> float | None:
    """Seconds left on the active deadline, or None when unbudgeted."""
    dl = _current.get()
    return None if dl is None else dl.remaining()


def clamp(timeout: float | None) -> float | None:
    """Clamp a wait to the active budget; identity when unbudgeted."""
    dl = _current.get()
    return timeout if dl is None else dl.clamp(timeout)


def check(what: str = "") -> None:
    """Raise DeadlineExceeded when the active budget has run out; no-op
    when unbudgeted. Cheap enough for per-task seams."""
    dl = _current.get()
    if dl is not None:
        dl.check(what)


class _NullScope:
    """Shared no-op scope for unbudgeted requests (stateless, reusable).
    Keeps the disabled path at one isinstance check + two no-op calls."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *_exc):
        return False


_NULL_SCOPE = _NullScope()


class _Scope:
    """Class-based scope (a contextlib generator costs ~5µs per
    enter/exit — measurable against a ~200µs cached query; this is
    ~1µs)."""

    __slots__ = ("dl", "_tok")

    def __init__(self, dl: Deadline) -> None:
        self.dl = dl

    def __enter__(self) -> Deadline:
        dl = self.dl
        outer = _current.get()
        if outer is not None and outer.expires < dl.expires:
            dl = self.dl = outer
        self._tok = _current.set(dl)
        return dl

    def __exit__(self, *_exc):
        _current.reset(self._tok)
        return False


def scope(budget: "Deadline | float | int | None"):
    """Install a deadline for the dynamic extent of a request. Accepts a
    Deadline, a budget in SECONDS, or None (no-op). A nested scope never
    EXTENDS an enclosing deadline — the tighter bound wins, so a callee's
    default budget cannot outlive its caller's."""
    if budget is None:
        return _NULL_SCOPE
    return _Scope(budget if isinstance(budget, Deadline)
                  else Deadline(float(budget)))


class _AdoptScope:
    __slots__ = ("dl", "_tok")

    def __init__(self, dl: Deadline | None) -> None:
        self.dl = dl

    def __enter__(self) -> Deadline | None:
        self._tok = _current.set(self.dl)
        return self.dl

    def __exit__(self, *_exc):
        _current.reset(self._tok)
        return False


def adopt(dl: "Deadline | None"):
    """Install EXACTLY `dl` for the dynamic extent — None clears the
    ambient deadline; unlike scope(), an enclosing tighter deadline does
    NOT win. For an agent executing pooled work on behalf of SEVERAL
    callers (the batched-dispatch leader, query/batch.py): the pool's
    budget is the most permissive member's, not whichever member happened
    to lead, so one tight-budget leader cannot shed work that other
    members had ample time for."""
    return _AdoptScope(dl)


# -- wire propagation (gRPC invocation metadata) ----------------------------

def to_metadata() -> tuple | None:
    """(WIRE_KEY, remaining-ms) for the active deadline, or None. Send-side
    clamping: the callee receives what is left NOW, so queueing on the
    caller's side has already been charged."""
    dl = _current.get()
    if dl is None:
        return None
    return (WIRE_KEY, f"{max(dl.remaining(), 0.0) * 1000.0:.1f}")


def from_metadata(md) -> Deadline | None:
    """Parse a propagated deadline out of invocation metadata pairs."""
    for k, v in md or ():
        if k == WIRE_KEY:
            try:
                return Deadline.after_ms(float(v))
            except (TypeError, ValueError):
                return None
    return None
