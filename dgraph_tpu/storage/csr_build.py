"""Build immutable device snapshots: posting store → HBM-resident CSR graphs.

This is the load-bearing TPU redesign (SURVEY.md §7): the reference reads
posting lists one (predicate, uid) at a time through an LRU over badger
(posting/lists.go Get → mvcc.ReadPostingList), merging the mutable layer on
every read. Here a *snapshot at read_ts* is folded once into flat arrays and
uploaded; the device then serves every read of that epoch with zero host
round-trips:

  - uid predicates      → forward CSR (subjects / indptr / indices) and, for
                          @reverse predicates, a reverse CSR
                          (ReverseKey tablets, posting/index.go:190).
  - indexed predicates  → per-tokenizer token→uid CSR. The host keeps the
                          sorted term list; inequality functions binary-search
                          it and the device unions the chosen token rows
                          (worker/tokens.go:124 getInequalityTokens redesigned
                          as an expand over token rows).
  - value predicates    → host-side exact {uid: Val} map (post-filters,
                          output encoding) plus a best-effort numeric mirror
                          aligned to value_subjects for device aggregation.
  - count index         → implicit: degree = indptr[i+1]-indptr[i] on device
                          (CountKey tablets exist host-side for exactness).

Snapshot isolation falls out naturally: a snapshot is just read_ts plus
immutable arrays; concurrent txns keep writing to the store and later epochs
build new snapshots (posting/mvcc.go's readTs gating, without device MVCC).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Iterable

import numpy as np
import jax.numpy as jnp

from dgraph_tpu.storage import keys as K
from dgraph_tpu.storage.postings import VALUE_UID, PostingList
from dgraph_tpu.storage.store import Store
from dgraph_tpu.utils.types import TypeID, Val, to_device_scalar

MAX_DEVICE_UID = 2**31 - 2  # int32 space, sentinel-exclusive


class PredCSR:
    """Adjacency of one predicate: row r = subjects[r] → indices[indptr[r]:indptr[r+1]].

    Residency refactor (storage/residency.py): the HOST numpy columns are
    the authoritative fold; the device columns are a droppable cache that
    uploads lazily on first kernel access and — when a ResidencyManager
    is attached at fold time — admits against the node's device-byte
    budget (evicting colder tablets) and can be demoted back to the warm
    host tier without touching this object's identity. Identity stability
    is the contract qcache per-predicate tokens, the DeviceBatcher's
    same-CSR-object rule, and mesh placement caches all rely on."""

    # residency owner protocol (set by ResidencyManager.adopt_pred)
    _res = None
    _res_attr = ""
    _res_kind = "csr"

    def __init__(self, subjects, indptr, indices) -> None:
        self._subjects_h = np.asarray(subjects)   # int32[N] sorted
        self._indptr_h = np.asarray(indptr)       # int32[N+1]
        self._indices_h = np.asarray(indices)     # int32[E] sorted per row
        self._dev: tuple | None = None            # droppable device cache
        self._max_degree: int | None = None       # lazy per-snapshot const

    @property
    def num_subjects(self) -> int:
        return int(self._subjects_h.shape[0])

    @property
    def num_edges(self) -> int:
        return int(self._indices_h.shape[0])

    # -- device tier ----------------------------------------------------------

    def device_arrays(self, prefetch: bool = False) -> tuple:
        """(subjects, indptr, indices) on device — the HBM tier. Uploads
        on first access through the residency seam (budget admission +
        the residency.h2d_upload fault point) when managed."""
        from dgraph_tpu.storage import residency as resmod

        return resmod.ensure_device(
            self, "_dev",
            lambda: (jnp.asarray(self._subjects_h),
                     jnp.asarray(self._indptr_h),
                     jnp.asarray(self._indices_h)),
            prefetch=prefetch)

    @property
    def subjects(self):
        return self.device_arrays()[0]

    @property
    def indptr(self):
        return self.device_arrays()[1]

    @property
    def indices(self):
        return self.device_arrays()[2]

    def device_resident(self) -> bool:
        return self._dev is not None

    def drop_device(self) -> None:
        """Demote to the warm tier: free the device buffers, keep the
        host fold. Kernels mid-flight keep their array references alive;
        the next device access re-uploads byte-identical columns."""
        self._dev = None

    def device_nbytes(self) -> int:
        return int(self._subjects_h.nbytes + self._indptr_h.nbytes
                   + self._indices_h.nbytes)

    def host_nbytes(self) -> int:
        return self.device_nbytes()

    def prefer_host(self) -> bool:
        """Tier consult for the query layer: True = COLD (footprint
        exceeds the whole device budget) — serve via the host-cutover
        machinery instead of uploading."""
        from dgraph_tpu.storage import residency as resmod

        return resmod.prefer_host(self)

    # -- host tier ------------------------------------------------------------

    def host_arrays(self) -> tuple:
        """(subjects, indptr, indices) as numpy — the warm-tier truth:
        frontier→row mapping, degree counting, and recurse edge-dedup run
        per expand and never touch the device."""
        return (self._subjects_h, self._indptr_h, self._indices_h)

    def max_degree(self) -> int:
        """Largest row length — cached: capacity sizing (the fused ANN
        pipeline's ecap) runs per query and must not rescan indptr."""
        if self._max_degree is None:
            ptr = self._indptr_h
            self._max_degree = int(np.max(ptr[1:] - ptr[:-1])) \
                if len(ptr) > 1 else 0
        return self._max_degree


class TokenIndex:
    """token→uid CSR for one (predicate, tokenizer). Same host-truth +
    droppable-device-cache shape as PredCSR (the residency tiers)."""

    _res = None
    _res_attr = ""
    _res_kind = "index"

    def __init__(self, terms: list[bytes], indptr, uids) -> None:
        self.terms = terms      # sorted; host-side (binary-searched)
        self._indptr_h = np.asarray(indptr)   # int32[T+1]
        self._uids_h = np.asarray(uids)       # int32[sum lens], sorted/row
        self._dev: tuple | None = None
        self._host: tuple | None = None       # lazy (indptr, uids64)

    def term_row(self, term: bytes) -> int:
        import bisect

        i = bisect.bisect_left(self.terms, term)
        return i if i < len(self.terms) and self.terms[i] == term else -1

    def device_arrays(self, prefetch: bool = False) -> tuple:
        from dgraph_tpu.storage import residency as resmod

        return resmod.ensure_device(
            self, "_dev",
            lambda: (jnp.asarray(self._indptr_h),
                     jnp.asarray(self._uids_h)),
            prefetch=prefetch)

    @property
    def indptr(self):
        return self.device_arrays()[0]

    @property
    def uids(self):
        return self.device_arrays()[1]

    def device_resident(self) -> bool:
        return self._dev is not None

    def drop_device(self) -> None:
        self._dev = None

    def device_nbytes(self) -> int:
        return int(self._indptr_h.nbytes + self._uids_h.nbytes)

    def host_nbytes(self) -> int:
        return self.device_nbytes()

    def prefer_host(self) -> bool:
        from dgraph_tpu.storage import residency as resmod

        return resmod.prefer_host(self)

    def host_arrays(self) -> tuple[np.ndarray, np.ndarray]:
        """(indptr, uids int64) host mirrors (index sorts / bucket walks
        are host-orchestrated and never touch the device)."""
        if self._host is None:
            self._host = (self._indptr_h,
                          self._uids_h.astype(np.int64))
        return self._host


@dataclass
class PredData:
    attr: str
    type_id: TypeID
    csr: PredCSR | None = None
    rev_csr: PredCSR | None = None
    value_subjects: jnp.ndarray | None = None    # int32[N] sorted uids with a value
    value_subjects_host: np.ndarray | None = None  # int64[N] host mirror (searches)
    num_values: jnp.ndarray | None = None        # float32[N] numeric mirror (NaN=non-numeric)
    num_values_host: np.ndarray | None = None    # float64[N] exact mirror (compares)
    host_values: dict[int, Val] = field(default_factory=dict)
    # [type] list predicates: every value per subject (host_values keeps the
    # first for single-value compare/sort paths)
    list_values: dict[int, list[Val]] = field(default_factory=dict)
    lang_values: dict[int, dict[str, Val]] = field(default_factory=dict)
    facets: dict[tuple[int, int], tuple] = field(default_factory=dict)  # (subj,obj/slot)->facets
    indexes: dict[str, TokenIndex] = field(default_factory=dict)
    # @index(vector) predicates: row-aligned embedding matrix + IVF
    # (storage/vecindex.VectorIndex, or VecOverlay when delta-stamped)
    vecindex: object | None = None

    def has_subjects(self) -> np.ndarray:
        """uids for has(attr): subjects with any edge or value (host
        mirrors — a device fetch per query would pay transfer latency for
        an array the host already holds)."""
        outs = []
        if self.csr is not None:
            sub_fn = getattr(self.csr, "subjects_host", None)
            if sub_fn is not None:
                # delta overlay (storage/delta.OverlayCSR): merged subjects
                # without forcing the full edge merge
                outs.append(sub_fn())
            elif hasattr(self.csr, "host_arrays"):
                outs.append(self.csr.host_arrays()[0])
            else:   # mesh-sharded tablet (DistPredCSR): device fetch
                outs.append(np.asarray(self.csr.subjects))
        if self.value_subjects_host is not None:
            outs.append(self.value_subjects_host)
        if not outs:
            return np.zeros(0, dtype=np.int32)
        return np.unique(np.concatenate(outs))


def _csr_from_rows(rows: list[tuple[int, np.ndarray]]) -> PredCSR | None:
    rows = [(s, o) for s, o in rows if len(o)]
    if not rows:
        return None
    rows.sort(key=lambda x: x[0])
    subjects = np.asarray([s for s, _ in rows], dtype=np.int64)
    if len(subjects) and subjects[-1] > MAX_DEVICE_UID:
        raise ValueError(f"uid {subjects[-1]} exceeds device uid space")
    lens = np.asarray([len(o) for _, o in rows], dtype=np.int64)
    indptr = np.zeros(len(rows) + 1, dtype=np.int32)
    np.cumsum(lens, out=indptr[1:])
    indices = np.concatenate([o for _, o in rows]).astype(np.int64)
    if len(indices) and indices.max() > MAX_DEVICE_UID:
        raise ValueError("object uid exceeds device uid space")
    return PredCSR(
        subjects.astype(np.int32),
        indptr,
        indices.astype(np.int32),
    )


def _token_index(rows: list[tuple[bytes, np.ndarray]]) -> TokenIndex:
    rows.sort(key=lambda x: x[0])
    terms = [t for t, _ in rows]
    lens = np.asarray([len(u) for _, u in rows], dtype=np.int64)
    indptr = np.zeros(len(rows) + 1, dtype=np.int32)
    if len(rows):
        np.cumsum(lens, out=indptr[1:])
        uids = np.concatenate([u for _, u in rows]).astype(np.int32)
    else:
        uids = np.zeros(0, dtype=np.int32)
    return TokenIndex(terms, indptr, uids)


class GraphSnapshot:
    """Immutable device-resident view of (a subset of) the graph at read_ts."""

    def __init__(self, read_ts: int) -> None:
        self.read_ts = read_ts
        self.preds: dict[str, PredData] = {}

    def pred(self, attr: str) -> PredData | None:
        return self.preds.get(attr)

    @property
    def nbytes(self) -> int:
        total = 0
        # memory accounting must never force folds: a lazy snapshot counts
        # only its materialized tablets (unfolded thunks hold no arrays)
        folded = getattr(self.preds, "folded_values", None)
        for pd in (folded() if folded is not None else self.preds.values()):
            for csr in (pd.csr, pd.rev_csr):
                if csr is not None:
                    est = getattr(csr, "approx_nbytes", None)
                    if est is not None:  # overlay: don't force a merge
                        total += est()
                        continue
                    hn = getattr(csr, "host_nbytes", None)
                    if hn is not None:   # host truth — never forces upload
                        total += hn()
                    else:                # mesh-sharded DistPredCSR
                        total += csr.subjects.nbytes + \
                            csr.indptr.nbytes + csr.indices.nbytes
            if pd.value_subjects is not None:
                total += pd.value_subjects.nbytes
            if pd.num_values is not None:
                total += pd.num_values.nbytes
            for ti in pd.indexes.values():
                hn = getattr(ti, "host_nbytes", None)
                total += hn() if hn is not None else \
                    (ti.indptr.nbytes + ti.uids.nbytes)
            if pd.vecindex is not None:
                total += pd.vecindex.nbytes()
        return total


# ---------------------------------------------------------------------------
# lazy on-demand snapshot folds (ISSUE 15)
# ---------------------------------------------------------------------------
#
# Eager assembly folded EVERY predicate at snapshot time — ~4 µs/list of
# Python (PERF.md round 5), i.e. 13-20 s to the first query at 10M edges
# and minutes at LDBC-SNB SF10+. The scale-regime cold path instead
# registers unfolded tablets as fold-THUNKS: the first read of a predicate
# (task/engine seams via GraphSnapshot.pred / LazyPreds.get), a residency
# plan-driven prefetch (storage/residency.prefetch, overlapped through the
# shared fold pool), or an overlay-forced inline compaction triggers the
# fold, with singleflight per tablet so racing first readers share ONE
# fold. PredData identity is minted at first fold and then reused exactly
# like the eager path's, so qcache per-predicate tokens, the
# DeviceBatcher's same-CSR-object rule, and mesh placement caches behave
# identically — and the fold itself is byte-identical to eager assembly
# (same build_pred at the same effective read_ts).
#
# Consistency window (the one deliberate divergence from eager): an
# unresolved thunk folds against the LIVE store at its registration-time
# read_ts. Normal commits land above that ts and stay invisible — the
# fold is byte-identical to eager. The exceptions are the races the
# staleness machinery already polices: a predicate DROP resolves the
# pending tablet as empty (build_pred's dropped-mid-build contract —
# eager would have served the pre-drop fold), and a replication replay
# BELOW the watermark is included by a post-replay fold while tablets
# folded earlier excluded it; pred_replay_seq marks such snapshots stale
# and the next snapshot() call rebuilds, bounding the mixed view to
# queries already holding the snapshot — the same exposure the stamped
# eager cache accepts between _stale() checks.

# fold-trigger counters (pre-registered in utils/metrics.Registry; literal
# names so the analysis metric rule and the runtime audit both see them)
_FOLD_COUNTERS = {
    "lazy": "dgraph_fold_lazy_total",
    "eager": "dgraph_fold_eager_total",
    "prefetch": "dgraph_fold_prefetch_total",
    "inline": "dgraph_fold_inline_total",
}


def _note_fold(metrics, trigger: str, dt_ms: float | None) -> None:
    if metrics is None:
        return
    metrics.counter(_FOLD_COUNTERS.get(trigger,
                                       "dgraph_fold_lazy_total")).inc()
    if dt_ms is not None:
        metrics.histogram("dgraph_fold_ms").observe(dt_ms)


class _FoldThunk:
    """One unfolded tablet: fold-on-first-read with per-tablet
    singleflight. The claim lock is held only to elect a leader — the
    fold itself runs outside it (no nested lock acquisition, so
    lockdep-armed runs see no new edges). A failed fold propagates to the
    waiters of THAT attempt and resets leadership so a later read
    retries; a resolved thunk answers every subsequent caller (including
    LazyPreds copies sharing it) without re-folding."""

    __slots__ = ("attr", "eff", "pct", "seq", "inline", "fold",
                 "pd", "error", "_lock", "_event", "_claimed")

    def __init__(self, attr: str, eff: int, fold, pct: int = 0,
                 seq: int = 0, inline: bool = False) -> None:
        self.attr = attr
        self.eff = eff
        self.pct = pct
        self.seq = seq
        self.inline = inline      # fold forced by overlay depth/stamp miss
        self.fold = fold          # callable(thunk, trigger) -> PredData
        self.pd: PredData | None = None
        self.error: BaseException | None = None
        self._lock = threading.Lock()
        self._event = threading.Event()
        self._claimed = False

    def resolve(self, trigger: str = "lazy") -> PredData:
        pd = self.pd
        if pd is not None:
            return pd
        with self._lock:
            if self.pd is not None:
                return self.pd
            lead = not self._claimed
            if lead:
                self._claimed = True
            event = self._event
        if not lead:
            # racing first reader: share the leader's fold. Clamped to the
            # caller's own deadline budget (same contract as the task-cache
            # singleflight follower) — never an unbounded hang.
            from dgraph_tpu.utils import deadline as dl

            if not event.wait(dl.clamp(None)):
                dl.check("lazy fold follower")
                raise dl.DeadlineExceeded(
                    f"lazy fold of {self.attr} timed out")
            if self.pd is not None:
                return self.pd
            if self.error is not None:
                raise self.error
            return self.resolve(trigger)       # leader failed then reset
        try:
            pd = self.fold(self, "inline" if self.inline else trigger)
        except BaseException as e:
            with self._lock:
                self.error = e
                self._claimed = False
                self._event = threading.Event()
            event.set()
            raise
        self.pd = pd
        self.error = None
        event.set()
        return pd


class DelegateThunk:
    """Pass-through thunk: resolves another lazy map's entry (embedded
    Cluster assembly, mesh placement). The FOLD singleflight lives in the
    underlying map's own thunk; the claim lock here serializes the `wrap`
    transform too — racing first readers must receive ONE placed identity
    (and pay one sharding/upload), not two."""

    __slots__ = ("src", "attr", "wrap", "pd", "_lock")

    def __init__(self, src, attr: str, wrap=None) -> None:
        self.src = src
        self.attr = attr
        self.wrap = wrap          # optional post-fold transform (placement)
        self.pd = None
        self._lock = threading.Lock()

    def resolve(self, trigger: str = "lazy"):
        if self.pd is not None:
            return self.pd
        with self._lock:
            if self.pd is None:
                pd = self.src.get(self.attr)
                if pd is not None and self.wrap is not None:
                    pd = self.wrap(pd)
                if pd is None:
                    return None
                self.pd = pd
        return self.pd


class LazyPreds(dict):
    """attr → PredData where unfolded tablets are fold-thunks.

    The dict storage holds FOLDED entries only; `_thunks` holds the
    pending tablets. Key views (len / contains / iter / keys) see the
    union WITHOUT folding; `get`/`[]` fold exactly the requested tablet
    (the demand-driven seam every query path reads through); `values()` /
    `items()` materialize everything first — callers that genuinely need
    the whole world (mesh re-sharding, expand() known-uid validation)
    keep eager semantics, in parallel through the shared fold pool.
    Mutation (`[k] = v`, `update`) drops any shadowed thunk: an explicit
    entry (txn overlay, placed tablet) always wins."""

    __slots__ = ("_thunks", "hint_fn", "on_resolve")

    def __init__(self) -> None:
        super().__init__()
        self._thunks: dict[str, object] = {}
        self.hint_fn = None       # callable(attr) -> cardinality estimate
        self.on_resolve = None    # callback(attr, pd) per materialization

    # -- registration ---------------------------------------------------------

    def register(self, attr: str, thunk) -> None:
        if not dict.__contains__(self, attr):
            self._thunks[attr] = thunk

    # -- resolution -----------------------------------------------------------

    def resolve(self, attr: str, trigger: str = "lazy"):
        """Fold one pending tablet (or return the folded entry)."""
        pd = dict.get(self, attr)
        if pd is not None:
            return pd
        th = self._thunks.get(attr)
        if th is None:
            return dict.get(self, attr)   # raced another resolver
        pd = th.resolve(trigger)
        if pd is None:                    # delegate over an absent tablet
            self._thunks.pop(attr, None)
            return None
        dict.__setitem__(self, attr, pd)
        self._thunks.pop(attr, None)
        cb = self.on_resolve
        if cb is not None:
            try:
                cb(attr, pd)
            except Exception:
                pass          # gauges/bookkeeping must never fail a read
        return pd

    def materialize_all(self, trigger: str = "eager") -> int:
        """Fold every pending tablet, in parallel through the shared fold
        pool. Distinct attrs have distinct thunks and each pool task waits
        only on a leader that is already RUNNING (claims happen inside
        resolve), so pool-width saturation cannot deadlock."""
        pending = [a for a in list(self._thunks)
                   if not dict.__contains__(self, a)]
        if not pending:
            return 0
        if len(pending) > 1:
            from concurrent.futures import TimeoutError as _FutTimeout

            from dgraph_tpu.utils import deadline as dl

            pool = _fold_pool()
            # dgraph: allow(ctxvar-copy) folds build SHARED snapshot
            # state cached across requests — they must not inherit any
            # one request's deadline/trace context
            futs = [pool.submit(self.resolve, a, trigger) for a in pending]
            for f in futs:
                try:
                    # clamped to the CALLER's budget: a timed-out request
                    # raises typed instead of waiting out the whole fold
                    # wall; the pool keeps folding for the next reader
                    f.result(timeout=dl.clamp(None))
                except _FutTimeout:
                    dl.check("materialize_all fold")
                    raise dl.DeadlineExceeded(
                        "materialize-all folds timed out")
        else:
            self.resolve(pending[0], trigger)
        return len(pending)

    # -- mapping protocol -----------------------------------------------------

    def __getitem__(self, attr):
        pd = dict.get(self, attr)
        if pd is not None:
            return pd
        if attr in self._thunks:
            pd = self.resolve(attr)
            if pd is not None:
                return pd
        raise KeyError(attr)

    def get(self, attr, default=None):
        pd = dict.get(self, attr)
        if pd is not None:
            return pd
        if attr in self._thunks:
            pd = self.resolve(attr)
            if pd is not None:
                return pd
        return default

    def __contains__(self, attr) -> bool:
        return dict.__contains__(self, attr) or attr in self._thunks

    def __len__(self) -> int:
        return len(set(dict.keys(self)) | set(self._thunks))

    def __iter__(self):
        return iter(sorted(set(dict.keys(self)) | set(self._thunks)))

    def keys(self):
        return sorted(set(dict.keys(self)) | set(self._thunks))

    def values(self):
        # sorted-key order: eager assembly inserted in sorted
        # store.predicates() order, while on-demand resolution inserts in
        # completion order — iteration must stay deterministic (tablet
        # routing assigns groups in iteration order)
        self.materialize_all()
        return [v for _k, v in sorted(dict.items(self))]

    def items(self):
        self.materialize_all()
        return sorted(dict.items(self))

    def __setitem__(self, attr, pd) -> None:
        self._thunks.pop(attr, None)
        dict.__setitem__(self, attr, pd)

    def update(self, other=(), **kw) -> None:
        d = dict(other, **kw)
        for k in d:
            self._thunks.pop(k, None)
        dict.update(self, d)

    # -- lazy-aware views (planner / stats / residency / memory) --------------

    def folded_get(self, attr, default=None):
        """Folded entry or default — NEVER resolves a thunk (identity
        probes like compact()'s pinned-view scan must not fold)."""
        return dict.get(self, attr, default)

    def folded_items(self):
        return list(dict.items(self))

    def folded_values(self):
        return list(dict.values(self))

    def pending_attrs(self) -> list[str]:
        return [a for a in list(self._thunks)
                if not dict.__contains__(self, a)]

    def is_pending(self, attr: str) -> bool:
        return attr in self._thunks and not dict.__contains__(self, attr)

    def pending_card(self, attr: str) -> int:
        """Cardinality ESTIMATE for an unfolded tablet (planner universe
        normalization — order decisions only, never results)."""
        fn = self.hint_fn
        if fn is None:
            return 0
        try:
            return int(fn(attr))
        except Exception:
            return 0

    def lazy_copy(self) -> "LazyPreds":
        """Folded entries copied, pending thunks SHARED — the txn
        read-view copy (api/server._read_view). A fold through either
        map resolves the one shared thunk; `dict(base.preds)` would
        silently drop the pending tablets via the CPython dict fast
        path, which is why that call site uses this instead."""
        out = LazyPreds()
        dict.update(out, self)
        out._thunks = dict(self._thunks)
        out.hint_fn = self.hint_fn
        out.on_resolve = self.on_resolve
        return out


_UNPACK_CHUNK = 16384   # lists decoded per vectorized unpack_many call


def _tablet_uids(store: Store, kbs: list[bytes], read_ts: int,
                 own: int | None,
                 pls: list | None = None) -> list[np.ndarray]:
    """uids() for every key of a tablet, batching pure-base lists through one
    vectorized decode (packed.unpack_many) — per-list numpy overhead
    dominates a 100k-list snapshot build otherwise."""
    # .get: a predicate dropped mid-build (follower live-apply) reads as
    # empty rather than KeyError; the reader's version bump rebuilds after
    if pls is None:
        pls = [store.lists.get(kb) for kb in kbs]
    pls = [pl if pl is not None else PostingList() for pl in pls]
    out: list[np.ndarray | None] = [None] * len(pls)
    batch_idx: list[int] = []
    for i, pl in enumerate(pls):
        if pl._base_only(read_ts, own):
            batch_idx.append(i)
        else:
            out[i] = pl.uids(read_ts, own_start_ts=own)
    for lo in range(0, len(batch_idx), _UNPACK_CHUNK):
        part = batch_idx[lo : lo + _UNPACK_CHUNK]
        from dgraph_tpu.storage import native

        for i, u in zip(part, native.unpack_many(
                [pls[i].base_packed for i in part])):
            out[i] = u.astype(np.int64)
    return out


def _uids_of_keys(kbs: list[bytes]) -> np.ndarray:
    """Vectorized K.uid_of over a tablet's DATA/REVERSE keys (all the same
    length for one attr: kind + u32 len + attr + u64 uid, big-endian)."""
    n = len(kbs)
    if n == 0:
        return np.zeros(0, np.int64)
    buf = b"".join(kbs)
    L = len(kbs[0])
    arr = np.frombuffer(buf, dtype=np.uint8).reshape(n, L)
    return np.ascontiguousarray(arr[:, -8:]).view(">u8").ravel().astype(
        np.int64)


def _csr_from_flat(subjects: np.ndarray, counts: np.ndarray,
                   indices: np.ndarray) -> PredCSR:
    """Assemble a PredCSR from flat arrays, dropping empty rows."""
    keep = counts > 0
    subjects_k = subjects[keep]
    if len(subjects_k) and subjects_k[-1] > MAX_DEVICE_UID:
        raise ValueError(f"uid {subjects_k[-1]} exceeds device uid space")
    if len(indices) and indices.max() > MAX_DEVICE_UID:
        raise ValueError("object uid exceeds device uid space")
    indptr = np.zeros(int(keep.sum()) + 1, dtype=np.int32)
    np.cumsum(counts[keep], out=indptr[1:])
    return PredCSR(
        subjects_k.astype(np.int32),
        indptr,
        indices.astype(np.int32),
    )


def _fold_uid_tablet(store: Store, kbs: list[bytes], read_ts: int,
                     own: int | None, pd: PredData | None,
                     kind: int = int(K.KeyKind.DATA)) -> PredCSR | None:
    """Flat fold of a uid-edge tablet (the 10M-scale hot path): one
    vectorized key parse, one batched native decode into a single flat
    index array, bulk span copies — no per-key numpy slicing and no
    100k-array np.concatenate (reference predicate.go:84-176 streams a
    shard build the same way: key-ordered, single pass).

    pd: facet capture target for lists with live postings (None for
    reverse tablets — the forward fold owns facets)."""
    from dgraph_tpu.storage import native

    N = len(kbs)
    if N == 0:
        return None

    # COLD-OPEN FAST PATH: the snapshot loader captured this tablet's
    # packed columns contiguously (store.TabletPacked; entry survives only
    # while untouched by writes) — decode every list in ONE native call,
    # zero per-list Python. This is the >=10x lever at 10M-edge scale.
    attr = K.kind_attr_of(kbs[0])[1]
    tp = store.packed_tablet(kind, attr)
    if tp is not None and tp.pure and tp.n == N:
        if read_ts < tp.max_base_ts:
            raise ValueError(
                f"read at ts {read_ts} below rollup watermark "
                f"{tp.max_base_ts}")
        flat = native.unpack_columns(tp, int(tp.counts.sum()))
        if flat is not None:
            return _csr_from_flat(_uids_of_keys(kbs), tp.counts,
                                  flat.view(np.int64))

    pls = [store.lists.get(kb) for kb in kbs]
    subjects = _uids_of_keys(kbs)      # keys_of is sorted → ascending
    max_bts = max((pl.base_ts for pl in pls if pl is not None), default=0)
    if read_ts < max_bts:
        # same isolation guard the per-list path enforces
        # (PostingList._base_only): a rollup above read_ts folded
        # later commits into the base — this read cannot be served
        raise ValueError(
            f"read at ts {read_ts} below rollup watermark {max_bts}")
    pure = np.fromiter(
        ((pl is not None and not pl.layers and not pl.uncommitted
          and not pl.base_postings) for pl in pls), bool, N)
    comp_rows: dict[int, np.ndarray] = {}
    for i in np.flatnonzero(~pure).tolist():
        pl = pls[i]
        if pl is None:                 # dropped mid-build: reads as empty
            comp_rows[i] = np.zeros(0, np.int64)
            continue
        comp_rows[i] = pl.uids(read_ts, own_start_ts=own)
        if pd is not None:
            live = pl.live_map(read_ts, own_start_ts=own)
            subj = int(subjects[i])
            for p in live.values():
                if p.facets:
                    pd.facets[(subj, p.uid)] = p.facets
    pure_idx = np.flatnonzero(pure)
    flat, counts_pure = native.unpack_many_flat(
        [pls[i].base_packed for i in pure_idx.tolist()])
    counts = np.zeros(N, np.int64)
    counts[pure] = counts_pure
    for i, u in comp_rows.items():
        counts[i] = len(u)
    total = int(counts.sum())
    if total == 0:
        return None
    offs = np.zeros(N + 1, np.int64)
    np.cumsum(counts, out=offs[1:])
    indices = np.empty(total, np.int64)
    if not comp_rows:
        indices[:] = flat              # single bulk copy (casts u64→i64)
    else:
        pure_off = np.zeros(len(pure_idx) + 1, np.int64)
        np.cumsum(counts_pure, out=pure_off[1:])
        # consecutive pure keys form runs → one span copy per run
        starts = np.concatenate(
            [[0], np.flatnonzero(np.diff(pure_idx) != 1) + 1])
        ends = np.concatenate([starts[1:], [len(pure_idx)]])
        for j0, j1 in zip(starts.tolist(), ends.tolist()):
            if j0 == j1:
                continue
            i0, i_last = int(pure_idx[j0]), int(pure_idx[j1 - 1])
            indices[offs[i0]: offs[i_last + 1]] = \
                flat[pure_off[j0]: pure_off[j1]]
        for i, u in comp_rows.items():
            indices[offs[i]: offs[i + 1]] = u
    return _csr_from_flat(subjects, counts, indices)


def _fold_value_subject(pd: PredData, entry, tid: TypeID, subj: int, pl,
                        read_ts: int, own: int | None) -> tuple[bool, float | None]:
    """Per-subject value/facet fold — the ONE implementation shared by
    build_pred and the delta-overlay stamp (storage/delta.py), so a stamped
    entry is byte-identical to a full fold at the same read_ts.

    Mutates pd's value/facet dicts; returns (is_edge_row, num_mirror):
    is_edge_row means the subject's uids belong in the CSR (uid-typed, or
    DEFAULT with no value postings); num_mirror is the subject's
    value_subjects numeric-mirror entry (None = no entry)."""
    live = pl.live_map(read_ts, own_start_ts=own)
    # type heuristic for untyped predicates probes ANY value ("." tag);
    # host_values below still reads only the untagged slot
    has_value = any(p.value is not None for p in live.values())
    if tid == TypeID.UID or (tid == TypeID.DEFAULT and not has_value):
        for p in live.values():
            if p.facets:
                pd.facets[(subj, p.uid)] = p.facets
        return True, None
    p0 = live.get(VALUE_UID)
    v = p0.value if p0 is not None else None
    if v is None and entry is not None and entry.is_list:
        # [type] list predicate: values live at fingerprint slots;
        # surface the whole list plus the first as the compare/sort
        # representative
        lv = sorted((p.value for p in live.values()
                     if p.value is not None and not p.lang),
                    key=lambda x: str(x.value))
        if lv:
            pd.list_values[subj] = lv
            v = lv[0]
    num: float | None = None
    if v is not None:
        pd.host_values[subj] = v
        s = to_device_scalar(v)
        num = np.nan if s is None else float(s)
    # language-tagged values
    had_lang = False
    for p in live.values():
        if p.value is not None and p.lang:
            pd.lang_values.setdefault(subj, {})[p.lang] = p.value
            had_lang = True
        if p.facets:
            pd.facets[(subj, p.uid)] = p.facets
    if v is None and had_lang:
        # lang-only node: still a has(attr) subject (the reference's
        # data key exists), but carries no untagged value
        num = np.nan
    return False, num


def build_pred(store: Store, attr: str, read_ts: int,
               own_start_ts: int | None = None) -> PredData:
    """Fold one predicate's tablets at read_ts into a PredData.

    own_start_ts: when set, the caller's open txn's uncommitted layers are
    visible too (posting/list.go:528 — postings with StartTs == readTs are
    visible to their own txn). Such views must not be cached.
    """
    entry = store.schema.get(attr)
    tid = entry.type_id if entry else TypeID.DEFAULT
    pd = PredData(attr, tid)

    fwd_rows: list[tuple[int, np.ndarray]] = []
    val_subjects: list[int] = []
    num_vals: list[float] = []
    own = own_start_ts
    kbs = store.keys_of(K.KeyKind.DATA, attr)
    uid_typed = tid == TypeID.UID
    if uid_typed:
        # flat fold: no per-key loop at all for declared-uid predicates
        pd.csr = _fold_uid_tablet(store, kbs, read_ts, own, pd,
                                  kind=int(K.KeyKind.DATA))
        kbs = []
    tablet_pls = store.tablet_lists(int(K.KeyKind.DATA), attr, kbs)
    tablet_uids = _tablet_uids(store, kbs, read_ts, own, pls=tablet_pls)
    for kb, u, pl in zip(kbs, tablet_uids, tablet_pls):
        subj = K.uid_of(kb)        # DATA key: partial parse, hot loop
        if pl is None:             # predicate dropped mid-build (follower
            continue               # live-apply); version bump rebuilds
        if uid_typed and not pl.layers and not pl.uncommitted \
                and not pl.base_postings:
            # post-bulk fast path: a pure packed uid list carries no
            # values/facets — skip the live_map fold entirely (unlocked
            # peek is safe: a layer landing mid-check commits ABOVE this
            # snapshot's ts and is invisible to it anyway; replayed
            # below-watermark commits invalidate via pred_replay_seq)
            if len(u):
                fwd_rows.append((subj, u))
            continue
        is_edge, num = _fold_value_subject(pd, entry, tid, subj, pl,
                                           read_ts, own)
        if is_edge:
            if len(u):
                fwd_rows.append((subj, u))
        elif num is not None:
            val_subjects.append(subj)
            num_vals.append(num)
    if fwd_rows:                  # non-uid-typed heuristic edges only
        pd.csr = _csr_from_rows(fwd_rows)
    if val_subjects:
        order = np.argsort(np.asarray(val_subjects, dtype=np.int64))
        vs = np.asarray(val_subjects, dtype=np.int64)[order]
        if vs[-1] > MAX_DEVICE_UID:
            raise ValueError("value subject uid exceeds device uid space")
        pd.value_subjects_host = vs
        # the narrow value-table mirrors are host-resident: nothing reads
        # them on device (compares run on the float64 host mirror), so
        # eagerly uploading them only burned HBM the residency budget now
        # accounts for
        pd.value_subjects = vs.astype(np.int32)
        pd.num_values_host = np.asarray(num_vals, dtype=np.float64)[order]
        pd.num_values = pd.num_values_host.astype(np.float32)

    # reverse CSR (flat fold; facets belong to the forward tablet)
    if entry is not None and entry.reverse:
        rkbs = store.keys_of(K.KeyKind.REVERSE, attr)
        pd.rev_csr = _fold_uid_tablet(store, rkbs, read_ts, own, None,
                                      kind=int(K.KeyKind.REVERSE))

    # vector index: fold the predicate's embeddings into the row-aligned
    # device matrix (+ IVF coarse quantizer past the size threshold)
    if entry is not None and entry.vector is not None:
        from dgraph_tpu.storage import vecindex as vecmod

        pd.vecindex = vecmod.build_vecindex(
            attr, entry.vector, pd.host_values,
            knobs=getattr(store, "vector_knobs", None))

    # token indexes, split per tokenizer by the 1-byte term prefix
    if entry is not None and entry.indexed:
        from dgraph_tpu.utils import tok as tokmod

        by_tok: dict[str, list[tuple[bytes, np.ndarray]]] = {
            name: [] for name in entry.tokenizers}
        ident_to_name = {tokmod.get(n).ident: n for n in entry.tokenizers}
        ikbs = store.keys_of(K.KeyKind.INDEX, attr)
        ipls = store.tablet_lists(int(K.KeyKind.INDEX), attr, ikbs)
        for kb, u in zip(ikbs, _tablet_uids(store, ikbs, read_ts, own,
                                            pls=ipls)):
            key = K.parse_key(kb)
            if not key.term or not len(u):
                continue
            name = ident_to_name.get(key.term[0])
            if name is None:
                continue
            by_tok[name].append((key.term[1:], u))
        for name, rows in by_tok.items():
            pd.indexes[name] = _token_index(rows)

    # residency adoption: when the owning node runs a device working-set
    # manager (storage/residency.py), every device-buffer owner of this
    # fold admits against the node's budget and is demotable/evictable
    mgr = getattr(store, "residency", None)
    if mgr is not None:
        mgr.adopt_pred(pd)
    return pd


_FOLD_POOL = None
_FOLD_POOL_LOCK = __import__("threading").Lock()


def default_fold_workers() -> int:
    import os

    return max(1, min(8, (os.cpu_count() or 2) - 1))


def _fold_pool():
    """ONE process-wide fixed-width thread pool for parallel tablet folds
    (never resized or shut down — replacing a live pool would race other
    assemblers' submits). Per-predicate folds are independent reads (the
    same unlocked reads the serial path does under the owning node's lock)
    and mostly numpy/native work that releases the GIL, so a cold
    multi-predicate snapshot builds in ~max(tablet) instead of
    sum(tablet). Callers wanting fewer concurrent folds cap via a
    semaphore in _fold_attrs."""
    global _FOLD_POOL
    from concurrent.futures import ThreadPoolExecutor

    with _FOLD_POOL_LOCK:
        if _FOLD_POOL is None:
            _FOLD_POOL = ThreadPoolExecutor(
                max_workers=default_fold_workers(),
                thread_name_prefix="dgt-fold")
        return _FOLD_POOL


def _fold_attrs(store: Store, attrs: list[str], read_ts: int,
                own_start_ts: int | None, workers: int,
                metrics=None) -> list[PredData]:
    """build_pred over many attrs, through the fold pool when it pays;
    `workers` caps this call's concurrency without resizing the pool."""
    def one(a):
        t0 = time.perf_counter()
        pd = build_pred(store, a, read_ts, own_start_ts)
        # per COMPLETED fold, wall observed on dgraph_fold_ms — the same
        # accounting every lazy/prefetch/inline trigger gets
        _note_fold(metrics, "eager", (time.perf_counter() - t0) * 1e3)
        return pd

    if len(attrs) > 1 and workers > 1:
        pool = _fold_pool()
        sem = threading.Semaphore(workers)
        if metrics is not None:
            metrics.counter("dgraph_parallel_folds_total").inc(len(attrs))
            metrics.counter("dgraph_fold_pool_width").set(
                min(workers, default_fold_workers()))

        def run(a):
            with sem:
                return one(a)

        # dgraph: allow(ctxvar-copy) folds build SHARED snapshot state
        # cached across requests — they must not inherit any one
        # request's deadline/trace context
        futs = [pool.submit(run, a) for a in attrs]
        return [f.result() for f in futs]
    return [one(a) for a in attrs]


def build_snapshot(store: Store, read_ts: int,
                   attrs: Iterable[str] | None = None,
                   own_start_ts: int | None = None,
                   fold_workers: int | None = None,
                   lazy: bool = False) -> GraphSnapshot:
    """Fold the store at read_ts into a GraphSnapshot (upload to device).
    Folds run across the shared thread pool (per-predicate folds are
    independent); fold_workers=1 forces the serial path.

    lazy=True registers every tablet as a fold-thunk instead: the first
    read of a predicate folds exactly that tablet (singleflighted), with
    output byte-identical to the eager fold at the same read_ts. The
    serving path (SnapshotAssembler) is lazy by default; this one-shot
    utility stays eager by default because its callers (replication
    quorum reads, smoke-test reference builds) want the complete fold."""
    snap = GraphSnapshot(read_ts)
    todo = sorted(attrs) if attrs is not None else store.predicates()
    if lazy:
        metrics = getattr(store, "metrics", None)
        preds = LazyPreds()
        snap.preds = preds

        def bare_fold(th, trigger):
            t0 = time.perf_counter()
            pd = build_pred(store, th.attr, th.eff, own_start_ts)
            _note_fold(metrics, trigger,
                       (time.perf_counter() - t0) * 1e3)
            return pd

        for attr in todo:
            preds.register(attr, _FoldThunk(attr, read_ts, bare_fold))
        return snap
    workers = fold_workers if fold_workers is not None \
        else default_fold_workers()
    for attr, pd in zip(todo, _fold_attrs(store, todo, read_ts,
                                          own_start_ts, workers)):
        snap.preds[attr] = pd
    return snap


@dataclass
class _OverlayState:
    """Book-keeping for one predicate's live overlay: the TRUE folded base
    it stacks on (re-stamps always start from here — overlays never nest),
    its current depth in touched keys, and its birth time (age-triggered
    compaction)."""

    base_ts: int
    base_pd: PredData
    depth: int
    born: float


class SnapshotAssembler:
    """Incremental snapshot cache: per-predicate PredData reuse keyed on the
    store's per-predicate commit watermark (pred_commit_ts), plus a small
    per-read-ts snapshot cache. This is the read-through contract of
    posting/lists.go:243 — the world is never rebuilt — shared by the
    embedded Node, the worker wire service, and follower readers.

    Commit-to-visible is O(Δ): a commit whose touched keys are in the
    store's delta journal STAMPS the cached PredData with replacement rows
    (storage/delta.py) instead of re-folding the tablet — base device
    arrays keep identity, and only the touched subjects/terms are
    re-derived. Deep or old overlays compact back into folded bases
    (inline past OVERLAY_MAX_KEYS; in the background via compact())."""

    SNAP_CACHE = 4
    OVERLAY_MAX_KEYS = 512       # stamp depth ceiling: past it, fold inline
    OVERLAY_MAX_AGE_S = 30.0     # background compaction age trigger

    def __init__(self, store, on_pred_build=None, metrics=None,
                 overlay_enabled: bool = True,
                 overlay_max_keys: int | None = None,
                 overlay_max_age_s: float | None = None,
                 fold_workers: int | None = None,
                 lazy_folds: bool = True) -> None:
        self.store = store
        self.on_pred_build = on_pred_build       # callback(attr) per re-fold
        self.metrics = metrics                   # utils.metrics.Registry|None
        self.overlay_enabled = overlay_enabled
        if overlay_max_keys is not None:
            self.OVERLAY_MAX_KEYS = int(overlay_max_keys)
        if overlay_max_age_s is not None:
            self.OVERLAY_MAX_AGE_S = float(overlay_max_age_s)
        self.fold_workers = (fold_workers if fold_workers is not None
                             else default_fold_workers())
        # lazy on-demand folds (ISSUE 15): assembly registers fold-thunks
        # and the first read of a predicate folds exactly that tablet
        self.lazy_folds = bool(lazy_folds)
        # attr -> (built_ts, PredData, replay_seq at build)
        self._pred_cache: dict[str, tuple[int, PredData, int]] = {}
        self._overlays: dict[str, _OverlayState] = {}
        self._snaps: dict[int, GraphSnapshot] = {}
        # attr -> unresolved fold thunk: carried across assemblies while
        # the data window is unchanged so successive snapshots share one
        # pending fold exactly like they share one cached PredData
        self._pending: dict[str, _FoldThunk] = {}
        self._card_hints: dict[str, int] = {}    # attr -> DATA key count
        self._first_assembled = False
        # bumped by invalidate(): structural changes ('s'/'dp'/'dk'
        # records) don't move pred_commit_ts/pred_replay_seq, so a lazy
        # fold in flight across an alter needs its own stability check
        # before writing _pred_cache
        self._cache_gen = 0

    def snapshot(self, read_ts: int) -> GraphSnapshot:
        """Committed view at read_ts (clamped to the newest commit: two
        read_ts above it see identical data and share the cache entry)."""
        eff = min(read_ts, self.store.max_seen_commit_ts)
        snap = self._snaps.get(eff)
        if snap is None or self._stale(snap):
            snap = self._assemble(eff)
            self._snaps[eff] = snap
            while len(self._snaps) > self.SNAP_CACHE:
                self._snaps.pop(next(iter(self._snaps)))
        return snap

    def _stale(self, snap: GraphSnapshot) -> bool:
        # A cached snapshot at read_ts is immutable under NORMAL commits
        # (they land above read_ts and are invisible to it). The only way
        # it rots is a commit arriving AT/BELOW read_ts after assembly —
        # replication replay races — so compare each predicate's commit
        # watermark against the value stamped at assembly, and only when
        # the new watermark is visible at this read_ts. A plain
        # "watermark > read_ts" check would mark every old-ts snapshot
        # permanently stale the moment any newer commit lands.
        stamped = getattr(snap, "pred_watermarks", None)
        replays = getattr(snap, "pred_replays", None)
        if stamped is None:
            return True                   # built before stamping existed
        for attr in self.store.predicates():
            pct = self.store.pred_commit_ts.get(attr, 0)
            if pct <= snap.read_ts and stamped.get(attr) != pct:
                return True               # replayed/new commit now visible
            if self.store.pred_replay_seq.get(attr, 0) != \
                    (replays or {}).get(attr, 0):
                # a commit landed BELOW the predicate's watermark since
                # assembly — the max-only watermark can't place it relative
                # to read_ts, so treat every cached view as suspect
                return True
        return False

    def _stamp(self, snap: GraphSnapshot) -> None:
        snap.pred_watermarks = {
            a: self.store.pred_commit_ts.get(a, 0) for a in snap.preds}
        snap.pred_replays = {
            a: self.store.pred_replay_seq.get(a, 0) for a in snap.preds}

    def _assemble(self, eff: int) -> GraphSnapshot:
        t0 = time.perf_counter()
        snap = GraphSnapshot(eff)
        if self.lazy_folds:
            preds = LazyPreds()
            preds.hint_fn = self._card_hint
            snap.preds = preds
        reused = 0
        todo: list[tuple[str, int, int, bool]] = []
        for attr in self.store.predicates():
            pct = self.store.pred_commit_ts.get(attr, 0)
            seq = self.store.pred_replay_seq.get(attr, 0)
            cached = self._pred_cache.get(attr)
            if cached is not None and cached[2] != seq:
                # a commit landed BELOW the watermark after the cached fold
                # (replication replay): the cached view silently misses it —
                # the max-only watermark check alone would keep serving it
                self._pred_cache.pop(attr, None)
                self._overlays.pop(attr, None)
                cached = None
            if cached is not None and cached[0] >= pct and eff >= pct:
                # both views contain every commit to attr (all <= pct)
                snap.preds[attr] = cached[1]
                reused += 1
                continue
            pd = self._try_stamp(attr, cached, pct, seq, eff)
            if pd is not None:
                snap.preds[attr] = pd
            else:
                todo.append((attr, pct, seq, cached is not None))
        if todo and self.lazy_folds:
            # register fold-thunks instead of folding: the first read of
            # a predicate (or a residency prefetch) folds exactly that
            # tablet, singleflighted. A still-pending thunk from an
            # earlier assembly is reused while its data window matches —
            # the same both-views-complete rule as _pred_cache reuse
            for attr, pct, seq, had_cached in todo:
                th = self._pending.get(attr)
                if th is None or not (th.eff >= pct and eff >= pct
                                      and th.seq == seq):
                    th = _FoldThunk(attr, eff, self._fold_pending,
                                    pct=pct, seq=seq, inline=had_cached)
                    if eff >= pct:
                        self._pending[attr] = th
                snap.preds.register(attr, th)
            self._set_pending_gauge()
        elif todo:
            attrs = [a for a, _p, _s, _c in todo]
            for attr, pd in zip(attrs, _fold_attrs(
                    self.store, attrs, eff, None, self.fold_workers,
                    self.metrics)):
                if self.on_pred_build is not None:
                    self.on_pred_build(attr)
                pct = self.store.pred_commit_ts.get(attr, 0)
                if eff >= pct:
                    self._pred_cache[attr] = (
                        eff, pd, self.store.pred_replay_seq.get(attr, 0))
                    self._overlays.pop(attr, None)
                    self._set_depth(attr, 0)
                    self.store.prune_delta(attr, eff)
                snap.preds[attr] = pd
        if reused and len(snap.preds) > reused and self.metrics is not None:
            # clean predicates carried across a change to OTHER predicates:
            # exactly the task-cache invalidations per-predicate tokens avoid
            self.metrics.counter(
                "dgraph_cache_invalidations_avoided_total").inc(reused)
        # query-time instrumentation that lives below the Node (vector
        # searches in query/task.py) reads the owning registry off the
        # snapshot — per-node correct, no module globals
        snap.metrics = self.metrics
        self._stamp(snap)
        if not self._first_assembled:
            # the cold-open lever: under eager folds this wall covered
            # EVERY tablet's fold; lazy assembly is O(predicates)
            self._first_assembled = True
            if self.metrics is not None:
                self.metrics.counter("dgraph_cold_open_ms").set(
                    (time.perf_counter() - t0) * 1e3)
        return snap

    def _fold_pending(self, th: _FoldThunk, trigger: str) -> PredData:
        """On-demand fold of one registered thunk (the _FoldThunk leader
        runs this OUTSIDE the claim lock) plus the cache bookkeeping the
        eager assembly tail performs. pct/seq are read around the fold
        and the cache entry written only when nothing moved mid-fold (the
        compact() pattern), so a racing commit or replication replay can
        never pin a view whose delta the journal can't reproduce."""
        store = self.store
        gen0 = self._cache_gen
        pct0 = store.pred_commit_ts.get(th.attr, 0)
        seq0 = store.pred_replay_seq.get(th.attr, 0)
        t0 = time.perf_counter()
        pd = build_pred(store, th.attr, th.eff)
        _note_fold(self.metrics, trigger, (time.perf_counter() - t0) * 1e3)
        if self.on_pred_build is not None:
            self.on_pred_build(th.attr)
        pct = store.pred_commit_ts.get(th.attr, 0)
        seq = store.pred_replay_seq.get(th.attr, 0)
        if th.eff >= pct and (pct0, seq0) == (pct, seq) \
                and gen0 == self._cache_gen:
            self._pred_cache[th.attr] = (th.eff, pd, seq)
            self._overlays.pop(th.attr, None)
            self._set_depth(th.attr, 0)
            store.prune_delta(th.attr, th.eff)
        if self._pending.get(th.attr) is th:
            self._pending.pop(th.attr, None)
        self._set_pending_gauge()
        return pd

    def _card_hint(self, attr: str) -> int:
        """DATA key count of one tablet — the planner's universe
        normalization for unfolded tablets (order decisions only, never
        results; exact post-bulk via the packed-tablet count, a decode-free
        key scan otherwise). Cached until invalidate()."""
        h = self._card_hints.get(attr)
        if h is None:
            tp = self.store.packed_tablet(int(K.KeyKind.DATA), attr)
            h = int(tp.n) if tp is not None else \
                len(self.store.keys_of(K.KeyKind.DATA, attr))
            self._card_hints[attr] = h
        return h

    def _set_pending_gauge(self) -> None:
        if self.metrics is not None:
            self.metrics.counter("dgraph_fold_pending_tablets").set(
                len(self._pending))

    def _set_depth(self, attr: str, depth: int) -> None:
        if self.metrics is not None:
            self.metrics.keyed("dgraph_overlay_depth").set(attr, depth)

    def _try_stamp(self, attr: str, cached, pct: int, seq: int,
                   eff: int) -> PredData | None:
        """O(Δ) overlay stamp of the cached PredData; None = not stampable
        (caller folds). Never stacks: re-stamps start from the true base."""
        if not self.overlay_enabled or cached is None:
            return None
        if eff < pct or cached[0] > eff:
            return None       # old-ts view: fold it (and don't cache)
        st = self._overlays.get(attr)
        base_ts, base_pd = (st.base_ts, st.base_pd) if st is not None \
            else (cached[0], cached[1])
        dmap = self.store.delta_since(attr, base_ts)
        if dmap is None:
            return None       # journal can't prove completeness: fold
        dkeys = [kb for kb, cts in dmap.items() if cts <= eff]
        if len(dkeys) > self.OVERLAY_MAX_KEYS:
            return None       # deep overlay: inline compaction via fold
        from dgraph_tpu.storage import delta as dmod

        try:
            pd = dmod.stamp_pred(self.store, attr, base_pd, eff, dkeys)
        except Exception:
            if self.metrics is not None:
                self.metrics.counter(
                    "dgraph_overlay_fold_fallbacks_total").inc()
            return None
        self._pred_cache[attr] = (eff, pd, seq)
        import time as _time

        born = st.born if st is not None else _time.monotonic()
        self._overlays[attr] = _OverlayState(base_ts, base_pd,
                                             len(dkeys), born)
        if self.metrics is not None:
            self.metrics.counter("dgraph_overlay_stamps_total").inc()
        self._set_depth(attr, len(dkeys))
        return pd

    # -- background compaction (rollup) --------------------------------------

    def overlay_stats(self) -> dict[str, int]:
        """attr -> overlay depth in touched keys. An ops readout: callers
        (e.g. /debug/metrics handler threads) may race assembly, so retry
        the briefly-inconsistent iteration instead of requiring the lock."""
        for _ in range(4):
            try:
                return {attr: st.depth
                        for attr, st in list(self._overlays.items())}
            except RuntimeError:
                continue
        return {}

    def overlay_bytes(self) -> int:
        """Host bytes held by live overlay rows (enforce_memory input).
        Same lock-free-readout contract as overlay_stats."""
        from dgraph_tpu.storage import delta as dmod

        for _ in range(4):
            try:
                return sum(dmod.overlay_nbytes(c[1])
                           for c in list(self._pred_cache.values()))
            except RuntimeError:
                continue
        return 0

    def compact_candidates(self, force: bool = False) -> list[str]:
        import time as _time

        now = _time.monotonic()
        # lazy folds pop _overlays from query threads (_fold_pending runs
        # lock-free); retry the briefly-inconsistent iteration like
        # overlay_stats does instead of requiring the node lock
        for _ in range(4):
            try:
                return [attr for attr, st in list(self._overlays.items())
                        if force or st.depth >= self.OVERLAY_MAX_KEYS
                        or now - st.born >= self.OVERLAY_MAX_AGE_S]
            except RuntimeError:
                continue
        return []

    def compact(self, lock, attrs: list[str] | None = None,
                force: bool = False) -> int:
        """Merge overlays back into folded bases OFF the query path (the
        background rollup): fold outside `lock` at a pinned watermark, swap
        under `lock` only if nothing moved meanwhile. After a successful
        compaction the predicate's overlay is empty, the delta journal is
        pruned, and reads serve the fresh base — results unchanged (the
        overlay and the fold describe the same data). Returns the number of
        predicates compacted."""
        import time as _time

        with lock:
            cands = (list(attrs) if attrs is not None
                     else self.compact_candidates(force=force))
            pinned = {
                attr: (self.store.pred_commit_ts.get(attr, 0),
                       self.store.pred_replay_seq.get(attr, 0))
                for attr in cands if attr in self._overlays}
        done = 0
        for attr, (ts, seq) in pinned.items():
            t0 = _time.perf_counter()
            try:
                pd = build_pred(self.store, attr, ts)
            except Exception:
                continue      # store moved under us: the next tick retries
            with lock:
                if (self.store.pred_commit_ts.get(attr, 0),
                        self.store.pred_replay_seq.get(attr, 0)) != (ts, seq):
                    continue  # commit/replay raced the fold: retry later
                old = self._pred_cache.get(attr)
                if attr not in self._overlays:
                    continue
                self._pred_cache[attr] = (ts, pd, seq)
                self._overlays.pop(attr, None)
                self.store.prune_delta(attr, ts)
                # cached snapshots pinning the stamped view: drop them so
                # the next read reassembles over the fresh base (cheap — all
                # predicates are cache hits) and the overlay memory frees
                if old is not None:
                    # folded-only peek: a pinned stamped view is always a
                    # materialized entry — .get here would FOLD pending
                    # tablets of every cached snapshot just to compare
                    for k in [k for k, s in self._snaps.items()
                              if getattr(s.preds, "folded_get",
                                         s.preds.get)(attr) is old[1]]:
                        self._snaps.pop(k, None)
                done += 1
                self._set_depth(attr, 0)
                if self.metrics is not None:
                    self.metrics.counter("dgraph_compactions_total").inc()
                    self.metrics.histogram("dgraph_compaction_s").observe(
                        _time.perf_counter() - t0)
        return done

    def invalidate(self) -> int:
        """Structural change (schema, drop, predicate delete): every cached
        view may be wrong — rebuild from scratch on next read. Returns the
        number of dropped cache entries (memory accounting)."""
        n = len(self._pred_cache) + len(self._snaps)
        for attr in self._overlays:
            self._set_depth(attr, 0)
        self._pred_cache.clear()
        self._overlays.clear()
        self._snaps.clear()
        # outstanding lazy thunks (held by handed-out snapshots) still
        # resolve against the live store at their own read_ts; the
        # assembler just stops reusing them — and the generation bump
        # keeps an in-flight fold (started pre-alter) from writing its
        # stale view back into _pred_cache after this clear
        self._cache_gen += 1
        self._pending.clear()
        self._card_hints.clear()
        self._set_pending_gauge()
        return n

    def cache_size(self) -> int:
        return len(self._pred_cache) + len(self._snaps)


# WAL record types that change visible structure beyond the per-predicate
# commit watermark: schema lines, predicate/kind drops
STRUCTURAL_RECORDS = frozenset({"s", "dp", "dk"})
