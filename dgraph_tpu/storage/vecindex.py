"""HBM-resident vector index: per-predicate embedding matrix + overlay.

The tokenizer/index extension point (reference tok/ + posting/index.go;
mirrored in utils/tok.py + storage/index.py) admits new index types; this
is the TPU-native one (ROADMAP item 4): a predicate declared
`pred: float32vector @index(vector(dim: D[, metric: ...]))` folds — at
snapshot assembly, exactly where token indexes fold — into a row-aligned
`[n_subjects, D]` float32 device matrix with precomputed norms, so the
similarity probe (`similar_to` in DQL) is a segmented matmul + top-k
(ops/vector.py), the hardware's best operation.

Freshness follows storage/delta.py's delta-main split, one level up:

  * a commit touching the predicate STAMPS a `VecOverlay` — the UNCHANGED
    base matrix (device identity preserved: no re-fold, no re-upload) plus
    replacement rows for exactly the touched subjects, O(Δ);
  * searches merge on read: base candidates (touched rows masked on
    device) + overlay rows re-scored host-side, one ranking rule;
  * compaction (SnapshotAssembler.compact -> build_pred) folds the overlay
    back into a fresh base — stamped and folded views rank identically
    (tests/test_vector.py asserts byte-equivalence).

Ranking rule (shared by EVERY path — host scan, device brute force, IVF,
mesh-sharded, fused ANN->expand): float32 device stages only produce a
candidate superset; the final k is picked host-side by exact float64
(distance, uid). Brute force is therefore byte-identical to a host
float64 scan whenever the float32 margin holds (the acceptance gate), and
toggling host/device/mesh paths can never change a result.

IVF: at fold time, tablets past `IVF_MIN_ROWS` also build a k-means
coarse quantizer (deterministic seeded Lloyd's); searches scan the
`nprobe` nearest lists (`VECTOR_NPROBE`, --vector_nprobe) and re-score
candidates exactly. Recall@k >= 0.95 is gated in tests and bench.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from dgraph_tpu.obs import otrace
from dgraph_tpu.ops import vector as vops
from dgraph_tpu.utils.schema import VectorSpec
from dgraph_tpu.utils.types import TypeID, Val

# below this many row*dim float32 cells the float64 host scan beats the
# device's fixed per-dispatch + sync cost (the same size-adaptive switch
# task.HOST_EXPAND_MAX applies to frontier expands)
HOST_SCAN_MAX = 1 << 16

# IVF knobs (ops-tunable: --vector_nprobe / --vector_centroids; docs/ops.md)
IVF_MIN_ROWS = 4096          # smaller tablets stay brute-force exact
VECTOR_NPROBE = 8            # coarse lists scanned per query
VECTOR_CENTROIDS = 0         # 0 = auto (~sqrt(n), clamped to [8, 1024])
_KMEANS_ITERS = 8
_KMEANS_SEED = 7


@dataclass(frozen=True)
class VectorKnobs:
    """Per-node IVF knob overrides (Node kwargs / serve flags). Rides the
    node's Store into the fold (csr_build) so two Nodes in one process
    never see each other's thresholds; zero/negative fields keep the
    module defaults above.

    nprobe is stamped onto each VectorIndex at fold time — the coarse
    quantizer and the lists-scanned-per-query knob belong to the same
    index instance."""

    nprobe: int = 0              # 0 = VECTOR_NPROBE
    centroids: int = -1          # -1 = VECTOR_CENTROIDS, 0 = auto
    ivf_min_rows: int = 0        # 0 = IVF_MIN_ROWS


@dataclass
class IVFIndex:
    """Coarse quantizer: centroids + row lists (CSR over parent rows)."""

    centroids: np.ndarray        # float32 [C, D]
    list_indptr: np.ndarray      # int64 [C+1]
    list_rows: np.ndarray        # int32 [n] parent row ids, grouped by list

    @property
    def n_lists(self) -> int:
        return len(self.centroids)

    def nbytes(self) -> int:
        return int(self.centroids.nbytes + self.list_indptr.nbytes +
                   self.list_rows.nbytes)


class VectorIndex:
    """One predicate's folded vector index: sorted subjects + row-aligned
    embedding matrix (host float32 mirror; device arrays upload lazily on
    the first device-path search and keep identity for the snapshot's
    lifetime — the HBM-resident contract)."""

    is_overlay = False
    # residency owner protocol (storage/residency.py): the [R, D] device
    # matrix + norms + subjects are the droppable buffer group; the host
    # float32 fold is the warm-tier truth
    _res = None
    _res_attr = ""
    _res_kind = "vec"

    def __init__(self, attr: str, spec: VectorSpec, subjects: np.ndarray,
                 vecs: np.ndarray, ivf: IVFIndex | None = None,
                 nprobe: int = 0) -> None:
        self.attr = attr
        self.dim = int(spec.dim)
        self.metric = spec.metric
        self.subjects = np.asarray(subjects, dtype=np.int64)   # sorted
        self.vecs = np.asarray(vecs, dtype=np.float32).reshape(
            len(self.subjects), self.dim)
        self.ivf = ivf
        self.nprobe = int(nprobe)    # 0 = VECTOR_NPROBE at search time
        self._vecs64 = None      # lazy float64 mirror (exact re-rank)
        self._dev = None         # lazy (matrix[R,D], norms[R], subs[R])
        self._mesh = None        # mesh placement (parallel/mesh_exec.py)

    @property
    def n(self) -> int:
        return len(self.subjects)

    def nbytes(self) -> int:
        return int(self.subjects.nbytes + self.vecs.nbytes +
                   (self._vecs64.nbytes if self._vecs64 is not None else 0) +
                   (self.ivf.nbytes() if self.ivf is not None else 0))

    def vecs64(self) -> np.ndarray:
        """Full float64 mirror — host-scan-class tablets only (<= 64 KB
        float32); device-class paths must slice candidates via rows64()
        so a large tablet never pins an 8*n*D host copy."""
        if self._vecs64 is None:
            m = self.vecs.astype(np.float64)
            if self.n * self.dim <= HOST_SCAN_MAX:
                self._vecs64 = m
            else:
                return m
        return self._vecs64

    def rows64(self, rows: np.ndarray) -> np.ndarray:
        """Float64 view of the selected candidate rows (exact re-rank)."""
        if self._vecs64 is not None:
            return self._vecs64[rows]
        return self.vecs[rows].astype(np.float64)

    def device(self, prefetch: bool = False):
        """(matrix [R, D], norms [R], subjects [R] int32) padded to the
        pow2 row-capacity class (bounds jit retraces, ops/vector.py).
        Uploads through the residency seam when managed — admission
        against the device budget, evictable back to the warm host tier
        without touching this object's identity."""
        from dgraph_tpu.storage import residency as resmod

        def build():
            import jax.numpy as jnp

            R = vops.row_capacity(self.n)
            mat = np.zeros((R, self.dim), dtype=np.float32)
            mat[: self.n] = self.vecs
            norms = np.ones(R, dtype=np.float32)
            norms[: self.n] = np.linalg.norm(self.vecs, axis=1)
            subs = np.zeros(R, dtype=np.int32)
            subs[: self.n] = self.subjects.astype(np.int32)
            return (jnp.asarray(mat), jnp.asarray(norms),
                    jnp.asarray(subs))

        return resmod.ensure_device(self, "_dev", build, prefetch=prefetch)

    def device_resident(self) -> bool:
        return self._dev is not None

    def drop_device(self) -> None:
        self._dev = None

    def device_nbytes(self) -> int:
        R = vops.row_capacity(self.n)
        return int(R * self.dim * 4 + R * 4 + R * 4)

    def prefer_host(self) -> bool:
        from dgraph_tpu.storage import residency as resmod

        return resmod.prefer_host(self)


class VecOverlay:
    """VectorIndex view = unchanged base + replacement rows for the
    touched subjects (has[i]=False deletes). Never stacks: the assembler
    re-stamps from the true folded base (storage/delta.py contract)."""

    is_overlay = True

    def __init__(self, base: VectorIndex | None, attr: str,
                 spec: VectorSpec, subs: np.ndarray, vecs: np.ndarray,
                 has: np.ndarray) -> None:
        assert base is None or not base.is_overlay
        self.base = base
        self.attr = attr
        self.dim = int(spec.dim)
        self.metric = spec.metric
        self.subs = np.asarray(subs, dtype=np.int64)        # sorted
        self.ovecs = np.asarray(vecs, dtype=np.float32).reshape(
            len(self.subs), self.dim)
        self.has = np.asarray(has, dtype=bool)
        # base rows shadowed by the overlay (masked out of device scans)
        if base is not None and base.n:
            from dgraph_tpu.ops import uidset as us

            rb = us.host_rank_of(base.subjects, self.subs, -1)
            self.dead_rows = rb[rb >= 0].astype(np.int32)
        else:
            self.dead_rows = np.zeros(0, np.int32)

    @property
    def n(self) -> int:
        base_n = self.base.n if self.base is not None else 0
        return base_n - len(self.dead_rows) + int(self.has.sum())

    def nbytes(self) -> int:
        return int(self.subs.nbytes + self.ovecs.nbytes +
                   self.has.nbytes + self.dead_rows.nbytes)

    def live_rows(self) -> tuple[np.ndarray, np.ndarray]:
        """(subjects, vecs float64) of the overlay's live replacement rows."""
        return self.subs[self.has], self.ovecs[self.has].astype(np.float64)


# ---------------------------------------------------------------------------
# fold / stamp
# ---------------------------------------------------------------------------

def _kmeans(vecs: np.ndarray, k: int, iters: int, seed: int) -> np.ndarray:
    """Deterministic Lloyd's over float32 rows; empty clusters re-seed from
    the farthest points so every centroid stays live."""
    rng = np.random.default_rng(seed)
    n = len(vecs)
    cent = vecs[rng.choice(n, size=k, replace=False)].astype(np.float64)
    x = vecs.astype(np.float64)
    x2 = np.einsum("ij,ij->i", x, x)
    for _ in range(iters):
        d = x2[:, None] - 2.0 * (x @ cent.T) + \
            np.einsum("ij,ij->i", cent, cent)[None, :]
        assign = np.argmin(d, axis=1)
        empties = []
        for c in range(k):
            m = assign == c
            if m.any():
                cent[c] = x[m].mean(axis=0)
            else:
                empties.append(c)
        if empties:
            # DISTINCT farthest points per empty cluster (k <= n
            # guarantees enough), not one shared argmax — duplicate
            # centroids would split a list's rows arbitrarily and waste
            # nprobe slots on clones
            far = np.argsort(d.min(axis=1))[::-1]
            for j, c in enumerate(empties):
                cent[c] = x[int(far[j])]
    return cent.astype(np.float32)


def _build_ivf(vecs: np.ndarray, metric: str,
               centroids: int = -1) -> IVFIndex:
    n = len(vecs)
    k = (centroids if centroids >= 0 else VECTOR_CENTROIDS) \
        or int(np.clip(int(np.sqrt(n)), 8, 1024))
    k = min(k, n)
    if metric == "cosine":
        # cosine is scale-invariant: cluster DIRECTIONS (row-normalized
        # spherical space), or vectors of different norms pointing the
        # same way land in different lists and the probe misses them
        norms = np.linalg.norm(vecs, axis=1, keepdims=True)
        vecs = (vecs / np.maximum(norms, 1e-30)).astype(np.float32)
    cent = _kmeans(vecs, k, _KMEANS_ITERS, _KMEANS_SEED)
    # assignment by L2 to the centroid in the (possibly normalized)
    # coarse space — standard IVF
    x = vecs.astype(np.float64)
    c64 = cent.astype(np.float64)
    d = (np.einsum("ij,ij->i", x, x)[:, None] - 2.0 * (x @ c64.T) +
         np.einsum("ij,ij->i", c64, c64)[None, :])
    assign = np.argmin(d, axis=1)
    order = np.argsort(assign, kind="stable")
    counts = np.bincount(assign, minlength=k)
    indptr = np.zeros(k + 1, np.int64)
    np.cumsum(counts, out=indptr[1:])
    return IVFIndex(cent, indptr, order.astype(np.int32))


def _subject_vectors(spec: VectorSpec, host_values: dict[int, Val]):
    subs, rows = [], []
    for u in sorted(host_values):
        v = host_values[u]
        if v.tid != TypeID.VECTOR or len(v.value) != spec.dim:
            continue          # defensive: mutation validation enforces dim
        subs.append(u)
        rows.append(v.value)
    return subs, rows


def build_vecindex(attr: str, spec: VectorSpec,
                   host_values: dict[int, Val],
                   knobs: VectorKnobs | None = None) -> VectorIndex | None:
    """Fold one predicate's vector rows at snapshot assembly (the vector
    analog of csr_build's token-index fold). None when no rows."""
    from dgraph_tpu.storage.csr_build import MAX_DEVICE_UID

    subs, rows = _subject_vectors(spec, host_values)
    if not subs:
        return None
    if subs[-1] > MAX_DEVICE_UID:      # sorted; same read-time contract
        raise ValueError(             # as the CSR/value-table folds
            f"uid {subs[-1]} exceeds device uid space")
    vecs = np.asarray(rows, dtype=np.float32)
    min_rows = (knobs.ivf_min_rows if knobs and knobs.ivf_min_rows > 0
                else IVF_MIN_ROWS)
    ivf = _build_ivf(vecs, spec.metric,
                     knobs.centroids if knobs else -1) \
        if len(subs) >= min_rows else None
    return VectorIndex(attr, spec, np.asarray(subs, dtype=np.int64),
                       vecs, ivf, nprobe=knobs.nprobe if knobs else 0)


def stamp_vecindex(base: VectorIndex | None, attr: str, spec: VectorSpec,
                   touched: np.ndarray,
                   host_values: dict[int, Val]) -> "VecOverlay | VectorIndex | None":
    """O(Δ) overlay stamp: replacement rows for the commit's touched
    subjects, derived from the already-patched host_values (the same
    source a full fold reads — byte-equivalence by construction)."""
    subs = np.asarray(sorted(int(s) for s in touched), dtype=np.int64)
    vecs = np.zeros((len(subs), spec.dim), dtype=np.float32)
    has = np.zeros(len(subs), dtype=bool)
    for i, u in enumerate(subs.tolist()):
        v = host_values.get(u)
        if v is not None and v.tid == TypeID.VECTOR and \
                len(v.value) == spec.dim:
            vecs[i] = np.asarray(v.value, dtype=np.float32)
            has[i] = True
    if base is None and not has.any():
        return None
    return VecOverlay(base, attr, spec, subs, vecs, has)


# ---------------------------------------------------------------------------
# search
# ---------------------------------------------------------------------------

def _rank(dists: np.ndarray, uids: np.ndarray, k: int):
    """Final (distance, uid) ascending rank — THE selection rule."""
    order = np.lexsort((uids, dists))[: k]
    return uids[order], dists[order]


def _rescore(base: VectorIndex, rows: np.ndarray, q64: np.ndarray):
    d = vops.host_distances(base.rows64(rows), q64, base.metric)
    return base.subjects[rows], d


def _device_candidates(vi: VectorIndex, q: np.ndarray, kprime: int,
                       dead_rows: np.ndarray, metrics=None) -> np.ndarray:
    """Float32 device candidate rows (superset stage). Mesh-sharded
    placements fan the row scan across the device mesh with a replicated
    top-k merge (parallel/mesh_exec.py)."""
    if vi._mesh is not None:
        return vi._mesh.vector_topk(vi, q, kprime, dead_rows)
    import jax.numpy as jnp

    mat, norms, _subs = vi.device()
    block = min(int(mat.shape[0]), max(vops.BLOCK_ROWS, kprime))
    mcap = 1 << max(int(np.ceil(np.log2(max(len(dead_rows), 1) + 1))), 3)
    dr = np.full(mcap, mat.shape[0], np.int32)
    dr[: len(dead_rows)] = dead_rows
    with otrace.span("device_kernel", kernel="vector.topk",
                     rows=int(vi.n), k=kprime) as sp:
        nd, rows = vops.topk_candidates(
            mat, norms, jnp.asarray(q.astype(np.float32)),
            jnp.int32(vi.n), jnp.asarray(dr),
            k=kprime, metric=vi.metric, block=block)
        rows_h = np.asarray(rows)
        nd_h = np.asarray(nd)
        if sp:
            sp.set(transfer_d2h_bytes=int(rows_h.nbytes + nd_h.nbytes))
    return rows_h[nd_h > -np.inf]


def _ivf_candidate_rows(vi: VectorIndex, q64: np.ndarray,
                        nprobe: int) -> np.ndarray:
    ivf = vi.ivf
    # coarse ranking in the index's own metric: cosine queries must rank
    # lists scale-invariantly (a 0.01x query has the same exact answer,
    # so it must probe the same lists)
    cd = vops.host_distances(ivf.centroids.astype(np.float64), q64,
                             vi.metric)
    lists = np.argsort(cd, kind="stable")[: max(nprobe, 1)]
    parts = [ivf.list_rows[ivf.list_indptr[c]: ivf.list_indptr[c + 1]]
             for c in sorted(lists.tolist())]
    return np.concatenate(parts) if parts else np.zeros(0, np.int32)


def search(vi, q, k: int, *, nprobe: int | None = None,
           exact: bool | None = None, metrics=None):
    """Top-k nearest subjects of one vector index view.

    Returns (uids int64[<=k], dists float64[<=k]) ranked by (distance,
    uid) ascending — identical across the host-scan / device / IVF /
    mesh / overlay paths by the shared float64 re-rank.

    exact: None = auto (IVF when the fold built one), True forces the
    brute-force path (the recall gate's reference), False forces IVF.
    """
    if vi is None or k <= 0:
        return np.zeros(0, np.int64), np.zeros(0, np.float64)
    q = np.asarray(q, dtype=np.float32).reshape(-1)
    if len(q) != vi.dim:
        from dgraph_tpu.query.task import TaskError

        raise TaskError(
            f"similar_to({vi.attr}): query vector dim {len(q)} != "
            f"index dim {vi.dim}")
    q64 = q.astype(np.float64)
    if metrics is not None:
        metrics.counter("dgraph_vector_searches_total").inc()

    base = vi.base if vi.is_overlay else vi
    dead = vi.dead_rows if vi.is_overlay else np.zeros(0, np.int32)

    # residency tier consult: a COLD vector tablet (device matrix larger
    # than the whole device budget) serves the exact float64 host scan —
    # the same ranking rule, never an upload
    cold = base is not None and base.prefer_host()
    if cold and getattr(base, "_res", None) is not None:
        base._res.note_cold_serve()

    def _host_scan():
        d = vops.host_distances(base.vecs64(), q64, base.metric)
        if len(dead):
            d[dead] = np.inf
        rows = np.argsort(d, kind="stable")[: min(k, base.n)]
        rows = rows[np.isfinite(d[rows])]
        cand_subs.append(base.subjects[rows])
        cand_d.append(d[rows])

    cand_subs: list[np.ndarray] = []
    cand_d: list[np.ndarray] = []
    if base is not None and base.n:
        # a mesh-sharded placement wins over IVF: the sharded brute scan
        # is what the placement exists for (per-device row slices), while
        # _ivf_device_stage would upload the FULL base matrix to one
        # device — exactly the memory profile sharding avoids
        use_ivf = base._mesh is None and ((exact is False) or (
            exact is None and base.ivf is not None))
        if use_ivf and base.ivf is not None:
            if metrics is not None:
                metrics.counter("dgraph_vector_ivf_probes_total").inc()
            rows = _ivf_candidate_rows(
                base, q64,
                nprobe or base.nprobe or VECTOR_NPROBE)
            if len(dead):
                rows = rows[~np.isin(rows, dead)]
            if len(rows):
                if len(rows) * base.dim > HOST_SCAN_MAX and not cold:
                    from dgraph_tpu.utils.faults import FaultError

                    try:
                        rows = _ivf_device_stage(base, q, rows, k, metrics)
                    except FaultError:
                        pass    # injected h2d fault: exact host re-rank
                        # of the full probed candidate set (a superset)
                s, d = _rescore(base, rows, q64)
                cand_subs.append(s)
                cand_d.append(d)
        elif base.n * base.dim <= HOST_SCAN_MAX or cold:
            # tiny tablet (or cold tier): exact float64 host scan, no
            # dispatch (sized on the BASE so vecs64() caching always
            # applies for the tiny case; a large base with many
            # overlay-dead rows stays on the device path, which masks
            # them without pinning a full float64 mirror)
            _host_scan()
        else:
            from dgraph_tpu.utils.faults import FaultError

            kprime = vops.k_capacity(k, vops.row_capacity(base.n))
            try:
                rows = _device_candidates(base, q, kprime, dead, metrics)
            except FaultError:
                # injected h2d fault at the upload seam: byte-identical
                # host scan (the shared float64 ranking rule)
                rows = None
            if rows is None:
                _host_scan()
            elif len(rows):
                s, d = _rescore(base, rows, q64)
                cand_subs.append(s)
                cand_d.append(d)
    if vi.is_overlay:
        osubs, ovecs = vi.live_rows()
        if len(osubs):
            cand_subs.append(osubs)
            cand_d.append(vops.host_distances(ovecs, q64, vi.metric))
    if not cand_subs:
        return np.zeros(0, np.int64), np.zeros(0, np.float64)
    return _rank(np.concatenate(cand_d), np.concatenate(cand_subs), k)


def _ivf_device_stage(base: VectorIndex, q: np.ndarray, rows: np.ndarray,
                      k: int, metrics=None) -> np.ndarray:
    """Large IVF candidate set: gather + score + top-k on device, then the
    usual float64 re-rank over the reduced set."""
    import jax.numpy as jnp

    mat, norms, _subs = base.device()
    R = int(mat.shape[0])
    ccap = 1 << max(int(np.ceil(np.log2(len(rows) + 1))), 4)
    cr = np.full(ccap, R, np.int32)
    cr[: len(rows)] = rows
    kprime = vops.k_capacity(k, ccap)
    with otrace.span("device_kernel", kernel="vector.ivf_topk",
                     cands=int(len(rows)), k=kprime) as sp:
        nd, sel = vops.ivf_topk(mat, norms,
                                jnp.asarray(q.astype(np.float32)),
                                jnp.asarray(cr), k=kprime,
                                metric=base.metric)
        sel_h = np.asarray(sel)
        nd_h = np.asarray(nd)
        if sp:
            sp.set(transfer_d2h_bytes=int(sel_h.nbytes + nd_h.nbytes))
    return sel_h[nd_h > -np.inf]
