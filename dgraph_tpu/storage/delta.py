"""Incremental delta-overlay posting maintenance: O(Δ) commit-to-visible.

Reference semantics: the reference never rebuilds the world on a write — a
posting list is an immutable packed base plus a mutable delta layer merged at
read time (posting/lists.go:243 read-through, posting/mvcc.go), compacted by
background rollups. Our snapshot builder violated that: any commit moving a
predicate's watermark re-folded the WHOLE tablet (build_pred) and re-uploaded
the CSR, so a single-edge commit on a 16M-edge predicate paid O(tablet).

This module restores the delta-main split at snapshot granularity:

  * A commit's touched keys land in the store's per-predicate delta journal
    (storage/store.py `delta_since`). The SnapshotAssembler STAMPS a cached
    PredData with the journal delta instead of re-folding: replacement rows
    for exactly the touched subjects, computed from each key's own layer
    stack at read_ts — cost O(Δ), not O(tablet).
  * `OverlayCSR` = unchanged base `PredCSR` (device arrays keep identity —
    no re-fold, no re-upload) + sorted replacement rows for the touched
    subjects. The hot expand path patches per-frontier-slot
    (query/task._expand_csr merge-on-read via uidset.host_rank_of; device
    path via ops/csr.expand_masked); cold consumers (kernels, sorts) see
    lazily merged mirrors.
  * Token indexes and value tables patch the same way: touched terms /
    subjects are re-derived, everything else is shared BY REFERENCE with the
    base PredData, so unrelated device arrays also keep identity.
  * A size/age threshold triggers background compaction (csr_build.
    SnapshotAssembler.compact): the overlay folds into a fresh base off the
    query path — the rollup of posting/list.go, one level up.

Byte-identity contract: a stamped PredData must be indistinguishable from a
from-scratch `build_pred` at the same read_ts (contrib/scripts/
smoke_ingest.sh asserts it). Replacement rows use the exact same per-key
fold (`PostingList.uids` / csr_build's shared `_fold_value_subject`), so the
contract holds by construction.
"""

from __future__ import annotations

import bisect

import numpy as np

from dgraph_tpu.ops import uidset as us
from dgraph_tpu.storage import keys as K
from dgraph_tpu.utils.types import TypeID

_EMPTY64 = np.zeros(0, np.int64)


class OverlayRows:
    """Replacement rows for the touched subjects of one (kind, attr) CSR:
    subject -> its COMPLETE sorted uid row at read_ts (empty = all edges
    gone). Replacement (not add/del sets) keeps DEL_ALL, re-adds, and
    mixed-op layers correct with one code path — the row is re-derived from
    the key's own layer stack, which is O(that key), not O(tablet)."""

    __slots__ = ("subs", "rows", "lens")

    def __init__(self, subs: np.ndarray, rows: list[np.ndarray]) -> None:
        self.subs = np.asarray(subs, dtype=np.int64)      # sorted, unique
        self.rows = rows
        self.lens = np.fromiter((len(r) for r in rows), np.int64,
                                count=len(rows))

    @property
    def depth(self) -> int:
        return len(self.rows)

    def nbytes(self) -> int:
        return int(self.subs.nbytes + self.lens.nbytes +
                   sum(r.nbytes for r in self.rows))


def overlay_rows(store, kbs: list[bytes], read_ts: int) -> OverlayRows:
    """Build replacement rows for a delta's DATA/REVERSE keys at read_ts."""
    from dgraph_tpu.storage.csr_build import MAX_DEVICE_UID

    pairs = sorted((K.uid_of(kb), kb) for kb in kbs)
    subs = np.asarray([s for s, _ in pairs], dtype=np.int64)
    rows = []
    for subj, kb in pairs:
        pl = store.lists.get(kb)
        u = pl.uids(read_ts) if pl is not None else _EMPTY64
        if len(u) and int(u[-1]) > MAX_DEVICE_UID:
            raise ValueError("object uid exceeds device uid space")
        rows.append(u)
    if len(subs) and int(subs[-1]) > MAX_DEVICE_UID:
        raise ValueError(f"uid {subs[-1]} exceeds device uid space")
    return OverlayRows(subs, rows)


class OverlayCSR:
    """PredCSR view = immutable base + replacement rows for touched
    subjects. Duck-types PredCSR:

      * `.base` keeps the original device arrays untouched (identity across
        overlay-only commits — the no-re-upload contract).
      * `subjects_host()` / `subjects_degrees_host()` merge subjects and
        degrees only — O(N) vectorized, no edge copy (has(), count()).
      * `host_arrays()` lazily materializes fully merged host mirrors
        (recurse seed mapping, sorts — rare on overlaid predicates).
      * `.subjects/.indptr/.indices` lazily upload merged device arrays for
        kernel consumers; compaction soon replaces the overlay, so this is
        a transient cost, never the steady state.
      * the hot expand path never touches the merged mirrors:
        `frontier_plan` hands task._expand_csr a per-slot patch plan.
    """

    is_dist = False
    # residency owner protocol (storage/residency.py): the overlay's
    # MERGED device view is the droppable buffer group; the base PredCSR
    # is adopted separately and keeps its own entry
    _res = None
    _res_attr = ""
    _res_kind = "csr:merged"

    def __init__(self, base, delta: OverlayRows) -> None:
        # stacking overlays would hide the true base: the assembler always
        # re-stamps from the folded PredData, so `base` is plain (or None)
        assert not isinstance(base, OverlayCSR)
        self.base = base
        self.delta = delta
        self._subs_deg = None          # merged (subjects, degrees)
        self._merged_host = None       # merged (subjects, indptr, indices)
        self._merged_dev = None        # merged device PredCSR

    # -- base mirrors --------------------------------------------------------

    def _base_host(self):
        if self.base is None:
            return (_EMPTY64, np.zeros(1, np.int64), _EMPTY64)
        return self.base.host_arrays()

    # -- merged subject/degree view (O(N), no edge copy) ---------------------

    def subjects_degrees_host(self) -> tuple[np.ndarray, np.ndarray]:
        if self._subs_deg is None:
            bs, bip, _ = self._base_host()
            bs = np.asarray(bs, dtype=np.int64)
            deg_b = (np.asarray(bip[1:], np.int64)
                     - np.asarray(bip[:-1], np.int64))
            rb = us.host_rank_of(bs, self.delta.subs, -1)
            keep = np.ones(len(bs), dtype=bool)
            keep[rb[rb >= 0]] = False
            add = self.delta.lens > 0          # empty rows fall out of the CSR
            subs = np.concatenate([bs[keep], self.delta.subs[add]])
            degs = np.concatenate([deg_b[keep], self.delta.lens[add]])
            order = np.argsort(subs, kind="stable")
            self._subs_deg = (subs[order], degs[order])
        return self._subs_deg

    def subjects_host(self) -> np.ndarray:
        return self.subjects_degrees_host()[0]

    @property
    def num_subjects(self) -> int:
        return len(self.subjects_host())

    @property
    def num_edges(self) -> int:
        return int(self.subjects_degrees_host()[1].sum())

    def approx_nbytes(self) -> int:
        base = self.base.host_nbytes() if self.base is not None else 0
        return base + self.delta.nbytes()

    # -- hot-path merge plan (task._expand_csr) ------------------------------

    def frontier_plan(self, uids: np.ndarray):
        """Per-frontier-slot merge plan: (base rows with touched slots
        masked to SENTINEL32, overlay row index or -1, base degree, overlay
        degree). O(|frontier| log N + Δ) — never materializes the merge."""
        bs, bip, _ = self._base_host()
        ro = us.host_rank_of(self.delta.subs, uids, -1)
        touched = ro >= 0
        if len(bs) == 0:        # base-less overlay (tablet born from deltas)
            rb = np.full(len(uids), us.SENTINEL32, np.int32)
            deg_b = np.zeros(len(uids), np.int64)
        else:
            rb = us.host_rank_of(bs, uids, us.SENTINEL32).astype(np.int32)
            rb = np.where(touched, us.SENTINEL32, rb).astype(np.int32)
            rc = np.clip(rb, 0, len(bip) - 2)
            bip = np.asarray(bip, dtype=np.int64)
            deg_b = np.where(rb != us.SENTINEL32, bip[rc + 1] - bip[rc], 0)
        lens = self.delta.lens
        lc = np.clip(ro, 0, max(len(lens) - 1, 0))
        deg_o = np.where(touched, lens[lc] if len(lens) else 0, 0)
        return rb, ro, deg_b.astype(np.int64), deg_o.astype(np.int64)

    # -- fully merged mirrors (cold consumers) -------------------------------

    def host_arrays(self):
        if self._merged_host is None:
            bs, bip, bix = self._base_host()
            bs = np.asarray(bs, dtype=np.int64)
            bip = np.asarray(bip, dtype=np.int64)
            bix = np.asarray(bix, dtype=np.int64)
            rb = us.host_rank_of(bs, self.delta.subs, -1)
            keep = np.ones(len(bs), dtype=bool)
            keep[rb[rb >= 0]] = False
            add = self.delta.lens > 0
            ov_rows = [r for r, a in zip(self.delta.rows, add) if a]
            ov_flat = (np.concatenate(ov_rows).astype(np.int64)
                       if ov_rows else _EMPTY64)
            ov_starts = np.zeros(len(ov_rows), np.int64)
            if ov_rows:
                np.cumsum(self.delta.lens[add][:-1], out=ov_starts[1:])
            src = np.concatenate([bix, ov_flat])
            subs = np.concatenate([bs[keep], self.delta.subs[add]])
            counts = np.concatenate(
                [bip[1:][keep] - bip[:-1][keep], self.delta.lens[add]])
            starts = np.concatenate([bip[:-1][keep], len(bix) + ov_starts])
            order = np.argsort(subs, kind="stable")
            subs, counts, starts = subs[order], counts[order], starts[order]
            indptr = np.zeros(len(subs) + 1, np.int64)
            np.cumsum(counts, out=indptr[1:])
            total = int(indptr[-1])
            idx = (np.repeat(starts - indptr[:-1], counts)
                   + np.arange(total, dtype=np.int64))
            self._merged_host = (subs, indptr, src[idx])
        return self._merged_host

    def _merged_device(self):
        if self._merged_dev is None:
            from dgraph_tpu.storage import residency as resmod
            from dgraph_tpu.storage.csr_build import PredCSR

            def build():
                subs, indptr, indices = self.host_arrays()
                return PredCSR(subs.astype(np.int32),
                               indptr.astype(np.int32),
                               indices.astype(np.int32))

            resmod.ensure_device(self, "_merged_dev", build)
        return self._merged_dev

    def device_resident(self) -> bool:
        return self._merged_dev is not None

    def drop_device(self) -> None:
        self._merged_dev = None

    def device_nbytes(self) -> int:
        return self.approx_nbytes()

    def prefer_host(self) -> bool:
        from dgraph_tpu.storage import residency as resmod

        # the hot expand path merges on read against the BASE device
        # arrays: the overlay defers to the base's tier for that decision
        if self.base is not None:
            return self.base.prefer_host()
        return resmod.prefer_host(self)

    @property
    def subjects(self):
        return self._merged_device().subjects

    @property
    def indptr(self):
        return self._merged_device().indptr

    @property
    def indices(self):
        return self._merged_device().indices


def csr_subjects_host(csr) -> np.ndarray:
    """Host-side subject uids of a PredCSR-like, without forcing an overlay
    edge merge (int64)."""
    f = getattr(csr, "subjects_host", None)
    if f is not None:
        return f()
    if hasattr(csr, "host_arrays"):
        return np.asarray(csr.host_arrays()[0], dtype=np.int64)
    return np.asarray(csr.subjects).astype(np.int64)   # mesh-sharded tablet


def csr_subjects_degrees(csr) -> tuple[np.ndarray, np.ndarray]:
    """(subjects, out-degrees) of a PredCSR-like — the count-index base
    quantity — without forcing an overlay edge merge."""
    f = getattr(csr, "subjects_degrees_host", None)
    if f is not None:
        return f()
    if hasattr(csr, "host_arrays"):
        s, ip, _ = csr.host_arrays()
        ip = np.asarray(ip, dtype=np.int64)
        return np.asarray(s, dtype=np.int64), ip[1:] - ip[:-1]
    s = np.asarray(csr.subjects).astype(np.int64)
    ip = np.asarray(csr.indptr).astype(np.int64)
    return s, ip[1:] - ip[:-1]


class LazyTokenIndex:
    """TokenIndex duck-type over merged HOST columns: the terms list and
    host mirrors are exact at stamp time (inequality walks, sorts, and the
    sub-64k union path never touch the device); the device columns upload
    lazily on the first large union — through the residency seam when a
    manager is attached (storage/residency.py owner protocol)."""

    _res = None
    _res_attr = ""
    _res_kind = "index:merged"

    def __init__(self, terms: list[bytes], indptr: np.ndarray,
                 uids: np.ndarray) -> None:
        self.terms = terms
        self._indptr_h = indptr.astype(np.int64)
        self._uids_h = uids.astype(np.int64)
        self._dev = None

    def term_row(self, term: bytes) -> int:
        i = bisect.bisect_left(self.terms, term)
        return i if i < len(self.terms) and self.terms[i] == term else -1

    def host_arrays(self):
        return self._indptr_h, self._uids_h

    def device_resident(self) -> bool:
        return self._dev is not None

    def drop_device(self) -> None:
        self._dev = None

    def device_nbytes(self) -> int:
        # int32 device columns (half the int64 host mirror width)
        return int(self._indptr_h.nbytes + self._uids_h.nbytes) // 2

    def host_nbytes(self) -> int:
        return int(self._indptr_h.nbytes + self._uids_h.nbytes)

    def prefer_host(self) -> bool:
        from dgraph_tpu.storage import residency as resmod

        return resmod.prefer_host(self)

    def _device(self):
        from dgraph_tpu.storage import residency as resmod

        def build():
            import jax.numpy as jnp

            return (jnp.asarray(self._indptr_h.astype(np.int32)),
                    jnp.asarray(self._uids_h.astype(np.int32)))

        return resmod.ensure_device(self, "_dev", build)

    @property
    def indptr(self):
        return self._device()[0]

    @property
    def uids(self):
        return self._device()[1]


def merge_token_index(base, patches: dict[bytes, np.ndarray]):
    """base TokenIndex + {term: replacement uid row} -> merged index.
    Empty replacement rows delete the term (build_pred never emits empty
    index rows); unknown terms insert. O(T + rows) vectorized."""
    if base is not None:
        b_terms = list(base.terms)
        b_indptr, b_uids = base.host_arrays()
        b_indptr = np.asarray(b_indptr, dtype=np.int64)
        b_uids = np.asarray(b_uids, dtype=np.int64)
    else:
        b_terms, b_indptr, b_uids = [], np.zeros(1, np.int64), _EMPTY64
    keep = np.ones(len(b_terms), dtype=bool)
    inserts: list[tuple[bytes, np.ndarray]] = []
    for term in patches:
        i = bisect.bisect_left(b_terms, term)
        if i < len(b_terms) and b_terms[i] == term:
            keep[i] = False
        row = patches[term]
        if len(row):
            inserts.append((term, np.asarray(row, dtype=np.int64)))
    inserts.sort(key=lambda t: t[0])
    kept_idx = np.flatnonzero(keep)
    terms = [b_terms[i] for i in kept_idx] + [t for t, _ in inserts]
    counts = np.concatenate(
        [b_indptr[kept_idx + 1] - b_indptr[kept_idx],
         np.asarray([len(r) for _, r in inserts], dtype=np.int64)])
    ins_flat = (np.concatenate([r for _, r in inserts])
                if inserts else _EMPTY64)
    ins_starts = np.zeros(len(inserts), np.int64)
    if inserts:
        np.cumsum(counts[len(kept_idx):][:-1], out=ins_starts[1:])
    starts = np.concatenate([b_indptr[kept_idx], len(b_uids) + ins_starts])
    order = np.argsort(np.array(terms, dtype=object), kind="stable") \
        if terms else np.zeros(0, np.int64)
    terms = [terms[i] for i in order]
    counts, starts = counts[order], starts[order]
    indptr = np.zeros(len(terms) + 1, np.int64)
    np.cumsum(counts, out=indptr[1:])
    total = int(indptr[-1])
    idx = (np.repeat(starts - indptr[:-1], counts)
           + np.arange(total, dtype=np.int64))
    src = np.concatenate([b_uids, ins_flat])
    return LazyTokenIndex(terms, indptr, src[idx] if total else _EMPTY64)


# ---------------------------------------------------------------------------
# the stamp: cached PredData + journal delta -> patched PredData
# ---------------------------------------------------------------------------

def stamp_pred(store, attr: str, base_pd, read_ts: int,
               dkeys: list[bytes]):
    """Patch a folded PredData with a commit delta at read_ts — O(Δ).

    base_pd MUST be a plain fold (never itself stamped — the assembler
    re-stamps from the true base so overlays never stack). Untouched state
    is shared BY REFERENCE with base_pd; every touched subject/term is
    re-derived with the exact logic build_pred uses, so the result is
    byte-identical to a from-scratch fold at read_ts. Raises on shapes the
    stamp can't express (caller falls back to the full fold)."""
    from dgraph_tpu.storage import csr_build as cb

    entry = store.schema.get(attr)
    tid = entry.type_id if entry else TypeID.DEFAULT
    if tid != base_pd.type_id:
        raise ValueError("schema type changed under the overlay")

    data_k: list[bytes] = []
    rev_k: list[bytes] = []
    idx_k: list[bytes] = []
    for kb in dkeys:
        kind = kb[0]
        if kind == int(K.KeyKind.DATA):
            data_k.append(kb)
        elif kind == int(K.KeyKind.REVERSE):
            rev_k.append(kb)
        elif kind == int(K.KeyKind.INDEX):
            idx_k.append(kb)
        # COUNT buckets are implicit in the CSR (degree) — nothing to patch

    pd = cb.PredData(attr, tid)
    # share everything by reference; touched pieces are replaced below
    pd.csr = base_pd.csr
    pd.rev_csr = base_pd.rev_csr
    pd.value_subjects = base_pd.value_subjects
    pd.value_subjects_host = base_pd.value_subjects_host
    pd.num_values = base_pd.num_values
    pd.num_values_host = base_pd.num_values_host
    pd.host_values = base_pd.host_values
    pd.list_values = base_pd.list_values
    pd.lang_values = base_pd.lang_values
    pd.facets = base_pd.facets
    pd.indexes = base_pd.indexes
    pd.vecindex = base_pd.vecindex

    if data_k:
        _stamp_data(store, pd, base_pd, entry, tid, data_k, read_ts)
    if rev_k:
        if entry is not None and entry.reverse:
            base = base_pd.rev_csr
            if isinstance(base, OverlayCSR):
                raise ValueError("stacked overlay")
            pd.rev_csr = OverlayCSR(base, overlay_rows(store, rev_k, read_ts))
    if idx_k:
        _stamp_indexes(store, pd, base_pd, entry, idx_k, read_ts)
    # residency adoption of the NEW owners a stamp minted (OverlayCSR
    # merged views, merged token indexes); base objects keep their
    # existing manager entries — the no-re-upload contract
    mgr = getattr(store, "residency", None)
    if mgr is not None:
        mgr.adopt_pred(pd)
    return pd


def _stamp_data(store, pd, base_pd, entry, tid, data_k, read_ts) -> None:
    """Patch the forward CSR + value tables for the delta's DATA keys."""
    from dgraph_tpu.storage import csr_build as cb

    if isinstance(base_pd.csr, OverlayCSR):
        raise ValueError("stacked overlay")
    pairs = sorted((K.uid_of(kb), kb) for kb in data_k)
    touched = np.asarray([s for s, _ in pairs], dtype=np.int64)
    touched_set = set(touched.tolist())

    uid_typed = tid == TypeID.UID
    value_side = not uid_typed     # DEFAULT predicates may carry either
    if value_side:
        pd.host_values = {u: v for u, v in base_pd.host_values.items()
                          if u not in touched_set}
        pd.list_values = {u: v for u, v in base_pd.list_values.items()
                          if u not in touched_set}
        pd.lang_values = {u: v for u, v in base_pd.lang_values.items()
                          if u not in touched_set}
    if base_pd.facets:
        pd.facets = {k: v for k, v in base_pd.facets.items()
                     if k[0] not in touched_set}
    else:
        pd.facets = {}

    edge_rows: list[np.ndarray] = []
    val_entries: dict[int, float] = {}      # subj -> num mirror value
    for subj, kb in pairs:
        pl = store.lists.get(kb)
        if pl is None:
            edge_rows.append(_EMPTY64)
            continue
        u = pl.uids(read_ts)
        if uid_typed:
            # the flat fold's facet capture: only lists carrying postings
            if pl.base_postings or pl.layers or pl.uncommitted:
                for p in pl.live_map(read_ts).values():
                    if p.facets:
                        pd.facets[(int(subj), p.uid)] = p.facets
            edge_rows.append(u)
            continue
        is_edge, num = cb._fold_value_subject(
            pd, entry, tid, int(subj), pl, read_ts, None)
        if is_edge:
            edge_rows.append(u)
        else:
            edge_rows.append(_EMPTY64)     # value subject: no CSR row
            if num is not None:
                val_entries[int(subj)] = num

    if len(touched) and int(touched[-1]) > cb.MAX_DEVICE_UID:
        raise ValueError(f"uid {touched[-1]} exceeds device uid space")
    for r in edge_rows:
        if len(r) and int(r[-1]) > cb.MAX_DEVICE_UID:
            raise ValueError("object uid exceeds device uid space")

    rows = OverlayRows(touched, edge_rows)
    if base_pd.csr is not None or rows.lens.any():
        pd.csr = OverlayCSR(base_pd.csr, rows)

    if value_side:
        _patch_value_arrays(pd, base_pd, touched, val_entries)
        if entry is not None and entry.vector is not None:
            # vector-index overlay: replacement embedding rows for exactly
            # the touched subjects (base matrix keeps device identity —
            # commit-to-visible costs O(Δ), never a re-fold/re-upload)
            from dgraph_tpu.storage import vecindex as vecmod

            base_vi = base_pd.vecindex
            if base_vi is not None and base_vi.is_overlay:
                raise ValueError("stacked overlay")
            pd.vecindex = vecmod.stamp_vecindex(
                base_vi, entry.predicate, entry.vector, touched,
                pd.host_values)


def _patch_value_arrays(pd, base_pd, touched: np.ndarray,
                        val_entries: dict[int, float]) -> None:
    """Splice the touched subjects into the sorted value tables (host
    mirrors — value compares run on the float64 host mirror, never on
    device; the uid-edge CSR is the identity-preserving one)."""
    from dgraph_tpu.storage.csr_build import MAX_DEVICE_UID

    vs = base_pd.value_subjects_host
    nv = base_pd.num_values_host
    if vs is None:
        vs, nv = _EMPTY64, np.zeros(0, np.float64)
    rb = us.host_rank_of(vs, touched, -1)
    keep = np.ones(len(vs), dtype=bool)
    keep[rb[rb >= 0]] = False
    add_subs = np.asarray(sorted(val_entries), dtype=np.int64)
    add_nums = np.asarray([val_entries[int(s)] for s in add_subs],
                          dtype=np.float64)
    new_vs = np.concatenate([vs[keep], add_subs])
    new_nv = np.concatenate([nv[keep], add_nums])
    order = np.argsort(new_vs, kind="stable")
    new_vs, new_nv = new_vs[order], new_nv[order]
    if len(new_vs) == 0:
        pd.value_subjects = pd.value_subjects_host = None
        pd.num_values = pd.num_values_host = None
        return
    if int(new_vs[-1]) > MAX_DEVICE_UID:
        raise ValueError("value subject uid exceeds device uid space")
    pd.value_subjects_host = new_vs
    pd.value_subjects = new_vs.astype(np.int32)
    pd.num_values_host = new_nv
    pd.num_values = new_nv.astype(np.float32)


def _stamp_indexes(store, pd, base_pd, entry, idx_k, read_ts) -> None:
    """Patch touched token rows of each tokenizer's index."""
    from dgraph_tpu.utils import tok as tokmod

    if entry is None or not entry.indexed:
        return          # index keys without schema index: nothing visible
    ident_to_name = {tokmod.get(n).ident: n for n in entry.tokenizers}
    per_tok: dict[str, dict[bytes, np.ndarray]] = {}
    for kb in idx_k:
        key = K.parse_key(kb)
        if not key.term:
            continue
        name = ident_to_name.get(key.term[0])
        if name is None:
            continue     # stale tokenizer ident (schema changed: the
            # structural invalidation path rebuilds from scratch anyway)
        pl = store.lists.get(kb)
        u = pl.uids(read_ts) if pl is not None else _EMPTY64
        per_tok.setdefault(name, {})[key.term[1:]] = u
    if not per_tok:
        return
    pd.indexes = dict(base_pd.indexes)
    for name, patches in per_tok.items():
        pd.indexes[name] = merge_token_index(
            base_pd.indexes.get(name), patches)


def overlay_nbytes(pd) -> int:
    """Host bytes attributable to a stamped PredData's overlay state
    (enforce_memory accounting)."""
    n = 0
    for csr in (pd.csr, pd.rev_csr):
        if isinstance(csr, OverlayCSR):
            n += csr.delta.nbytes()
    vi = getattr(pd, "vecindex", None)
    if vi is not None and getattr(vi, "is_overlay", False):
        n += vi.nbytes()
    return n
