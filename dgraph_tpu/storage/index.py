"""Secondary index maintenance, synchronous with mutations.

Reference semantics: posting/index.go — indexTokens runs the schema's
tokenizers (:44); addIndexMutation writes subject uids into IndexKey(attr,
token) posting lists (:120); reverse-edge mutations mirror uid edges under
ReverseKey (:190); count-index mutations move subjects between
CountKey(attr, n) buckets as their degree changes (:283-326);
AddMutationWithIndex orchestrates data + index + reverse + count edits under
one transaction (:377); full rebuilds iterate the data tablet and re-tokenize
(:609-839).
"""

from __future__ import annotations

from dgraph_tpu.storage import keys as K
from dgraph_tpu.storage.postings import DirectedEdge, Op, Posting
from dgraph_tpu.storage.store import Store
from dgraph_tpu.utils import tok
from dgraph_tpu.utils.schema import SchemaEntry
from dgraph_tpu.utils.types import TypeID, Val, convert


def index_tokens(entry: SchemaEntry, v: Val, lang: str = "") -> list[bytes]:
    """All index terms for a value under a predicate's tokenizers
    (reference posting/index.go:44 indexTokens). The lang tag selects the
    full-text analyzer (tok/fts.go) — index and query must agree."""
    out: list[bytes] = []
    for name in entry.tokenizers:
        tz = tok.get(name)
        if name == "fulltext" and lang:
            out.extend(bytes([tz.ident]) + t
                       for t in tok.fulltext_tokens(str(v.value), lang))
            continue
        sv = convert(v, tz.type_id) if v.tid != tz.type_id else v
        out.extend(tz.tokens(sv))
    return out


def _edge_val(edge: DirectedEdge, entry: SchemaEntry) -> Val | None:
    if edge.value is None:
        return None
    if entry.type_id not in (TypeID.DEFAULT, edge.value.tid):
        return convert(edge.value, entry.type_id)
    return edge.value


def add_mutation_with_index(store: Store, edge: DirectedEdge, start_ts: int) -> list[bytes]:
    """Apply one edge with all derived index/reverse/count mutations.

    Returns the conflict-relevant key bytes touched (fed to the transaction
    context for SSI conflict detection, posting/mvcc.go:222 Fill).
    """
    attr = edge.attr
    inferred = edge.value.tid if edge.value is not None else TypeID.UID
    entry = store.schema.ensure(attr, inferred)
    data_k = K.data_key(attr, edge.subject)
    pl = store.get(data_k)
    touched = [data_k.encode()]

    old_count = len(pl.uids(start_ts, own_start_ts=start_ts)) if entry.count else 0

    # index edits for value predicates
    if entry.indexed:
        if edge.op == Op.DEL_ALL:
            for p in pl.postings(start_ts, own_start_ts=start_ts):
                if p.value is not None:
                    _index_edit(store, entry, p.value, edge.subject,
                                start_ts, Op.DEL, touched, lang=p.lang)
        elif edge.value is not None:
            new_val = _edge_val(edge, entry)
            if entry.is_list:
                # list-valued scalars accumulate; only an explicit DEL of one
                # value removes that value's tokens
                _index_edit(store, entry, new_val, edge.subject, start_ts,
                            edge.op, touched, lang=edge.lang)
            else:
                # single-valued: the old value lives in exactly this slot —
                # a lang-agnostic read here would wrongly delete another
                # language's (or the untagged) index terms
                from dgraph_tpu.storage.postings import lang_uid

                old_val = pl.value_for_slot(start_ts, lang_uid(edge.lang),
                                            own_start_ts=start_ts)
                if old_val is not None:
                    _index_edit(store, entry, old_val, edge.subject, start_ts,
                                Op.DEL, touched, lang=edge.lang)
                if edge.op == Op.SET:
                    _index_edit(store, entry, new_val, edge.subject, start_ts,
                                Op.SET, touched, lang=edge.lang)
                elif edge.op == Op.DEL and old_val is None:
                    _index_edit(store, entry, new_val, edge.subject, start_ts,
                                Op.DEL, touched, lang=edge.lang)

    # reverse edges (uid predicates with @reverse)
    if entry.reverse and edge.value is None and edge.op != Op.DEL_ALL:
        rk = K.reverse_key(attr, edge.object_uid)
        store.add_mutation(start_ts, rk, Posting(edge.subject, edge.op))
        touched.append(rk.encode())
    if entry.reverse and edge.op == Op.DEL_ALL:
        for obj in pl.uids(start_ts, own_start_ts=start_ts):
            rk = K.reverse_key(attr, int(obj))
            store.add_mutation(start_ts, rk, Posting(edge.subject, Op.DEL))
            touched.append(rk.encode())

    # the data edge itself
    store.add_mutation(start_ts, data_k, edge.to_posting(is_list=entry.is_list))

    # count index: move subject between degree buckets
    if entry.count:
        new_count = len(pl.uids(start_ts, own_start_ts=start_ts))
        if new_count != old_count:
            ck_old = K.count_key(attr, old_count)
            ck_new = K.count_key(attr, new_count)
            store.add_mutation(start_ts, ck_old, Posting(edge.subject, Op.DEL))
            store.add_mutation(start_ts, ck_new, Posting(edge.subject, Op.SET))
            touched += [ck_old.encode(), ck_new.encode()]

    return touched


def _index_edit(store: Store, entry: SchemaEntry, v: Val | None, subject: int,
                start_ts: int, op: Op, touched: list[bytes],
                lang: str = "") -> None:
    if v is None:
        return
    for term in index_tokens(entry, v, lang):
        ik = K.index_key(entry.predicate, term)
        store.add_mutation(start_ts, ik, Posting(subject, op))
        touched.append(ik.encode())


# ---------------------------------------------------------------------------
# Full rebuilds (reference posting/index.go:609-839)
# ---------------------------------------------------------------------------

def rebuild_index(store: Store, attr: str, read_ts: int, commit_ts: int) -> None:
    """Drop and rebuild the token index of a predicate from its data tablet."""
    entry = store.schema.get(attr)
    if entry is None or not entry.indexed:
        return
    store.drop_kind(attr, K.KeyKind.INDEX)
    sts = -commit_ts  # synthetic rebuild txn
    for kb in store.keys_of(K.KeyKind.DATA, attr):
        key = K.parse_key(kb)
        for p in store.lists[kb].postings(read_ts):
            if p.value is not None:
                _index_edit(store, entry, p.value, key.uid, sts, Op.SET, [],
                            lang=p.lang)
    _commit_synthetic(store, attr, K.KeyKind.INDEX, sts, commit_ts)


def rebuild_reverse(store: Store, attr: str, read_ts: int, commit_ts: int) -> None:
    entry = store.schema.get(attr)
    if entry is None or not entry.reverse:
        return
    store.drop_kind(attr, K.KeyKind.REVERSE)
    sts = -commit_ts
    for kb in store.keys_of(K.KeyKind.DATA, attr):
        key = K.parse_key(kb)
        for obj in store.lists[kb].uids(read_ts):
            store.add_mutation(sts, K.reverse_key(attr, int(obj)), Posting(key.uid, Op.SET))
    _commit_synthetic(store, attr, K.KeyKind.REVERSE, sts, commit_ts)


def rebuild_count(store: Store, attr: str, read_ts: int, commit_ts: int) -> None:
    entry = store.schema.get(attr)
    if entry is None or not entry.count:
        return
    store.drop_kind(attr, K.KeyKind.COUNT)
    sts = -commit_ts
    for kb in store.keys_of(K.KeyKind.DATA, attr):
        key = K.parse_key(kb)
        n = store.lists[kb].length(read_ts)
        if n:
            store.add_mutation(sts, K.count_key(attr, n), Posting(key.uid, Op.SET))
    _commit_synthetic(store, attr, K.KeyKind.COUNT, sts, commit_ts)


def _commit_synthetic(store: Store, attr: str, kind: K.KeyKind,
                      start_ts: int, commit_ts: int) -> None:
    store.commit(start_ts, commit_ts, store.keys_of(kind, attr))


def needs_reindex(old: SchemaEntry | None, new: SchemaEntry) -> bool:
    """Schema change requires an index rebuild (worker/mutation.go:199)."""
    if old is None:
        return bool(new.tokenizers or new.reverse or new.count or new.vector)
    return (set(old.tokenizers) != set(new.tokenizers)
            or old.reverse != new.reverse
            or old.count != new.count
            or old.type_id != new.type_id
            or old.vector != new.vector)
