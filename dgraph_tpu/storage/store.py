"""Durable posting store: in-memory map + append-only WAL + packed snapshots.

Replaces the reference's embedded badger LSM (vendor/github.com/dgraph-io/
badger) for the posting space. The reference relies on badger's managed MVCC
transactions (NewTransactionAt/CommitAt) plus an LRU of decoded lists
(posting/lists.go lcache); here MVCC lives in PostingList layers
(storage/postings.py) and durability comes from:

  - WAL: every buffered mutation / commit / abort / schema change is appended
    as a length-prefixed JSON record and fsync'd on commit; replayed on open
    (analog of badger's value log + the Raft WAL replay path,
    worker/draft.go:738 InitAndStartNode).
  - Snapshot: `checkpoint()` rolls lists up to a watermark ts and writes a
    binary segment file of packed lists; on open the snapshot is loaded and
    the WAL tail replayed (analog of Raft snapshot + log truncation,
    worker/draft.go:636-705).

Keys are storage/keys.py encoded bytes; a per-(kind, attr) registry gives O(1)
tablet scans (a predicate's keys are a contiguous range in the reference,
x/keys.go; here they're an explicit set).
"""

from __future__ import annotations

import base64
import contextlib
import dataclasses
import io
import json
import os
import struct
import threading

import numpy as np

from dgraph_tpu.storage import keys as K
from dgraph_tpu.storage import packed
from dgraph_tpu.storage.postings import Op, Posting, PostingList
from dgraph_tpu.utils.schema import SchemaEntry, SchemaState, parse_schema
from dgraph_tpu.utils.sync import SafeLock
from dgraph_tpu.utils.types import TypeID, Val, marshal, unmarshal

_U32 = struct.Struct("<I")


# -- posting (de)serialization ----------------------------------------------

def _val_to_json(v: Val | None):
    if v is None:
        return None
    return {"t": int(v.tid), "b": base64.b64encode(marshal(v)).decode("ascii")}


def _val_from_json(j) -> Val | None:
    if j is None:
        return None
    return unmarshal(TypeID(j["t"]), base64.b64decode(j["b"]))


def posting_to_json(p: Posting) -> dict:
    d: dict = {"u": p.uid, "o": int(p.op)}
    if p.value is not None:
        d["v"] = _val_to_json(p.value)
    if p.lang:
        d["l"] = p.lang
    if p.facets:
        d["f"] = [[n, _val_to_json(v)] for n, v in p.facets]
    return d


def posting_from_json(d: dict) -> Posting:
    return Posting(
        uid=d["u"],
        op=Op(d["o"]),
        value=_val_from_json(d.get("v")),
        lang=d.get("l", ""),
        facets=tuple((n, _val_from_json(v)) for n, v in d.get("f", [])),
    )


# -- binary WAL record codec -------------------------------------------------
# The hot record types (mutation / commit / abort — ~all of a load's volume)
# encode as packed structs; rare types (schema, drops) stay JSON. The first
# byte discriminates: '{' (0x7b) = JSON, else the binary tag. Old JSON WALs
# replay unchanged. Decoded records carry RAW key bytes and Posting objects
# ("fast form"); _apply_record_locked accepts both forms. This is also the
# replication wire format — followers decode the same bytes.
#
# VERSIONING: tags 0x01-0x03 denote EXACTLY this layout (u32 key lengths,
# u16 lang/facet lengths). Any future layout change must claim NEW tag
# bytes — the tag byte is the format version, like the snapshot header
# (DGTS1/DGTS2/DGTS3 below; the writer emits DGTS3, all three still load).

_REC_M, _REC_C, _REC_A, _REC_GC = 0x01, 0x02, 0x03, 0x04
_Q = struct.Struct("<q")
_HDR_M = struct.Struct("<q I")        # start_ts, key len
_HDR_C = struct.Struct("<q q I")      # start_ts, commit_ts, n keys
_HDR_A = struct.Struct("<q I")        # start_ts, n keys
# group commit (ISSUE 16): one record = one window's commit decisions,
# appended and fsynced as ONE WAL write. Layout: tag, u32 member count,
# then per member exactly the _REC_C payload (_HDR_C + length-prefixed
# keys). Replays identically to N _REC_C records; pre-16 WALs (per-commit
# records) still load — tags discriminate.
_HDR_GC = struct.Struct("<I")         # n member commits


@dataclasses.dataclass
class TabletPacked:
    """One tablet's packed columns as contiguous slices of the snapshot's
    shared buffers (DGTS2 is key-sorted, so a tablet is one run). `pure`
    means no row carried base_postings at load; any later write drops the
    whole entry, so a surviving entry implies layer-free lists too."""

    n: int
    counts: np.ndarray            # int64[n]
    nbs: np.ndarray               # int64[n] blocks per row
    row_word_start: np.ndarray    # int64[n] word base per row (tablet-rel)
    bfirst: np.ndarray
    bcount: np.ndarray
    bwidth: np.ndarray
    boff: np.ndarray
    words: np.ndarray
    pure: bool
    max_base_ts: int              # reads below this must raise (isolation)


@dataclasses.dataclass
class SegmentRun:
    """One tablet's rows in the mmap'd snapshot (paged mode): everything a
    PostingList needs, as FILE-BACKED views the OS pages in and out. The
    badger-LSM role (SURVEY §2.1): datasets larger than host RAM, served
    through lazy per-key materialization + eviction of clean lists."""

    n: int
    uid_keyed: bool                # DATA/REVERSE: fixed-len keys ending in
    # a big-endian uid (enables the vectorized find index)
    keys_blob: "np.ndarray"        # uint8 view of this run's key bytes
    kends: "np.ndarray"            # int64[n] key end offsets (run-relative)
    base_ts: "np.ndarray"
    counts: "np.ndarray"
    nbs: "np.ndarray"              # blocks per row
    bstarts: "np.ndarray"          # int64[n+1] block offsets (run-relative)
    wstarts: "np.ndarray"          # int64[n+1] word offsets (run-relative)
    pstarts: "np.ndarray"          # int64[n+1] postings-json offsets
    bfirst: "np.ndarray"
    blast: "np.ndarray"
    bcount: "np.ndarray"
    bwidth: "np.ndarray"
    boff: "np.ndarray"
    words: "np.ndarray"
    post_blob: "np.ndarray"        # uint8 view

    def key_at(self, i: int) -> bytes:
        k0 = int(self.kends[i - 1]) if i else 0
        return bytes(self.keys_blob[k0: int(self.kends[i])])

    def _uid_index(self):
        """For fixed-length uid-keyed runs (DATA/REVERSE): the sorted
        big-endian uid column, built lazily ONCE — find() becomes one
        numpy searchsorted instead of ~log2(n) Python byte compares."""
        idx = getattr(self, "_uids", None)
        if idx is None:
            L = int(self.kends[0])
            if not self.uid_keyed or self.n * L != int(self.kends[-1]):
                self._uids = False        # variable-length keys (index)
            else:
                blob = np.ascontiguousarray(
                    np.asarray(self.keys_blob).reshape(self.n, L)[:, -8:])
                self._uids = blob.view(">u8").ravel().astype(np.uint64)
            idx = self._uids
        return idx

    def find(self, kb: bytes) -> int:
        """Binary search (keys are sorted); -1 = absent."""
        uids = self._uid_index()
        if uids is not False:
            u = np.uint64(int.from_bytes(kb[-8:], "big"))
            i = int(np.searchsorted(uids, u))
            return i if i < self.n and uids[i] == u else -1
        lo, hi = 0, self.n - 1
        while lo <= hi:
            mid = (lo + hi) // 2
            k = self.key_at(mid)
            if k == kb:
                return mid
            if k < kb:
                lo = mid + 1
            else:
                hi = mid - 1
        return -1

    def iter_keys(self):
        for i in range(self.n):
            yield self.key_at(i)

    def build_list(self, i: int) -> PostingList:
        b0, b1 = int(self.bstarts[i]), int(self.bstarts[i + 1])
        w0, w1 = int(self.wstarts[i]), int(self.wstarts[i + 1])
        p0, p1 = int(self.pstarts[i]), int(self.pstarts[i + 1])
        pl = PostingList()
        pl.base_ts = int(self.base_ts[i])
        pl.base_packed = packed.PackedUidList(
            int(self.counts[i]), self.bfirst[b0:b1], self.blast[b0:b1],
            self.bcount[b0:b1], self.bwidth[b0:b1], self.boff[b0:b1],
            self.words[w0:w1])
        if p1 > p0:
            pl.base_postings = {
                p.uid: p for p in map(
                    posting_from_json,
                    json.loads(bytes(self.post_blob[p0:p1])))}
        pl._seg_ts = pl.base_ts      # eviction safety marker
        return pl


class LazyLists(dict):
    """store.lists in paged mode: a plain dict of materialized lists whose
    misses fall through to the snapshot segments. Mutation paths write
    through normal dict assignment; eviction drops CLEAN entries (the
    segment row can reproduce them exactly)."""

    def __init__(self, store: "Store") -> None:
        super().__init__()
        self._store = store

    def get(self, kb, default=None):
        pl = super().get(kb)
        if pl is None:
            pl = self._store._materialize(kb)
        return pl if pl is not None else default

    def __getitem__(self, kb):
        pl = self.get(kb)
        if pl is None:
            raise KeyError(kb)
        return pl

    def __contains__(self, kb) -> bool:
        return super().__contains__(kb) or \
            self._store._segment_find(kb) is not None


def _key_bytes(k) -> bytes:
    return k if isinstance(k, (bytes, bytearray)) else base64.b64decode(k)


def _enc_val(out: list, v: Val) -> None:
    b = marshal(v)
    out.append(struct.pack("<B I", int(v.tid), len(b)))
    out.append(b)


def _dec_val(raw: bytes, off: int) -> tuple[Val, int]:
    tid, blen = struct.unpack_from("<B I", raw, off)
    off += 5
    return unmarshal(TypeID(tid), raw[off: off + blen]), off + blen


def encode_record(rec: dict) -> bytes:
    """Record dict -> wire/WAL bytes (binary for m/c/a, JSON otherwise)."""
    t = rec["t"]
    if t == "m":
        kb = _key_bytes(rec["k"])
        p = rec["p"]
        if not isinstance(p, Posting):
            p = posting_from_json(p)
        out = [bytes([_REC_M]), _HDR_M.pack(rec["s"], len(kb)), kb]
        flags = ((1 if p.value is not None else 0)
                 | (2 if p.lang else 0) | (4 if p.facets else 0))
        out.append(struct.pack("<Q B B", p.uid, int(p.op), flags))
        if p.value is not None:
            _enc_val(out, p.value)
        if p.lang:
            lb = p.lang.encode()
            out.append(struct.pack("<H", len(lb)) + lb)
        if p.facets:
            out.append(struct.pack("<H", len(p.facets)))
            for name, fv in p.facets:
                nb = name.encode()
                out.append(struct.pack("<H", len(nb)) + nb)
                _enc_val(out, fv)
        return b"".join(out)
    if t in ("c", "a"):
        keys = [_key_bytes(k) for k in rec["k"]]
        if t == "c":
            out = [bytes([_REC_C]), _HDR_C.pack(rec["s"], rec["ts"], len(keys))]
        else:
            out = [bytes([_REC_A]), _HDR_A.pack(rec["s"], len(keys))]
        for kb in keys:
            out.append(struct.pack("<I", len(kb)))
            out.append(kb)
        return b"".join(out)
    if t == "gc":
        out = [bytes([_REC_GC]), _HDR_GC.pack(len(rec["txns"]))]
        for sub in rec["txns"]:
            keys = [_key_bytes(k) for k in sub["k"]]
            out.append(_HDR_C.pack(sub["s"], sub["ts"], len(keys)))
            for kb in keys:
                out.append(struct.pack("<I", len(kb)))
                out.append(kb)
        return b"".join(out)
    return json.dumps(rec, separators=(",", ":")).encode("utf-8")


def decode_record(raw: bytes) -> dict:
    """Wire/WAL bytes -> record dict (fast form for binary records)."""
    tag = raw[0]
    if tag == 0x7B:                     # '{' — JSON record
        return json.loads(raw)
    off = 1
    if tag == _REC_M:
        s, klen = _HDR_M.unpack_from(raw, off)
        off += _HDR_M.size
        kb = raw[off: off + klen]
        off += klen
        uid, op, flags = struct.unpack_from("<Q B B", raw, off)
        off += 10
        value = lang = None
        facets = ()
        if flags & 1:
            value, off = _dec_val(raw, off)
        if flags & 2:
            (n,) = struct.unpack_from("<H", raw, off)
            lang = raw[off + 2: off + 2 + n].decode()
            off += 2 + n
        if flags & 4:
            (cnt,) = struct.unpack_from("<H", raw, off)
            off += 2
            fs = []
            for _ in range(cnt):
                (n,) = struct.unpack_from("<H", raw, off)
                name = raw[off + 2: off + 2 + n].decode()
                off += 2 + n
                fv, off = _dec_val(raw, off)
                fs.append((name, fv))
            facets = tuple(fs)
        return {"t": "m", "s": s, "k": kb,
                "p": Posting(uid, Op(op), value, lang or "", facets)}
    if tag == _REC_GC:
        (cnt,) = _HDR_GC.unpack_from(raw, off)
        off += _HDR_GC.size
        txns = []
        for _ in range(cnt):
            s, ts, n = _HDR_C.unpack_from(raw, off)
            off += _HDR_C.size
            keys = []
            for _ in range(n):
                (klen,) = struct.unpack_from("<I", raw, off)
                off += 4
                keys.append(raw[off: off + klen])
                off += klen
            # members are plain "c" records: replay/replication apply them
            # through the exact single-commit branch
            txns.append({"t": "c", "s": s, "k": keys, "ts": ts})
        return {"t": "gc", "txns": txns}
    if tag == _REC_C:
        s, ts, n = _HDR_C.unpack_from(raw, off)
        off += _HDR_C.size
    elif tag == _REC_A:
        s, n = _HDR_A.unpack_from(raw, off)
        ts = None
        off += _HDR_A.size
    else:
        raise ValueError(f"unknown WAL record tag {tag}")
    keys = []
    for _ in range(n):
        (klen,) = struct.unpack_from("<I", raw, off)
        off += 4
        keys.append(raw[off: off + klen])
        off += klen
    rec = {"t": "c" if tag == _REC_C else "a", "s": s, "k": keys}
    if ts is not None:
        rec["ts"] = ts
    return rec


class Store:
    """One group's posting store (the `pstore` of a server node)."""

    def __init__(self, dirpath: str | None = None,
                 memory_budget: int | None = None,
                 max_delta_keys: int | None = None) -> None:
        """memory_budget (bytes): enables PAGED mode — the snapshot is
        mmap'd, posting lists materialize lazily per key, and clean lists
        are evicted once the resident estimate exceeds the budget. The
        badger-LSM role: the dataset no longer has to fit in host RAM.

        max_delta_keys: per-attr delta-journal bound (--delta_journal_max_keys
        on the CLI); shadows the MAX_DELTA_KEYS class default. Size it to
        the working set a live subscriber may fall behind by — overflow
        forces affected subscriptions through a full resync."""
        self.dir = dirpath
        if max_delta_keys:
            self.MAX_DELTA_KEYS = int(max_delta_keys)
        self.paged = memory_budget is not None
        self.memory_budget = int(memory_budget or 0)
        self._segments: dict[tuple[int, str], SegmentRun] = {}
        self._touched: set[tuple[int, str]] = set()   # tablets with writes
        self._lazy_bytes = 0           # resident estimate (paged mode)
        self._evict_tick = 0
        self.lists: dict[bytes, PostingList] = \
            LazyLists(self) if self.paged else {}
        self.by_pred: dict[tuple[int, str], set[bytes]] = {}
        self.schema = SchemaState()
        self.dirty: set[bytes] = set()
        # lock-discipline asserts (utils/sync.py) + lockdep class name
        # (utils/locks.py) for runtime order verification in chaos runs
        self._lock = SafeLock("store.Store._lock")
        self._wal: io.BufferedWriter | None = None
        self.max_seen_commit_ts = 0
        # attr -> highest commit_ts of any commit touching it: the dirty
        # watermark incremental snapshot builds compare against (the
        # reference never rebuilds the world — posting/lists.go:243
        # read-through; here clean predicates reuse device arrays)
        self.pred_commit_ts: dict[str, int] = {}
        self.pred_replay_seq: dict[str, int] = {}   # below-watermark commits
        # per-predicate applied WaterMarks mirroring pred_commit_ts: the
        # replica-read gate (remote.serve_task min_applied) blocks on
        # wait_for_mark(timeout=) instead of a sleep/poll loop, so a
        # catching-up follower wakes the exact moment the commit applies
        self._applied_marks: dict[str, "WaterMark"] = {}
        # per-predicate delta journal: attr -> {key bytes: last commit_ts}
        # for every key committed since _delta_floor_for(attr). This is what
        # makes commit-to-visible O(Δ): the snapshot assembler stamps cached
        # device views with exactly these keys (storage/delta.py) instead of
        # re-folding the tablet. Bounded per attr; overflow resets the
        # completeness floor and the next full fold re-bases stamping.
        self._delta_log: dict[str, dict[bytes, int]] = {}
        self._delta_floor: dict[str, int] = {}
        self._delta_base_floor = 0   # commits at/below this live in bases
        # live-query retention: the oldest active subscription cursor pins
        # prune_delta so a reconnect-with-cursor stays provable; overflow
        # (the bound above still wins over the pin) notifies the live
        # manager which predicates lost completeness. The callback runs
        # INSIDE the commit critical section and must not take locks.
        self._delta_pin: int | None = None
        self._delta_overflows = 0
        self.on_delta_overflow = None
        # cold-open fold accelerator: per-(kind, attr) CONTIGUOUS packed
        # columns captured at snapshot load (the DGTS2 layout is already
        # tablet-ordered). While an entry survives — dropped on the first
        # write touching its tablet — the snapshot fold decodes the whole
        # tablet in ONE native call with zero per-list marshalling.
        self._packed_tablets: dict[tuple[int, str], "TabletPacked"] = {}
        self.snapshot_ts = 0  # commits at/below this are folded into bases
        # records currently in wal.log (an up-to-dateness signal for
        # elections; NOT the replication ship index — that is a per-term
        # session sequence, parallel/remote.py — because checkpoint
        # compaction rewrites this file)
        self.wal_record_count = 0
        if dirpath:
            os.makedirs(dirpath, exist_ok=True)
            self._load()
            self._wal = open(os.path.join(dirpath, "wal.log"), "ab")

    # -- basic access -------------------------------------------------------

    def get(self, key: K.Key) -> PostingList:
        kb = key.encode()
        with self._lock:
            pl = self.lists.get(kb)
            if pl is None:
                pl = PostingList()
                self.lists[kb] = pl
                self.by_pred.setdefault((int(key.kind), key.attr), set()).add(kb)
                self._drop_packed(int(key.kind), key.attr)
            return pl

    def _drop_packed(self, kind: int, attr: str) -> None:
        """Invalidate the cold-open fold cache for one tablet (any write
        breaks the contiguous-and-pure contract of TabletPacked — and the
        paged bulk fold's pristine-segment assumption)."""
        if self._packed_tablets:
            self._packed_tablets.pop((kind, attr), None)
        if self._segments:
            self._touched.add((kind, attr))

    def packed_tablet(self, kind: int, attr: str) -> TabletPacked | None:
        return self._packed_tablets.get((kind, attr))

    def _purge_cached(self, kind: int, attr: str) -> None:
        """Drop materialized segment-backed lists of a dropped tablet —
        they never entered by_pred, so the by_pred purge misses them."""
        if not self.paged:
            return
        for kb in [k for k in dict.keys(self.lists)
                   if K.kind_attr_of(k) == (kind, attr)]:
            pl = dict.pop(self.lists, kb, None)
            if pl is not None:
                self._lazy_bytes -= pl.approx_bytes()
        self._lazy_bytes = max(self._lazy_bytes, 0)

    # -- paged mode (segments + lazy lists + eviction) ----------------------

    def _segment_find(self, kb: bytes):
        if not self._segments:
            return None
        seg = self._segments.get(K.kind_attr_of(kb))
        if seg is None:
            return None
        i = seg.find(kb)
        return (seg, i) if i >= 0 else None

    def _materialize(self, kb: bytes, cache: bool = True):
        """Build a PostingList from its snapshot segment row; None when the
        key has no segment backing. Cached copies count toward the resident
        estimate and are evictable while clean."""
        hit = self._segment_find(kb)
        if hit is None:
            return None
        seg, i = hit
        pl = seg.build_list(i)
        tick = False
        if cache:
            with self._lock:
                # re-check under the lock immediately before inserting: a
                # writer (Store.get + add_mutation) may have installed —
                # and dirtied — a list for this key while we built our
                # pristine copy from the segment. Clobbering theirs would
                # make a committed write invisible until WAL replay;
                # return the existing object instead.
                existing = dict.get(self.lists, kb)
                if existing is not None:
                    return existing
                dict.__setitem__(self.lists, kb, pl)
                self._lazy_bytes += pl.approx_bytes()
                self._evict_tick += 1
                if self._evict_tick >= 512:
                    self._evict_tick = 0
                    tick = True
        if tick:
            self._evict_clean()
        return pl

    def _evict_clean(self) -> None:
        """Drop clean segment-backed lists until under budget. Clean =
        reproducible from the segment row bit-for-bit: no layers, no
        uncommitted txns, base untouched since materialization, not dirty.
        Readers holding a reference keep a valid object (drop only unlinks
        from the map — the read-through contract of posting/lists.go)."""
        if self.memory_budget <= 0 or self._lazy_bytes <= self.memory_budget:
            return
        import sys

        target = int(self.memory_budget * 0.8)
        for kb, pl in list(self.lists.items()):
            if self._lazy_bytes <= target:
                break
            if (getattr(pl, "_seg_ts", None) == pl.base_ts
                    and not pl.layers and not pl.uncommitted
                    and kb not in self.dirty):
                # a writer may hold this object between Store.get and its
                # add_mutation: external references (> the 4 we create:
                # dict slot, items() snapshot, loop var, getrefcount arg)
                # mean in-flight use — skip
                if sys.getrefcount(pl) > 4:
                    continue
                dict.pop(self.lists, kb, None)
                if pl.layers or pl.uncommitted or kb in self.dirty:
                    # lost the race after all: reinstate, never drop a write
                    dict.__setitem__(self.lists, kb, pl)
                    continue
                self._lazy_bytes -= pl.approx_bytes()
        self._lazy_bytes = max(self._lazy_bytes, 0)

    def segment_max_uid(self, uid_typed_fn, slot_bits: int) -> int:
        """Max uid across segment-backed rows without materializing them
        (paged-mode uid-lease recovery): subject uids from each run's last
        key, object uids from packed block_last metadata. Rows whose
        metadata is slot-tagged (>= slot_bits: value postings) decode
        transiently — the max REAL uid hides below the slots."""
        m = 0
        for (kind, attr), seg in self._segments.items():
            if kind not in (int(K.KeyKind.DATA), int(K.KeyKind.REVERSE)) \
                    or seg.n == 0:
                continue
            m = max(m, K.uid_of(seg.key_at(seg.n - 1)))
            if kind != int(K.KeyKind.DATA) or not uid_typed_fn(attr):
                continue
            bl = np.asarray(seg.blast)
            if len(bl) == 0:
                continue
            mx = int(bl.max())
            if mx < slot_bits:
                m = max(m, mx)
                continue
            # per-row last-block max (vectorized): decode ONLY slot-tagged
            # rows — one tagged list must not force an O(edges) scan
            nz = np.flatnonzero(np.asarray(seg.nbs) > 0)
            row_last = bl[np.asarray(seg.bstarts)[nz + 1] - 1]
            clean = row_last < slot_bits
            if clean.any():
                m = max(m, int(row_last[clean].max()))
            for i in nz[~clean].tolist():   # tagged rows: transient decode
                pl = seg.build_list(i)
                u = pl.uids(max(self.max_seen_commit_ts, pl.base_ts))
                real = u[u < slot_bits]
                if len(real):
                    m = max(m, int(real[-1]))
        return m

    def tablet_lists(self, kind: int, attr: str,
                     kbs: list[bytes]) -> list:
        """PostingLists for a whole tablet scan (fold paths). Paged mode
        with no post-snapshot writes on the tablet serves the segment rows
        IN ORDER — transient objects, no per-key search, no cache churn;
        any other shape falls back to per-key lookup."""
        seg = self._segments.get((kind, attr))
        if (seg is not None and seg.n == len(kbs)
                and (kind, attr) not in self._touched
                and not self.by_pred.get((kind, attr))):
            return [seg.build_list(i) for i in range(seg.n)]
        return [self.lists.get(kb) for kb in kbs]

    def get_no_store(self, key: K.Key) -> PostingList | None:
        """Read-only peek (reference posting/lists.go GetNoStore :274)."""
        return self.lists.get(key.encode())

    def keys_of(self, kind: K.KeyKind, attr: str) -> list[bytes]:
        """All keys of one (kind, predicate) — a tablet scan. Paged mode
        merges the snapshot segment's keys (not resident in by_pred) with
        keys created by later writes."""
        with self._lock:
            extra = self.by_pred.get((int(kind), attr), ())
            seg = self._segments.get((int(kind), attr))
            if seg is None:
                return sorted(extra)
            if not extra:
                return list(seg.iter_keys())   # already sorted
            return sorted(set(seg.iter_keys()) | set(extra))

    def memory_stats(self) -> dict:
        """Approximate host memory held by posting lists (the accounting
        behind the --memory_mb budget; posting/lists.go:123-180)."""
        total = 0
        layers = 0
        with self._lock:
            pls = list(self.lists.values())
        for pl in pls:
            total += pl.approx_bytes()
            layers += pl.layer_count()
        out = {"bytes": total, "lists": len(pls), "layers": layers}
        if self.paged:
            out["paged"] = True
            out["segment_keys"] = sum(s.n for s in self._segments.values())
        return out

    def predicates(self) -> list[str]:
        with self._lock:
            out = {attr for (kind, attr) in self.by_pred
                   if kind == int(K.KeyKind.DATA)}
            out |= {attr for (kind, attr) in self._segments
                    if kind == int(K.KeyKind.DATA)}
            return sorted(out)

    def tablet_sizes(self) -> dict[str, int]:
        """Approximate bytes served per predicate, across every key space it
        owns (the size reports a group streams to Zero for rebalancing —
        worker/groups.go:454-549 periodicMembershipUpdate)."""
        out: dict[str, int] = {}
        with self._lock:
            items = [(attr, list(keys))
                     for (_kind, attr), keys in self.by_pred.items()]
        for attr, keys in items:
            n = out.get(attr, 0)
            for kb in keys:
                pl = self.lists.get(kb)
                if pl is not None:
                    n += 64 + pl.approx_bytes()
            out[attr] = n
        return out

    # -- write path ---------------------------------------------------------

    def add_mutation(self, start_ts: int, key: K.Key, p: Posting) -> None:
        self._wal_write({"t": "m", "s": start_ts, "k": key.encode(), "p": p})
        self._drop_packed(int(key.kind), key.attr)
        self.get(key).add_mutation(start_ts, p)
        self.dirty.add(key.encode())

    def commit(self, start_ts: int, commit_ts: int, key_bytes: list[bytes]) -> None:
        self._wal_write({"t": "c", "s": start_ts, "ts": commit_ts,
                         "k": list(key_bytes)}, sync=True)
        with self._lock:
            for kb in key_bytes:
                pl = self.lists.get(kb)
                if pl is not None:
                    pl.commit(start_ts, commit_ts)
                self._bump_pred_ts(kb, commit_ts)
            self.max_seen_commit_ts = max(self.max_seen_commit_ts, commit_ts)

    def commit_group(self, members: list[tuple[int, int, list[bytes]]]) -> None:
        """One commit window's durability + visibility (ISSUE 16 group
        commit): members is [(start_ts, commit_ts, key_bytes), ...] already
        decided conflict-free by the oracle. The whole window appends as
        ONE contiguous WAL record with ONE fsync (and one wal_sink ship),
        then every member's in-memory apply — pl.commit + _bump_pred_ts
        watermark/journal advance — runs under ONE store-lock hold, so the
        delta journal accumulates the window's UNION delta per predicate
        and the next read stamps each touched predicate once. A crash
        mid-append leaves a torn tail replay drops whole: the window is
        all-or-nothing in the log, never torn across members."""
        self._wal_write(
            {"t": "gc", "txns": [{"s": s, "ts": ts, "k": list(kbs)}
                                 for s, ts, kbs in members]}, sync=True)
        with self._lock:
            for start_ts, commit_ts, key_bytes in members:
                for kb in key_bytes:
                    pl = self.lists.get(kb)
                    if pl is not None:
                        pl.commit(start_ts, commit_ts)
                    self._bump_pred_ts(kb, commit_ts)
                self.max_seen_commit_ts = max(self.max_seen_commit_ts,
                                              commit_ts)

    MAX_DELTA_KEYS = 8192     # per-attr journal bound (bulk loads overflow
    # it on purpose: their next fold re-bases incremental stamping)

    def _bump_pred_ts(self, kb: bytes, commit_ts: int) -> None:
        self._lock.assert_held()   # caller owns the commit critical section
        attr = K.kind_attr_of(kb)[1]
        cur = self.pred_commit_ts.get(attr, 0)
        if commit_ts > cur:
            self.pred_commit_ts[attr] = commit_ts
            mark = self._applied_marks.get(attr)
            if mark is not None:
                # lock order store._lock -> mark cv is safe: waiters take
                # only the mark's cv, never the store lock
                mark.set_done_until(commit_ts)
        elif commit_ts < cur:
            # a commit arriving BELOW the watermark (replication replay /
            # out-of-order apply): max-only watermarks can't see it, so
            # cached snapshots key staleness on this counter too
            self.pred_replay_seq[attr] = self.pred_replay_seq.get(attr, 0) + 1
        log = self._delta_log.get(attr)
        if log is None:
            log = self._delta_log[attr] = {}
        if commit_ts > log.get(kb, 0):
            log[kb] = commit_ts
        if len(log) > self.MAX_DELTA_KEYS:
            log.clear()
            self._delta_floor[attr] = max(
                self.pred_commit_ts.get(attr, 0),
                self._delta_floor.get(attr, 0))
            self._delta_overflows += 1
            if self.metrics is not None:
                self.metrics.counter("dgraph_delta_journal_overflows").inc()
            cb = self.on_delta_overflow
            if cb is not None:   # lock-free by contract (see __init__)
                cb(attr)

    # -- delta journal (overlay stamping feed, storage/delta.py) ------------

    def _delta_floor_for(self, attr: str) -> int:
        return max(self._delta_base_floor, self._delta_floor.get(attr, 0))

    def delta_since(self, attr: str, base_ts: int) -> dict[bytes, int] | None:
        """Keys of attr committed after base_ts ({kb: commit_ts}), or None
        when the journal can't prove completeness above base_ts (overflow,
        bulk install, pre-journal snapshot) — the caller must full-fold."""
        with self._lock:
            if self._delta_floor_for(attr) > base_ts:
                return None
            log = self._delta_log.get(attr)
            if not log:
                return {}
            return {kb: ts for kb, ts in log.items() if ts > base_ts}

    def prune_delta(self, attr: str, upto_ts: int) -> None:
        """A full fold at upto_ts subsumes journal entries at/below it.
        Clamped at the subscription pin: retained extra entries are
        harmless for stamping but keep reconnect cursors provable."""
        with self._lock:
            pin = self._delta_pin
            if pin is not None and upto_ts > pin:
                upto_ts = pin
            if upto_ts < self._delta_floor_for(attr):
                return
            log = self._delta_log.get(attr)
            if log:
                for kb in [kb for kb, ts in log.items() if ts <= upto_ts]:
                    del log[kb]
            self._delta_floor[attr] = max(
                self._delta_floor.get(attr, 0), upto_ts)

    def pin_delta_floor(self, ts: int | None) -> None:
        """Retention pin from the live manager: prune_delta will not erase
        journal entries above `ts` (None unpins). The per-attr bound still
        wins — a subscriber cannot make the journal unbounded, it can only
        be told (via on_delta_overflow) that its cursor became unprovable."""
        with self._lock:
            self._delta_pin = None if ts is None else int(ts)
            if self.metrics is not None:
                self.metrics.counter("dgraph_delta_journal_pinned_floor") \
                    .set(0 if ts is None else int(ts))

    def delta_log_stats(self) -> dict:
        with self._lock:
            keys = sum(len(v) for v in self._delta_log.values())
            if self.metrics is not None:
                self.metrics.counter("dgraph_delta_journal_keys").set(keys)
            return {"attrs": len(self._delta_log), "keys": keys,
                    "max_keys": self.MAX_DELTA_KEYS,
                    "overflows": self._delta_overflows,
                    "pinned_floor": self._delta_pin,
                    "base_floor": self._delta_base_floor}

    def delta_log_by_attr(self) -> dict[str, int]:
        """attr -> journal keys held. The per-tenant accounting input:
        tenant attrs are distinct storage attrs, so grouping these by
        namespace prefix attributes journal retention to its tenant."""
        with self._lock:
            return {attr: len(v) for attr, v in self._delta_log.items()}

    def applied_mark(self, attr: str):
        """The predicate's applied watermark (done_until mirrors
        pred_commit_ts[attr]); created lazily and advanced by every commit
        bump. Callers block via wait_for_mark(ts, timeout=) — the
        replica-read gate's wait primitive."""
        from ..utils.watermark import WaterMark

        with self._lock:
            mark = self._applied_marks.get(attr)
            if mark is None:
                mark = WaterMark(name=f"applied:{attr}")
                mark.set_done_until(self.pred_commit_ts.get(attr, 0))
                self._applied_marks[attr] = mark
            return mark

    def abort(self, start_ts: int, key_bytes: list[bytes]) -> None:
        self._wal_write({"t": "a", "s": start_ts, "k": list(key_bytes)})
        with self._lock:
            for kb in key_bytes:
                pl = self.lists.get(kb)
                if pl is not None:
                    pl.abort(start_ts)

    def set_schema(self, e: SchemaEntry) -> None:
        self._wal_write({"t": "s", "line": str(e)})
        self.schema.set(e)

    def delete_predicate(self, attr: str) -> None:
        """Drop every key of a predicate (reference posting/index.go:946
        DeletePredicate; used by predicate moves and drop operations)."""
        self._wal_write({"t": "dp", "attr": attr}, sync=True)
        self._delete_predicate_mem(attr)

    def drop_kind(self, attr: str, kind: K.KeyKind) -> None:
        """Drop all keys of one (kind, predicate) — WAL-logged so index
        rebuilds survive crash+replay without resurrecting stale postings."""
        self._wal_write({"t": "dk", "attr": attr, "kind": int(kind)}, sync=True)
        self._drop_kind_mem(attr, kind)

    def _reset_delta(self, attr: str) -> None:
        """Drops are structural: the journal can't express them — reset
        completeness so stamping waits for the next full fold."""
        self._delta_log.pop(attr, None)
        self._delta_floor[attr] = max(self._delta_floor.get(attr, 0),
                                      self.max_seen_commit_ts)

    def _drop_kind_mem(self, attr: str, kind: K.KeyKind) -> None:
        with self._lock:
            self._drop_packed(int(kind), attr)
            self._reset_delta(attr)
            self._segments.pop((int(kind), attr), None)
            for kb in self.by_pred.pop((int(kind), attr), set()):
                self.lists.pop(kb, None)
                self.dirty.discard(kb)
            self._purge_cached(int(kind), attr)

    def _delete_predicate_mem(self, attr: str) -> None:
        with self._lock:
            self._reset_delta(attr)
            for kind in list(K.KeyKind):
                self._drop_packed(int(kind), attr)
                self._segments.pop((int(kind), attr), None)
                for kb in self.by_pred.pop((int(kind), attr), set()):
                    self.lists.pop(kb, None)
                    self.dirty.discard(kb)
                self._purge_cached(int(kind), attr)
            self.schema.delete(attr)

    # -- bulk ingest ---------------------------------------------------------

    @contextlib.contextmanager
    def _sink_suspended(self):
        """Checkpoint's WAL-reset rewrites are LOCAL compaction — shipping
        them would append duplicates to follower logs while the leader
        truncates its own (followers keep full history instead)."""
        sink, self.wal_sink = self.wal_sink, None
        try:
            yield
        finally:
            self.wal_sink = sink

    def clone_to(self, dst_dir: str) -> None:
        """Copy this store's durable state (snapshot + WAL) to another dir,
        atomically vs concurrent writers (follower catch-up,
        worker/predicate_move.go populateShard / retrieveSnapshot)."""
        import shutil

        with self._lock:
            if self._wal is not None:
                self._wal.flush()
                os.fsync(self._wal.fileno())
            for name in ("snapshot.bin", "wal.log"):
                src = os.path.join(self.dir, name)
                dst = os.path.join(dst_dir, name)
                if os.path.exists(src):
                    shutil.copyfile(src, dst)
                elif os.path.exists(dst):
                    os.remove(dst)

    @contextlib.contextmanager
    def suspend_wal(self):
        """Run with the WAL off (bulk loads write packed bases directly and
        then checkpoint — reference bulk loader writes SSTs, not the Raft
        WAL, dgraph/cmd/bulk/reduce.go:36)."""
        wal, self._wal = self._wal, None
        try:
            yield self
        finally:
            self._wal = wal

    def bulk_install(self, lists: dict[bytes, "PostingList"],
                     commit_ts: int) -> None:
        """Register fully-built posting lists (packed bases at commit_ts).

        The caller is expected to run under suspend_wal() and checkpoint()
        afterwards so durability comes from the snapshot, not per-posting
        WAL records."""
        with self._lock:
            self._packed_tablets.clear()   # direct installs bypass get()
            for kb, pl in lists.items():
                key = K.parse_key(kb)
                self.lists[kb] = pl
                self.by_pred.setdefault((int(key.kind), key.attr), set()).add(kb)
                if commit_ts > self.pred_commit_ts.get(key.attr, 0):
                    self.pred_commit_ts[key.attr] = commit_ts
                    mark = self._applied_marks.get(key.attr)
                    if mark is not None:
                        mark.set_done_until(commit_ts)
                # installs bypass the delta journal: stamping resumes after
                # the next full fold re-bases these tablets
                self._delta_floor[key.attr] = max(
                    self._delta_floor.get(key.attr, 0), commit_ts)
            self.max_seen_commit_ts = max(self.max_seen_commit_ts, commit_ts)

    # -- WAL ----------------------------------------------------------------

    # Replication hook: when set, every WAL record is offered to the sink
    # BEFORE the local append (a record must reach the quorum before the
    # leader treats it as durable — worker/draft.go proposeAndWait waits for
    # the Raft commit the same way). The sink raising aborts the local write.
    wal_sink = None

    def _wal_write(self, rec: dict, sync: bool = False) -> None:
        if self._wal is None and self.wal_sink is None:
            return    # in-memory, unreplicated: records have nowhere to go
        from ..utils import faults

        # disk fault seam: a failing/slow WAL write surfaces BEFORE the
        # in-memory apply, the same ordering a real fsync failure has
        faults.fire("disk.wal_write", m=getattr(self, "metrics", None))
        data = encode_record(rec)
        with self._lock:
            # ship under the same lock as the local append so followers see
            # records in exactly the leader's log order (replication is
            # independent of local durability: an in-memory leader still
            # ships — its quorum of follower fsyncs IS the durability)
            if self.wal_sink is not None:
                self.wal_sink(data, sync)
            if self._wal is not None:
                self._wal.write(_U32.pack(len(data)) + data)
                self.wal_record_count += 1
                if sync:
                    # the durability seam itself (sync writes only): a
                    # delay fault here emulates the fsync cost class of
                    # durable disks (bench_write's sync sweep) — it
                    # sleeps under the lock exactly as a real fsync
                    # serializes writers
                    faults.fire("disk.fsync",
                                m=getattr(self, "metrics", None))
                    self._wal.flush()
                    os.fsync(self._wal.fileno())

    def _replay_wal(self, path: str) -> None:
        if not os.path.exists(path):
            return
        with open(path, "rb") as f:
            raw = f.read()
        off = 0
        with self._lock:       # one lock hold for the whole replay
            while off + 4 <= len(raw):
                (n,) = _U32.unpack_from(raw, off)
                off += 4
                if off + n > len(raw):
                    break  # torn tail write — ignore (crash mid-append)
                self._apply_record_locked(decode_record(raw[off: off + n]))
                off += n
                self.wal_record_count += 1

    def ingest_record(self, rec: dict, sync: bool = False) -> None:
        """Write-and-apply one record through the normal WAL path — the
        receiving side of a predicate move (worker/predicate_move.go:187
        batches received KVs into proposals; here the records ARE proposals,
        so a replicated leader ships them to its quorum automatically)."""
        self._wal_write(rec, sync=sync)
        self.apply_record(rec)

    def append_replica_record(self, data: bytes, sync: bool = True,
                              rec: dict | None = None) -> None:
        """Follower-side replication apply: one shipped WAL record becomes
        durable in this replica's own log AND live in memory, atomically
        under the store lock (the worker/draft.go:485-624 store-then-apply
        order, collapsed because the record is already quorum-ordered by
        the leader). Pass `rec` when the caller already parsed the bytes
        (the replication hot path parses once)."""
        with self._lock:
            if self._wal is not None:
                self._wal.write(_U32.pack(len(data)) + data)
                if sync:
                    self._wal.flush()
                    os.fsync(self._wal.fileno())
            self._apply_record_locked(rec if rec is not None
                                      else decode_record(data))
            self.wal_record_count += 1

    def apply_record(self, rec: dict) -> None:
        """Apply one WAL record to in-memory state — replay on restart, and
        the follower-side live apply when records arrive over replication
        (worker/draft.go:485-624 applies committed entries the same way)."""
        with self._lock:
            self._apply_record_locked(rec)

    def _apply_record_locked(self, rec: dict) -> None:
        t = rec["t"]
        if t == "m":
            kb = _key_bytes(rec["k"])
            # unconditional: _drop_packed also records the tablet in
            # _touched (paged mode). Gating it on a non-empty
            # _packed_tablets skipped that side effect after checkpoint()
            # cleared the packed cache, so tablet_lists() kept serving
            # pristine segment rows that omit this applied mutation
            # (stale reads on WAL replay / follower ship-apply /
            # predicate-move ingest).
            self._drop_packed(*K.kind_attr_of(kb))
            pl = self.lists.get(kb)
            if pl is None:      # full parse only on first sight of the key
                key = K.parse_key(kb)
                pl = PostingList()
                self.lists[kb] = pl
                self.by_pred.setdefault(
                    (int(key.kind), key.attr), set()).add(kb)
            p = rec["p"]
            pl.add_mutation(
                rec["s"], p if isinstance(p, Posting) else posting_from_json(p))
            self.dirty.add(kb)
        elif t == "c":
            for kraw in rec["k"]:
                kb = _key_bytes(kraw)
                self._bump_pred_ts(kb, rec["ts"])
                pl = self.lists.get(kb)
                if pl is None:
                    continue
                if rec["ts"] <= self.snapshot_ts:
                    # already folded into the snapshot base (crash between
                    # snapshot replace and WAL truncation): replaying would
                    # double-apply — notably DEL_ALL — on the rolled-up base
                    pl.abort(rec["s"])
                else:
                    pl.commit(rec["s"], rec["ts"])
            self.max_seen_commit_ts = max(self.max_seen_commit_ts, rec["ts"])
        elif t == "gc":
            # a group record IS its member commits: each applies through
            # the exact "c" branch above (including the ts <= snapshot_ts
            # already-folded abort rule, per member)
            for sub in rec["txns"]:
                self._apply_record_locked(
                    {"t": "c", "s": sub["s"], "k": sub["k"],
                     "ts": sub["ts"]})
        elif t == "a":
            for kraw in rec["k"]:
                pl = self.lists.get(_key_bytes(kraw))
                if pl is not None:
                    pl.abort(rec["s"])
        elif t == "s":
            for e in parse_schema(rec["line"]):
                self.schema.set(e)
        elif t == "dp":
            self._delete_predicate_mem(rec["attr"])
        elif t == "dk":
            self._drop_kind_mem(rec["attr"], K.KeyKind(rec["kind"]))

    # -- snapshot / checkpoint ---------------------------------------------

    def checkpoint(self, upto_ts: int) -> None:
        """Roll lists up to upto_ts, STREAM a snapshot tablet-by-tablet,
        truncate the WAL.

        The write is external-memory (ingest/snapwrite.py DGTS3): pristine
        mmap'd tablets copy file-to-file with zero per-row work, touched
        tablets merge resident lists over their segment rows, and rows of
        purely-resident tablets stream one at a time — peak transient
        memory is the writer's spool ceiling, independent of key count
        (the v2 writer materialized a PostingList per row and held every
        column in RAM, making a 100M-key checkpoint a memory event).

        Uncommitted txns and layers above upto_ts survive via the fresh WAL.
        (Reference: worker/draft.go snapshot at min pending-txn ts.)
        """
        from dgraph_tpu.ingest.snapwrite import SnapshotWriter

        self._packed_tablets.clear()   # rollup replaces packed bases
        if self.dir is None:
            for pl in list(self.lists.values()):
                pl.rollup(upto_ts)
            self.snapshot_ts = max(self.snapshot_ts, upto_ts)
            return
        with self._lock, self._sink_suspended():
            self.snapshot_ts = max(self.snapshot_ts, upto_ts)
            snap_path = os.path.join(self.dir, "snapshot.bin.tmp")
            with open(snap_path, "wb") as f:
                w = SnapshotWriter(f, upto_ts, spool_max=self.SNAP_SPOOL_MAX)
                self._write_sections(w, upto_ts)
                w.finish({"schema": self.schema.to_text(),
                          "max_commit_ts": self.max_seen_commit_ts})
            self.last_checkpoint_stats = {
                "rows": w.rows,
                "peak_transient_bytes": w.peak_transient}
            if self.metrics is not None:
                self.metrics.counter(
                    "dgraph_checkpoint_peak_transient_bytes").set(
                        w.peak_transient)
            os.replace(snap_path, os.path.join(self.dir, "snapshot.bin"))
            # reset WAL with still-relevant records (uncommitted + layers > upto_ts)
            if self._wal is not None:
                self._wal.close()
            wal_path = os.path.join(self.dir, "wal.log")
            self._wal = open(wal_path + ".tmp", "ab")
            self.wal_record_count = 0   # re-counted by the rewrites below
            for kb in sorted(self.lists):
                pl = self.lists[kb]
                for sts, layer in pl.uncommitted.items():
                    if layer.del_all:
                        self._wal_write({"t": "m", "s": sts, "k": kb,
                                         "p": Posting(0, Op.DEL_ALL)})
                    for p in layer.postings.values():
                        self._wal_write({"t": "m", "s": sts, "k": kb, "p": p})
                for layer in pl.layers:
                    fake_start = -layer.commit_ts  # synthetic txn id for replay
                    recs = list(layer.postings.values())
                    if layer.del_all:
                        recs = [Posting(0, Op.DEL_ALL)] + recs
                    for p in recs:
                        self._wal_write({"t": "m", "s": fake_start, "k": kb,
                                         "p": p})
                    self._wal_write({"t": "c", "s": fake_start,
                                     "ts": layer.commit_ts, "k": [kb]})
            self._wal.flush()
            os.fsync(self._wal.fileno())
            self._wal.close()
            os.replace(wal_path + ".tmp", wal_path)
            self._wal = open(wal_path, "ab")
            self.dirty.clear()

    # spool ceiling per section column before the writer rolls to disk
    # (class attr so tests can shrink it to prove bounded transients)
    SNAP_SPOOL_MAX = 1 << 22
    metrics = None                  # optional utils/metrics.Registry
    last_checkpoint_stats: dict = {}

    def _write_sections(self, w, upto_ts: int) -> None:
        """Feed the DGTS3 writer one tablet at a time.

        Three shapes, cheapest first:
          - pristine segment tablet (paged, untouched since load): attach
            the mmap'd run wholesale — file-to-file column copy;
          - touched segment tablet: two-pointer merge of the (sorted)
            resident keys over the (sorted) segment rows; resident lists
            shadow their row, untouched rows copy as metadata VIEWS —
            no PostingList is ever built for them;
          - memory-only tablet: stream the resident lists in key order.
        """
        self._lock.assert_held()
        resident: dict[tuple[int, str], list[bytes]] = {}
        for kb in dict.keys(self.lists):
            resident.setdefault(K.kind_attr_of(kb), []).append(kb)
        for t in set(resident) | set(self._segments):
            seg = self._segments.get(t)
            res = sorted(resident.get(t, ()))
            if seg is not None and not res and t not in self._touched \
                    and not self.by_pred.get(t):
                w.add_run(t[0], t[1], seg)
                continue
            sec = w.section(t[0], t[1])
            si, seg_n = 0, (seg.n if seg is not None else 0)
            for kb in res:
                while si < seg_n and seg.key_at(si) < kb:
                    self._emit_segment_row(sec, seg, si)
                    si += 1
                if si < seg_n and seg.key_at(si) == kb:
                    si += 1          # the resident copy shadows its row
                self._emit_resident_row(sec, kb, upto_ts)
            while si < seg_n:
                self._emit_segment_row(sec, seg, si)
                si += 1

    @staticmethod
    def _emit_segment_row(sec, seg: "SegmentRun", i: int) -> None:
        """Copy one pristine row segment->section as column slices (the
        packed list is a bundle of views into the mmap, never decoded)."""
        b0, b1 = int(seg.bstarts[i]), int(seg.bstarts[i + 1])
        w0, w1 = int(seg.wstarts[i]), int(seg.wstarts[i + 1])
        p0, p1 = int(seg.pstarts[i]), int(seg.pstarts[i + 1])
        pu = packed.PackedUidList(
            int(seg.counts[i]), seg.bfirst[b0:b1], seg.blast[b0:b1],
            seg.bcount[b0:b1], seg.bwidth[b0:b1], seg.boff[b0:b1],
            seg.words[w0:w1])
        sec.add_row(seg.key_at(i), int(seg.base_ts[i]), pu,
                    bytes(seg.post_blob[p0:p1]))

    def _emit_resident_row(self, sec, kb: bytes, upto_ts: int) -> None:
        pl = dict.get(self.lists, kb)
        had_fold = any(l.commit_ts <= upto_ts for l in pl.layers)
        pl.rollup(upto_ts)
        if not had_fold and hasattr(pl, "_seg_ts"):
            # content unchanged (only the watermark moved): keep the
            # list evictable, or the first checkpoint would pin every
            # resident list for the life of the process
            pl._seg_ts = pl.base_ts
        post = b"" if not pl.base_postings else json.dumps(
            [posting_to_json(p) for p in pl.base_postings.values()]).encode()
        sec.add_row(kb, int(pl.base_ts), pl.base_packed, post)

    def _load(self) -> None:
        snap = os.path.join(self.dir, "snapshot.bin")
        if os.path.exists(snap):
            if self.paged and os.path.getsize(snap) > 5:
                # mmap: columns become file-backed views the OS pages in
                # and out — the dataset no longer has to fit in RAM
                raw = np.memmap(snap, dtype=np.uint8, mode="r")
                magic = bytes(raw[:5])
                if magic == b"DGTS3":
                    self._load_v3(raw)
                elif magic == b"DGTS2":
                    self._load_v2(raw)
                else:
                    self._load_v1(bytes(raw))     # legacy format: eager
            else:
                with open(snap, "rb") as f:
                    raw = f.read()
                if raw[:5] == b"DGTS3":
                    self._load_v3(raw)
                elif raw[:5] == b"DGTS2":
                    self._load_v2(raw)
                else:
                    self._load_v1(raw)
        # commits at/below the snapshot ts live in the loaded bases, not the
        # journal; the WAL tail replay below records everything above it
        self._delta_base_floor = self.snapshot_ts
        self._replay_wal(os.path.join(self.dir, "wal.log"))

    def _load_v3(self, raw) -> None:
        """Tablet-sectioned columnar snapshot (DGTS3, the checkpoint's
        streaming write format — ingest/snapwrite.py). Sections arrive in
        globally sorted key order, so each one IS a tablet run: no run
        detection pass, the per-tablet structures build directly."""
        off = 5
        (self.snapshot_ts,) = struct.unpack_from("<Q", raw, off)
        off += 8
        (n,) = _U32.unpack_from(raw, off)
        off += 4
        meta = json.loads(bytes(raw[off: off + n]))
        off += n
        for e in parse_schema(meta.get("schema", "")):
            self.schema.set(e)
        self.max_seen_commit_ts = meta.get("max_commit_ts", 0)
        paged = self.paged and isinstance(raw, np.memmap)
        total = len(raw)
        while off + 4 <= total:
            off = self._load_v3_section(raw, off, paged)

    def _load_v3_section(self, raw, off: int, paged: bool) -> int:
        (N,) = _U32.unpack_from(raw, off)
        off += 4

        def col(dt):
            nonlocal off
            (blen,) = struct.unpack_from("<Q", raw, off)
            off += 8
            if paged:
                # file-backed view (see _load_v2.col for the downcast note)
                arr = raw[off: off + blen].view(dt).view(np.ndarray)
            else:
                arr = np.frombuffer(raw[off: off + blen], dtype=dt)
            off += blen
            return arr

        key_lens = col(np.uint32)
        keys_blob_arr = col(np.uint8)
        base_ts = col(np.uint64)
        counts = col(np.uint32)
        nblocks = col(np.uint32)
        bfirst = col(np.uint64)
        blast = col(np.uint64)
        bcount = col(np.int32)
        bwidth = col(np.int32)
        boff = col(np.int64)
        word_lens = col(np.uint64)
        words = col(np.uint32)
        post_lens = col(np.uint32)
        post_blob_arr = col(np.uint8)
        if N == 0:
            return off

        kends = np.cumsum(key_lens.astype(np.int64))
        bends = np.cumsum(nblocks.astype(np.int64))
        wends = np.cumsum(word_lens.astype(np.int64))
        pends = np.cumsum(post_lens.astype(np.int64))
        first_key = bytes(keys_blob_arr[: int(kends[0])]) if paged \
            else keys_blob_arr[: int(kends[0])].tobytes()
        kind, attr = K.kind_attr_of(first_key)

        def starts(ends):
            out = np.zeros(len(ends) + 1, np.int64)
            out[1:] = ends
            return out

        if paged:
            self._segments[(kind, attr)] = SegmentRun(
                n=N,
                uid_keyed=kind in (int(K.KeyKind.DATA),
                                   int(K.KeyKind.REVERSE)),
                keys_blob=keys_blob_arr, kends=kends,
                base_ts=base_ts, counts=counts, nbs=nblocks,
                bstarts=starts(bends), wstarts=starts(wends),
                pstarts=starts(pends),
                bfirst=bfirst, blast=blast, bcount=bcount, bwidth=bwidth,
                boff=boff, words=words, post_blob=post_blob_arr)
        else:
            keys_blob = keys_blob_arr.tobytes()
            post_blob = post_blob_arr.tobytes()
            k0 = b0 = w0 = p0 = 0
            preds = self.by_pred.setdefault((kind, attr), set())
            for i in range(N):
                k1, b1 = int(kends[i]), int(bends[i])
                w1, p1 = int(wends[i]), int(pends[i])
                kb = keys_blob[k0:k1]
                pl = PostingList()
                pl.base_ts = int(base_ts[i])
                # zero-copy slices of the shared (read-only) buffers:
                # packed bases are immutable — rollup REPLACES base_packed
                pl.base_packed = packed.PackedUidList(
                    int(counts[i]), bfirst[b0:b1], blast[b0:b1],
                    bcount[b0:b1], bwidth[b0:b1], boff[b0:b1], words[w0:w1])
                if p1 > p0:
                    pl.base_postings = {
                        p.uid: p for p in map(posting_from_json,
                                              json.loads(post_blob[p0:p1]))}
                self.lists[kb] = pl
                preds.add(kb)
                k0, b0, w0, p0 = k1, b1, w1, p1
        if kind in (int(K.KeyKind.DATA), int(K.KeyKind.REVERSE)):
            wl = word_lens.astype(np.int64)
            self._packed_tablets[(kind, attr)] = TabletPacked(
                n=N,
                counts=counts.astype(np.int64),
                nbs=nblocks.astype(np.int64),
                row_word_start=wends - wl,
                bfirst=bfirst, bcount=bcount, bwidth=bwidth, boff=boff,
                words=words,
                pure=not post_lens.any(),
                max_base_ts=int(base_ts.max()))
        return off

    def _load_v2(self, raw: bytes) -> None:
        off = 5
        (self.snapshot_ts,) = struct.unpack_from("<Q", raw, off)
        off += 8
        (n,) = _U32.unpack_from(raw, off)
        off += 4
        meta = json.loads(bytes(raw[off : off + n]))
        off += n
        for e in parse_schema(meta.get("schema", "")):
            self.schema.set(e)
        self.max_seen_commit_ts = meta.get("max_commit_ts", 0)
        (N,) = _U32.unpack_from(raw, off)
        off += 4

        paged = self.paged and isinstance(raw, np.memmap)

        def col(dt):
            nonlocal off
            (blen,) = struct.unpack_from("<Q", raw, off)
            off += 8
            if paged:
                # file-backed view: the OS pages it; nothing is pinned in
                # anonymous memory. Downcast to plain ndarray (same buffer,
                # the mmap stays alive via .base): every later slice of a
                # memmap subclass pays ~2us of __array_finalize__, and the
                # fold slices these millions of times
                arr = raw[off: off + blen].view(dt).view(np.ndarray)
            else:
                # per-column copy: a view into `raw` would pin the ENTIRE
                # snapshot bytes for as long as any single list survives
                arr = np.frombuffer(raw[off: off + blen], dtype=dt)
            off += blen
            return arr

        key_lens = col(np.uint32)
        keys_blob_arr = col(np.uint8)
        keys_blob = keys_blob_arr if paged else keys_blob_arr.tobytes()
        base_ts = col(np.uint64)
        counts = col(np.uint32)
        nblocks = col(np.uint32)
        bfirst = col(np.uint64)
        blast = col(np.uint64)
        bcount = col(np.int32)
        bwidth = col(np.int32)
        boff = col(np.int64)
        word_lens = col(np.uint64)
        words = col(np.uint32)
        post_lens = col(np.uint32)
        post_blob_arr = col(np.uint8)
        post_blob = post_blob_arr if paged else post_blob_arr.tobytes()

        kends = np.cumsum(key_lens)
        bends = np.cumsum(nblocks.astype(np.int64))
        wends = np.cumsum(word_lens.astype(np.int64))
        pends = np.cumsum(post_lens.astype(np.int64))

        # tablet-run capture: keys are globally sorted, so a (kind, attr)
        # occupies one contiguous row run — record its column slices for
        # the one-call cold-open fold (csr_build._fold_uid_tablet)
        run_key: tuple[int, str] | None = None
        run_start = 0
        wstarts = wends - word_lens.astype(np.int64)
        bstarts = bends - nblocks.astype(np.int64)

        pstarts = pends - post_lens.astype(np.int64)
        kstarts = kends - key_lens.astype(np.int64)

        def flush_run(end: int) -> None:
            if run_key is None or end <= run_start:
                return
            r0, r1 = run_start, end
            bb0, bb1 = int(bstarts[r0]), int(bends[r1 - 1])
            ww0, ww1 = int(wstarts[r0]), int(wends[r1 - 1])
            if paged:
                # paged mode: the lazy-materialization segment (ALL kinds)
                pp0, pp1 = int(pstarts[r0]), int(pends[r1 - 1])
                kk0, kk1 = int(kstarts[r0]), int(kends[r1 - 1])
                bst = np.concatenate(
                    [bstarts[r0:r1] - bb0, [bb1 - bb0]]).astype(np.int64)
                wst = np.concatenate(
                    [wstarts[r0:r1] - ww0, [ww1 - ww0]]).astype(np.int64)
                pst = np.concatenate(
                    [pstarts[r0:r1] - pp0, [pp1 - pp0]]).astype(np.int64)
                self._segments[run_key] = SegmentRun(
                    n=r1 - r0,
                    uid_keyed=run_key[0] in (int(K.KeyKind.DATA),
                                             int(K.KeyKind.REVERSE)),
                    keys_blob=keys_blob_arr[kk0:kk1],
                    kends=(kends[r0:r1] - kk0).astype(np.int64),
                    base_ts=base_ts[r0:r1], counts=counts[r0:r1],
                    nbs=nblocks[r0:r1], bstarts=bst, wstarts=wst,
                    pstarts=pst,
                    bfirst=bfirst[bb0:bb1], blast=blast[bb0:bb1],
                    bcount=bcount[bb0:bb1], bwidth=bwidth[bb0:bb1],
                    boff=boff[bb0:bb1], words=words[ww0:ww1],
                    post_blob=post_blob_arr[pp0:pp1])
            if run_key[0] not in (int(K.KeyKind.DATA),
                                  int(K.KeyKind.REVERSE)):
                return       # only uid-edge tablets consult the fold cache
            self._packed_tablets[run_key] = TabletPacked(
                n=r1 - r0,
                counts=counts[r0:r1].astype(np.int64),
                nbs=nblocks[r0:r1].astype(np.int64),
                row_word_start=wstarts[r0:r1] - ww0,
                bfirst=bfirst[bb0:bb1], bcount=bcount[bb0:bb1],
                bwidth=bwidth[bb0:bb1], boff=boff[bb0:bb1],
                words=words[ww0:ww1],
                pure=not post_lens[r0:r1].any(),
                max_base_ts=int(base_ts[r0:r1].max()) if r1 > r0 else 0)

        k0 = b0 = w0 = p0 = 0
        for i in range(N):
            k1, b1 = int(kends[i]), int(bends[i])
            w1, p1 = int(wends[i]), int(pends[i])
            kb = bytes(keys_blob[k0:k1]) if paged else keys_blob[k0:k1]
            kind, attr = K.kind_attr_of(kb)
            if not paged:
                pl = PostingList()
                pl.base_ts = int(base_ts[i])
                # zero-copy slices of the shared (read-only) buffers: packed
                # bases are immutable — rollup REPLACES base_packed wholesale
                pl.base_packed = packed.PackedUidList(
                    int(counts[i]), bfirst[b0:b1], blast[b0:b1],
                    bcount[b0:b1], bwidth[b0:b1], boff[b0:b1], words[w0:w1])
                if p1 > p0:
                    pl.base_postings = {
                        p.uid: p for p in map(posting_from_json,
                                              json.loads(post_blob[p0:p1]))}
                self.lists[kb] = pl
                self.by_pred.setdefault((kind, attr), set()).add(kb)
            # paged: keys stay in the segment — no per-key object, no
            # per-key registry entry (the LSM role: RAM ∝ touched keys)
            if (kind, attr) != run_key:
                flush_run(i)
                run_key, run_start = (kind, attr), i
            k0, b0, w0, p0 = k1, b1, w1, p1
        flush_run(N)

    def _load_v1(self, raw: bytes) -> None:
        """Row-format reader kept for snapshots written before DGTS2."""
        assert raw[:5] == b"DGTS1", "bad snapshot magic"
        off = 5
        (snap_ts,) = struct.unpack_from("<Q", raw, off)
        self.snapshot_ts = snap_ts
        off += 8
        (n,) = _U32.unpack_from(raw, off)
        off += 4
        meta = json.loads(raw[off : off + n])
        off += n
        for e in parse_schema(meta.get("schema", "")):
            self.schema.set(e)
        self.max_seen_commit_ts = meta.get("max_commit_ts", 0)
        while off < len(raw):
            (klen,) = _U32.unpack_from(raw, off)
            off += 4
            kb = raw[off : off + klen]
            off += klen
            base_ts, count = struct.unpack_from("<QI", raw, off)
            off += 12
            arrs = []
            for dt in (np.uint64, np.uint64, np.int32, np.int32, np.int64, np.uint32):
                (blen,) = _U32.unpack_from(raw, off)
                off += 4
                arrs.append(np.frombuffer(raw[off : off + blen], dtype=dt).copy())
                off += blen
            (plen,) = _U32.unpack_from(raw, off)
            off += 4
            pbody = raw[off : off + plen]
            off += plen
            pl = PostingList()
            pl.base_ts = base_ts
            pl.base_packed = packed.PackedUidList(count, *arrs)
            if pbody != b"[]":   # uid-only lists skip the json machinery
                pl.base_postings = {
                    p.uid: p
                    for p in map(posting_from_json, json.loads(pbody))}
            kind, attr = K.kind_attr_of(kb)
            self.lists[kb] = pl
            self.by_pred.setdefault((kind, attr), set()).add(kb)

    def close(self) -> None:
        if self._wal is not None:
            self._wal.flush()
            os.fsync(self._wal.fileno())
            self._wal.close()
            self._wal = None
