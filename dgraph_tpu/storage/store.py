"""Durable posting store: in-memory map + append-only WAL + packed snapshots.

Replaces the reference's embedded badger LSM (vendor/github.com/dgraph-io/
badger) for the posting space. The reference relies on badger's managed MVCC
transactions (NewTransactionAt/CommitAt) plus an LRU of decoded lists
(posting/lists.go lcache); here MVCC lives in PostingList layers
(storage/postings.py) and durability comes from:

  - WAL: every buffered mutation / commit / abort / schema change is appended
    as a length-prefixed JSON record and fsync'd on commit; replayed on open
    (analog of badger's value log + the Raft WAL replay path,
    worker/draft.go:738 InitAndStartNode).
  - Snapshot: `checkpoint()` rolls lists up to a watermark ts and writes a
    binary segment file of packed lists; on open the snapshot is loaded and
    the WAL tail replayed (analog of Raft snapshot + log truncation,
    worker/draft.go:636-705).

Keys are storage/keys.py encoded bytes; a per-(kind, attr) registry gives O(1)
tablet scans (a predicate's keys are a contiguous range in the reference,
x/keys.go; here they're an explicit set).
"""

from __future__ import annotations

import base64
import contextlib
import dataclasses
import io
import json
import os
import struct
import threading

import numpy as np

from dgraph_tpu.storage import keys as K
from dgraph_tpu.storage import packed
from dgraph_tpu.storage.postings import Op, Posting, PostingList
from dgraph_tpu.utils.schema import SchemaEntry, SchemaState, parse_schema
from dgraph_tpu.utils.sync import SafeLock
from dgraph_tpu.utils.types import TypeID, Val, marshal, unmarshal

_U32 = struct.Struct("<I")


# -- posting (de)serialization ----------------------------------------------

def _val_to_json(v: Val | None):
    if v is None:
        return None
    return {"t": int(v.tid), "b": base64.b64encode(marshal(v)).decode("ascii")}


def _val_from_json(j) -> Val | None:
    if j is None:
        return None
    return unmarshal(TypeID(j["t"]), base64.b64decode(j["b"]))


def posting_to_json(p: Posting) -> dict:
    d: dict = {"u": p.uid, "o": int(p.op)}
    if p.value is not None:
        d["v"] = _val_to_json(p.value)
    if p.lang:
        d["l"] = p.lang
    if p.facets:
        d["f"] = [[n, _val_to_json(v)] for n, v in p.facets]
    return d


def posting_from_json(d: dict) -> Posting:
    return Posting(
        uid=d["u"],
        op=Op(d["o"]),
        value=_val_from_json(d.get("v")),
        lang=d.get("l", ""),
        facets=tuple((n, _val_from_json(v)) for n, v in d.get("f", [])),
    )


# -- binary WAL record codec -------------------------------------------------
# The hot record types (mutation / commit / abort — ~all of a load's volume)
# encode as packed structs; rare types (schema, drops) stay JSON. The first
# byte discriminates: '{' (0x7b) = JSON, else the binary tag. Old JSON WALs
# replay unchanged. Decoded records carry RAW key bytes and Posting objects
# ("fast form"); _apply_record_locked accepts both forms. This is also the
# replication wire format — followers decode the same bytes.
#
# VERSIONING: tags 0x01-0x03 denote EXACTLY this layout (u32 key lengths,
# u16 lang/facet lengths). Any future layout change must claim NEW tag
# bytes — the tag byte is the format version, like the snapshot header
# (DGTS1/DGTS2 below).

_REC_M, _REC_C, _REC_A = 0x01, 0x02, 0x03
_Q = struct.Struct("<q")
_HDR_M = struct.Struct("<q I")        # start_ts, key len
_HDR_C = struct.Struct("<q q I")      # start_ts, commit_ts, n keys
_HDR_A = struct.Struct("<q I")        # start_ts, n keys


@dataclasses.dataclass
class TabletPacked:
    """One tablet's packed columns as contiguous slices of the snapshot's
    shared buffers (DGTS2 is key-sorted, so a tablet is one run). `pure`
    means no row carried base_postings at load; any later write drops the
    whole entry, so a surviving entry implies layer-free lists too."""

    n: int
    counts: np.ndarray            # int64[n]
    nbs: np.ndarray               # int64[n] blocks per row
    row_word_start: np.ndarray    # int64[n] word base per row (tablet-rel)
    bfirst: np.ndarray
    bcount: np.ndarray
    bwidth: np.ndarray
    boff: np.ndarray
    words: np.ndarray
    pure: bool
    max_base_ts: int              # reads below this must raise (isolation)


def _key_bytes(k) -> bytes:
    return k if isinstance(k, (bytes, bytearray)) else base64.b64decode(k)


def _enc_val(out: list, v: Val) -> None:
    b = marshal(v)
    out.append(struct.pack("<B I", int(v.tid), len(b)))
    out.append(b)


def _dec_val(raw: bytes, off: int) -> tuple[Val, int]:
    tid, blen = struct.unpack_from("<B I", raw, off)
    off += 5
    return unmarshal(TypeID(tid), raw[off: off + blen]), off + blen


def encode_record(rec: dict) -> bytes:
    """Record dict -> wire/WAL bytes (binary for m/c/a, JSON otherwise)."""
    t = rec["t"]
    if t == "m":
        kb = _key_bytes(rec["k"])
        p = rec["p"]
        if not isinstance(p, Posting):
            p = posting_from_json(p)
        out = [bytes([_REC_M]), _HDR_M.pack(rec["s"], len(kb)), kb]
        flags = ((1 if p.value is not None else 0)
                 | (2 if p.lang else 0) | (4 if p.facets else 0))
        out.append(struct.pack("<Q B B", p.uid, int(p.op), flags))
        if p.value is not None:
            _enc_val(out, p.value)
        if p.lang:
            lb = p.lang.encode()
            out.append(struct.pack("<H", len(lb)) + lb)
        if p.facets:
            out.append(struct.pack("<H", len(p.facets)))
            for name, fv in p.facets:
                nb = name.encode()
                out.append(struct.pack("<H", len(nb)) + nb)
                _enc_val(out, fv)
        return b"".join(out)
    if t in ("c", "a"):
        keys = [_key_bytes(k) for k in rec["k"]]
        if t == "c":
            out = [bytes([_REC_C]), _HDR_C.pack(rec["s"], rec["ts"], len(keys))]
        else:
            out = [bytes([_REC_A]), _HDR_A.pack(rec["s"], len(keys))]
        for kb in keys:
            out.append(struct.pack("<I", len(kb)))
            out.append(kb)
        return b"".join(out)
    return json.dumps(rec, separators=(",", ":")).encode("utf-8")


def decode_record(raw: bytes) -> dict:
    """Wire/WAL bytes -> record dict (fast form for binary records)."""
    tag = raw[0]
    if tag == 0x7B:                     # '{' — JSON record
        return json.loads(raw)
    off = 1
    if tag == _REC_M:
        s, klen = _HDR_M.unpack_from(raw, off)
        off += _HDR_M.size
        kb = raw[off: off + klen]
        off += klen
        uid, op, flags = struct.unpack_from("<Q B B", raw, off)
        off += 10
        value = lang = None
        facets = ()
        if flags & 1:
            value, off = _dec_val(raw, off)
        if flags & 2:
            (n,) = struct.unpack_from("<H", raw, off)
            lang = raw[off + 2: off + 2 + n].decode()
            off += 2 + n
        if flags & 4:
            (cnt,) = struct.unpack_from("<H", raw, off)
            off += 2
            fs = []
            for _ in range(cnt):
                (n,) = struct.unpack_from("<H", raw, off)
                name = raw[off + 2: off + 2 + n].decode()
                off += 2 + n
                fv, off = _dec_val(raw, off)
                fs.append((name, fv))
            facets = tuple(fs)
        return {"t": "m", "s": s, "k": kb,
                "p": Posting(uid, Op(op), value, lang or "", facets)}
    if tag == _REC_C:
        s, ts, n = _HDR_C.unpack_from(raw, off)
        off += _HDR_C.size
    elif tag == _REC_A:
        s, n = _HDR_A.unpack_from(raw, off)
        ts = None
        off += _HDR_A.size
    else:
        raise ValueError(f"unknown WAL record tag {tag}")
    keys = []
    for _ in range(n):
        (klen,) = struct.unpack_from("<I", raw, off)
        off += 4
        keys.append(raw[off: off + klen])
        off += klen
    rec = {"t": "c" if tag == _REC_C else "a", "s": s, "k": keys}
    if ts is not None:
        rec["ts"] = ts
    return rec


class Store:
    """One group's posting store (the `pstore` of a server node)."""

    def __init__(self, dirpath: str | None = None) -> None:
        self.dir = dirpath
        self.lists: dict[bytes, PostingList] = {}
        self.by_pred: dict[tuple[int, str], set[bytes]] = {}
        self.schema = SchemaState()
        self.dirty: set[bytes] = set()
        self._lock = SafeLock()   # lock-discipline asserts: utils/sync.py
        self._wal: io.BufferedWriter | None = None
        self.max_seen_commit_ts = 0
        # attr -> highest commit_ts of any commit touching it: the dirty
        # watermark incremental snapshot builds compare against (the
        # reference never rebuilds the world — posting/lists.go:243
        # read-through; here clean predicates reuse device arrays)
        self.pred_commit_ts: dict[str, int] = {}
        self.pred_replay_seq: dict[str, int] = {}   # below-watermark commits
        # cold-open fold accelerator: per-(kind, attr) CONTIGUOUS packed
        # columns captured at snapshot load (the DGTS2 layout is already
        # tablet-ordered). While an entry survives — dropped on the first
        # write touching its tablet — the snapshot fold decodes the whole
        # tablet in ONE native call with zero per-list marshalling.
        self._packed_tablets: dict[tuple[int, str], "TabletPacked"] = {}
        self.snapshot_ts = 0  # commits at/below this are folded into bases
        # records currently in wal.log (an up-to-dateness signal for
        # elections; NOT the replication ship index — that is a per-term
        # session sequence, parallel/remote.py — because checkpoint
        # compaction rewrites this file)
        self.wal_record_count = 0
        if dirpath:
            os.makedirs(dirpath, exist_ok=True)
            self._load()
            self._wal = open(os.path.join(dirpath, "wal.log"), "ab")

    # -- basic access -------------------------------------------------------

    def get(self, key: K.Key) -> PostingList:
        kb = key.encode()
        with self._lock:
            pl = self.lists.get(kb)
            if pl is None:
                pl = PostingList()
                self.lists[kb] = pl
                self.by_pred.setdefault((int(key.kind), key.attr), set()).add(kb)
                self._drop_packed(int(key.kind), key.attr)
            return pl

    def _drop_packed(self, kind: int, attr: str) -> None:
        """Invalidate the cold-open fold cache for one tablet (any write
        breaks the contiguous-and-pure contract of TabletPacked)."""
        if self._packed_tablets:
            self._packed_tablets.pop((kind, attr), None)

    def packed_tablet(self, kind: int, attr: str) -> TabletPacked | None:
        return self._packed_tablets.get((kind, attr))

    def get_no_store(self, key: K.Key) -> PostingList | None:
        """Read-only peek (reference posting/lists.go GetNoStore :274)."""
        return self.lists.get(key.encode())

    def keys_of(self, kind: K.KeyKind, attr: str) -> list[bytes]:
        """All keys of one (kind, predicate) — a tablet scan."""
        with self._lock:
            return sorted(self.by_pred.get((int(kind), attr), ()))

    def memory_stats(self) -> dict:
        """Approximate host memory held by posting lists (the accounting
        behind the --memory_mb budget; posting/lists.go:123-180)."""
        total = 0
        layers = 0
        with self._lock:
            pls = list(self.lists.values())
        for pl in pls:
            total += pl.approx_bytes()
            layers += pl.layer_count()
        return {"bytes": total, "lists": len(pls), "layers": layers}

    def predicates(self) -> list[str]:
        with self._lock:
            return sorted({attr for (kind, attr) in self.by_pred
                           if kind == int(K.KeyKind.DATA)})

    def tablet_sizes(self) -> dict[str, int]:
        """Approximate bytes served per predicate, across every key space it
        owns (the size reports a group streams to Zero for rebalancing —
        worker/groups.go:454-549 periodicMembershipUpdate)."""
        out: dict[str, int] = {}
        with self._lock:
            items = [(attr, list(keys))
                     for (_kind, attr), keys in self.by_pred.items()]
        for attr, keys in items:
            n = out.get(attr, 0)
            for kb in keys:
                pl = self.lists.get(kb)
                if pl is not None:
                    n += 64 + pl.approx_bytes()
            out[attr] = n
        return out

    # -- write path ---------------------------------------------------------

    def add_mutation(self, start_ts: int, key: K.Key, p: Posting) -> None:
        self._wal_write({"t": "m", "s": start_ts, "k": key.encode(), "p": p})
        self._drop_packed(int(key.kind), key.attr)
        self.get(key).add_mutation(start_ts, p)
        self.dirty.add(key.encode())

    def commit(self, start_ts: int, commit_ts: int, key_bytes: list[bytes]) -> None:
        self._wal_write({"t": "c", "s": start_ts, "ts": commit_ts,
                         "k": list(key_bytes)}, sync=True)
        with self._lock:
            for kb in key_bytes:
                pl = self.lists.get(kb)
                if pl is not None:
                    pl.commit(start_ts, commit_ts)
                self._bump_pred_ts(kb, commit_ts)
            self.max_seen_commit_ts = max(self.max_seen_commit_ts, commit_ts)

    def _bump_pred_ts(self, kb: bytes, commit_ts: int) -> None:
        self._lock.assert_held()   # caller owns the commit critical section
        attr = K.kind_attr_of(kb)[1]
        cur = self.pred_commit_ts.get(attr, 0)
        if commit_ts > cur:
            self.pred_commit_ts[attr] = commit_ts
        elif commit_ts < cur:
            # a commit arriving BELOW the watermark (replication replay /
            # out-of-order apply): max-only watermarks can't see it, so
            # cached snapshots key staleness on this counter too
            self.pred_replay_seq[attr] = self.pred_replay_seq.get(attr, 0) + 1

    def abort(self, start_ts: int, key_bytes: list[bytes]) -> None:
        self._wal_write({"t": "a", "s": start_ts, "k": list(key_bytes)})
        with self._lock:
            for kb in key_bytes:
                pl = self.lists.get(kb)
                if pl is not None:
                    pl.abort(start_ts)

    def set_schema(self, e: SchemaEntry) -> None:
        self._wal_write({"t": "s", "line": str(e)})
        self.schema.set(e)

    def delete_predicate(self, attr: str) -> None:
        """Drop every key of a predicate (reference posting/index.go:946
        DeletePredicate; used by predicate moves and drop operations)."""
        self._wal_write({"t": "dp", "attr": attr}, sync=True)
        self._delete_predicate_mem(attr)

    def drop_kind(self, attr: str, kind: K.KeyKind) -> None:
        """Drop all keys of one (kind, predicate) — WAL-logged so index
        rebuilds survive crash+replay without resurrecting stale postings."""
        self._wal_write({"t": "dk", "attr": attr, "kind": int(kind)}, sync=True)
        self._drop_kind_mem(attr, kind)

    def _drop_kind_mem(self, attr: str, kind: K.KeyKind) -> None:
        with self._lock:
            self._drop_packed(int(kind), attr)
            for kb in self.by_pred.pop((int(kind), attr), set()):
                self.lists.pop(kb, None)
                self.dirty.discard(kb)

    def _delete_predicate_mem(self, attr: str) -> None:
        with self._lock:
            for kind in list(K.KeyKind):
                self._drop_packed(int(kind), attr)
                for kb in self.by_pred.pop((int(kind), attr), set()):
                    self.lists.pop(kb, None)
                    self.dirty.discard(kb)
            self.schema.delete(attr)

    # -- bulk ingest ---------------------------------------------------------

    @contextlib.contextmanager
    def _sink_suspended(self):
        """Checkpoint's WAL-reset rewrites are LOCAL compaction — shipping
        them would append duplicates to follower logs while the leader
        truncates its own (followers keep full history instead)."""
        sink, self.wal_sink = self.wal_sink, None
        try:
            yield
        finally:
            self.wal_sink = sink

    def clone_to(self, dst_dir: str) -> None:
        """Copy this store's durable state (snapshot + WAL) to another dir,
        atomically vs concurrent writers (follower catch-up,
        worker/predicate_move.go populateShard / retrieveSnapshot)."""
        import shutil

        with self._lock:
            if self._wal is not None:
                self._wal.flush()
                os.fsync(self._wal.fileno())
            for name in ("snapshot.bin", "wal.log"):
                src = os.path.join(self.dir, name)
                dst = os.path.join(dst_dir, name)
                if os.path.exists(src):
                    shutil.copyfile(src, dst)
                elif os.path.exists(dst):
                    os.remove(dst)

    @contextlib.contextmanager
    def suspend_wal(self):
        """Run with the WAL off (bulk loads write packed bases directly and
        then checkpoint — reference bulk loader writes SSTs, not the Raft
        WAL, dgraph/cmd/bulk/reduce.go:36)."""
        wal, self._wal = self._wal, None
        try:
            yield self
        finally:
            self._wal = wal

    def bulk_install(self, lists: dict[bytes, "PostingList"],
                     commit_ts: int) -> None:
        """Register fully-built posting lists (packed bases at commit_ts).

        The caller is expected to run under suspend_wal() and checkpoint()
        afterwards so durability comes from the snapshot, not per-posting
        WAL records."""
        with self._lock:
            self._packed_tablets.clear()   # direct installs bypass get()
            for kb, pl in lists.items():
                key = K.parse_key(kb)
                self.lists[kb] = pl
                self.by_pred.setdefault((int(key.kind), key.attr), set()).add(kb)
                if commit_ts > self.pred_commit_ts.get(key.attr, 0):
                    self.pred_commit_ts[key.attr] = commit_ts
            self.max_seen_commit_ts = max(self.max_seen_commit_ts, commit_ts)

    # -- WAL ----------------------------------------------------------------

    # Replication hook: when set, every WAL record is offered to the sink
    # BEFORE the local append (a record must reach the quorum before the
    # leader treats it as durable — worker/draft.go proposeAndWait waits for
    # the Raft commit the same way). The sink raising aborts the local write.
    wal_sink = None

    def _wal_write(self, rec: dict, sync: bool = False) -> None:
        if self._wal is None and self.wal_sink is None:
            return    # in-memory, unreplicated: records have nowhere to go
        data = encode_record(rec)
        with self._lock:
            # ship under the same lock as the local append so followers see
            # records in exactly the leader's log order (replication is
            # independent of local durability: an in-memory leader still
            # ships — its quorum of follower fsyncs IS the durability)
            if self.wal_sink is not None:
                self.wal_sink(data, sync)
            if self._wal is not None:
                self._wal.write(_U32.pack(len(data)) + data)
                self.wal_record_count += 1
                if sync:
                    self._wal.flush()
                    os.fsync(self._wal.fileno())

    def _replay_wal(self, path: str) -> None:
        if not os.path.exists(path):
            return
        with open(path, "rb") as f:
            raw = f.read()
        off = 0
        with self._lock:       # one lock hold for the whole replay
            while off + 4 <= len(raw):
                (n,) = _U32.unpack_from(raw, off)
                off += 4
                if off + n > len(raw):
                    break  # torn tail write — ignore (crash mid-append)
                self._apply_record_locked(decode_record(raw[off: off + n]))
                off += n
                self.wal_record_count += 1

    def ingest_record(self, rec: dict, sync: bool = False) -> None:
        """Write-and-apply one record through the normal WAL path — the
        receiving side of a predicate move (worker/predicate_move.go:187
        batches received KVs into proposals; here the records ARE proposals,
        so a replicated leader ships them to its quorum automatically)."""
        self._wal_write(rec, sync=sync)
        self.apply_record(rec)

    def append_replica_record(self, data: bytes, sync: bool = True,
                              rec: dict | None = None) -> None:
        """Follower-side replication apply: one shipped WAL record becomes
        durable in this replica's own log AND live in memory, atomically
        under the store lock (the worker/draft.go:485-624 store-then-apply
        order, collapsed because the record is already quorum-ordered by
        the leader). Pass `rec` when the caller already parsed the bytes
        (the replication hot path parses once)."""
        with self._lock:
            if self._wal is not None:
                self._wal.write(_U32.pack(len(data)) + data)
                if sync:
                    self._wal.flush()
                    os.fsync(self._wal.fileno())
            self._apply_record_locked(rec if rec is not None
                                      else decode_record(data))
            self.wal_record_count += 1

    def apply_record(self, rec: dict) -> None:
        """Apply one WAL record to in-memory state — replay on restart, and
        the follower-side live apply when records arrive over replication
        (worker/draft.go:485-624 applies committed entries the same way)."""
        with self._lock:
            self._apply_record_locked(rec)

    def _apply_record_locked(self, rec: dict) -> None:
        t = rec["t"]
        if t == "m":
            kb = _key_bytes(rec["k"])
            if self._packed_tablets:
                self._drop_packed(*K.kind_attr_of(kb))
            pl = self.lists.get(kb)
            if pl is None:      # full parse only on first sight of the key
                key = K.parse_key(kb)
                pl = PostingList()
                self.lists[kb] = pl
                self.by_pred.setdefault(
                    (int(key.kind), key.attr), set()).add(kb)
            p = rec["p"]
            pl.add_mutation(
                rec["s"], p if isinstance(p, Posting) else posting_from_json(p))
            self.dirty.add(kb)
        elif t == "c":
            for kraw in rec["k"]:
                kb = _key_bytes(kraw)
                self._bump_pred_ts(kb, rec["ts"])
                pl = self.lists.get(kb)
                if pl is None:
                    continue
                if rec["ts"] <= self.snapshot_ts:
                    # already folded into the snapshot base (crash between
                    # snapshot replace and WAL truncation): replaying would
                    # double-apply — notably DEL_ALL — on the rolled-up base
                    pl.abort(rec["s"])
                else:
                    pl.commit(rec["s"], rec["ts"])
            self.max_seen_commit_ts = max(self.max_seen_commit_ts, rec["ts"])
        elif t == "a":
            for kraw in rec["k"]:
                pl = self.lists.get(_key_bytes(kraw))
                if pl is not None:
                    pl.abort(rec["s"])
        elif t == "s":
            for e in parse_schema(rec["line"]):
                self.schema.set(e)
        elif t == "dp":
            self._delete_predicate_mem(rec["attr"])
        elif t == "dk":
            self._drop_kind_mem(rec["attr"], K.KeyKind(rec["kind"]))

    # -- snapshot / checkpoint ---------------------------------------------

    def checkpoint(self, upto_ts: int) -> None:
        """Roll lists up to upto_ts, write a snapshot, truncate the WAL.

        Uncommitted txns and layers above upto_ts survive via the fresh WAL.
        (Reference: worker/draft.go snapshot at min pending-txn ts.)
        """
        self._packed_tablets.clear()   # rollup replaces packed bases
        if self.dir is None:
            for pl in list(self.lists.values()):
                pl.rollup(upto_ts)
            self.snapshot_ts = max(self.snapshot_ts, upto_ts)
            return
        with self._lock, self._sink_suspended():
            self.snapshot_ts = max(self.snapshot_ts, upto_ts)
            snap_path = os.path.join(self.dir, "snapshot.bin.tmp")
            with open(snap_path, "wb") as f:
                self._write_snapshot_v2(f, upto_ts)
            os.replace(snap_path, os.path.join(self.dir, "snapshot.bin"))
            # reset WAL with still-relevant records (uncommitted + layers > upto_ts)
            if self._wal is not None:
                self._wal.close()
            wal_path = os.path.join(self.dir, "wal.log")
            self._wal = open(wal_path + ".tmp", "ab")
            self.wal_record_count = 0   # re-counted by the rewrites below
            for kb in sorted(self.lists):
                pl = self.lists[kb]
                for sts, layer in pl.uncommitted.items():
                    if layer.del_all:
                        self._wal_write({"t": "m", "s": sts, "k": kb,
                                         "p": Posting(0, Op.DEL_ALL)})
                    for p in layer.postings.values():
                        self._wal_write({"t": "m", "s": sts, "k": kb, "p": p})
                for layer in pl.layers:
                    fake_start = -layer.commit_ts  # synthetic txn id for replay
                    recs = list(layer.postings.values())
                    if layer.del_all:
                        recs = [Posting(0, Op.DEL_ALL)] + recs
                    for p in recs:
                        self._wal_write({"t": "m", "s": fake_start, "k": kb,
                                         "p": p})
                    self._wal_write({"t": "c", "s": fake_start,
                                     "ts": layer.commit_ts, "k": [kb]})
            self._wal.flush()
            os.fsync(self._wal.fileno())
            self._wal.close()
            os.replace(wal_path + ".tmp", wal_path)
            self._wal = open(wal_path, "ab")
            self.dirty.clear()

    @staticmethod
    def _cat(dt, arrs):
        arrs = [np.asarray(a, dt) for a in arrs if len(a)]
        return np.concatenate(arrs) if arrs else np.zeros(0, dt)

    def _write_snapshot_v2(self, f, upto_ts: int) -> None:
        """Columnar snapshot (DGTS2): every list's packed metadata rides in a
        handful of big arrays, so load is a few frombuffer slices instead of
        nine reads per list (1.2M numpy calls per million edges in the v1
        row format — the cold-open bottleneck)."""
        f.write(b"DGTS2")
        f.write(struct.pack("<Q", upto_ts))
        meta = {"schema": self.schema.to_text(),
                "max_commit_ts": self.max_seen_commit_ts}
        mb = json.dumps(meta).encode()
        f.write(_U32.pack(len(mb)) + mb)
        keys = sorted(self.lists)
        pls = []
        for kb in keys:
            pl = self.lists[kb]
            pl.rollup(upto_ts)
            pls.append(pl)
        N = len(keys)
        f.write(_U32.pack(N))
        key_lens = np.fromiter((len(k) for k in keys), np.uint32, count=N)
        posts = [b"" if not pl.base_postings else json.dumps(
            [posting_to_json(p) for p in pl.base_postings.values()]).encode()
            for pl in pls]
        post_lens = np.fromiter((len(p) for p in posts), np.uint32, count=N)
        bps = [pl.base_packed for pl in pls]
        cols = [
            key_lens,
            np.frombuffer(b"".join(keys), np.uint8),
            np.fromiter((pl.base_ts for pl in pls), np.uint64, count=N),
            np.fromiter((bp.count for bp in bps), np.uint32, count=N),
            np.fromiter((bp.nblocks for bp in bps), np.uint32, count=N),
            self._cat(np.uint64, [bp.block_first for bp in bps]),
            self._cat(np.uint64, [bp.block_last for bp in bps]),
            self._cat(np.int32, [bp.block_count for bp in bps]),
            self._cat(np.int32, [bp.block_width for bp in bps]),
            self._cat(np.int64, [bp.block_off for bp in bps]),
            np.fromiter((len(bp.words) for bp in bps), np.uint64, count=N),
            self._cat(np.uint32, [bp.words for bp in bps]),
            post_lens,
            np.frombuffer(b"".join(posts), np.uint8) if posts
            else np.zeros(0, np.uint8),
        ]
        for arr in cols:
            b = arr.tobytes()
            f.write(struct.pack("<Q", len(b)))
            f.write(b)

    def _load(self) -> None:
        snap = os.path.join(self.dir, "snapshot.bin")
        if os.path.exists(snap):
            with open(snap, "rb") as f:
                raw = f.read()
            if raw[:5] == b"DGTS2":
                self._load_v2(raw)
            else:
                self._load_v1(raw)
        self._replay_wal(os.path.join(self.dir, "wal.log"))

    def _load_v2(self, raw: bytes) -> None:
        off = 5
        (self.snapshot_ts,) = struct.unpack_from("<Q", raw, off)
        off += 8
        (n,) = _U32.unpack_from(raw, off)
        off += 4
        meta = json.loads(raw[off : off + n])
        off += n
        for e in parse_schema(meta.get("schema", "")):
            self.schema.set(e)
        self.max_seen_commit_ts = meta.get("max_commit_ts", 0)
        (N,) = _U32.unpack_from(raw, off)
        off += 4

        def col(dt):
            nonlocal off
            (blen,) = struct.unpack_from("<Q", raw, off)
            off += 8
            # per-column copy: a view into `raw` would pin the ENTIRE
            # snapshot bytes for as long as any single list survives
            arr = np.frombuffer(raw[off: off + blen], dtype=dt)
            off += blen
            return arr

        key_lens = col(np.uint32)
        keys_blob = col(np.uint8).tobytes()
        base_ts = col(np.uint64)
        counts = col(np.uint32)
        nblocks = col(np.uint32)
        bfirst = col(np.uint64)
        blast = col(np.uint64)
        bcount = col(np.int32)
        bwidth = col(np.int32)
        boff = col(np.int64)
        word_lens = col(np.uint64)
        words = col(np.uint32)
        post_lens = col(np.uint32)
        post_blob = col(np.uint8).tobytes()

        kends = np.cumsum(key_lens)
        bends = np.cumsum(nblocks.astype(np.int64))
        wends = np.cumsum(word_lens.astype(np.int64))
        pends = np.cumsum(post_lens.astype(np.int64))

        # tablet-run capture: keys are globally sorted, so a (kind, attr)
        # occupies one contiguous row run — record its column slices for
        # the one-call cold-open fold (csr_build._fold_uid_tablet)
        run_key: tuple[int, str] | None = None
        run_start = 0
        wstarts = wends - word_lens.astype(np.int64)
        bstarts = bends - nblocks.astype(np.int64)

        def flush_run(end: int) -> None:
            if run_key is None or end <= run_start:
                return
            r0, r1 = run_start, end
            bb0, bb1 = int(bstarts[r0]), int(bends[r1 - 1])
            ww0, ww1 = int(wstarts[r0]), int(wends[r1 - 1])
            if run_key[0] not in (int(K.KeyKind.DATA),
                                  int(K.KeyKind.REVERSE)):
                return       # only uid-edge tablets consult the cache
            self._packed_tablets[run_key] = TabletPacked(
                n=r1 - r0,
                counts=counts[r0:r1].astype(np.int64),
                nbs=nblocks[r0:r1].astype(np.int64),
                row_word_start=wstarts[r0:r1] - ww0,
                bfirst=bfirst[bb0:bb1], bcount=bcount[bb0:bb1],
                bwidth=bwidth[bb0:bb1], boff=boff[bb0:bb1],
                words=words[ww0:ww1],
                pure=not post_lens[r0:r1].any(),
                max_base_ts=int(base_ts[r0:r1].max()) if r1 > r0 else 0)

        k0 = b0 = w0 = p0 = 0
        for i in range(N):
            k1, b1 = int(kends[i]), int(bends[i])
            w1, p1 = int(wends[i]), int(pends[i])
            kb = keys_blob[k0:k1]
            pl = PostingList()
            pl.base_ts = int(base_ts[i])
            # zero-copy slices of the shared (read-only) buffers: packed
            # bases are immutable — rollup REPLACES base_packed wholesale
            pl.base_packed = packed.PackedUidList(
                int(counts[i]), bfirst[b0:b1], blast[b0:b1], bcount[b0:b1],
                bwidth[b0:b1], boff[b0:b1], words[w0:w1])
            if p1 > p0:
                pl.base_postings = {
                    p.uid: p for p in map(posting_from_json,
                                          json.loads(post_blob[p0:p1]))}
            kind, attr = K.kind_attr_of(kb)
            self.lists[kb] = pl
            self.by_pred.setdefault((kind, attr), set()).add(kb)
            if (kind, attr) != run_key:
                flush_run(i)
                run_key, run_start = (kind, attr), i
            k0, b0, w0, p0 = k1, b1, w1, p1
        flush_run(N)

    def _load_v1(self, raw: bytes) -> None:
        """Row-format reader kept for snapshots written before DGTS2."""
        assert raw[:5] == b"DGTS1", "bad snapshot magic"
        off = 5
        (snap_ts,) = struct.unpack_from("<Q", raw, off)
        self.snapshot_ts = snap_ts
        off += 8
        (n,) = _U32.unpack_from(raw, off)
        off += 4
        meta = json.loads(raw[off : off + n])
        off += n
        for e in parse_schema(meta.get("schema", "")):
            self.schema.set(e)
        self.max_seen_commit_ts = meta.get("max_commit_ts", 0)
        while off < len(raw):
            (klen,) = _U32.unpack_from(raw, off)
            off += 4
            kb = raw[off : off + klen]
            off += klen
            base_ts, count = struct.unpack_from("<QI", raw, off)
            off += 12
            arrs = []
            for dt in (np.uint64, np.uint64, np.int32, np.int32, np.int64, np.uint32):
                (blen,) = _U32.unpack_from(raw, off)
                off += 4
                arrs.append(np.frombuffer(raw[off : off + blen], dtype=dt).copy())
                off += blen
            (plen,) = _U32.unpack_from(raw, off)
            off += 4
            pbody = raw[off : off + plen]
            off += plen
            pl = PostingList()
            pl.base_ts = base_ts
            pl.base_packed = packed.PackedUidList(count, *arrs)
            if pbody != b"[]":   # uid-only lists skip the json machinery
                pl.base_postings = {
                    p.uid: p
                    for p in map(posting_from_json, json.loads(pbody))}
            kind, attr = K.kind_attr_of(kb)
            self.lists[kb] = pl
            self.by_pred.setdefault((kind, attr), set()).add(kb)

    def close(self) -> None:
        if self._wal is not None:
            self._wal.flush()
            os.fsync(self._wal.fileno())
            self._wal.close()
            self._wal = None
