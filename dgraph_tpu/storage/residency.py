"""HBM working-set manager: tiered device residency (HBM ↔ host ↔ paged).

The paper's acceptance target (LDBC-SNB SF100, ~2B edges) cannot fit in
HBM, yet until this module device residency was all-or-nothing per tablet:
snapshot assembly uploaded every folded CSR eagerly and the only relief
valve was `Node.enforce_memory`'s blunt force-compact. The reference's LSM
tiering (badger levels, SURVEY §storage) is the blueprint one level up:

  HBM (hot)   device buffers resident — CSR columns, token-index columns,
              vector matrices. Identity-stable: eviction drops ONLY the
              device buffers, never the owning PredCSR / TokenIndex /
              VectorIndex object, so qcache per-predicate tokens, the
              DeviceBatcher's same-CSR-object compatibility rule, and mesh
              placement caches all survive an evict → re-admit cycle.
  warm        host-RAM folded arrays only (the fold every tablet keeps
              anyway). Upload-on-demand through the normal device paths;
              demoted here by LRU-of-score eviction when the budget binds.
  cold        tablets whose device footprint exceeds the WHOLE budget.
              They can never be admitted, so the query layer consults
              `prefer_host()` and serves them through the existing
              host-cutover machinery (task._expand_csr host gather,
              vecindex host float64 scan) — byte-identical by the
              size-adaptive-strategy contract.

Admission/eviction is scored with the SAME rate × log2(size) signal the
placement controller ships (coord/placement.tablet_score), fed by the
executor's on_task hook (Node._count_task calls `touch`). Guards:

  * pin floors — `--residency_pin a,b` tablets are never evicted;
  * hysteresis — entries younger than `min_resident_s` are only evicted
    when nothing older can free enough bytes;
  * thrash accounting — a re-admission within `thrash_window_s` of the
    same tablet's eviction counts dgraph_residency_thrash_total (the
    runbook's "budget too small / working set too hot" signal).

Prefetch is plan-driven: the planner already enumerates a plan's
predicate read set (qcache.plan_attrs), so Node.query hands it to
`prefetch()` BEFORE dispatch — warm-tier uploads run on a small async
pool and overlap the preceding host work / device step. Uploads that get
used before eviction count prefetch_hits; uploads evicted untouched count
prefetch_wasted.

Owner protocol (duck-typed; PredCSR, TokenIndex, LazyTokenIndex,
OverlayCSR, VectorIndex implement it):

    owner._res        the manager (None = unmanaged, e.g. bare build_pred)
    owner._res_attr   tablet attr for scoring/pinning
    owner._res_kind   "csr" | "rev" | "index:<tok>" | "vec" | "merged"
    owner.device_nbytes()   device footprint if/when uploaded
    owner.device_resident() device buffers currently held?
    owner.drop_device()     free the device buffers (host fold survives)
    owner.prefer_host()     True when the manager says serve host-side

The upload seam fires the `residency.h2d_upload` fault point
(utils/faults.py); query paths catch the injected FaultError and fall
back to the byte-identical host gather, so an eviction storm under chaos
never produces a wrong read.
"""

from __future__ import annotations

import threading
import time
import weakref

from dgraph_tpu.obs import otrace
from dgraph_tpu.utils import faults, locks

TIER_HBM = "hbm"
TIER_WARM = "warm"
TIER_COLD = "cold"

# rate decay half-life: a tablet idle for one half-life scores half its
# peak load. Long enough to ride out bursty plans, short enough that a
# cooled-off tablet loses its slot to the new working set.
RATE_HALFLIFE_S = 30.0


def tablet_score(size_bytes: float, rate: float) -> float:
    """rate × log2(size): the placement controller's scoring rule
    (coord/placement.tablet_score), reused verbatim so the device working
    set and the cluster placement agree on what "hot" means."""
    from dgraph_tpu.coord.placement import tablet_score as _ts

    return _ts(size_bytes, rate)


class _Entry:
    """One resident device-buffer group (one owner object)."""

    __slots__ = ("ref", "attr", "kind", "nbytes", "admitted_at",
                 "last_touch", "prefetched", "touched")

    def __init__(self, ref, attr: str, kind: str, nbytes: int,
                 now: float) -> None:
        self.ref = ref                   # weakref to the owner
        self.attr = attr
        self.kind = kind
        self.nbytes = int(nbytes)
        self.admitted_at = now
        self.last_touch = now
        self.prefetched = False          # uploaded by the prefetcher
        self.touched = False             # used by a task since admission


def pred_host_nbytes(pd) -> int:
    """Host bytes held by one folded PredData — CSR columns, value
    tables, token indexes, AND vector matrices (the bytes
    Node.enforce_memory undercounted before this module)."""
    n = 0
    for csr in (pd.csr, pd.rev_csr):
        if csr is None:
            continue
        est = getattr(csr, "approx_nbytes", None)
        if est is not None:              # overlay: don't force a merge
            n += est()
            continue
        hn = getattr(csr, "host_nbytes", None)
        if hn is not None:
            n += hn()
    for fld in (pd.value_subjects, pd.num_values):
        if fld is not None:
            n += int(getattr(fld, "nbytes", 0))
    for ti in pd.indexes.values():
        hn = getattr(ti, "host_nbytes", None)
        if hn is not None:
            n += hn()
    if pd.vecindex is not None:
        n += pd.vecindex.nbytes()
    return n


class ResidencyManager:
    """Per-node device-byte budget + tier bookkeeping. budget_bytes <= 0
    means unbounded (accounting and metrics still run, nothing is ever
    denied or evicted for space)."""

    def __init__(self, budget_bytes: int = 0, metrics=None,
                 pins: tuple[str, ...] = (),
                 min_resident_s: float = 2.0,
                 thrash_window_s: float = 10.0,
                 rate_halflife_s: float = RATE_HALFLIFE_S,
                 prefetch_workers: int = 2,
                 clock=None) -> None:
        from dgraph_tpu.utils.metrics import Registry

        self.budget = int(budget_bytes)
        self.metrics = metrics if metrics is not None else Registry()
        self.pins = {p for p in pins if p}
        self.min_resident_s = float(min_resident_s)
        self.thrash_window_s = float(thrash_window_s)
        self.rate_halflife_s = float(rate_halflife_s)
        self.clock = clock if clock is not None else time.monotonic
        # serializes managed uploads PER OWNER (striped by identity): two
        # threads racing the same tablet's first device access must
        # produce ONE buffer set, but a prefetch of tablet A must not
        # block a foreground query's first access to tablet B
        # ONE lockdep class for the whole stripe family: stripe choice is
        # hash-derived (id % 16), so any nesting of two stripes is a
        # latent ABBA — the shared name makes lockdep's
        # same-class-nesting check catch it from a single observation
        self._upload_locks = tuple(
            locks.RLock("residency.upload") for _ in range(16))
        self._lock = locks.RLock("residency.ResidencyManager._lock")
        self._entries: dict[int, _Entry] = {}
        # attr -> resident entry keys: touch() runs per TASK and must not
        # scan every resident buffer group on the node
        self._attr_keys: dict[str, set[int]] = {}
        self._bytes = 0
        # attr -> (decayed use count, last decay ts): the executor
        # on_task hook feeds this; score = rate × log2(size)
        self._rates: dict[str, tuple[float, float]] = {}
        self._evicted_at: dict[str, float] = {}   # attr -> last eviction ts
        # id(pd) -> PredData (weak): PredData has value-equality
        # semantics (dataclass), so a WeakSet would need hashing — key on
        # identity instead; entries vanish as folds are collected
        self._preds: weakref.WeakValueDictionary = \
            weakref.WeakValueDictionary()
        self._pool = None
        self._pool_workers = max(1, int(prefetch_workers))
        m = self.metrics
        self._c_admit = m.counter("dgraph_residency_admissions_total")
        self._c_evict = m.counter("dgraph_residency_evictions_total")
        self._c_pf_hit = m.counter("dgraph_residency_prefetch_hits_total")
        self._c_pf_waste = m.counter(
            "dgraph_residency_prefetch_wasted_total")
        self._c_thrash = m.counter("dgraph_residency_thrash_total")
        self._c_cold = m.counter("dgraph_residency_cold_serves_total")
        self._c_upfail = m.counter(
            "dgraph_residency_upload_failures_total")
        self._c_overrun = m.counter(
            "dgraph_residency_budget_overruns_total")
        self._g_hbm = m.counter("dgraph_residency_hbm_bytes")
        self._g_host = m.counter("dgraph_residency_host_bytes")
        self._tier_gauge = m.keyed("dgraph_residency_tier_bytes",
                                   labels=("tier",))

    # -- config ---------------------------------------------------------------

    @property
    def enabled(self) -> bool:
        """True when a finite budget is configured (eviction can happen)."""
        return self.budget > 0

    def upload_lock_for(self, owner):
        return self._upload_locks[id(owner) % len(self._upload_locks)]

    def pin(self, attr: str) -> None:
        with self._lock:
            self.pins.add(attr)

    def unpin(self, attr: str) -> None:
        with self._lock:
            self.pins.discard(attr)

    # -- load signals (executor on_task hook) ---------------------------------

    def touch(self, attr: str, n: float = 1.0) -> None:
        """One task read against attr: bump the decayed rate and resolve
        prefetch-hit accounting for its resident buffers."""
        now = self.clock()
        with self._lock:
            cnt, ts = self._rates.get(attr, (0.0, now))
            if now > ts:
                cnt *= 0.5 ** ((now - ts) / self.rate_halflife_s)
            self._rates[attr] = (cnt + float(n), now)
            for key in self._attr_keys.get(attr, ()):
                e = self._entries.get(key)
                if e is None:
                    continue
                e.last_touch = now
                if e.prefetched and not e.touched:
                    self._c_pf_hit.inc()
                e.touched = True

    def _rate(self, attr: str, now: float) -> float:
        cnt, ts = self._rates.get(attr, (0.0, now))
        if now > ts:
            cnt *= 0.5 ** ((now - ts) / self.rate_halflife_s)
        return cnt

    def _score(self, e: _Entry, now: float) -> float:
        return tablet_score(e.nbytes, self._rate(e.attr, now))

    # -- admission / eviction -------------------------------------------------

    def allows_device(self, nbytes: int) -> bool:
        """False only for COLD tablets: a device footprint larger than the
        whole budget can never be admitted — serve it host-side."""
        return self.budget <= 0 or int(nbytes) <= self.budget

    def note_cold_serve(self) -> None:
        from dgraph_tpu.obs import costs

        costs.note("cold_serve")
        self._c_cold.inc()

    def before_upload(self, owner) -> None:
        """Called by an owner immediately before its H2D upload (the
        caller holds upload_lock). Fires the chaos fault point, then
        evicts colder tablets until the new buffers fit."""
        faults.fire("residency.h2d_upload", m=self.metrics)
        need = int(owner.device_nbytes())
        if self.budget <= 0:
            return
        with self._lock:
            if need > self.budget:
                # cold tablet forced onto the device by a path that never
                # consulted prefer_host (belt-and-braces: never fail the
                # read, but make the overrun visible)
                self._c_overrun.inc()
                return
            self._evict_for_locked(need)

    def _evict_for_locked(self, need: int) -> None:
        now = self.clock()
        for honor_hysteresis in (True, False):
            if self._bytes + need <= self.budget:
                return
            cands = [e for e in self._entries.values()
                     if e.attr not in self.pins]
            if honor_hysteresis:
                cands = [e for e in cands
                         if now - e.admitted_at >= self.min_resident_s]
            cands.sort(key=lambda e: (self._score(e, now), e.last_touch))
            for e in cands:
                if self._bytes + need <= self.budget:
                    return
                self._evict_entry_locked(e, now, reason="budget")

    def _evict_entry_locked(self, e: _Entry, now: float,
                            reason: str) -> None:
        owner = e.ref()
        key = None
        for k, v in list(self._entries.items()):
            if v is e:
                key = k
                break
        if key is None:
            return                 # weakref callback already reaped it
        self._entries.pop(key, None)
        self._attr_keys.get(e.attr, set()).discard(key)
        self._bytes -= e.nbytes
        self._c_evict.inc()
        if e.prefetched and not e.touched:
            self._c_pf_waste.inc()
        # thrash counts ONCE per cycle, at re-admission (after_upload) —
        # the documented "re-admitted within thrash_window_s of its
        # eviction" semantics
        self._evicted_at[e.attr] = now
        if len(self._evicted_at) > 4096:
            self._evicted_at.pop(next(iter(self._evicted_at)))
        self._g_hbm.set(self._bytes)
        self._tier_gauge.set(TIER_HBM, self._bytes)
        otrace.event("residency_tier", attr=e.attr, kind=e.kind,
                     transition="hbm->warm", reason=reason,
                     nbytes=e.nbytes)
        if owner is not None:
            owner.drop_device()

    def after_upload(self, owner, prefetch: bool = False) -> None:
        """Register freshly-uploaded device buffers (caller holds
        upload_lock)."""
        now = self.clock()
        attr = getattr(owner, "_res_attr", "")
        kind = getattr(owner, "_res_kind", "")
        nbytes = int(owner.device_nbytes())
        with self._lock:
            key = id(owner)
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes

            def _gone(_ref, _key=key):
                with self._lock:
                    ent = self._entries.pop(_key, None)
                    if ent is not None:
                        self._attr_keys.get(ent.attr, set()).discard(_key)
                        self._bytes -= ent.nbytes
                        self._g_hbm.set(self._bytes)

            e = _Entry(weakref.ref(owner, _gone), attr, kind, nbytes, now)
            e.prefetched = bool(prefetch)
            self._entries[key] = e
            self._attr_keys.setdefault(attr, set()).add(key)
            self._bytes += nbytes
            self._c_admit.inc()
            ev = self._evicted_at.get(attr)
            if ev is not None and now - ev <= self.thrash_window_s:
                self._c_thrash.inc()
            self._g_hbm.set(self._bytes)
            # the hbm tier series stays live on /metrics without a
            # usage() walk; warm/cold refresh on usage()/debug reads
            self._tier_gauge.set(TIER_HBM, self._bytes)
        otrace.event("residency_tier", attr=attr, kind=kind,
                     transition="warm->hbm",
                     prefetch=bool(prefetch), nbytes=nbytes)

    def evict_to(self, budget_bytes: int) -> int:
        """Shrink resident device bytes to at most budget_bytes, ignoring
        hysteresis (enforce_memory / tests). Pinned tablets survive unless
        the target is 0. Returns the number of buffer groups evicted."""
        n = 0
        target = max(0, int(budget_bytes))
        with self._lock:
            now = self.clock()
            cands = sorted(self._entries.values(),
                           key=lambda e: (e.attr in self.pins,
                                          self._score(e, now),
                                          e.last_touch))
            for e in cands:
                if self._bytes <= target:
                    break
                if target > 0 and e.attr in self.pins:
                    continue
                self._evict_entry_locked(e, now, reason="enforce")
                n += 1
        return n

    # -- tier queries ---------------------------------------------------------

    def tier_of(self, attr: str, nbytes: int | None = None) -> str:
        with self._lock:
            if self._attr_keys.get(attr):
                return TIER_HBM
        if nbytes is not None and not self.allows_device(nbytes):
            return TIER_COLD
        return TIER_WARM

    # -- host-side accounting (enforce_memory) --------------------------------

    def track_pred(self, pd) -> None:
        # WeakValueDictionary is not thread-safe; folds can land from the
        # fold pool while a /debug reader walks host_bytes()
        with self._lock:
            self._preds[id(pd)] = pd

    def host_bytes(self) -> int:
        """Host bytes pinned by live folded PredData objects — including
        vector embedding matrices (the enforce_memory undercount fix)."""
        total = 0
        with self._lock:
            live = list(self._preds.values())
        for pd in live:
            try:
                total += pred_host_nbytes(pd)
            except Exception:
                continue
        self._g_host.set(total)
        return total

    # -- owner adoption (fold/stamp seam) -------------------------------------

    def adopt_pred(self, pd) -> None:
        """Attach this manager to every device-buffer owner of one folded
        or stamped PredData (csr_build.build_pred / delta.stamp_pred
        tails) and start host-byte tracking for it."""
        attr = pd.attr
        self._adopt(pd.csr, attr, "csr")
        self._adopt(pd.rev_csr, attr, "rev")
        for name, ti in pd.indexes.items():
            self._adopt(ti, attr, f"index:{name}")
        vi = pd.vecindex
        if vi is not None:
            if getattr(vi, "is_overlay", False):
                self._adopt(getattr(vi, "base", None), attr, "vec")
            else:
                self._adopt(vi, attr, "vec")
        self.track_pred(pd)

    def _adopt(self, owner, attr: str, kind: str) -> None:
        if owner is None:
            return
        base = getattr(owner, "base", None)
        if base is not None and hasattr(owner, "delta"):
            # OverlayCSR: manage the base AND the overlay's merged view
            self._adopt(base, attr, kind)
            kind = f"{kind}:merged"
        if not (hasattr(owner, "drop_device")
                and hasattr(owner, "device_nbytes")):
            return
        if getattr(owner, "_res", None) is self:
            return
        owner._res = self
        owner._res_attr = attr
        owner._res_kind = kind

    # -- plan-driven prefetch -------------------------------------------------

    def prefetch(self, attrs, snap, sync: bool = False) -> int:
        """Plan-driven prefetch, two legs issued BEFORE dispatch:

        * lazy FOLDS (ISSUE 15) — attrs still registered as fold-thunks
          (storage/csr_build.LazyPreds) resolve on the shared fold pool,
          overlapping the fold with the request's preceding host work;
          the request's own first read then JOINS the in-flight fold via
          the thunk's singleflight. Folding is host-side cost, so this
          leg runs regardless of the device budget.
        * warm→HBM UPLOADS (ISSUE 11) — folded, admissible,
          not-yet-resident buffer groups upload on the async pool, only
          with a finite budget (`enabled`), exactly as before. A
          prefetched fold chains into its own upload when admissible.

        Returns the number of fold+upload actions scheduled. sync=True
        runs everything inline (tests / deterministic benches)."""
        if not attrs:
            return 0
        preds = snap.preds
        is_pending = getattr(preds, "is_pending", None)
        scheduled = 0
        folded_attrs = []
        for attr in attrs:
            if is_pending is not None and is_pending(attr):
                scheduled += 1
                if sync:
                    self._prefetch_fold(preds, attr, sync=True)
                else:
                    from dgraph_tpu.storage.csr_build import _fold_pool

                    # dgraph: allow(ctxvar-copy) prefetched folds build
                    # SHARED snapshot state cached across requests — they
                    # must not inherit any one request's deadline/trace
                    _fold_pool().submit(self._prefetch_fold, preds, attr)
            else:
                folded_attrs.append(attr)
        if not self.enabled:
            return scheduled
        todo = []
        for attr in folded_attrs:
            pd = preds.get(attr)
            if pd is None:
                continue
            todo.extend(self._upload_candidates(pd))
        for owner in todo:
            if sync:
                self._prefetch_one(owner)
            else:
                # dgraph: allow(ctxvar-copy) prefetch outlives the
                # admitting request by design (the uploaded buffers are
                # shared) — inheriting its deadline would cancel uploads
                # the NEXT query needs
                self._prefetch_pool().submit(self._prefetch_one, owner)
        return scheduled + len(todo)

    def _upload_candidates(self, pd) -> list:
        """Managed, admissible, not-yet-resident buffer groups of one
        folded PredData."""
        out = []
        for owner in (pd.csr, pd.rev_csr, pd.vecindex):
            if owner is None or getattr(owner, "_res", None) is not self:
                continue
            try:
                if owner.device_resident() or \
                        not self.allows_device(owner.device_nbytes()):
                    continue
            except Exception:
                continue
            out.append(owner)
        return out

    def _prefetch_fold(self, preds, attr: str, sync: bool = False) -> None:
        """Resolve one pending fold-thunk (trigger=prefetch), then chain
        its warm→HBM uploads when a finite budget is configured. The
        uploads route through the dedicated prefetch pool — a blocking
        H2D transfer must not occupy a fold-pool slot other queries'
        lazy folds are waiting on (sync=True runs them inline)."""
        try:
            pd = preds.resolve(attr, "prefetch")
        except Exception:
            # racing drops / injected faults: the on-demand read path
            # retries; a failed prefetch must never surface anywhere
            return
        if pd is None or not self.enabled:
            return
        for owner in self._upload_candidates(pd):
            if sync:
                self._prefetch_one(owner)
            else:
                # dgraph: allow(ctxvar-copy) prefetch uploads are shared
                # node work detached from any request's deadline/trace
                self._prefetch_pool().submit(self._prefetch_one, owner)

    def _prefetch_pool(self):
        with self._lock:
            if self._pool is None:
                from concurrent.futures import ThreadPoolExecutor

                self._pool = ThreadPoolExecutor(
                    max_workers=self._pool_workers,
                    thread_name_prefix="dgt-prefetch")
            return self._pool

    def _prefetch_one(self, owner) -> None:
        try:
            fn = getattr(owner, "device_arrays", None) or \
                getattr(owner, "device", None)
            if fn is None:
                return
            fn(prefetch=True)
        except Exception:
            # injected upload faults / racing drops: the on-demand path
            # retries; a failed prefetch must never surface anywhere
            pass

    # -- readouts -------------------------------------------------------------

    def usage(self) -> dict:
        """Tier byte totals + counters; refreshes the /metrics gauges."""
        hbm = warm = cold = 0
        with self._lock:
            hbm = self._bytes
            resident_ids = {id(e.ref()) for e in self._entries.values()
                            if e.ref() is not None}
            live = list(self._preds.values())
        for pd in live:
            owners = [pd.csr, pd.rev_csr, pd.vecindex] + \
                list(pd.indexes.values())
            for owner in owners:
                if owner is None or \
                        not hasattr(owner, "device_nbytes"):
                    continue
                if id(owner) in resident_ids:
                    continue
                try:
                    nb = int(owner.device_nbytes())
                except Exception:
                    continue
                if not self.allows_device(nb):
                    cold += nb
                else:
                    warm += nb
        self._tier_gauge.set(TIER_HBM, hbm)
        self._tier_gauge.set(TIER_WARM, warm)
        self._tier_gauge.set(TIER_COLD, cold)
        self._g_hbm.set(hbm)
        return {"budget_bytes": self.budget, "hbm_bytes": hbm,
                "warm_bytes": warm, "cold_bytes": cold,
                "entries": len(self._entries)}

    def debug_snapshot(self) -> dict:
        """The /debug/metrics "residency" section payload."""
        u = self.usage()
        c = lambda n: self.metrics.counter(n).value
        with self._lock:
            resident = {}
            for e in self._entries.values():
                resident[f"{e.attr}/{e.kind}"] = e.nbytes
        return {
            "budget_mb": round(self.budget / (1 << 20), 2),
            "enabled": self.enabled,
            "tiers": {TIER_HBM: u["hbm_bytes"], TIER_WARM: u["warm_bytes"],
                      TIER_COLD: u["cold_bytes"]},
            "host_bytes": self.host_bytes(),
            "admissions": c("dgraph_residency_admissions_total"),
            "evictions": c("dgraph_residency_evictions_total"),
            "prefetch_hits": c("dgraph_residency_prefetch_hits_total"),
            "prefetch_wasted":
                c("dgraph_residency_prefetch_wasted_total"),
            "thrash": c("dgraph_residency_thrash_total"),
            "cold_serves": c("dgraph_residency_cold_serves_total"),
            "upload_failures":
                c("dgraph_residency_upload_failures_total"),
            "budget_overruns":
                c("dgraph_residency_budget_overruns_total"),
            "pinned": sorted(self.pins),
            "resident": resident,
        }

    def close(self) -> None:
        pool = self._pool
        if pool is not None:
            pool.shutdown(wait=False)


def ensure_device(owner, cache_attr: str, build, prefetch: bool = False):
    """The shared upload seam for every owner's lazy device property:
    unmanaged owners upload directly (exactly the pre-residency
    behavior); managed ones serialize through the manager's upload lock
    (two threads racing the same tablet's first access must mint ONE
    buffer set), fire the `residency.h2d_upload` fault point, evict for
    space, and register with the manager. `build` returns the device
    buffer tuple, cached on the owner under `cache_attr`."""
    dev = getattr(owner, cache_attr)
    if dev is not None:
        return dev
    mgr = getattr(owner, "_res", None)
    if mgr is None:
        dev = build()
        setattr(owner, cache_attr, dev)
        return dev
    with mgr.upload_lock_for(owner):
        dev = getattr(owner, cache_attr)
        if dev is None:
            try:
                mgr.before_upload(owner)
            except faults.FaultError:
                mgr._c_upfail.inc()
                raise
            dev = build()
            setattr(owner, cache_attr, dev)
            mgr.after_upload(owner, prefetch=prefetch)
            if not prefetch:
                # warm->HBM upload at SERVE time: the querying request
                # paid the transfer — charge its cost ledger (prefetch
                # uploads are the node's background work, not the
                # query's)
                from dgraph_tpu.obs import costs

                try:
                    costs.add_upload(int(owner.device_nbytes()))
                    costs.note("residency_upload")
                except Exception:
                    pass       # accounting must never fail an upload
    return dev


def prefer_host(owner) -> bool:
    """True when the owner's manager classifies it COLD (device footprint
    larger than the whole budget) and it is not already resident — the
    query layer serves it through the existing host-cutover machinery.
    Unmanaged owners never prefer host (pre-residency behavior).

    Pure consult: callers that actually SERVE the read host-side count
    dgraph_residency_cold_serves_total themselves (note_cold_serve) — a
    query may consult several owners (fused-shape checks) but serves each
    read once."""
    mgr = getattr(owner, "_res", None)
    if mgr is None or owner.device_resident():
        return False
    return not mgr.allows_device(owner.device_nbytes())
