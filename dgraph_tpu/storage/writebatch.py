"""Group-commit write batching: one fsync per commit window (ISSUE 16).

Fifteen rounds scaled reads while every commit still paid its own oracle
conflict pass, its own fsync'd WAL append, and its own per-predicate
watermark advance. This module is the write-side sibling of
query/batch.py's DeviceBatcher — the same short-window collector shape
(window / early-fire / idle-bypass / per-member demux), applied to the
badger-style group commit the reference's write path uses (SURVEY
§storage):

  * WriteBatcher — committing txns that arrive within a ~2ms window form
    ONE group: one Oracle.commit_batch conflict pass under one oracle
    lock hold, one contiguous WAL append with ONE os.fsync
    (Store.commit_group), and one store-lock hold advancing every
    member's watermarks — so the delta journal accumulates the window's
    UNION delta and the next read stamps each touched predicate once
    instead of once per commit.
  * Per-member outcomes demux exactly like solo commits: a conflicting
    member gets its typed TxnConflict (and its buffered layers abort)
    while the rest of the window commits; an unknown txn gets
    TxnNotFound. Acks release only AFTER the window's apply lands, so a
    committer's next read observes its own write (read-your-writes is
    preserved through the watermark the apply advanced).
  * A WAL append failure AFTER the oracle assigned commit timestamps is
    typed CommitAmbiguous for every surviving member: the decision
    cannot be re-run (retrying could double-apply), and whether the
    record reached the log/quorum is unknowable from here — the exact
    contract utils/retry refuses to retry.
  * Idle-fire: when no group append is in flight the leader skips the
    window entirely — unloaded writers pay zero added latency. Deadline
    bypass: a committer whose remaining budget cannot cover the window
    plus the expected append runs the solo per-commit path instead.
  * A batch of ONE runs its solo closure — the exact per-commit path
    (per-commit WAL record, per-commit fsync), so unaccompanied traffic
    produces byte-identical logs to the pre-16 write path.

Observability: dgraph_write_batch_* counters + occupancy histogram on
/metrics and the /debug/metrics "writes" section; group appends note
"group_commit" on member cost ledgers with the append wall-ms
apportioned across the window.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable

from dgraph_tpu.coord.zero import TxnConflict
from dgraph_tpu.obs import costs, otrace
from dgraph_tpu.utils import deadline as dl
from dgraph_tpu.utils import locks
from dgraph_tpu.utils.retry import CommitAmbiguous


class _Entry:
    __slots__ = ("start_ts", "keys", "solo", "dl", "lg", "tenant",
                 "event", "result", "error", "batch_size")

    def __init__(self, start_ts: int, keys, solo: Callable,
                 tenant: str = "") -> None:
        self.start_ts = start_ts
        self.keys = keys
        self.solo = solo          # zero-arg exact per-commit path
        self.dl = dl.current()    # the committing caller's deadline
        self.lg = costs.current()  # ... and cost ledger (apportioned)
        self.tenant = tenant      # committing namespace (slot caps)
        self.event = threading.Event()
        self.result: Any = None   # commit_ts on success
        self.error: BaseException | None = None
        self.batch_size = 0


class _Batch:
    __slots__ = ("entries", "full", "closed")

    def __init__(self, entry: _Entry) -> None:
        self.entries = [entry]
        self.full = threading.Event()
        self.closed = False


# follower safety net: a leader always sets every entry's event in its
# finally block, so this only fires on catastrophic leader death
_FOLLOWER_WAIT_S = 120.0


class WriteBatcher:
    """Short-window collector of concurrent committing transactions.

    All commits are mutually compatible (they share the oracle and the
    journal), so there is a single open batch at a time — no
    classification key. `oracle` is the Zero txn oracle, `store` the
    posting store whose WAL the window appends to."""

    def __init__(self, oracle, store, metrics=None, window_ms: float = 2.0,
                 max_batch: int = 64, idle_fire: bool = True) -> None:
        from dgraph_tpu.utils.metrics import Registry

        self.oracle = oracle
        self.store = store
        self.metrics = metrics if metrics is not None else Registry()
        self.window_s = max(float(window_ms), 0.0) / 1000.0
        self.max_batch = max(int(max_batch), 1)
        # fire-immediately when the journal is idle: a batch leader skips
        # the window when no group append is in flight, so concurrency-1
        # writers pay ZERO added latency. Tests disable it to force
        # deterministic full windows.
        self.idle_fire = idle_fire
        self._lock = locks.Lock("writebatch.WriteBatcher._lock")
        self._open: _Batch | None = None
        self._own_inflight = 0
        # EWMA of one group append+apply (seconds) — the deadline-bypass
        # estimate of what joining the window costs beyond the window
        self._step_s = 0.001
        m = self.metrics
        self._formed = m.counter("dgraph_write_batch_formed_total")
        self._commits = m.counter("dgraph_write_batch_commits_total")
        self._fsyncs = m.counter("dgraph_write_batch_fsyncs_total")
        self._occupancy = m.histogram("dgraph_write_batch_occupancy")
        self._window_waits = m.counter(
            "dgraph_write_batch_window_waits_total")
        self._bypass = m.counter(
            "dgraph_write_batch_deadline_bypass_total")
        self._conflicts = m.counter(
            "dgraph_write_batch_conflict_aborts_total")
        self._tenant_solo = m.counter(
            "dgraph_write_batch_tenant_solo_total")
        # multi-tenant QoS (dgraph_tpu/tenancy/; ISSUE 20): when armed,
        # Node injects tenant_fn (tenancy.current) and tenant_cap_fn
        # (tenant -> max window slots, None = uncapped). An over-cap
        # tenant's commit runs the exact solo per-commit path — still
        # correct, still durable, but it pays its OWN fsync instead of
        # crowding lighter tenants out of the shared window. Disarmed
        # (--no_qos / unconfigured): both stay None, zero overhead.
        self.tenant_fn = None
        self.tenant_cap_fn = None

    def _busy(self) -> bool:
        return self._own_inflight > 0

    def _deadline_bypasses(self) -> bool:
        """True when the caller's remaining budget cannot cover the
        window plus the expected group append — it commits solo instead,
        where the per-commit path's own deadline machinery applies."""
        rem = dl.remaining()
        if rem is None:
            return False
        if rem < self.window_s + self._step_s:
            self._bypass.inc()
            otrace.event("write_batch_bypass",
                         remaining_ms=round(rem * 1000, 1))
            costs.note("write_batch_bypass")
            return True
        return False

    def submit(self, start_ts: int, keys, solo: Callable) -> int:
        """Commit one txn through the window. Returns commit_ts; raises
        the same typed errors the solo path would (TxnConflict after the
        member's layers abort, TxnNotFound, CommitAmbiguous when the
        group append failed after the oracle decided). `solo` is the
        exact per-commit path, run for deadline bypasses and windows of
        one."""
        if self._deadline_bypasses():
            return solo()
        tenant = self.tenant_fn() if self.tenant_fn is not None else ""
        cap = self.tenant_cap_fn(tenant) \
            if self.tenant_cap_fn is not None else None
        entry = _Entry(start_ts, keys, solo, tenant)
        over_cap = False
        with self._lock:
            b = self._open
            if b is not None and not b.closed and \
                    len(b.entries) < self.max_batch:
                if cap is not None and sum(
                        1 for en in b.entries
                        if en.tenant == tenant) >= cap:
                    # this tenant already holds its share of the window:
                    # commit solo (own fsync) rather than crowding the
                    # group — leading a FRESH window stays allowed, so a
                    # lone heavy writer on an idle node still batches
                    over_cap = True
                else:
                    b.entries.append(entry)
                    if len(b.entries) >= self.max_batch:
                        b.full.set()
                    leader = False
            else:
                b = _Batch(entry)
                self._open = b
                leader = True
        if over_cap:
            self._tenant_solo.inc()
            costs.note("write_batch_tenant_cap")
            otrace.event("write_batch_tenant_cap", tenant=tenant)
            return solo()
        if not leader:
            rem = dl.remaining()
            wait_s = _FOLLOWER_WAIT_S if rem is None else \
                min(_FOLLOWER_WAIT_S, max(rem, 0.0) + 0.1)
            if not entry.event.wait(wait_s):
                # own budget gone while the window still runs: typed
                # DeadlineExceeded (never a hang past the budget) — the
                # window's outcome for this txn is discarded
                dl.check("group commit window")
                raise RuntimeError("group commit leader never completed")
            otrace.event("group_commit", size=entry.batch_size)
            if entry.error is not None:
                raise entry.error
            return entry.result
        try:
            if self.window_s > 0 and \
                    not (self.idle_fire and not self._busy()):
                self._window_waits.inc()
                t0 = time.perf_counter()
                # dgraph: allow(deadline-wait) leader window wait is
                # bounded by the ~2ms collection window constant; tight
                # budgets bypassed the window entirely upstream
                b.full.wait(self.window_s)
                # continuous collection: while a group append is already
                # in flight this window could only queue behind it, so
                # keep collecting until the journal frees up (bounded by
                # one window + one expected append)
                cap = self.window_s + self._step_s
                while (not b.full.is_set()) and self._busy() and \
                        time.perf_counter() - t0 < cap:
                    # dgraph: allow(deadline-wait) bounded by `cap` (one
                    # window + one expected append) in the loop condition
                    b.full.wait(self.window_s)
        finally:
            with self._lock:
                b.closed = True
                if self._open is b:
                    self._open = None
                self._own_inflight += 1
        entries = b.entries
        try:
            if len(entries) == 1:
                entries[0].result = entries[0].solo()
                self._fsyncs.inc()   # solo path pays its own fsync
                self._commits.inc()
            else:
                # the window acts for SEVERAL committers: run under the
                # most permissive member's deadline (unbudgeted if any
                # member is) so a tight-budget leader cannot shed the
                # append the other members had ample time for
                dls = [en.dl for en in entries]
                batch_dl = None if any(d is None for d in dls) else \
                    max(dls, key=lambda d: d.expires)
                with dl.adopt(batch_dl):
                    self._run_group(entries)
        except BaseException as e:
            # a failure of the WINDOW fails every member without a
            # per-member outcome yet; per-member conflicts/aborts were
            # assigned individually inside the runner
            for en in entries:
                if en.result is None and en.error is None:
                    en.error = e
        finally:
            with self._lock:
                self._own_inflight -= 1
            n = len(entries)
            self._formed.inc()
            self._occupancy.observe(float(n))
            for en in entries:
                en.batch_size = n
                en.event.set()
        otrace.event("group_commit", size=entry.batch_size)
        if entry.error is not None:
            raise entry.error
        return entry.result

    def _run_group(self, entries: list[_Entry]) -> None:
        """One window: batched oracle decision, then ONE WAL append +
        fsync + in-memory apply for every committing member."""
        t0 = time.perf_counter()
        with otrace.span("zero:commit_batch", size=len(entries)):
            decisions = self.oracle.commit_batch(
                [en.start_ts for en in entries])
        members: list[tuple[_Entry, int]] = []
        for en, res in zip(entries, decisions):
            if isinstance(res, BaseException):
                if isinstance(res, TxnConflict):
                    self._conflicts.inc()
                    try:
                        self.store.abort(en.start_ts, list(en.keys))
                    except (ConnectionError, OSError):
                        # the abort record is advisory (an unreplayed
                        # abort only leaves uncommitted layers rollup
                        # discards); the member's outcome stays the
                        # typed TxnConflict
                        pass
                en.error = res
            else:
                members.append((en, res))
        if not members:
            return
        try:
            with otrace.span("store:group_commit", size=len(members)):
                self.store.commit_group(
                    [(en.start_ts, ts, list(en.keys))
                     for en, ts in members])
        except BaseException as e:
            # commit timestamps are already assigned and conflict-
            # tracked: the decision cannot be re-run, and whether the
            # record reached the log (or a replication quorum) before
            # the failure is unknowable here — ambiguous, typed, never
            # retried (utils/retry's contract)
            for en, _ts in members:
                amb = CommitAmbiguous(
                    f"group commit append failed mid-window: {e!r}")
                amb.__cause__ = e
                en.error = amb
            return
        dt_ms = (time.perf_counter() - t0) * 1e3
        self._step_s = 0.8 * self._step_s + 0.2 * (dt_ms / 1e3)
        self._fsyncs.inc()           # ONE fsync covered len(members)
        self._commits.inc(len(members))
        frac = dt_ms / len(members)
        for en, ts in members:
            if en.lg is not None:
                # apportion the window's append+apply wall ms across the
                # member commits it acted for
                en.lg.add_kernel("group_commit", frac)
                en.lg.note("group_commit")
            en.result = ts
