"""Posting lists with an immutable packed layer + MVCC mutation layers.

Reference semantics: posting/list.go — a List is an immutable bp128-packed
`plist` + a sorted mutable layer of posting deltas + per-transaction
uncommitted postings (posting/list.go:71-84); AddMutation (:292),
CommitMutation/AbortTransaction (:423,:384), Iterate(readTs, afterUid) (:502);
posting/mvcc.go — Txn deltas keyed by StartTs, commit writes deltas at
commitTs.

Redesign notes: the reference interleaves a skiplist-ish mlayer with compressed
blocks during every read. Here reads at a readTs fold committed delta layers
over the packed base *once per snapshot build* (storage/csr_build.py) — the
device always sees immutable CSR snapshots, so per-read merging happens only
for host-side point reads (values, single-list iteration). rollup() re-packs
committed layers into the base, the analog of SyncIfDirty (posting/list.go).

An SP* wildcard delete (subject-predicate star, rdf "S P *") is a DEL_ALL
posting that shadows everything at or below its commit ts.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from enum import IntEnum

import numpy as np

from dgraph_tpu.storage import native, packed
from dgraph_tpu.utils.types import Val

# uid slot used by non-lang value postings (reference uses math.MaxUint64 for
# the value fingerprint; we reserve 0 — real uids start at 1).
VALUE_UID = 0


class Op(IntEnum):
    SET = 0
    DEL = 1
    DEL_ALL = 2  # S P * wildcard


def lang_uid(lang: str) -> int:
    """Fingerprint for @lang value postings (stable per language tag)."""
    if not lang:
        return VALUE_UID
    import hashlib

    h = int.from_bytes(hashlib.blake2b(lang.encode(), digest_size=7).digest(), "big")
    return h | (1 << 60)  # keep clear of real uid space


def value_fingerprint(v: Val) -> int:
    """Posting slot for one value of a list-valued scalar predicate
    (reference: multi-valued postings keyed by value fingerprint)."""
    import hashlib

    from dgraph_tpu.utils.types import marshal

    raw = bytes([int(v.tid)]) + marshal(v)
    h = int.from_bytes(hashlib.blake2b(raw, digest_size=7).digest(), "big")
    return h | (1 << 61)  # distinct from lang (1<<60) and uid space


@dataclass(frozen=True)
class Posting:
    uid: int                      # object uid (uid-edges) or value slot
    op: Op = Op.SET
    value: Val | None = None      # value postings
    lang: str = ""
    facets: tuple = ()            # tuple of (name, Val), sorted by name


@dataclass(frozen=True)
class DirectedEdge:
    """One mutation edge (reference: protos DirectedEdge, intern.proto:167)."""

    subject: int
    attr: str
    object_uid: int = 0           # uid edges
    value: Val | None = None      # value edges
    op: Op = Op.SET
    lang: str = ""
    facets: tuple = ()

    def to_posting(self, is_list: bool = False) -> Posting:
        if self.op == Op.DEL_ALL:
            return Posting(VALUE_UID, Op.DEL_ALL)
        if self.value is not None:
            slot = value_fingerprint(self.value) if is_list else lang_uid(self.lang)
            return Posting(slot, self.op, self.value, self.lang, self.facets)
        return Posting(self.object_uid, self.op, None, self.lang, self.facets)


@dataclass
class _Layer:
    commit_ts: int
    postings: dict[int, Posting] = field(default_factory=dict)  # uid -> last write wins
    del_all: bool = False


# shared immutable empty base: bulk loads create hundreds of thousands of
# lists, and packing a fresh empty array per list measurably slowed them
_EMPTY_PACKED = packed.pack(np.zeros(0, dtype=np.uint64))


class PostingList:
    """MVCC posting list for one storage key."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self.base_ts: int = 0
        self.base_packed: packed.PackedUidList = _EMPTY_PACKED
        self.base_postings: dict[int, Posting] = {}   # only uids with value/facets
        self.layers: list[_Layer] = []                # sorted by commit_ts
        self.uncommitted: dict[int, _Layer] = {}      # start_ts -> pending layer

    # -- write path ---------------------------------------------------------

    def add_mutation(self, start_ts: int, p: Posting) -> None:
        """Buffer a posting under a transaction (reference AddMutation :292)."""
        with self._lock:
            layer = self.uncommitted.setdefault(start_ts, _Layer(0))
            if p.op == Op.DEL_ALL:
                layer.del_all = True
                layer.postings.clear()
            else:
                layer.postings[p.uid] = p

    def commit(self, start_ts: int, commit_ts: int) -> bool:
        """Promote a txn's postings to a committed layer (CommitMutation :423)."""
        with self._lock:
            layer = self.uncommitted.pop(start_ts, None)
            if layer is None:
                return False
            layer.commit_ts = commit_ts
            # insert sorted (commits arrive nearly ordered)
            i = len(self.layers)
            while i > 0 and self.layers[i - 1].commit_ts > commit_ts:
                i -= 1
            self.layers.insert(i, layer)
            return True

    def abort(self, start_ts: int) -> None:
        with self._lock:
            self.uncommitted.pop(start_ts, None)

    def has_uncommitted(self, start_ts: int | None = None) -> bool:
        with self._lock:
            return bool(self.uncommitted) if start_ts is None else start_ts in self.uncommitted

    # -- read path ----------------------------------------------------------

    def _fold(self, read_ts: int, own_start_ts: int | None = None):
        """Effective (uids set, postings map) at read_ts.

        Folds: packed base → committed layers with commit_ts <= read_ts →
        (optionally) the reader's own uncommitted layer. Returns
        (sorted uid numpy array, {uid: Posting}).
        """
        if read_ts < self.base_ts:
            # rollup discarded history below base_ts; serving this read would
            # silently return future state (reference gates with a min-readTs
            # watermark before snapshotting, posting/mvcc.go:105).
            raise ValueError(f"read at ts {read_ts} below rollup watermark {self.base_ts}")
        uids = native.unpack(self.base_packed).astype(np.int64)
        live: dict[int, Posting] = dict(self.base_postings)
        present = dict.fromkeys(uids.tolist(), True)

        def apply(layer: _Layer):
            nonlocal present
            if layer.del_all:
                present = {}
                live.clear()
            for uid, p in sorted(layer.postings.items()):
                if p.op == Op.DEL:
                    present.pop(uid, None)
                    live.pop(uid, None)
                else:
                    present[uid] = True
                    if p.value is not None or p.facets:
                        live[uid] = p
                    else:
                        live.pop(uid, None)

        for layer in self.layers:
            if layer.commit_ts > read_ts:
                break
            apply(layer)
        if own_start_ts is not None and own_start_ts in self.uncommitted:
            apply(self.uncommitted[own_start_ts])
        out = np.fromiter(present.keys(), dtype=np.int64, count=len(present))
        out.sort()
        return out, live

    def _base_only(self, read_ts: int, own_start_ts: int | None) -> bool:
        """True when the read is served by the packed base alone — the common
        shape after a bulk load or rollup. Lets readers skip the per-uid dict
        fold (50k lists x dict-of-20 costs seconds on snapshot builds)."""
        if read_ts < self.base_ts:
            raise ValueError(
                f"read at ts {read_ts} below rollup watermark {self.base_ts}")
        if own_start_ts is not None and own_start_ts in self.uncommitted:
            return False
        return not self.layers or self.layers[0].commit_ts > read_ts

    def uids(self, read_ts: int, after_uid: int = 0, own_start_ts: int | None = None) -> np.ndarray:
        if self._base_only(read_ts, own_start_ts):
            u = native.unpack(self.base_packed).astype(np.int64)
        else:
            u, _ = self._fold(read_ts, own_start_ts)
        if after_uid:
            u = u[u > after_uid]
        return u

    def postings(self, read_ts: int, own_start_ts: int | None = None) -> list[Posting]:
        if self._base_only(read_ts, own_start_ts):
            u = native.unpack(self.base_packed).astype(np.int64)
            live = self.base_postings
        else:
            u, live = self._fold(read_ts, own_start_ts)
        return [live.get(int(x), Posting(int(x))) for x in u]

    def value(self, read_ts: int, lang: str = "", own_start_ts: int | None = None) -> Val | None:
        """The value posting (reference Value/ValueForTag, posting/list.go).

        lang="" reads ONLY the untagged slot (reference postingForLangs: an
        untagged read returns ErrNoValue when only lang-tagged values exist);
        the any-language fallback applies only to the explicit "." tag
        (`name@.`), preferring the untagged value first."""
        if self._base_only(read_ts, own_start_ts):
            live = self.base_postings
        else:
            _, live = self._fold(read_ts, own_start_ts)
        if lang == ".":
            p = live.get(lang_uid(""))
            if p is not None and p.value is not None:
                return p.value
            for q in live.values():
                if q.value is not None:
                    return q.value
            return None
        p = live.get(lang_uid(lang))
        return p.value if p else None

    def live_map(self, read_ts: int, own_start_ts: int | None = None) -> dict[int, Posting]:
        """Only the value/facet-carrying postings (uid→Posting) at read_ts —
        snapshot builds scan this instead of materializing a Posting per uid."""
        if self._base_only(read_ts, own_start_ts):
            return self.base_postings
        _, live = self._fold(read_ts, own_start_ts)
        return live

    def value_for_slot(self, read_ts: int, slot: int,
                       own_start_ts: int | None = None) -> Val | None:
        """Exact slot read, no language fallback (index maintenance must not
        see a different language's value as 'the old value')."""
        if self._base_only(read_ts, own_start_ts):
            live = self.base_postings
        else:
            _, live = self._fold(read_ts, own_start_ts)
        p = live.get(slot)
        return p.value if p else None

    def all_values(self, read_ts: int, own_start_ts: int | None = None) -> list[Val]:
        """Every live value posting (list-valued scalars, @lang variants)."""
        if self._base_only(read_ts, own_start_ts):
            live = self.base_postings
        else:
            _, live = self._fold(read_ts, own_start_ts)
        return [p.value for p in live.values() if p.value is not None]

    def length(self, read_ts: int, after_uid: int = 0) -> int:
        return int(len(self.uids(read_ts, after_uid)))

    def is_empty(self, read_ts: int) -> bool:
        return self.length(read_ts) == 0

    # -- maintenance --------------------------------------------------------

    def rollup(self, upto_ts: int) -> None:
        """Fold committed layers <= upto_ts into the packed base (SyncIfDirty
        analog: re-pack uids, keep value/facet postings in the base map)."""
        with self._lock:
            if not any(l.commit_ts <= upto_ts for l in self.layers):
                # nothing to fold — keep the packed base untouched (bulk-built
                # stores would otherwise unpack+repack every list on checkpoint)
                self.base_ts = max(self.base_ts, upto_ts)
                return
            u, live = self._fold(upto_ts)
            keep = [l for l in self.layers if l.commit_ts > upto_ts]
            self.base_packed = native.pack(u.astype(np.uint64))
            self.base_postings = live
            self.layers = keep
            self.base_ts = upto_ts

    # rough per-Posting host cost (object header + dict slot + Val), used by
    # the memory manager's budget accounting (posting/lists.go AllottedMemory)
    _POSTING_COST = 200

    def approx_bytes(self) -> int:
        with self._lock:
            n = 256 + self.base_packed.nbytes
            n += self._POSTING_COST * len(self.base_postings)
            for layer in self.layers:
                n += 64 + self._POSTING_COST * len(layer.postings)
            for layer in self.uncommitted.values():
                n += 64 + self._POSTING_COST * len(layer.postings)
            return n

    def layer_count(self) -> int:
        with self._lock:
            return len(self.layers)

    def min_pending_start_ts(self) -> int | None:
        with self._lock:
            return min(self.uncommitted) if self.uncommitted else None
