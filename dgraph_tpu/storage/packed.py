"""Block-packed sorted-uid codec — the TPU descendant of SIMD-BP128.

Reference semantics: bp128/ — delta compression of sorted uint64 uid lists in
256-int blocks with per-block metadata {2 seed uint64s, byte offset}
(bp128/bp128.go:23,137-144), block-skipping seek for galloping intersection
(BPackIterator.Init/AfterUid, :219-340), generated SSE2 kernels for each bit
width (bp128/peachpy/*.py).

TPU redesign — NOT a translation:
- Block size is 128 (the VPU lane width) so one block decodes as one vector op.
- Per-block metadata is a struct-of-arrays (first uid, last uid, count, bit
  width, word offset) instead of interleaved bytes: on device these become
  gatherable int arrays; `last` gives block-skip seek (the AfterUid analog) as
  a vectorized binary search instead of a pointer walk.
- Deltas are packed little-endian into a flat uint32 word stream, each block
  word-aligned. Decode is branch-free for every width w<=32:
      pair = words[k] | words[k+1] << 32 ;  v = (pair >> s) & mask
  followed by an intra-block cumsum — shifts-by-vector + cumsum are native VPU
  ops, so ONE kernel handles all widths (the reference generates 33 unrolled
  asm kernels per direction; XLA's vectorizer makes that unnecessary).
- Blocks whose deltas need >32 bits use a word-aligned raw64 escape
  (width=64, two words per value).

The host codec here is vectorized numpy (pack/unpack plus pack_many/
unpack_many batched forms for whole-tablet work); `ops/packed_decode.py`
decodes the same format on device so packed lists can live in HBM.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

BLOCK = 128
_MASK32 = np.uint64(0xFFFFFFFF)


@dataclass
class PackedUidList:
    """Immutable packed sorted uid list (struct-of-arrays block metadata)."""

    count: int                 # total uids
    block_first: np.ndarray    # uint64[nb] first uid of block
    block_last: np.ndarray     # uint64[nb] last uid of block (seek metadata)
    block_count: np.ndarray    # int32[nb]  uids in block (<= BLOCK; only last partial)
    block_width: np.ndarray    # int32[nb]  bits per delta (0..32, or 64 = raw escape)
    block_off: np.ndarray      # int64[nb]  word offset of block's packed deltas
    words: np.ndarray          # uint32[W]  packed delta stream

    @property
    def nblocks(self) -> int:
        return len(self.block_first)

    @property
    def nbytes(self) -> int:
        return int(self.words.nbytes + self.block_first.nbytes + self.block_last.nbytes
                   + self.block_count.nbytes + self.block_width.nbytes + self.block_off.nbytes)


def _width_for(maxdelta: np.ndarray) -> np.ndarray:
    """Bits needed per block; 64 = raw escape for deltas >= 2**32."""
    w = np.zeros(maxdelta.shape, dtype=np.int32)
    nz = maxdelta > 0
    w[nz] = np.floor(np.log2(maxdelta[nz].astype(np.float64))).astype(np.int32) + 1
    # float64 log2 is exact enough below 2**48; verify and bump any edge cases
    bad = (maxdelta >> np.minimum(w, 63).astype(np.uint64)) > 0
    w[bad] += 1
    w[w > 32] = 64
    return w


def pack(uids) -> PackedUidList:
    """Pack a sorted, duplicate-free uid array."""
    uids = np.asarray(uids, dtype=np.uint64)
    n = len(uids)
    if n == 0:
        z64 = np.zeros(0, dtype=np.uint64)
        z32 = np.zeros(0, dtype=np.int32)
        return PackedUidList(0, z64, z64.copy(), z32, z32.copy(),
                             np.zeros(0, dtype=np.int64), np.zeros(0, dtype=np.uint32))
    nb = -(-n // BLOCK)
    padded = np.empty(nb * BLOCK, dtype=np.uint64)
    padded[:n] = uids
    padded[n:] = uids[-1]  # zero deltas in the tail of the last block
    blocks = padded.reshape(nb, BLOCK)

    deltas = np.zeros_like(blocks)
    deltas[:, 1:] = blocks[:, 1:] - blocks[:, :-1]
    block_first = blocks[:, 0].copy()
    counts = np.full(nb, BLOCK, dtype=np.int32)
    counts[-1] = n - (nb - 1) * BLOCK
    block_last = padded.reshape(nb, BLOCK)[np.arange(nb), counts - 1].copy()
    widths = _width_for(deltas.max(axis=1))

    words_per_block = np.where(widths == 64, 2 * BLOCK, -(-(BLOCK * widths) // 32)).astype(np.int64)
    offs = np.zeros(nb, dtype=np.int64)
    offs[1:] = np.cumsum(words_per_block)[:-1]
    total_words = int(words_per_block.sum())
    words = np.zeros(total_words + 1, dtype=np.uint32)  # +1 pad word for pair reads

    # raw64 escape blocks: word-aligned lo/hi pairs
    raw = widths == 64
    if raw.any():
        for b in np.nonzero(raw)[0]:
            d = deltas[b]
            o = offs[b]
            words[o : o + 2 * BLOCK : 2] = (d & _MASK32).astype(np.uint32)
            words[o + 1 : o + 1 + 2 * BLOCK : 2] = (d >> np.uint64(32)).astype(np.uint32)

    # bitpacked blocks, fully vectorized across all blocks at once
    bp = np.nonzero(~raw & (widths > 0))[0]
    if len(bp) > 0:
        w = widths[bp][:, None].astype(np.int64)                     # [B,1]
        bitpos = np.arange(BLOCK, dtype=np.int64)[None, :] * w       # [B,128]
        widx = offs[bp][:, None] + (bitpos >> 5)
        shift = (bitpos & 31).astype(np.uint64)
        v = deltas[bp]
        lo = ((v << shift) & _MASK32).astype(np.uint32)
        hi = (v >> (np.uint64(32) - shift)).astype(np.uint32)        # shift==0 → v>>32
        np.bitwise_or.at(words, widx.ravel(), lo.ravel())
        np.bitwise_or.at(words, (widx + 1).ravel(), hi.ravel())

    return PackedUidList(n, block_first, block_last, counts, widths, offs, words[:-1])


def pack_many(rows: list[np.ndarray]) -> list[PackedUidList]:
    """Pack many sorted uid arrays in one vectorized pass.

    Semantically identical to [pack(r) for r in rows] but amortizes numpy
    call overhead across rows — the bulk loader packs hundreds of thousands
    of small per-subject lists (reduce.go:36 packs per key too, but in Go a
    call is cheap; in numpy the per-call fixed cost dominates tiny lists).
    Metadata and word arrays of each result are zero-copy slices of shared
    buffers."""
    R = len(rows)
    lens = np.fromiter((len(r) for r in rows), dtype=np.int64, count=R)
    nbs = -(-lens // BLOCK)                       # blocks per row (0 for empty)
    nonempty = lens > 0
    if not nonempty.any():
        return [pack(np.zeros(0, dtype=np.uint64)) for _ in rows]
    concat = np.concatenate([np.asarray(r, dtype=np.uint64)
                             for r, ne in zip(rows, nonempty) if ne])
    row_start = np.zeros(R, dtype=np.int64)
    np.cumsum(lens[:-1], out=row_start[1:])
    row_block_start = np.zeros(R, dtype=np.int64)
    np.cumsum(nbs[:-1], out=row_block_start[1:])
    NB = int(nbs.sum())

    block_row = np.repeat(np.arange(R, dtype=np.int64), nbs)          # [NB]
    block_pos = np.arange(NB, dtype=np.int64) - row_block_start[block_row]
    lane = np.arange(BLOCK, dtype=np.int64)
    elem = (row_start[block_row, None] + block_pos[:, None] * BLOCK
            + lane[None, :])
    row_end = row_start[block_row] + lens[block_row]                  # [NB]
    blocks = concat[np.minimum(elem, (row_end - 1)[:, None])]         # pad=last

    deltas = np.zeros_like(blocks)
    deltas[:, 1:] = blocks[:, 1:] - blocks[:, :-1]
    block_first = np.ascontiguousarray(blocks[:, 0])
    counts = np.minimum(BLOCK, lens[block_row] - block_pos * BLOCK).astype(np.int32)
    block_last = blocks[np.arange(NB), counts - 1].copy()
    widths = _width_for(deltas.max(axis=1))

    words_per_block = np.where(widths == 64, 2 * BLOCK,
                               -(-(BLOCK * widths) // 32)).astype(np.int64)
    offs = np.zeros(NB, dtype=np.int64)
    offs[1:] = np.cumsum(words_per_block)[:-1]
    total_words = int(words_per_block.sum())
    words = np.zeros(total_words + 1, dtype=np.uint32)

    raw = widths == 64
    if raw.any():
        for b in np.nonzero(raw)[0]:
            d, o = deltas[b], offs[b]
            words[o : o + 2 * BLOCK : 2] = (d & _MASK32).astype(np.uint32)
            words[o + 1 : o + 1 + 2 * BLOCK : 2] = (d >> np.uint64(32)).astype(np.uint32)
    bp = np.nonzero(~raw & (widths > 0))[0]
    if len(bp) > 0:
        w = widths[bp][:, None].astype(np.int64)
        bitpos = lane[None, :] * w
        widx = offs[bp][:, None] + (bitpos >> 5)
        shift = (bitpos & 31).astype(np.uint64)
        v = deltas[bp]
        lo = ((v << shift) & _MASK32).astype(np.uint32)
        hi = (v >> (np.uint64(32) - shift)).astype(np.uint32)
        np.bitwise_or.at(words, widx.ravel(), lo.ravel())
        np.bitwise_or.at(words, (widx + 1).ravel(), hi.ravel())

    out: list[PackedUidList] = []
    word_ends = offs + words_per_block
    for r in range(R):
        n = int(lens[r])
        if n == 0:
            out.append(pack(np.zeros(0, dtype=np.uint64)))
            continue
        b0 = int(row_block_start[r])
        b1 = b0 + int(nbs[r])
        wbase = int(offs[b0])
        wend = int(word_ends[b1 - 1])
        out.append(PackedUidList(
            n, block_first[b0:b1], block_last[b0:b1], counts[b0:b1],
            widths[b0:b1], offs[b0:b1] - wbase, words[wbase:wend]))
    return out


def unpack(pl: PackedUidList) -> np.ndarray:
    """Decode every uid (numpy mirror of the device kernel in ops/packed_decode.py)."""
    nb = pl.nblocks
    if nb == 0:
        return np.zeros(0, dtype=np.uint64)
    words = np.concatenate([pl.words, np.zeros(2, dtype=np.uint32)])
    w = pl.block_width[:, None].astype(np.int64)
    raw = pl.block_width == 64
    bitpos = np.arange(BLOCK, dtype=np.int64)[None, :] * np.where(w == 64, 0, w)
    widx = pl.block_off[:, None] + (bitpos >> 5)
    shift = (bitpos & 31).astype(np.uint64)
    pair = words[widx].astype(np.uint64) | (words[widx + 1].astype(np.uint64) << np.uint64(32))
    mask = np.where(w >= 32, _MASK32, (np.uint64(1) << w.astype(np.uint64)) - np.uint64(1))
    deltas = (pair >> shift) & mask
    deltas = np.where(w == 0, np.uint64(0), deltas)
    if raw.any():
        ro = pl.block_off[raw][:, None] + 2 * np.arange(BLOCK, dtype=np.int64)[None, :]
        deltas[raw] = words[ro].astype(np.uint64) | (words[ro + 1].astype(np.uint64) << np.uint64(32))
    deltas[:, 0] = 0
    out = pl.block_first[:, None] + np.cumsum(deltas, axis=1)
    lane = np.tile(np.arange(BLOCK), nb)
    keep = lane < np.repeat(pl.block_count, BLOCK)
    return out.ravel()[keep]


def unpack_many(pls: list[PackedUidList]) -> list[np.ndarray]:
    """Decode many packed lists in one vectorized pass (mirror of pack_many:
    snapshot builds decode every list of a tablet; per-call numpy overhead
    dominates small lists)."""
    R = len(pls)
    nbs = np.fromiter((p.nblocks for p in pls), dtype=np.int64, count=R)
    NB = int(nbs.sum())
    if NB == 0:
        return [np.zeros(0, dtype=np.uint64) for _ in pls]
    nz = [p for p in pls if p.nblocks]
    word_lens = np.fromiter((len(p.words) for p in nz), dtype=np.int64,
                            count=len(nz))
    word_base = np.zeros(len(nz), dtype=np.int64)
    np.cumsum(word_lens[:-1], out=word_base[1:])
    words = np.concatenate([p.words for p in nz] + [np.zeros(2, np.uint32)])
    block_first = np.concatenate([p.block_first for p in nz])
    block_count = np.concatenate([p.block_count for p in nz])
    block_width = np.concatenate([p.block_width for p in nz])
    block_off = np.concatenate(
        [p.block_off + b for p, b in zip(nz, word_base)])

    w = block_width[:, None].astype(np.int64)
    raw = block_width == 64
    bitpos = np.arange(BLOCK, dtype=np.int64)[None, :] * np.where(w == 64, 0, w)
    widx = block_off[:, None] + (bitpos >> 5)
    shift = (bitpos & 31).astype(np.uint64)
    pair = words[widx].astype(np.uint64) | (words[widx + 1].astype(np.uint64) << np.uint64(32))
    mask = np.where(w >= 32, _MASK32, (np.uint64(1) << w.astype(np.uint64)) - np.uint64(1))
    deltas = (pair >> shift) & mask
    deltas = np.where(w == 0, np.uint64(0), deltas)
    if raw.any():
        ro = block_off[raw][:, None] + 2 * np.arange(BLOCK, dtype=np.int64)[None, :]
        deltas[raw] = words[ro].astype(np.uint64) | (words[ro + 1].astype(np.uint64) << np.uint64(32))
    deltas[:, 0] = 0
    all_vals = block_first[:, None] + np.cumsum(deltas, axis=1)   # [NB, 128]

    out: list[np.ndarray] = []
    b0 = 0
    for p, nb in zip(pls, nbs):
        if nb == 0:
            out.append(np.zeros(0, dtype=np.uint64))
            continue
        rows = all_vals[b0 : b0 + nb]
        cnts = block_count[b0 : b0 + nb]
        lane = np.tile(np.arange(BLOCK), int(nb))
        keep = lane < np.repeat(cnts, BLOCK)
        out.append(rows.ravel()[keep])
        b0 += int(nb)
    return out


def seek_block(pl: PackedUidList, after_uid: int) -> int:
    """First block that can contain a uid > after_uid (AfterUid seek,
    reference bp128/bp128.go:276). Returns pl.nblocks when exhausted."""
    return int(np.searchsorted(pl.block_last, np.uint64(after_uid), side="right"))
