"""Block-packed sorted-uid codec — the TPU descendant of SIMD-BP128.

Reference semantics: bp128/ — delta compression of sorted uint64 uid lists in
256-int blocks with per-block metadata {2 seed uint64s, byte offset}
(bp128/bp128.go:23,137-144), block-skipping seek for galloping intersection
(BPackIterator.Init/AfterUid, :219-340), generated SSE2 kernels for each bit
width (bp128/peachpy/*.py).

TPU redesign — NOT a translation:
- Block size is 128 (the VPU lane width) so one block decodes as one vector op.
- Per-block metadata is a struct-of-arrays (first uid, last uid, count, bit
  width, word offset) instead of interleaved bytes: on device these become
  gatherable int arrays; `last` gives block-skip seek (the AfterUid analog) as
  a vectorized binary search instead of a pointer walk.
- Deltas are packed little-endian into a flat uint32 word stream, each block
  word-aligned. Decode is branch-free for every width w<=32:
      pair = words[k] | words[k+1] << 32 ;  v = (pair >> s) & mask
  followed by an intra-block cumsum — shifts-by-vector + cumsum are native VPU
  ops, so ONE kernel handles all widths (the reference generates 33 unrolled
  asm kernels per direction; XLA's vectorizer makes that unnecessary).
- Blocks whose deltas need >32 bits use a word-aligned raw64 escape
  (width=64, two words per value).

The host codec here is vectorized numpy; `native/` provides the same format in
C++ for ingest (see storage/native.py); `ops/packed_decode.py` decodes on
device so packed lists can live in HBM.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

BLOCK = 128
_MASK32 = np.uint64(0xFFFFFFFF)


@dataclass
class PackedUidList:
    """Immutable packed sorted uid list (struct-of-arrays block metadata)."""

    count: int                 # total uids
    block_first: np.ndarray    # uint64[nb] first uid of block
    block_last: np.ndarray     # uint64[nb] last uid of block (seek metadata)
    block_count: np.ndarray    # int32[nb]  uids in block (<= BLOCK; only last partial)
    block_width: np.ndarray    # int32[nb]  bits per delta (0..32, or 64 = raw escape)
    block_off: np.ndarray      # int64[nb]  word offset of block's packed deltas
    words: np.ndarray          # uint32[W]  packed delta stream

    @property
    def nblocks(self) -> int:
        return len(self.block_first)

    @property
    def nbytes(self) -> int:
        return int(self.words.nbytes + self.block_first.nbytes + self.block_last.nbytes
                   + self.block_count.nbytes + self.block_width.nbytes + self.block_off.nbytes)


def _width_for(maxdelta: np.ndarray) -> np.ndarray:
    """Bits needed per block; 64 = raw escape for deltas >= 2**32."""
    w = np.zeros(maxdelta.shape, dtype=np.int32)
    nz = maxdelta > 0
    w[nz] = np.floor(np.log2(maxdelta[nz].astype(np.float64))).astype(np.int32) + 1
    # float64 log2 is exact enough below 2**48; verify and bump any edge cases
    bad = (maxdelta >> np.minimum(w, 63).astype(np.uint64)) > 0
    w[bad] += 1
    w[w > 32] = 64
    return w


def pack(uids) -> PackedUidList:
    """Pack a sorted, duplicate-free uid array."""
    uids = np.asarray(uids, dtype=np.uint64)
    n = len(uids)
    if n == 0:
        z64 = np.zeros(0, dtype=np.uint64)
        z32 = np.zeros(0, dtype=np.int32)
        return PackedUidList(0, z64, z64.copy(), z32, z32.copy(),
                             np.zeros(0, dtype=np.int64), np.zeros(0, dtype=np.uint32))
    nb = -(-n // BLOCK)
    padded = np.empty(nb * BLOCK, dtype=np.uint64)
    padded[:n] = uids
    padded[n:] = uids[-1]  # zero deltas in the tail of the last block
    blocks = padded.reshape(nb, BLOCK)

    deltas = np.zeros_like(blocks)
    deltas[:, 1:] = blocks[:, 1:] - blocks[:, :-1]
    block_first = blocks[:, 0].copy()
    counts = np.full(nb, BLOCK, dtype=np.int32)
    counts[-1] = n - (nb - 1) * BLOCK
    block_last = padded.reshape(nb, BLOCK)[np.arange(nb), counts - 1].copy()
    widths = _width_for(deltas.max(axis=1))

    words_per_block = np.where(widths == 64, 2 * BLOCK, -(-(BLOCK * widths) // 32)).astype(np.int64)
    offs = np.zeros(nb, dtype=np.int64)
    offs[1:] = np.cumsum(words_per_block)[:-1]
    total_words = int(words_per_block.sum())
    words = np.zeros(total_words + 1, dtype=np.uint32)  # +1 pad word for pair reads

    # raw64 escape blocks: word-aligned lo/hi pairs
    raw = widths == 64
    if raw.any():
        for b in np.nonzero(raw)[0]:
            d = deltas[b]
            o = offs[b]
            words[o : o + 2 * BLOCK : 2] = (d & _MASK32).astype(np.uint32)
            words[o + 1 : o + 1 + 2 * BLOCK : 2] = (d >> np.uint64(32)).astype(np.uint32)

    # bitpacked blocks, fully vectorized across all blocks at once
    bp = np.nonzero(~raw & (widths > 0))[0]
    if len(bp) > 0:
        w = widths[bp][:, None].astype(np.int64)                     # [B,1]
        bitpos = np.arange(BLOCK, dtype=np.int64)[None, :] * w       # [B,128]
        widx = offs[bp][:, None] + (bitpos >> 5)
        shift = (bitpos & 31).astype(np.uint64)
        v = deltas[bp]
        lo = ((v << shift) & _MASK32).astype(np.uint32)
        hi = (v >> (np.uint64(32) - shift)).astype(np.uint32)        # shift==0 → v>>32
        np.bitwise_or.at(words, widx.ravel(), lo.ravel())
        np.bitwise_or.at(words, (widx + 1).ravel(), hi.ravel())

    return PackedUidList(n, block_first, block_last, counts, widths, offs, words[:-1])


def unpack(pl: PackedUidList) -> np.ndarray:
    """Decode every uid (numpy mirror of the device kernel in ops/packed_decode.py)."""
    nb = pl.nblocks
    if nb == 0:
        return np.zeros(0, dtype=np.uint64)
    words = np.concatenate([pl.words, np.zeros(2, dtype=np.uint32)])
    w = pl.block_width[:, None].astype(np.int64)
    raw = pl.block_width == 64
    bitpos = np.arange(BLOCK, dtype=np.int64)[None, :] * np.where(w == 64, 0, w)
    widx = pl.block_off[:, None] + (bitpos >> 5)
    shift = (bitpos & 31).astype(np.uint64)
    pair = words[widx].astype(np.uint64) | (words[widx + 1].astype(np.uint64) << np.uint64(32))
    mask = np.where(w >= 32, _MASK32, (np.uint64(1) << w.astype(np.uint64)) - np.uint64(1))
    deltas = (pair >> shift) & mask
    deltas = np.where(w == 0, np.uint64(0), deltas)
    if raw.any():
        ro = pl.block_off[raw][:, None] + 2 * np.arange(BLOCK, dtype=np.int64)[None, :]
        deltas[raw] = words[ro].astype(np.uint64) | (words[ro + 1].astype(np.uint64) << np.uint64(32))
    deltas[:, 0] = 0
    out = pl.block_first[:, None] + np.cumsum(deltas, axis=1)
    lane = np.tile(np.arange(BLOCK), nb)
    keep = lane < np.repeat(pl.block_count, BLOCK)
    return out.ravel()[keep]


def seek_block(pl: PackedUidList, after_uid: int) -> int:
    """First block that can contain a uid > after_uid (AfterUid seek,
    reference bp128/bp128.go:276). Returns pl.nblocks when exhausted."""
    return int(np.searchsorted(pl.block_last, np.uint64(after_uid), side="right"))
