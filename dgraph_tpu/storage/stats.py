"""Live per-predicate cardinality statistics — the planner's cost model feed.

Reference context: the reference has no cost-based planner — query.go
executes in parse order — but its badger levels DO keep per-table key
counts, and classic systems (Selinger et al.; Leis et al., "How Good Are
Query Optimizers, Really?") show cheap cardinality stats capture most of
the gap between good and bad evaluation orders. On a predicate-sharded
graph the quantities a planner needs are already sitting in the fold
outputs (storage/csr_build.PredData): the CSR host mirrors give subject
and edge counts and the exact degree distribution; every token index
gives exact per-term frequencies. This module snapshots them as a small
`PredStats` per predicate:

  * subject / edge counts and a log2 degree histogram per CSR (forward
    and reverse),
  * value-subject count and the numeric/other value-type mix,
  * per-tokenizer term counts, total postings, and a lazy top-K
    term-frequency sketch (EXPLAIN readout; point probes use the exact
    index row lengths, see `term_freq` / `range_count`).

Freshness contract: stats are cached ON the PredData / PredCSR objects
they describe. The snapshot assembler replaces those objects on any
visible change (fold or O(Δ) overlay stamp, storage/delta.py), so stats
can never describe dead data. An overlay stamp costs O(Δ): the stamped
`OverlayCSR` keeps base identity, so the base's cached stats are adjusted
by exactly the touched subjects' old/new degrees instead of recounting
the tablet — the same delta journal that drives overlay stamping drives
stats maintenance. Compaction folds a fresh base and stats recompute from
it, reconciling the deltas exactly (tests/test_stats.py asserts both).

Stats only ever steer ORDER (query/planner.py); stale or approximate
stats can cost time, never correctness.
"""

from __future__ import annotations

import bisect
from dataclasses import dataclass, field

import numpy as np

from dgraph_tpu.ops import uidset as us

HIST_BUCKETS = 32          # log2 degree buckets (degree < 2^31 by uid space)
_STATS_ATTR = "_dgt_stats"   # cache slot on PredData / PredCSR objects


@dataclass
class CSRStats:
    """Counts for one adjacency (forward or reverse CSR)."""

    n_subjects: int = 0
    n_edges: int = 0
    hist: np.ndarray = field(
        default_factory=lambda: np.zeros(HIST_BUCKETS, np.int64))
    via_delta: bool = False    # True = adjusted O(Δ) from a base's stats

    @property
    def avg_degree(self) -> float:
        return self.n_edges / self.n_subjects if self.n_subjects else 0.0


def _hist_of(deg: np.ndarray) -> np.ndarray:
    """log2-bucket histogram of a degree vector (degree >= 1)."""
    if len(deg) == 0:
        return np.zeros(HIST_BUCKETS, np.int64)
    b = np.clip(np.log2(np.maximum(deg, 1)).astype(np.int64), 0,
                HIST_BUCKETS - 1)
    return np.bincount(b, minlength=HIST_BUCKETS).astype(np.int64)


def csr_stats(csr, metrics=None) -> CSRStats:
    """Stats for a PredCSR-like, cached per object. An OverlayCSR adjusts
    its UNCHANGED base's cached stats by the delta's touched subjects —
    O(Δ), never a recount of the merged tablet."""
    if csr is None:
        return CSRStats()
    cached = getattr(csr, _STATS_ATTR, None)
    if cached is not None:
        return cached
    from dgraph_tpu.storage.delta import OverlayCSR

    if isinstance(csr, OverlayCSR):
        base_st = csr_stats(csr.base, metrics)
        bs, bip, _ = csr._base_host()
        bs = np.asarray(bs, dtype=np.int64)
        bip = np.asarray(bip, dtype=np.int64)
        if len(bs) == 0:       # base-less overlay (tablet born from deltas)
            inb = np.zeros(len(csr.delta.subs), dtype=bool)
            old_deg = np.zeros(len(csr.delta.subs), dtype=np.int64)
        else:
            rb = us.host_rank_of(bs, csr.delta.subs, -1)
            inb = rb >= 0
            rc = np.clip(rb, 0, len(bip) - 2)
            old_deg = np.where(inb, bip[rc + 1] - bip[rc], 0)
        new_deg = csr.delta.lens
        hist = base_st.hist.copy()
        if inb.any():
            hist -= _hist_of(old_deg[inb])
        add = new_deg > 0
        if add.any():
            hist += _hist_of(new_deg[add])
        st = CSRStats(
            n_subjects=base_st.n_subjects - int(inb.sum()) + int(add.sum()),
            n_edges=base_st.n_edges - int(old_deg.sum())
            + int(new_deg.sum()),
            hist=hist, via_delta=True)
        if metrics is not None:
            metrics.counter("dgraph_stats_delta_updates_total").inc()
    else:
        if getattr(csr, "is_dist", False):
            # mesh-sharded tablet: device metadata only, no host recount
            st = CSRStats(n_subjects=int(csr.num_subjects),
                          n_edges=int(csr.num_edges))
        else:
            _, indptr, _ = csr.host_arrays()
            indptr = np.asarray(indptr, dtype=np.int64)
            deg = indptr[1:] - indptr[:-1]
            st = CSRStats(n_subjects=len(deg), n_edges=int(deg.sum()),
                          hist=_hist_of(deg))
        if metrics is not None:
            metrics.counter("dgraph_stats_builds_total").inc()
    try:
        setattr(csr, _STATS_ATTR, st)
    except AttributeError:     # frozen duck-type: recompute per call
        pass
    return st


@dataclass
class PredStats:
    """One predicate's planner-facing statistics at a snapshot."""

    attr: str
    type_name: str
    fwd: CSRStats
    rev: CSRStats
    value_count: int = 0
    numeric_values: int = 0    # value-type mix: numeric vs other
    lang_values: int = 0
    index_terms: dict[str, int] = field(default_factory=dict)
    index_postings: dict[str, int] = field(default_factory=dict)
    # @index(vector) predicates: embedding row count + dimensionality
    # (deliberately OUTSIDE index_terms/index_postings — the vector index
    # is not a TokenIndex and must never trip the term-sketch paths)
    vector_rows: int = 0
    vector_dim: int = 0

    @property
    def has_card(self) -> int:
        """Upper-bound cardinality of has(attr): edge subjects + value
        subjects (the exact quantity PredData.has_subjects unions)."""
        return self.fwd.n_subjects + self.value_count

    @property
    def avg_degree(self) -> float:
        return self.fwd.avg_degree

    def to_dict(self) -> dict:
        return {
            "attr": self.attr, "type": self.type_name,
            "subjects": self.fwd.n_subjects, "edges": self.fwd.n_edges,
            "avg_degree": round(self.avg_degree, 2),
            "rev_subjects": self.rev.n_subjects,
            "rev_edges": self.rev.n_edges,
            "values": self.value_count,
            "value_mix": {"numeric": self.numeric_values,
                          "other": self.value_count - self.numeric_values,
                          "lang": self.lang_values},
            "degree_hist": {f"2^{i}": int(n)
                            for i, n in enumerate(self.fwd.hist) if n},
            "index_terms": dict(self.index_terms),
            "index_postings": dict(self.index_postings),
            "via_delta": self.fwd.via_delta,
            **({"vector": {"rows": self.vector_rows,
                           "dim": self.vector_dim}}
               if self.vector_rows else {}),
        }


def pred_stats(pd, metrics=None) -> PredStats:
    """PredStats for one PredData, cached per object. The assembler
    replaces PredData on any visible change (and the CSR sub-stats ride
    the delta path when the change was an overlay stamp), so a cache hit
    is always current."""
    cached = getattr(pd, _STATS_ATTR, None)
    if cached is not None:
        return cached
    vs = pd.value_subjects_host
    nv = pd.num_values_host
    st = PredStats(
        attr=pd.attr,
        type_name=pd.type_id.name,
        fwd=csr_stats(pd.csr, metrics),
        rev=csr_stats(pd.rev_csr, metrics),
        value_count=0 if vs is None else len(vs),
        numeric_values=0 if nv is None
        else int(np.count_nonzero(~np.isnan(nv))),
        lang_values=len(pd.lang_values),
        index_terms={name: len(ti.terms)
                     for name, ti in pd.indexes.items()},
        index_postings={
            name: int(np.asarray(ti.host_arrays()[0])[-1])
            if len(ti.terms) else 0
            for name, ti in pd.indexes.items()},
        vector_rows=0 if pd.vecindex is None else int(pd.vecindex.n),
        vector_dim=0 if pd.vecindex is None else int(pd.vecindex.dim),
    )
    pd.__dict__[_STATS_ATTR] = st
    return st


# ---------------------------------------------------------------------------
# exact index probes (the planner's point estimates)
# ---------------------------------------------------------------------------

def term_freq(ti, term: bytes) -> int:
    """Exact uid count of one token row (0 = absent). O(log T)."""
    r = ti.term_row(term)
    if r < 0:
        return 0
    indptr = np.asarray(ti.host_arrays()[0], dtype=np.int64)
    return int(indptr[r + 1] - indptr[r])


def range_count(ti, op: str, token: bytes) -> int:
    """Exact candidate count of an inequality over a SORTABLE tokenizer:
    the postings between the range's bucket bounds (worker/tokens.go:124
    getInequalityTokens, counted instead of walked). O(log T)."""
    indptr = np.asarray(ti.host_arrays()[0], dtype=np.int64)
    i = bisect.bisect_left(ti.terms, token)
    if op == "eq":
        lo, hi = i, i + 1 if (i < len(ti.terms) and ti.terms[i] == token) \
            else i
    elif op in ("lt", "le"):
        lo = 0
        hi = (i if op == "lt" and i < len(ti.terms)
              and ti.terms[i] == token
              else bisect.bisect_right(ti.terms, token))
    elif op in ("gt", "ge"):
        hi = len(ti.terms)
        lo = i if op == "ge" else bisect.bisect_right(ti.terms, token)
    else:
        return 0
    lo = min(lo, len(ti.terms))
    hi = min(hi, len(ti.terms))
    if hi <= lo:
        return 0
    return int(indptr[hi] - indptr[lo])


def topk_terms(ti, k: int = 8) -> list[tuple[str, int]]:
    """Top-K most frequent terms of one token index (EXPLAIN / ops
    readout), cached per index object. Vectorized argpartition over the
    row-length column."""
    cache = getattr(ti, "_dgt_topk", None)
    if cache is not None and cache[0] >= k:
        return cache[1][:k]
    indptr = np.asarray(ti.host_arrays()[0], dtype=np.int64)
    lens = indptr[1:] - indptr[:-1]
    if len(lens) == 0:
        out: list[tuple[str, int]] = []
    else:
        kk = min(k, len(lens))
        idx = np.argpartition(lens, -kk)[-kk:]
        idx = idx[np.argsort(-lens[idx], kind="stable")]
        out = [(ti.terms[int(i)].decode("utf-8", "replace"),
                int(lens[int(i)])) for i in idx]
    try:
        ti._dgt_topk = (k, out)
    except AttributeError:
        pass
    return out


def snapshot_stats(snap, metrics=None, top_k: int = 0) -> dict:
    """Whole-snapshot stats readout ({attr: PredStats dict}) — the
    /debug/metrics "stats" section and the EXPLAIN header. Lazy
    snapshots report FOLDED tablets only: a debug scrape must never
    trigger the folds the lazy cold path exists to defer."""
    out = {}
    items = getattr(snap.preds, "folded_items", snap.preds.items)()
    for attr, pd in sorted(items):
        d = pred_stats(pd, metrics).to_dict()
        if top_k:
            d["top_terms"] = {name: topk_terms(ti, top_k)
                              for name, ti in pd.indexes.items()}
        out[attr] = d
    return out
