"""ctypes binding for the native codec (native/codec.cc).

Builds libdgt.so on first import when missing (g++ one-liner — the image has
no pybind11, and a flat C ABI keeps the binding dependency-free). Every entry
degrades to the numpy codec when the toolchain or library is unavailable:
`available()` gates use, and storage/packed.py stays the source of truth for
the wire format (the native codec is bit-identical and tested against it).
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_SO = os.path.join(_DIR, "libdgt.so")

_lib = None
_tried = False


def _build() -> bool:
    src = os.path.join(_DIR, "codec.cc")
    if not os.path.exists(src):
        return False
    # compile to a temp path and rename into place: concurrent first-use
    # builders (parallel test workers, leader+follower on one host) must not
    # interleave writes into one .so. -mtune (not -march): the .so may travel
    # to an older CPU via a baked image, where -march=native would SIGILL.
    tmp = f"{_SO}.{os.getpid()}.tmp"
    try:
        subprocess.run(
            ["g++", "-O3", "-mtune=native", "-fPIC", "-shared", "-std=c++17",
             "-o", tmp, src],
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return True
    except (OSError, subprocess.SubprocessError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _load():
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    src = os.path.join(_DIR, "codec.cc")
    if not os.path.exists(_SO) or (
            os.path.exists(src)
            and os.path.getmtime(_SO) < os.path.getmtime(src)):
        if not _build():
            return None
    try:
        lib = ctypes.CDLL(_SO)
    except OSError:
        # stale/torn .so from an interrupted build: rebuild once
        if not _build():
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
    i64, u64p = ctypes.c_int64, np.ctypeslib.ndpointer(np.uint64, flags="C")
    u32p = np.ctypeslib.ndpointer(np.uint32, flags="C")
    i32p = np.ctypeslib.ndpointer(np.int32, flags="C")
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C")
    lib.dgt_pack.restype = i64
    lib.dgt_pack.argtypes = [u64p, i64, u64p, u64p, i32p, i32p, i64p, u32p]
    lib.dgt_unpack.restype = i64
    lib.dgt_unpack.argtypes = [u64p, i32p, i32p, i64p, u32p, i64, u64p]
    lib.dgt_pack_many.restype = i64
    lib.dgt_pack_many.argtypes = [u64p, i64p, i64p, i64, u64p, u64p, i32p,
                                  i32p, i64p, u32p, i64p]
    lib.dgt_unpack_many.restype = i64
    lib.dgt_unpack_many.argtypes = [u64p, i32p, i32p, i64p, u32p, i64p, i64p,
                                    i64, u64p]
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def pack(uids: np.ndarray):
    """Native pack; same result object as packed.pack. uids must be a sorted
    C-contiguous uint64 array."""
    from dgraph_tpu.storage import packed

    lib = _load()
    n = len(uids)
    if lib is None or n == 0:
        return packed.pack(uids)
    uids = np.ascontiguousarray(uids, dtype=np.uint64)
    nb = -(-n // packed.BLOCK)
    bfirst = np.empty(nb, np.uint64)
    blast = np.empty(nb, np.uint64)
    bcount = np.empty(nb, np.int32)
    bwidth = np.empty(nb, np.int32)
    boff = np.empty(nb, np.int64)
    words = np.empty(nb * 2 * packed.BLOCK, np.uint32)
    total = lib.dgt_pack(uids, n, bfirst, blast, bcount, bwidth, boff, words)
    return packed.PackedUidList(n, bfirst, blast, bcount, bwidth, boff,
                                words[:total].copy())


def unpack(pl) -> np.ndarray:
    """Native unpack; bit-identical to packed.unpack."""
    from dgraph_tpu.storage import packed

    lib = _load()
    if lib is None or pl.nblocks == 0:
        return packed.unpack(pl)
    words = np.empty(len(pl.words) + 2, np.uint32)   # decode pair-read pad
    words[: len(pl.words)] = pl.words
    words[len(pl.words):] = 0
    out = np.empty(pl.count, np.uint64)
    k = lib.dgt_unpack(
        np.ascontiguousarray(pl.block_first, np.uint64),
        np.ascontiguousarray(pl.block_count, np.int32),
        np.ascontiguousarray(pl.block_width, np.int32),
        np.ascontiguousarray(pl.block_off, np.int64),
        words, pl.nblocks, out)
    assert k == pl.count
    return out


def unpack_many_flat(pls) -> tuple[np.ndarray, np.ndarray]:
    """Batched unpack WITHOUT per-row slicing: (flat uint64 uids, int64
    per-row counts). The snapshot fold consumes rows as spans of the flat
    array — materializing 100k+ tiny arrays is the 10M-scale fold cliff."""
    from dgraph_tpu.storage import packed

    R = len(pls)
    counts = np.fromiter((p.count for p in pls), np.int64, count=R)
    if R == 0:
        return np.zeros(0, np.uint64), counts
    lib = _load()
    if lib is None:
        rows = packed.unpack_many(pls)
        return (np.concatenate(rows) if rows else np.zeros(0, np.uint64),
                counts)
    nbs = np.fromiter((p.nblocks for p in pls), dtype=np.int64, count=R)
    if int(nbs.sum()) == 0:
        return np.zeros(0, np.uint64), counts
    nz = [p for p in pls if p.nblocks]
    word_lens = np.fromiter((len(p.words) for p in nz), np.int64,
                            count=len(nz))
    word_base_nz = np.zeros(len(nz), np.int64)
    np.cumsum(word_lens[:-1], out=word_base_nz[1:])
    words = np.empty(int(word_lens.sum()) + 2, np.uint32)
    for p, b in zip(nz, word_base_nz):
        words[int(b): int(b) + len(p.words)] = p.words
    words[-2:] = 0
    row_word_start = np.zeros(R, np.int64)
    row_word_start[nbs > 0] = word_base_nz
    bfirst = np.concatenate([p.block_first for p in nz]).astype(
        np.uint64, copy=False)
    bcount = np.concatenate([p.block_count for p in nz]).astype(
        np.int32, copy=False)
    bwidth = np.concatenate([p.block_width for p in nz]).astype(
        np.int32, copy=False)
    boff = np.concatenate([p.block_off for p in nz]).astype(
        np.int64, copy=False)
    out = np.empty(int(counts.sum()), np.uint64)
    k = lib.dgt_unpack_many(
        np.ascontiguousarray(bfirst), np.ascontiguousarray(bcount),
        np.ascontiguousarray(bwidth), np.ascontiguousarray(boff),
        words, nbs, row_word_start, R, out)
    assert k == len(out)
    return out, counts


def unpack_columns(tp, total: int) -> np.ndarray | None:
    """Decode a whole TabletPacked in ONE native call (zero per-list
    marshalling — the cold-open fold hot path). None when the native
    library is unavailable (caller falls back to per-list decode)."""
    lib = _load()
    if lib is None:
        return None
    words = np.empty(len(tp.words) + 2, np.uint32)   # decode pair-read pad
    words[: len(tp.words)] = tp.words
    words[-2:] = 0
    out = np.empty(total, np.uint64)
    k = lib.dgt_unpack_many(
        np.ascontiguousarray(tp.bfirst, np.uint64),
        np.ascontiguousarray(tp.bcount, np.int32),
        np.ascontiguousarray(tp.bwidth, np.int32),
        np.ascontiguousarray(tp.boff, np.int64),
        words, np.ascontiguousarray(tp.nbs, np.int64),
        np.ascontiguousarray(tp.row_word_start, np.int64), tp.n, out)
    assert k == total
    return out


def unpack_many(pls) -> list[np.ndarray]:
    """Native batched unpack; same per-row arrays as packed.unpack_many."""
    from dgraph_tpu.storage import packed

    lib = _load()
    R = len(pls)
    if lib is None or R == 0:
        return packed.unpack_many(pls)
    nbs = np.fromiter((p.nblocks for p in pls), dtype=np.int64, count=R)
    NB = int(nbs.sum())
    if NB == 0:
        return [np.zeros(0, np.uint64) for _ in pls]
    nz = [p for p in pls if p.nblocks]
    word_lens = np.fromiter((len(p.words) for p in nz), np.int64,
                            count=len(nz))
    word_base_nz = np.zeros(len(nz), np.int64)
    np.cumsum(word_lens[:-1], out=word_base_nz[1:])
    words = np.empty(int(word_lens.sum()) + 2, np.uint32)
    for p, b in zip(nz, word_base_nz):
        words[int(b): int(b) + len(p.words)] = p.words
    words[-2:] = 0
    row_word_start = np.zeros(R, np.int64)
    row_word_start[nbs > 0] = word_base_nz
    bfirst = np.concatenate([p.block_first for p in nz]).astype(
        np.uint64, copy=False)
    bcount = np.concatenate([p.block_count for p in nz]).astype(
        np.int32, copy=False)
    bwidth = np.concatenate([p.block_width for p in nz]).astype(
        np.int32, copy=False)
    boff = np.concatenate([p.block_off for p in nz]).astype(
        np.int64, copy=False)
    counts = np.fromiter((p.count for p in pls), np.int64, count=R)
    out = np.empty(int(counts.sum()), np.uint64)
    k = lib.dgt_unpack_many(
        np.ascontiguousarray(bfirst), np.ascontiguousarray(bcount),
        np.ascontiguousarray(bwidth), np.ascontiguousarray(boff),
        words, nbs, row_word_start, R, out)
    assert k == len(out)
    ends = np.cumsum(counts)
    return [out[e - c: e] for c, e in zip(counts, ends)]


def pack_many(rows: list[np.ndarray]):
    """Native batched pack; same per-row results as packed.pack_many."""
    from dgraph_tpu.storage import packed

    lib = _load()
    R = len(rows)
    if lib is None or R == 0:
        return packed.pack_many(rows)
    lens = np.fromiter((len(r) for r in rows), dtype=np.int64, count=R)
    if not (lens > 0).any():
        return packed.pack_many(rows)
    nbs = -(-lens // packed.BLOCK)
    NB = int(nbs.sum())
    concat = np.concatenate(
        [np.ascontiguousarray(r, np.uint64) for r in rows if len(r)])
    row_block_start = np.zeros(R, np.int64)
    np.cumsum(nbs[:-1], out=row_block_start[1:])
    bfirst = np.empty(NB, np.uint64)
    blast = np.empty(NB, np.uint64)
    bcount = np.empty(NB, np.int32)
    bwidth = np.empty(NB, np.int32)
    boff = np.empty(NB, np.int64)
    words = np.empty(NB * 2 * packed.BLOCK, np.uint32)
    row_word_start = np.empty(R, np.int64)
    total = lib.dgt_pack_many(concat, lens, row_block_start, R, bfirst, blast,
                              bcount, bwidth, boff, words, row_word_start)
    words = words[:total].copy()
    out = []
    for r in range(R):
        n = int(lens[r])
        if n == 0:
            out.append(packed.pack(np.zeros(0, np.uint64)))
            continue
        b0, b1 = int(row_block_start[r]), int(row_block_start[r] + nbs[r])
        w0 = int(row_word_start[r])
        w1 = int(row_word_start[r + 1]) if r + 1 < R else total
        out.append(packed.PackedUidList(
            n, bfirst[b0:b1], blast[b0:b1], bcount[b0:b1], bwidth[b0:b1],
            boff[b0:b1], words[w0:w1]))
    return out
