"""Storage key scheme.

Reference semantics: x/keys.go — one byte space per key kind (data / index /
reverse / count / schema), attr-prefixed so all keys of one predicate are
contiguous and a "tablet" (unit of shard placement) is a contiguous key range
(x/keys.go:25-121, SURVEY.md §2.1).

This build keys the host-side segment store the same way, but with its own
encoding: kind byte, big-endian u32 attr length, attr utf8, then a
kind-specific payload. uids are encoded big-endian so lexicographic order ==
numeric order (needed for range scans / predicate iteration).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from enum import IntEnum


class KeyKind(IntEnum):
    DATA = 0x00      # (attr, subject uid)   -> object uids / value posting
    INDEX = 0x02     # (attr, token)         -> subject uids
    REVERSE = 0x04   # (attr, object uid)    -> subject uids
    COUNT = 0x08     # (attr, rev, count)    -> subject uids with that degree
    SCHEMA = 0x10    # (attr,)               -> schema entry


_U64 = struct.Struct(">Q")
_U32 = struct.Struct(">I")


@dataclass(frozen=True)
class Key:
    kind: KeyKind
    attr: str
    uid: int = 0          # DATA / REVERSE
    term: bytes = b""     # INDEX (tokenizer-id-prefixed token)
    count: int = 0        # COUNT
    reverse: bool = False  # COUNT on reverse edges

    def encode(self) -> bytes:
        a = self.attr.encode("utf-8")
        head = bytes([self.kind]) + _U32.pack(len(a)) + a
        if self.kind in (KeyKind.DATA, KeyKind.REVERSE):
            return head + _U64.pack(self.uid)
        if self.kind == KeyKind.INDEX:
            return head + self.term
        if self.kind == KeyKind.COUNT:
            return head + bytes([1 if self.reverse else 0]) + _U32.pack(self.count)
        return head  # SCHEMA


def data_key(attr: str, uid: int) -> Key:
    return Key(KeyKind.DATA, attr, uid=uid)


def reverse_key(attr: str, uid: int) -> Key:
    return Key(KeyKind.REVERSE, attr, uid=uid)


def index_key(attr: str, term: bytes) -> Key:
    return Key(KeyKind.INDEX, attr, term=term)


def count_key(attr: str, count: int, reverse: bool = False) -> Key:
    return Key(KeyKind.COUNT, attr, count=count, reverse=reverse)


def schema_key(attr: str) -> Key:
    return Key(KeyKind.SCHEMA, attr)


def kind_attr_of(b: bytes) -> tuple[int, str]:
    """Fast partial parse — just (kind, attr), no Key object. Hot in snapshot
    load and uid-lease recovery, which touch every key once."""
    (alen,) = _U32.unpack_from(b, 1)
    return b[0], b[5: 5 + alen].decode("utf-8")


def uid_of(b: bytes) -> int:
    """Subject/object uid of a DATA/REVERSE key without a full parse."""
    return _U64.unpack(b[-8:])[0]


def parse_key(b: bytes) -> Key:
    """Inverse of Key.encode (reference: x/keys.go:253 Parse)."""
    kind = KeyKind(b[0])
    (alen,) = _U32.unpack_from(b, 1)
    attr = b[5 : 5 + alen].decode("utf-8")
    rest = b[5 + alen :]
    if kind in (KeyKind.DATA, KeyKind.REVERSE):
        (uid,) = _U64.unpack(rest)
        return Key(kind, attr, uid=uid)
    if kind == KeyKind.INDEX:
        return Key(kind, attr, term=rest)
    if kind == KeyKind.COUNT:
        rev = rest[0] == 1
        (cnt,) = _U32.unpack_from(rest, 1)
        return Key(kind, attr, count=cnt, reverse=rev)
    return Key(kind, attr)


def predicate_prefix(attr: str, kind: KeyKind | None = None) -> bytes:
    """Prefix covering all keys of a predicate (one kind, or every kind when
    iterating a whole tablet for e.g. predicate move / export).

    Reference: x/keys.go:189-251 prefix helpers.
    """
    a = attr.encode("utf-8")
    if kind is None:
        raise ValueError("kind required; iterate kinds explicitly for a full tablet scan")
    return bytes([kind]) + _U32.pack(len(a)) + a
