"""R-MAT graph generator (Chakrabarti et al.) — vectorized numpy.

Generates power-law directed graphs with LDBC-like degree skew for the
traversal benchmarks (BASELINE.md: LDBC-SNB 3-hop friends-of-friends).
"""

from __future__ import annotations

import numpy as np


def rmat_edges(scale: int, edge_factor: int = 16,
               a: float = 0.57, b: float = 0.19, c: float = 0.19,
               seed: int = 1, dedup: bool = True) -> np.ndarray:
    """Generate ~edge_factor * 2**scale directed edges over 2**scale nodes.

    Returns int64 array [E, 2] of (src, dst), self-loops removed, optionally
    deduplicated. Vectorized bit-by-bit quadrant sampling.
    """
    n_edges = edge_factor << scale
    rng = np.random.default_rng(seed)
    src = np.zeros(n_edges, dtype=np.int64)
    dst = np.zeros(n_edges, dtype=np.int64)
    for bit in range(scale):
        r = rng.random(n_edges)
        # quadrant probabilities: a=(0,0) b=(0,1) c=(1,0) d=(1,1)
        src_bit = (r >= a + b).astype(np.int64)
        dst_bit = ((r >= a) & (r < a + b) | (r >= a + b + c)).astype(np.int64)
        src = (src << 1) | src_bit
        dst = (dst << 1) | dst_bit
    keep = src != dst
    edges = np.stack([src[keep], dst[keep]], axis=1)
    if dedup:
        edges = np.unique(edges, axis=0)
    return edges


def rmat_csr(scale: int, edge_factor: int = 16, seed: int = 1,
             base_uid: int = 1):
    """R-MAT graph as a CSR (subjects, indptr, indices) with uids starting at
    base_uid (uid 0 is reserved, storage/postings.py VALUE_UID)."""
    edges = rmat_edges(scale, edge_factor, seed=seed) + base_uid
    order = np.lexsort((edges[:, 1], edges[:, 0]))
    edges = edges[order]
    subjects, counts = np.unique(edges[:, 0], return_counts=True)
    indptr = np.zeros(len(subjects) + 1, dtype=np.int64)
    np.cumsum(counts, out=indptr[1:])
    return (subjects.astype(np.int32), indptr.astype(np.int32),
            edges[:, 1].astype(np.int32))
