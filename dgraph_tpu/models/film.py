"""Synthetic film/person graph — the goldendata-style benchmark dataset
(reference: contrib/scripts/load-test.sh loads a 1.1M-edge film graph;
this generator produces the same shape at a chosen scale for the BASELINE
config 2-5 query battery)."""

from __future__ import annotations

import numpy as np

GENRES = ["drama", "comedy", "noir", "scifi"]


def film_node(n_people: int = 20000, follows: int = 12, seed: int = 2):
    """An embedded Node loaded with n_people people, ages, genres, and
    n_people*follows random follow edges."""
    from dgraph_tpu.api.server import Node

    node = Node()
    node.alter(schema_text="name: string @index(exact) .\n"
                           "age: int @index(int) .\n"
                           "genre: string @index(exact) .\n"
                           "follows: [uid] .")
    rng = np.random.default_rng(seed)
    quads = []
    for i in range(n_people):
        quads.append(f'<0x{i + 1:x}> <name> "p{i}" .')
        quads.append(f'<0x{i + 1:x}> <age> "{18 + i % 60}"^^<xs:int> .')
        quads.append(f'<0x{i + 1:x}> <genre> "{GENRES[i % 4]}" .')
    src = rng.integers(1, n_people + 1, n_people * follows)
    dst = rng.integers(1, n_people + 1, n_people * follows)
    for s, d in zip(src.tolist(), dst.tolist()):
        quads.append(f"<0x{s:x}> <follows> <0x{d:x}> .")
    for lo in range(0, len(quads), 50000):
        node.mutate(set_nquads="\n".join(quads[lo: lo + 50000]),
                    commit_now=True)
    return node
