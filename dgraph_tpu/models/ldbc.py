"""Deterministic LDBC-SNB-interactive-SHAPED synthetic generator.

Emits the DATAGEN "social_network" CSV layout (pipe-separated, one header
row, `<stem>_0_0.csv` file names) that `convert --ldbc`
(loader/convert.convert_ldbc) already maps to N-Quads + schema — the
ISSUE-15 proving ground for the SF100 acceptance claim when the official
DATAGEN dumps are not on the box. LDBC-shaped, not DATAGEN-exact:

  * persons           ≈ 10 000 · SF^0.85 (the sub-linear person curve of
                        the official generator), power-law `knows` degree
                        (discrete Zipf, capped) over a random permutation
                        so uid order carries no structure.
  * posts / comments  per-person activity is itself power-law (a few
                        loud users, a long quiet tail — the fan-out that
                        makes depth-3 replyOf/hasCreator traversals
                        realistic). Comments reply to a post or to an
                        earlier comment (≈45%), forming reply chains.

Determinism contract (tested): same (sf, seed) ⇒ byte-identical CSVs ⇒
identical N-Quads sha256 through convert_ldbc. All randomness flows from
one seeded numpy Generator; no clocks, no dict-order dependence.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import numpy as np


@dataclass
class LdbcGenStats:
    sf: float = 0.0
    persons: int = 0
    knows: int = 0
    posts: int = 0
    comments: int = 0
    edges: int = 0          # knows + hasCreator + replyOf relation rows


_FIRST = ["Mahinda", "Carmen", "Jan", "Yang", "Ana", "Otto", "Priya",
          "Kenji", "Lars", "Abebe", "Bryn", "Chen", "Deepa", "Emeka",
          "Farah", "Hồ Chí"]
_LAST = ["Perera", "Lepland", "Zholobov", "Li", "Silva", "Weber",
         "Sharma", "Sato", "Berg", "Bekele", "Jones", "Wang", "Rao",
         "Okafor", "Haddad", "Do"]
_LANGS = ["en", "uz", "vi", "de", "pt", "hi", "ja", "zh"]
_WORDS = ["about", "graph", "mesh", "fold", "tablet", "frontier", "edge",
          "shard", "query", "snapshot", "photo", "friends", "travel",
          "music", "maybe", "exactly", "thanks", "agree"]


def _zipf_degrees(rng: np.random.Generator, n: int, mean: float,
                  cap: int) -> np.ndarray:
    """Discrete power-law degrees with roughly the requested mean: Zipf
    (a=2.2) rescaled and capped — a few hubs, a long tail."""
    if n == 0:
        return np.zeros(0, np.int64)
    raw = rng.zipf(2.2, size=n).astype(np.int64)
    raw = np.minimum(raw, cap)
    scale = mean / max(raw.mean(), 1e-9)
    deg = np.maximum(0, np.round(raw * scale)).astype(np.int64)
    return np.minimum(deg, cap)


def _date(rng: np.random.Generator, n: int) -> list[str]:
    """Deterministic creationDate column (2010, DATAGEN-styled)."""
    day = rng.integers(1, 359, size=n)
    sec = rng.integers(0, 86400, size=n)
    out = []
    for d, s in zip(day.tolist(), sec.tolist()):
        mo, dd = 1 + d // 30, 1 + d % 30
        out.append(f"2010-{mo:02d}-{dd:02d}T{s // 3600:02d}:"
                   f"{(s // 60) % 60:02d}:{s % 60:02d}.000+0000")
    return out


def generate_ldbc(out_dir: str, sf: float = 0.1,
                  seed: int = 20260804) -> LdbcGenStats:
    """Write an LDBC-shaped CSV dump for scale factor `sf` under
    `out_dir` (created if needed). Returns the generation stats."""
    rng = np.random.default_rng([int(seed), int(round(sf * 1_000_000))])
    os.makedirs(out_dir, exist_ok=True)
    st = LdbcGenStats(sf=float(sf))

    n_person = max(40, int(round(10_000 * sf ** 0.85)))
    person_ids = (933 + 7 * np.arange(n_person)).astype(np.int64)
    st.persons = n_person

    # -- person entities ------------------------------------------------------
    fi = rng.integers(0, len(_FIRST), size=n_person)
    la = rng.integers(0, len(_LAST), size=n_person)
    ge = rng.integers(0, 2, size=n_person)
    by = rng.integers(1950, 2000, size=n_person)
    bm = rng.integers(1, 13, size=n_person)
    bd = rng.integers(1, 29, size=n_person)
    dates = _date(rng, n_person)
    with open(os.path.join(out_dir, "person_0_0.csv"), "w",
              encoding="utf-8") as f:
        f.write("id|firstName|lastName|gender|birthday|creationDate|"
                "locationIP|browserUsed\n")
        for i in range(n_person):
            f.write(f"{person_ids[i]}|{_FIRST[fi[i]]}|{_LAST[la[i]]}|"
                    f"{'male' if ge[i] else 'female'}|"
                    f"{by[i]}-{bm[i]:02d}-{bd[i]:02d}|{dates[i]}|"
                    f"10.0.0.{i % 250}|Firefox\n")

    # -- knows (power-law, deduped, no self-loops) ----------------------------
    mean_deg = 18.0 + 4.0 * np.log10(max(sf, 1e-3) + 1.0)
    deg = _zipf_degrees(rng, n_person, mean_deg, cap=max(64, n_person // 4))
    src = np.repeat(np.arange(n_person), deg)
    dst = rng.integers(0, n_person, size=len(src))
    keep = src != dst
    pairs = np.unique(np.stack([src[keep], dst[keep]], axis=1), axis=0)
    st.knows = len(pairs)
    k_dates = _date(rng, len(pairs))
    with open(os.path.join(out_dir, "person_knows_person_0_0.csv"), "w",
              encoding="utf-8") as f:
        f.write("Person.id|Person.id|creationDate\n")
        for j, (a, b) in enumerate(pairs.tolist()):
            f.write(f"{person_ids[a]}|{person_ids[b]}|{k_dates[j]}\n")

    # -- posts (per-person power-law activity) --------------------------------
    pdeg = _zipf_degrees(rng, n_person, 3.0 + 2.0 * min(sf, 1.0),
                         cap=256)
    post_author = np.repeat(np.arange(n_person), pdeg)
    n_post = len(post_author)
    post_ids = (343 + 11 * np.arange(n_post)).astype(np.int64)
    st.posts = n_post
    p_dates = _date(rng, n_post)
    p_lang = rng.integers(0, len(_LANGS), size=max(n_post, 1))
    p_words = rng.integers(0, len(_WORDS), size=(max(n_post, 1), 3))
    p_img = rng.random(max(n_post, 1)) < 0.25
    with open(os.path.join(out_dir, "post_0_0.csv"), "w",
              encoding="utf-8") as f:
        f.write("id|imageFile|creationDate|locationIP|browserUsed|"
                "language|content|length\n")
        for i in range(n_post):
            if p_img[i]:
                img, content, lang = f"photo{post_ids[i]}.jpg", "", ""
            else:
                img = ""
                content = " ".join(_WORDS[w] for w in p_words[i])
                lang = _LANGS[p_lang[i]]
            f.write(f"{post_ids[i]}|{img}|{p_dates[i]}|10.0.0.{i % 250}|"
                    f"Firefox|{lang}|{content}|{len(content)}\n")
    with open(os.path.join(out_dir, "post_hasCreator_person_0_0.csv"),
              "w", encoding="utf-8") as f:
        f.write("Post.id|Person.id\n")
        for i in range(n_post):
            f.write(f"{post_ids[i]}|{person_ids[post_author[i]]}\n")

    # -- comments: reply to a post (55%) or an EARLIER comment (45%) ----------
    cdeg = _zipf_degrees(rng, n_person, 6.0 + 4.0 * min(sf, 1.0),
                         cap=512)
    com_author = np.repeat(np.arange(n_person), cdeg)
    n_com = len(com_author) if n_post else 0
    com_ids = (1012 + 13 * np.arange(n_com)).astype(np.int64)
    st.comments = n_com
    c_dates = _date(rng, max(n_com, 1))
    c_words = rng.integers(0, len(_WORDS), size=(max(n_com, 1), 2))
    to_comment = rng.random(max(n_com, 1)) < 0.45
    tgt_post = rng.integers(0, max(n_post, 1), size=max(n_com, 1))
    # reply chains: target an earlier comment (index < i); the first
    # comment always replies to a post
    tgt_com = (rng.random(max(n_com, 1))
               * np.maximum(np.arange(max(n_com, 1)), 1)).astype(np.int64)
    with open(os.path.join(out_dir, "comment_0_0.csv"), "w",
              encoding="utf-8") as fc, \
         open(os.path.join(out_dir, "comment_replyOf_post_0_0.csv"), "w",
              encoding="utf-8") as fp, \
         open(os.path.join(out_dir, "comment_replyOf_comment_0_0.csv"),
              "w", encoding="utf-8") as fr, \
         open(os.path.join(out_dir, "comment_hasCreator_person_0_0.csv"),
              "w", encoding="utf-8") as fh:
        fc.write("id|creationDate|locationIP|browserUsed|content|length\n")
        fp.write("Comment.id|Post.id\n")
        fr.write("Comment.id|Comment.id\n")
        fh.write("Comment.id|Person.id\n")
        for i in range(n_com):
            content = " ".join(_WORDS[w] for w in c_words[i])
            fc.write(f"{com_ids[i]}|{c_dates[i]}|10.0.0.{i % 250}|"
                     f"Firefox|{content}|{len(content)}\n")
            if to_comment[i] and i > 0:
                fr.write(f"{com_ids[i]}|{com_ids[tgt_com[i]]}\n")
            else:
                fp.write(f"{com_ids[i]}|{post_ids[tgt_post[i]]}\n")
            fh.write(f"{com_ids[i]}|{person_ids[com_author[i]]}\n")

    st.edges = st.knows + n_post + 2 * n_com
    return st
