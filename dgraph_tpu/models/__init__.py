"""Graph datasets & generators for tests and benchmarks.

The reference benchmarks against a 1.1M-edge film graph ("goldendata",
contrib/scripts/load-test.sh) and the north star targets LDBC-SNB-style
friends-of-friends traversal (BASELINE.md). This package provides:

  rmat:  R-MAT power-law graph generator (LDBC-ish degree skew) — the
         benchmark workload generator.
  film:  a small deterministic film graph (directors/actors/genres) used by
         engine tests and examples, in the spirit of the reference's
         query/benchmark movie-graph fixtures.
"""

from dgraph_tpu.models.rmat import rmat_edges, rmat_csr  # noqa: F401
