"""Pure-device iterative traversal: k-hop BFS and SSSP as SpMSpV under jit.

Reference semantics: query/recurse.go expandRecurse (level-synchronous
frontier loop with a reach-set) and query/shortest.go (host Dijkstra over a
hash-map adjacency). On TPU both become iterative sparse ops over the
HBM-resident CSR with NO host round-trips inside the loop:

  - k_hop: lax.fori_loop over levels; each level is one CSR gather
    (ops.csr.expand) + dedup + visited-mask filter. The visited set is a
    dense bool vector over the uid space — the reach-map of recurse.go:129
    becomes a vectorized scatter/gather.
  - sssp: Bellman-Ford edge relaxation — one segment-min per iteration over
    all E edges, lax.while_loop until fixpoint. Replaces pointer-chasing
    Dijkstra for the device path (the exact k-shortest-path semantics stay in
    query/shortest.py, which feeds off device-expanded adjacency).

These are the benchmark kernels (BASELINE.md: 3-hop traversed-edges/sec,
k-shortest p50).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from dgraph_tpu.ops.uidset import sentinel
from dgraph_tpu.ops.csr import expand


class KHopResult(NamedTuple):
    visited: jax.Array          # bool[num_nodes] — every uid reached (incl. seeds)
    frontier: jax.Array         # final frontier uid set (sentinel-padded)
    traversed: jax.Array        # total edges traversed (int32)
    frontier_sizes: jax.Array   # int32[hops] frontier size after each hop


@partial(jax.jit, static_argnames=("hops", "frontier_cap", "num_nodes", "edge_cap"))
def k_hop(subjects: jax.Array, indptr: jax.Array, indices: jax.Array,
          seeds: jax.Array, *, hops: int, frontier_cap: int,
          num_nodes: int, edge_cap: int | None = None) -> KHopResult:
    """BFS k hops from `seeds` (uid set) over one predicate's CSR.

    num_nodes: static bound on the uid space (max uid + 1).
    edge_cap: static capacity of one level's edge gather — must cover the
    largest level (indices.shape[0] is always safe); defaults to frontier_cap.
    frontier_cap: static frontier set size; both are capacity classes — if a
    level's true total (reported in `traversed`) exceeded them the host
    re-issues with the next class up (the ErrTooBig contract).
    """
    snt = sentinel(jnp.int32)
    edge_cap = edge_cap or frontier_cap

    def resolve_rows(uids):
        pos = jnp.searchsorted(subjects, uids)
        pos_c = jnp.clip(pos, 0, subjects.shape[0] - 1)
        ok = (jnp.take(subjects, pos_c, mode="clip") == uids) & (uids != snt)
        return jnp.where(ok, pos_c, snt).astype(jnp.int32)

    def body(_i, carry):
        frontier, visited, traversed, sizes, level = carry
        rows = resolve_rows(frontier)
        res = expand(indptr, indices, rows, edge_cap)
        # dedup targets then drop already-visited uids
        dest = jnp.sort(res.targets)
        dup = jnp.concatenate([jnp.zeros((1,), bool), dest[1:] == dest[:-1]])
        dest = jnp.where(dup, snt, dest)
        safe = jnp.where(dest == snt, num_nodes, dest)  # scatter-drop sentinel
        was_visited = jnp.take(visited, jnp.clip(safe, 0, num_nodes - 1),
                               mode="clip") & (dest != snt)
        fresh = jnp.sort(jnp.where(was_visited | (dest == snt), snt, dest))[:frontier_cap]
        visited = visited.at[jnp.where(fresh == snt, num_nodes, fresh)].set(
            True, mode="drop")
        size = jnp.sum(fresh != snt).astype(jnp.int32)
        sizes = sizes.at[level].set(size)
        return fresh, visited, traversed + res.total.astype(jnp.int32), sizes, level + 1

    visited0 = jnp.zeros((num_nodes,), dtype=bool)
    seeds_safe = jnp.where(seeds == snt, num_nodes, seeds)
    visited0 = visited0.at[seeds_safe].set(True, mode="drop")
    sizes0 = jnp.zeros((hops,), dtype=jnp.int32)
    # carry shape is static: widen (or truncate) seeds to the frontier capacity
    if seeds.shape[0] < frontier_cap:
        seeds = jnp.concatenate(
            [seeds, jnp.full((frontier_cap - seeds.shape[0],), snt, jnp.int32)])
    else:
        seeds = jnp.sort(seeds)[:frontier_cap]
    frontier, visited, traversed, sizes, _ = lax.fori_loop(
        0, hops, body, (seeds, visited0, jnp.int32(0), sizes0, jnp.int32(0)))
    return KHopResult(visited, frontier, traversed, sizes)


class SSSPResult(NamedTuple):
    dist: jax.Array        # float32[num_nodes]; inf = unreachable
    parent: jax.Array      # int32[num_nodes]; -1 = none/root
    iterations: jax.Array


@partial(jax.jit, static_argnames=("num_nodes", "max_iters"))
def sssp(subjects: jax.Array, indptr: jax.Array, indices: jax.Array,
         weights: jax.Array | None, src: jax.Array, *, num_nodes: int,
         max_iters: int = 64) -> SSSPResult:
    """Single-source shortest paths by iterated edge relaxation.

    One iteration = relax ALL E edges: candidate[dst] = min(dist[src]+w) via
    a segment-min scatter; while_loop until no distance changes. O(E) work
    per iteration, fully vectorized — the VPU-shaped dual of Dijkstra.
    """
    E = indices.shape[0]
    # per-edge source row: row r owns edges [indptr[r], indptr[r+1])
    edge_src_row = jnp.searchsorted(indptr, jnp.arange(E, dtype=indptr.dtype),
                                    side="right").astype(jnp.int32) - 1
    edge_src = jnp.take(subjects, edge_src_row)
    edge_dst = indices
    w = weights if weights is not None else jnp.ones((E,), dtype=jnp.float32)

    inf = jnp.float32(jnp.inf)
    dist0 = jnp.full((num_nodes,), inf).at[src].set(0.0)
    parent0 = jnp.full((num_nodes,), -1, dtype=jnp.int32)

    def cond(carry):
        _d, _p, changed, it = carry
        return changed & (it < max_iters)

    def body(carry):
        dist, parent, _changed, it = carry
        cand = jnp.take(dist, edge_src) + w
        # segment-min into destinations
        new_dist = dist.at[edge_dst].min(cand, mode="drop")
        improved = new_dist < dist
        # parent recovery: an edge "wins" if its candidate equals the new
        # distance of an improved dst; any winner is a valid SSSP-tree parent
        # (max picks one deterministically)
        wins = (cand == jnp.take(new_dist, edge_dst)) & jnp.take(improved, edge_dst)
        cleared = jnp.where(improved, jnp.int32(-1), parent)  # stale parents out
        new_parent = cleared.at[jnp.where(wins, edge_dst, num_nodes)].max(
            edge_src, mode="drop")
        return new_dist, new_parent, jnp.any(improved), it + 1

    dist, parent, _c, it = lax.while_loop(
        cond, body, (dist0, parent0, jnp.bool_(True), jnp.int32(0)))
    return SSSPResult(dist, parent, it)


class DenseBFSResult(NamedTuple):
    visited: jax.Array       # bool[num_nodes]
    frontier: jax.Array      # bool[num_nodes] — final frontier mask
    traversed: jax.Array     # int32 total edges scanned


@partial(jax.jit, static_argnames=("hops", "num_nodes"))
def k_hop_dense(subjects: jax.Array, indptr: jax.Array, indices: jax.Array,
                edge_src_row: jax.Array, seeds_mask: jax.Array, *, hops: int,
                num_nodes: int) -> DenseBFSResult:
    """Dense-frontier BFS: frontier and visited are bit-vectors over the uid
    space; one hop = one gather over E edges + one scatter — NO sorts.

    This is the throughput kernel for the 3-hop benchmark: compared to the
    sorted-set variant (k_hop) it trades O(F log F) bitonic sorts for O(E)
    streaming gathers, the right trade whenever a level touches a large
    fraction of the edge set (LDBC 3-hop does). edge_src_row[e] = CSR row of
    edge e's source (precompute once: searchsorted(indptr, arange(E), 'right')-1).

    Semantics match k_hop: traversed counts every adjacency entry of every
    frontier uid per hop (the reference's per-uid posting-list scan).
    """

    def body(_i, carry):
        frontier, visited, traversed = carry
        f_row = jnp.take(frontier, subjects)            # [R] row active?
        active = jnp.take(f_row, edge_src_row)          # [E] edge active?
        traversed = traversed + jnp.sum(active, dtype=jnp.int32)
        tgt = jnp.where(active, indices, num_nodes)     # drop inactive edges
        nxt = jnp.zeros((num_nodes,), dtype=bool).at[tgt].set(True, mode="drop")
        nxt = nxt & ~visited
        return nxt, visited | nxt, traversed

    frontier, visited, traversed = lax.fori_loop(
        0, hops, body, (seeds_mask, seeds_mask, jnp.int32(0)))
    return DenseBFSResult(visited, frontier, traversed)


def edge_src_rows(indptr: jax.Array) -> jax.Array:
    """Per-edge source row for k_hop_dense (edge e belongs to the row r with
    indptr[r] <= e < indptr[r+1])."""
    E = int(indptr[-1])
    return (jnp.searchsorted(indptr, jnp.arange(E, dtype=indptr.dtype),
                             side="right") - 1).astype(jnp.int32)


@partial(jax.jit, static_argnames=("hops", "num_nodes"))
def k_hop_pull(subjects: jax.Array, indptr: jax.Array,
               in_subjects: jax.Array, in_indptr: jax.Array,
               in_src: jax.Array, seeds_mask: jax.Array, *, hops: int,
               num_nodes: int) -> DenseBFSResult:
    """Pull-style dense BFS — the HBM-bandwidth-shaped formulation.

    Uses BOTH orientations of the predicate CSR (the @reverse tablet the
    storage layer already maintains, posting/index.go:190):

      traversed += Σ out-degree over frontier rows           (R-sized)
      active[e]  = frontier[in_src[e]]                       (E-sized gather)
      reached[r] = segment-any(active) via one cumsum + diff (no E-scatter)
      frontier'  = reached & ~visited                        (R-sized scatter)

    The only per-edge ops are a streaming gather and a cumsum; scatters are
    node-sized. This is what makes 3-hop throughput HBM-bound instead of
    scatter-bound (k_hop_dense) or sort-bound (k_hop).
    """
    out_deg = indptr[1:] - indptr[:-1]

    def body(_i, carry):
        frontier, visited, traversed = carry
        f_rows = jnp.take(frontier, subjects)
        traversed = traversed + jnp.sum(
            jnp.where(f_rows, out_deg, 0), dtype=jnp.int32)
        active = jnp.take(frontier, in_src).astype(jnp.int32)   # [E]
        c = jnp.concatenate([jnp.zeros((1,), jnp.int32), jnp.cumsum(active)])
        seg = jnp.take(c, in_indptr[1:]) - jnp.take(c, in_indptr[:-1])
        reached = seg > 0                                        # [R_in]
        fresh = reached & ~jnp.take(visited, in_subjects)
        nxt = jnp.zeros((num_nodes,), dtype=bool).at[in_subjects].set(
            fresh, mode="drop")
        return nxt, visited | nxt, traversed

    frontier, visited, traversed = lax.fori_loop(
        0, hops, body, (seeds_mask, seeds_mask, jnp.int32(0)))
    return DenseBFSResult(visited, frontier, traversed)


def reverse_csr(subjects: "np.ndarray", indptr: "np.ndarray",
                indices: "np.ndarray"):
    """Host-side transpose: (in_subjects, in_indptr, in_src) where in_src
    lists, per destination node, the source uids of its incoming edges."""
    import numpy as np

    E = len(indices)
    src = np.repeat(subjects, np.diff(indptr))
    order = np.argsort(indices, kind="stable")
    dst_sorted = indices[order]
    src_sorted = src[order]
    in_subjects, counts = np.unique(dst_sorted, return_counts=True)
    in_indptr = np.zeros(len(in_subjects) + 1, dtype=np.int64)
    np.cumsum(counts, out=in_indptr[1:])
    return (in_subjects.astype(np.int32), in_indptr.astype(np.int32),
            src_sorted.astype(np.int32))


# device-runtime observatory (obs/devprof.py, ISSUE 19): jitted entry
# points by program family, probed for live jit-cache size on
# /debug/compiles (see ops/segments.py).
JIT_PROGRAMS = {
    "traversal.k_hop": k_hop,
    "traversal.sssp": sssp,
    "traversal.k_hop_dense": k_hop_dense,
    "traversal.k_hop_pull": k_hop_pull,
}
