"""Sorted-uid set algebra, vectorized for TPU.

Reference semantics: algo/uidlist.go — IntersectWith (:133), IntersectSorted (:278),
MergeSorted (:344), Difference (:312), ApplyFilter (:31), IndexOf (:395).

The reference picks between linear / jump ("gallop") / binary-meld intersection by a
size-ratio heuristic (algo/uidlist.go:147-155) because it walks elements one at a time
on a CPU. On TPU every strategy collapses into one data-parallel plan: membership tests
are a vectorized binary search (jnp.searchsorted lowers to a logarithmic pass of
selects that XLA vectorizes across the whole array), and unions are bitonic sorts on
the VPU. There is no pointer chasing and no data-dependent branching, so one kernel
covers every size ratio.

Representation
--------------
A *uid set* is a fixed-capacity 1-D integer array, sorted ascending, strictly
increasing over its valid prefix, padded at the tail with SENTINEL (the dtype's max
value). Capacity is static (XLA needs static shapes); the logical size is the number
of non-sentinel entries. This mirrors the reference's packed posting blocks, which
also carry value-count metadata per fixed 256-int block (bp128/bp128.go:23,137-144).

All functions are pure jnp, jit/vmap/shard_map-compatible.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

SENTINEL32 = np.int32(np.iinfo(np.int32).max)
SENTINEL64 = np.int64(np.iinfo(np.int64).max)


def sentinel(dtype) -> np.generic:
    """Padding value for a uid-set of the given integer dtype."""
    return np.asarray(np.iinfo(np.dtype(dtype)).max, dtype=dtype)[()]


def host_rank_of(sorted_arr: np.ndarray, values: np.ndarray,
                 miss: int) -> np.ndarray:
    """Position of each value in a sorted host array, `miss` where absent
    (reference algo/uidlist.go:395 IndexOf, vectorized). The shared helper
    behind frontier→CSR-row mapping, rank compression, and seed mapping."""
    values = np.asarray(values)
    if len(sorted_arr) == 0:
        return np.full(values.shape, miss, dtype=np.int64)
    pos = np.searchsorted(sorted_arr, values)
    pos_c = np.clip(pos, 0, len(sorted_arr) - 1)
    ok = sorted_arr[pos_c] == values
    return np.where(ok, pos_c, miss)


# ---------------------------------------------------------------------------
# Construction / host interop
# ---------------------------------------------------------------------------

def make_set(uids, capacity: int | None = None, dtype=jnp.int32) -> jax.Array:
    """Build a device uid-set from host uids (any order, dupes allowed)."""
    if np.dtype(dtype).itemsize == 8 and not jax.config.jax_enable_x64:
        raise ValueError("int64 uid-sets require jax_enable_x64 (sentinel would "
                         "silently wrap to -1 under x64-disabled truncation)")
    arr = np.unique(np.asarray(uids, dtype=np.dtype(dtype)))
    cap = capacity if capacity is not None else max(len(arr), 1)
    if len(arr) > cap:
        raise ValueError(f"{len(arr)} uids exceed capacity {cap}")
    if len(arr) and arr[-1] == sentinel(dtype):
        raise ValueError(f"uid {arr[-1]} collides with the padding sentinel")
    out = np.full(cap, sentinel(dtype), dtype=np.dtype(dtype))
    out[: len(arr)] = arr
    return jnp.asarray(out)


def to_numpy(s) -> np.ndarray:
    """Valid (non-sentinel) entries of a uid-set as a host numpy array."""
    arr = np.asarray(s)
    return arr[arr != sentinel(arr.dtype)]


# ---------------------------------------------------------------------------
# Core algebra
# ---------------------------------------------------------------------------

def size(a: jax.Array) -> jax.Array:
    """Number of valid entries."""
    return jnp.sum(a != sentinel(a.dtype)).astype(jnp.int32)


def compact(a: jax.Array) -> jax.Array:
    """Push sentinels to the tail, preserving order of valid entries.

    Valid entries are already ascending and sentinel is the max value, so a sort
    is a compaction. XLA lowers this to a bitonic sort — O(n log^2 n) lanes but
    fully parallel on the VPU.
    """
    return jnp.sort(a)


def is_member(a: jax.Array, b: jax.Array) -> jax.Array:
    """Boolean mask over `a`: a[i] present in set `b`. Sentinels map to False."""
    snt = sentinel(a.dtype)
    idx = jnp.searchsorted(b, a)
    found = jnp.take(b, idx, mode="fill", fill_value=snt) == a
    return found & (a != snt)


def intersect(a: jax.Array, b: jax.Array) -> jax.Array:
    """Sorted intersection, result in a's capacity.

    Reference: algo/uidlist.go IntersectWith (:133) — all three strategies
    (linear/jump/binary) collapse to one vectorized membership test.
    """
    return compact(jnp.where(is_member(a, b), a, sentinel(a.dtype)))


def difference(a: jax.Array, b: jax.Array) -> jax.Array:
    """a \\ b.  Reference: algo/uidlist.go Difference (:312)."""
    snt = sentinel(a.dtype)
    keep = (~is_member(a, b)) & (a != snt)
    return compact(jnp.where(keep, a, snt))


def apply_filter(a: jax.Array, mask: jax.Array) -> jax.Array:
    """Keep a[i] where mask[i]; result is a valid (compacted) uid-set.

    Reference: algo/uidlist.go ApplyFilter (:31).
    """
    return compact(jnp.where(mask & (a != sentinel(a.dtype)), a, sentinel(a.dtype)))


def merge(a: jax.Array, b: jax.Array, out_size: int | None = None) -> jax.Array:
    """Sorted union with dedup. Default capacity = |a|+|b|.

    Reference: algo/uidlist.go MergeSorted (:344) — a k-way heap merge on CPU;
    on TPU a bitonic sort of the concatenation followed by run-dedup.
    """
    merged = jnp.sort(jnp.concatenate([a, b]))
    merged = _dedup_sorted(merged)
    if out_size is not None and out_size != merged.shape[0]:
        merged = resize(merged, out_size)
    return merged


def _dedup_sorted(x: jax.Array) -> jax.Array:
    """Kill duplicate runs in a sorted array (keeps first of each run), re-compact."""
    snt = sentinel(x.dtype)
    dup = jnp.concatenate([jnp.zeros((1,), dtype=bool), x[1:] == x[:-1]])
    return jnp.sort(jnp.where(dup, snt, x))


def merge_many(matrix: jax.Array, out_size: int | None = None) -> jax.Array:
    """Union of the rows of a 2-D array of uid-sets (MergeSorted over a uidMatrix).

    Reference: query/query.go:1928 — DestUIDs = MergeSorted(uidMatrix).
    """
    flat = jnp.sort(matrix.reshape(-1))
    flat = _dedup_sorted(flat)
    if out_size is not None and out_size != flat.shape[0]:
        flat = resize(flat, out_size)
    return flat


def intersect_many(matrix: jax.Array, out_size: int | None = None) -> jax.Array:
    """Intersection of the rows of a 2-D array of uid-sets.

    Reference: algo/uidlist.go IntersectSorted (:278) — smallest-first repeated
    intersection. Vectorized: each row is duplicate-free, so after sorting the
    flattened matrix a value is in every row iff it heads a run of length k.
    One sort instead of k-1 passes.
    """
    k = matrix.shape[0]
    flat = jnp.sort(matrix.reshape(-1))
    snt = sentinel(flat.dtype)
    n = flat.shape[0]
    if k == 1:
        result = flat
    else:
        # value at i starts a run of >= k iff flat[i+k-1] == flat[i] and flat[i-1] != flat[i]
        ahead = jnp.take(flat, jnp.arange(n) + k - 1, mode="fill", fill_value=snt)
        first = jnp.concatenate([jnp.ones((1,), dtype=bool), flat[1:] != flat[:-1]])
        keep = first & (ahead == flat) & (flat != snt)
        result = jnp.sort(jnp.where(keep, flat, snt))
    if out_size is not None and out_size != result.shape[0]:
        result = resize(result, out_size)
    return result


def index_of(a: jax.Array, v) -> jax.Array:
    """Index of uid v in set a, or -1. Reference: algo/uidlist.go IndexOf (:395)."""
    snt = sentinel(a.dtype)
    idx = jnp.searchsorted(a, v)
    hit = (jnp.take(a, idx, mode="fill", fill_value=snt) == v) & (jnp.asarray(v, a.dtype) != snt)
    return jnp.where(hit, idx, -1).astype(jnp.int32)


def resize(a: jax.Array, capacity: int) -> jax.Array:
    """Grow (pad) or shrink (truncate valid prefix) a compacted uid-set."""
    n = a.shape[0]
    if capacity == n:
        return a
    if capacity > n:
        pad = jnp.full((capacity - n,), sentinel(a.dtype), dtype=a.dtype)
        return jnp.concatenate([a, pad])
    return a[:capacity]


def paginate(a: jax.Array, offset, count) -> jax.Array:
    """Keep valid entries with rank in [offset, offset+count) (count<0 → to end).

    Reference: x/x.go:191 PageRange + query/query.go:2114 applyPagination.
    `a` must be compacted (valid prefix); rank == position.
    """
    ranks = jnp.arange(a.shape[0])
    total = size(a)
    off = jnp.where(offset < 0, jnp.maximum(total + offset, 0), offset)
    end = jnp.where(count < 0, total, off + count)
    keep = (ranks >= off) & (ranks < end)
    return compact(apply_filter(a, keep))


# ---------------------------------------------------------------------------
# Host-facing dispatchers (the engine's DestUIDs/filter combine seam)
# ---------------------------------------------------------------------------

# below this size numpy's C set ops beat a device round-trip; above it the
# device path wins and keeps the shape-class count small (pow2 capacities)
HOST_CUTOVER = 8192


def _pow2_cap(n: int) -> int:
    return 1 << max(int(np.ceil(np.log2(max(n, 1)))), 4)


def intersect_host(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Sorted-unique int64 intersection; device algebra above HOST_CUTOVER.

    Reference: query/query.go:1924 DestUIDs = IntersectSorted(uidMatrix) —
    the per-level combine the engine runs constantly."""
    if min(len(a), len(b)) < HOST_CUTOVER:
        return np.intersect1d(a, b)
    small, big = (a, b) if len(a) <= len(b) else (b, a)
    sa = make_set(small, capacity=_pow2_cap(len(small)))
    sb = make_set(big, capacity=_pow2_cap(len(big)))
    return to_numpy(intersect(sa, sb)).astype(np.int64)


def union_host(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Sorted-unique int64 union; device merge above HOST_CUTOVER."""
    if min(len(a), len(b)) < HOST_CUTOVER:
        return np.union1d(a, b)
    cap = _pow2_cap(len(a) + len(b))
    sa = make_set(a, capacity=_pow2_cap(len(a)))
    sb = make_set(b, capacity=_pow2_cap(len(b)))
    return to_numpy(merge(sa, sb, out_size=cap)).astype(np.int64)


def difference_host(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Sorted-unique int64 a \\ b; device path above HOST_CUTOVER."""
    if min(len(a), len(b)) < HOST_CUTOVER:
        return np.setdiff1d(a, b)
    sa = make_set(a, capacity=_pow2_cap(len(a)))
    sb = make_set(b, capacity=_pow2_cap(len(b)))
    return to_numpy(difference(sa, sb)).astype(np.int64)
