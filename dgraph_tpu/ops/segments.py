"""Segmented reductions — @groupby / aggregation on device.

Reference semantics: query/groupby.go:43-75,142-165 aggregates (count / min /
max / sum / avg) per group by iterating each group's uid list;
query/aggregator.go applies the op pairwise. TPU redesign: groups become
segment ids and every group's aggregate computes in ONE
jax.ops.segment_* call over the flat member array — the canonical
segment-reduction mapping of SURVEY.md §7 step 5.

Host-facing entry: `group_reduce(op, seg_ids, values, num_groups)` takes
numpy arrays (the engine's group assembly is host work), runs the fused
device reduction, and returns a numpy vector of per-group results with NaN
for empty groups (the caller drops them, matching the reference's
"aggregate of no values is absent" behavior).
"""

from __future__ import annotations

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp

_OPS = ("sum", "min", "max", "avg", "count")


@partial(jax.jit, static_argnames=("op", "num_segments"))
def segment_reduce(values: jax.Array, seg_ids: jax.Array, *, op: str,
                   num_segments: int) -> jax.Array:
    """One fused reduction over all segments.

    values: float32[N] (NaN = missing — excluded from every op)
    seg_ids: int32[N] in [0, num_segments)
    Returns float32[num_segments]; empty segments yield NaN (count yields 0).
    """
    valid = ~jnp.isnan(values)
    ns = num_segments
    cnt = jax.ops.segment_sum(valid.astype(jnp.float32), seg_ids, ns)
    if op == "count":
        return cnt
    empty = cnt == 0
    if op == "sum" or op == "avg":
        s = jax.ops.segment_sum(jnp.where(valid, values, 0.0), seg_ids, ns)
        out = s / jnp.maximum(cnt, 1.0) if op == "avg" else s
    elif op == "min":
        out = jax.ops.segment_min(jnp.where(valid, values, jnp.inf), seg_ids, ns)
    elif op == "max":
        out = jax.ops.segment_max(jnp.where(valid, values, -jnp.inf), seg_ids, ns)
    else:
        raise ValueError(f"unknown segment op {op!r}")
    return jnp.where(empty, jnp.nan, out)


def group_reduce(op: str, seg_ids: np.ndarray, values: np.ndarray,
                 num_groups: int) -> np.ndarray:
    """numpy → device → numpy wrapper (empty input → all-NaN/0 vector)."""
    if op not in _OPS:
        raise ValueError(f"unknown segment op {op!r}")
    if num_groups == 0:
        return np.zeros(0, dtype=np.float32)
    if len(seg_ids) == 0:
        out = np.full(num_groups, np.nan, dtype=np.float32)
        if op == "count":
            out[:] = 0.0
        return out
    res = segment_reduce(
        jnp.asarray(np.asarray(values, dtype=np.float32)),
        jnp.asarray(np.asarray(seg_ids, dtype=np.int32)),
        op=op, num_segments=int(num_groups))
    return np.asarray(res)
