"""Segmented reductions — @groupby / aggregation on device.

Reference semantics: query/groupby.go:43-75,142-165 aggregates (count / min /
max / sum / avg) per group by iterating each group's uid list;
query/aggregator.go applies the op pairwise. TPU redesign: groups become
segment ids and every group's aggregate computes in ONE
jax.ops.segment_* call over the flat member array — the canonical
segment-reduction mapping of SURVEY.md §7 step 5.

Two exactness regimes, mirroring ops/vector.py's candidate/finalize split:

  * the DEVICE stage reduces f32 CANDIDATES (sum/min/max) plus an exact
    valid-value count, with segment ids derived ON DEVICE from the
    per-group length vector (a cumsum + searchsorted — no host
    ``np.repeat`` tail) and padding/dead rows masked into a dump segment;
  * the HOST finalizes in f64: avg is always ``f64(sum)/f64(count)``, empty
    segments collapse to NaN, and the caller's f32-exactness rule
    (all-int values, |sum| < 2**24 — see groupby._batch_aggregates)
    guarantees the f32 candidates are bit-exact where they are used.

Host-facing entries: `group_reduce(op, seg_ids, values, num_groups)` (host
segment ids, one op) and `fused_group_reduce(ops, values, lens, num_groups)`
(device segment ids from lengths, many ops in one dispatch). Both return
numpy vectors with NaN for empty groups (count yields 0), matching the
reference's "aggregate of no values is absent" behavior.
"""

from __future__ import annotations

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp

_OPS = ("sum", "min", "max", "avg", "count")

# below this many lookups the numpy searchsorted wins (no transfer/jit);
# above it the device rank kernel amortizes
_RANK_DEVICE_MIN = 1 << 18


def seg_capacity(n: int) -> int:
    """Pow2 padding capacity: bounds jit retraces across input sizes."""
    return 1 << max(int(np.ceil(np.log2(max(int(n), 1)))), 4)


@partial(jax.jit, static_argnames=("op", "num_segments"))
def segment_reduce(values: jax.Array, seg_ids: jax.Array, *, op: str,
                   num_segments: int) -> jax.Array:
    """One fused reduction over all segments.

    values: float32[N] (NaN = missing — excluded from every op)
    seg_ids: int32[N] in [0, num_segments)
    Returns float32[num_segments]; empty segments yield NaN (count yields 0).
    """
    valid = ~jnp.isnan(values)
    ns = num_segments
    cnt = jax.ops.segment_sum(valid.astype(jnp.float32), seg_ids, ns)
    if op == "count":
        return cnt
    empty = cnt == 0
    if op == "sum" or op == "avg":
        s = jax.ops.segment_sum(jnp.where(valid, values, 0.0), seg_ids, ns)
        out = s / jnp.maximum(cnt, 1.0) if op == "avg" else s
    elif op == "min":
        out = jax.ops.segment_min(jnp.where(valid, values, jnp.inf), seg_ids, ns)
    elif op == "max":
        out = jax.ops.segment_max(jnp.where(valid, values, -jnp.inf), seg_ids, ns)
    else:
        raise ValueError(f"unknown segment op {op!r}")
    return jnp.where(empty, jnp.nan, out)


@partial(jax.jit, static_argnames=("num_segments",))
def _sum_count(values: jax.Array, seg_ids: jax.Array, *,
               num_segments: int) -> tuple[jax.Array, jax.Array]:
    """f32 sum candidate + exact valid count in one dispatch (avg feeds
    the host-f64 finalize from these instead of dividing on device)."""
    valid = ~jnp.isnan(values)
    s = jax.ops.segment_sum(jnp.where(valid, values, 0.0), seg_ids,
                            num_segments)
    cnt = jax.ops.segment_sum(valid.astype(jnp.float32), seg_ids,
                              num_segments)
    return s, cnt


def group_reduce(op: str, seg_ids: np.ndarray, values: np.ndarray,
                 num_groups: int) -> np.ndarray:
    """numpy → device → numpy wrapper (empty input → all-NaN/0 vector).

    avg finalizes on the host in f64 from the device's (sum, count)
    candidates — byte-identical to a host f64 tail whenever the sum is
    f32-exact.
    """
    if op not in _OPS:
        raise ValueError(f"unknown segment op {op!r}")
    if num_groups == 0:
        return np.zeros(0, dtype=np.float32)
    if len(seg_ids) == 0:
        out = np.full(num_groups, np.nan, dtype=np.float32)
        if op == "count":
            out[:] = 0.0
        return out
    vals = jnp.asarray(np.asarray(values, dtype=np.float32))
    segs = jnp.asarray(np.asarray(seg_ids, dtype=np.int32))
    if op == "avg":
        s, cnt = _sum_count(vals, segs, num_segments=int(num_groups))
        s64 = np.asarray(s, dtype=np.float64)
        c64 = np.asarray(cnt, dtype=np.float64)
        return np.where(c64 == 0, np.nan, s64 / np.maximum(c64, 1.0))
    res = segment_reduce(vals, segs, op=op, num_segments=int(num_groups))
    return np.asarray(res)


# ---------------------------------------------------------------------------
# fused multi-op reduce with device-derived segment ids
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("ops", "num_segments"))
def _lens_reduce(values: jax.Array, lens: jax.Array, total: jax.Array, *,
                 ops: tuple, num_segments: int) -> dict:
    """Reduce with segment ids built ON DEVICE from per-group lengths.

    values: f32[cap] flat member values, caller-padded (NaN = missing)
    lens:   i32[gcap] per-group member counts, zero-padded
    total:  i32 scalar — the live prefix of `values`

    Position p belongs to the group whose cumulative-length window covers
    it; padding/dead rows (p >= total) and overflow land in a dump segment
    `num_segments` that is sliced off. Returns f32 candidate arrays per
    requested op plus the exact valid count.
    """
    cap = values.shape[0]
    # int32 positions: total member count is bounded far below 2**31 by
    # the engine's traversed-edge budget (x64 stays off on device)
    ends = jnp.cumsum(lens, dtype=jnp.int32)
    pos = jnp.arange(cap, dtype=jnp.int32)
    seg = jnp.searchsorted(ends, pos, side="right").astype(jnp.int32)
    live = pos < total
    seg = jnp.where(live & (seg < num_segments), seg, num_segments)
    valid = live & ~jnp.isnan(values)
    ns = num_segments + 1
    out = {"count": jax.ops.segment_sum(
        valid.astype(jnp.float32), seg, ns)[:num_segments]}
    if "sum" in ops or "avg" in ops:
        out["sum"] = jax.ops.segment_sum(
            jnp.where(valid, values, 0.0), seg, ns)[:num_segments]
    if "min" in ops:
        out["min"] = jax.ops.segment_min(
            jnp.where(valid, values, jnp.inf), seg, ns)[:num_segments]
    if "max" in ops:
        out["max"] = jax.ops.segment_max(
            jnp.where(valid, values, -jnp.inf), seg, ns)[:num_segments]
    return out


def fused_group_reduce(ops, values: np.ndarray, lens,
                       num_groups: int) -> dict:
    """All requested ops over one flat value vector in ONE device dispatch.

    values: float per-member values in group-concatenation order (NaN =
    member has no value); lens: per-group member counts (their cumsum
    defines the segments — the device derives ids, no host np.repeat).
    Returns {op: float64[num_groups]} finalized on the host: sum/min/max
    widen the f32 candidates, avg = f64(sum)/f64(count), empty → NaN
    (count → 0).
    """
    for op in ops:
        if op not in _OPS:
            raise ValueError(f"unknown segment op {op!r}")
    ng = int(num_groups)
    if ng == 0:
        return {op: np.zeros(0, dtype=np.float64) for op in ops}
    n = len(values)
    if n == 0:
        return {op: (np.zeros(ng) if op == "count"
                     else np.full(ng, np.nan)) for op in ops}
    cap = seg_capacity(n)
    gcap = seg_capacity(ng)
    vp = np.full(cap, np.nan, dtype=np.float32)
    vp[:n] = np.asarray(values, dtype=np.float32)
    lp = np.zeros(gcap, dtype=np.int32)
    lp[:ng] = np.asarray(lens, dtype=np.int32)
    dev_ops = tuple(sorted(set(ops)))
    res = _lens_reduce(jnp.asarray(vp), jnp.asarray(lp), jnp.int32(n),
                       ops=dev_ops, num_segments=ng)
    cnt = np.asarray(res["count"], dtype=np.float64)
    empty = cnt == 0
    out = {}
    for op in ops:
        if op == "count":
            out[op] = cnt
        elif op == "avg":
            s = np.asarray(res["sum"], dtype=np.float64)
            out[op] = np.where(empty, np.nan, s / np.maximum(cnt, 1.0))
        else:
            cand = np.asarray(res[op], dtype=np.float64)
            out[op] = np.where(empty, np.nan, cand)
    return out


# ---------------------------------------------------------------------------
# rank-space coding against a distinct-target table
# ---------------------------------------------------------------------------

@jax.jit
def _rank_kernel(table: jax.Array, values: jax.Array):
    nt = table.shape[0]
    pos = jnp.clip(jnp.searchsorted(table, values), 0, max(nt - 1, 0))
    return pos, jnp.take(table, pos) == values


def rank_in_table(table: np.ndarray, values: np.ndarray):
    """(pos, hit): rank of each value in a SORTED table — the group-code
    primitive (codes = ranks in the tablet's distinct-target table, no
    per-query np.unique sort). Host numpy below _RANK_DEVICE_MIN lookups,
    device searchsorted above.
    """
    nv = len(values)
    if len(table) == 0 or nv == 0:
        return (np.zeros(nv, dtype=np.int64),
                np.zeros(nv, dtype=bool))
    if nv >= _RANK_DEVICE_MIN:
        cap = seg_capacity(nv)
        vp = np.full(cap, table[0], dtype=np.int64)
        vp[:nv] = values
        pos, hit = _rank_kernel(jnp.asarray(np.asarray(table, np.int64)),
                                jnp.asarray(vp))
        return (np.asarray(pos[:nv], dtype=np.int64),
                np.asarray(hit[:nv]))
    pos = np.searchsorted(table, values)
    posc = np.minimum(pos, len(table) - 1)
    return posc.astype(np.int64), table[posc] == values


# device-runtime observatory (obs/devprof.py, ISSUE 19): the module's
# jitted entry points by program family. Node registers their live jit
# cache sizes as /debug/compiles probes — a growing cache under steady
# traffic is shape churn (retraces); compile wall ms itself is
# attributed by the jax.monitoring listener under whatever costs.kernel
# family is active at first dispatch.
JIT_PROGRAMS = {
    "segments.reduce": segment_reduce,
    "segments.sum_count": _sum_count,
    "segments.lens_reduce": _lens_reduce,
    "segments.rank_kernel": _rank_kernel,
}
