"""Pallas pull-BFS: the locality-blocked frontier-bit gather kernel.

This is the native kernel the reference implements as 146k lines of
generated SSE2 (bp128/unpack_amd64.s + worker/task.go:476-602 per-uid
posting iteration). PERF.md (round 1) measured XLA's element-granularity
gather at ~1000x below HBM bandwidth — every BFS formulation pays one
E-sized random gather per hop (frontier[in_src[e]]), so the pull kernel
topped out at ~36M edges/s. Here that gather runs inside a Pallas kernel
where it can't miss:

  - the frontier is a bit-packed bitmap: num_nodes bits = num_nodes/8
    bytes, VMEM-resident for the whole kernel (1M nodes = 128 KB). Zero
    HBM traffic for masks.
  - the bitmap is laid out as (CHUNKS, 1024) int32 words; 1024 words =
    one 8x128 int32 vreg, the unit Mosaic can gather from in one op. The
    kernel loops over chunks, gathering each edge's frontier word from
    the chunk that owns it (chunks = ceil(num_nodes / 32768); a scale-20
    graph needs 33 — ~5 VPU ops per edge per chunk).
  - the edge stream (in_src, sorted by destination) is the ONLY O(E) HBM
    traffic: 4 bytes in + 4 bytes out per edge, at streaming rate.
  - the kernel fuses the inclusive prefix-sum of the per-edge active
    flags (two-level lane/sublane scan + a sequential-grid carry in
    SMEM), so the XLA side needs no E-sized cumsum: per-node reachability
    is diff-of-prefix at the dense in-CSR row boundaries — node-sized.

Per hop:   active[e] = frontier_bit[in_src[e]]          (Pallas, streaming)
           prefix    = cumsum(active)                   (fused in kernel)
           reached_v = prefix[iptr[v+1]] - prefix[iptr[v]] > 0   (node-sized)
           frontier' = reached & ~visited               (node-sized)

Reference semantics preserved: `traversed` counts every out-edge of every
frontier node per hop (== active in-edges), and `visited` matches
traversal.k_hop_pull / the host BFS exactly (bench.py's equality gate).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

WORDS_PER_CHUNK = 1024          # one 8x128 int32 vreg
NODES_PER_CHUNK = WORDS_PER_CHUNK * 32
EDGE_BLOCK = 8192               # edges per grid step (64 x 128)
_LANES = 128


def _block_prefix(active: jax.Array) -> jax.Array:
    """Inclusive prefix sum of a (R, 128) int block in row-major order,
    computed as two triangular matmuls on the MXU (f32 is exact here:
    block totals are <= EDGE_BLOCK << 2^24). Mosaic lowers matmuls far
    better than narrow pad/concat scans."""
    R, L = active.shape
    af = active.astype(jnp.float32)
    kk = lax.broadcasted_iota(jnp.int32, (L, L), 0)
    jj = lax.broadcasted_iota(jnp.int32, (L, L), 1)
    upper = (kk <= jj).astype(jnp.float32)             # inclusive lane scan
    lane = lax.dot_general(af, upper, (((1,), (0,)), ((), ())),
                           preferred_element_type=jnp.float32)
    rr = lax.broadcasted_iota(jnp.int32, (R, R), 0)
    cc = lax.broadcasted_iota(jnp.int32, (R, R), 1)
    lower = (cc < rr).astype(jnp.float32)              # strictly-lower: rows before
    row_sums = jnp.sum(af, axis=1, keepdims=True)      # (R, 1)
    row_off = lax.dot_general(lower, row_sums, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    return (lane + row_off).astype(jnp.int32)


def _prefix_kernel(words_ref, src_ref, out_ref, carry_ref, *, chunks: int):
    """One grid step: EDGE_BLOCK edges -> inclusive active-prefix values.

    The frontier-word lookup runs as a chunk loop: each chunk is 1024 words
    laid out (8, 128); Mosaic's dynamic_gather handles the lane dimension
    (take_along_axis along axis=1, single-vreg form) and an 8-way masked
    select handles the sublane row (masks hoisted out of the chunk loop) —
    zero HBM traffic for the bitmap (VMEM-resident throughout)."""
    blk = pl.program_id(0)

    @pl.when(blk == 0)
    def _():
        carry_ref[0] = 0

    src = src_ref[:]                                   # (R, 128) int32
    w = lax.shift_right_logical(src, 5)
    bit = jnp.bitwise_and(src, 31)
    cidx = lax.shift_right_logical(w, 10)              # owning chunk
    widx = jnp.bitwise_and(w, WORDS_PER_CHUNK - 1)     # word within chunk
    col = jnp.bitwise_and(widx, _LANES - 1)
    row = lax.shift_right_logical(widx, 7)             # 0..7
    row_masks = [row == r for r in range(8)]           # hoisted: 8 ops total

    def body(c, acc):
        cw = words_ref[pl.ds(c * 8, 8), :]             # (8,128): 1024 words
        cmask = cidx == c
        for r in range(8):
            row_r = jnp.broadcast_to(cw[r : r + 1, :], src.shape)
            g = jnp.take_along_axis(row_r, col, axis=1)    # in-vreg gather
            acc = jnp.where(row_masks[r] & cmask, g, acc)
        return acc

    wordv = lax.fori_loop(0, chunks, body, jnp.zeros_like(src))
    active = jnp.bitwise_and(lax.shift_right_logical(wordv, bit), 1)

    # inclusive scan in row-major (flattened-edge) order + sequential carry
    prefix = _block_prefix(active) + carry_ref[0]
    out_ref[:] = prefix
    carry_ref[0] = prefix[prefix.shape[0] - 1, _LANES - 1]


FRONTIER_CAP = 4096    # sparse-path capacity: 128 buckets x 32 entries


def _prefix_kernel_sparse(ftab_ref, src_ref, out_ref, carry_ref):
    """Sparse-frontier variant: membership test against a sorted frontier
    list (<= FRONTIER_CAP uids) in a 2-level 128-ary layout instead of the
    full-bitmap chunk loop — ~5x fewer VPU ops per edge, the win for the
    early BFS hops where the frontier is small.

    ftab layout (33, 128): row 0 = per-bucket max (bucket g = sorted
    frontier[32g:32g+32]); rows 1+j = element j of every bucket. Padding
    slots hold INT32_MAX (never equal to a real uid)."""
    blk = pl.program_id(0)

    @pl.when(blk == 0)
    def _():
        carry_ref[0] = 0

    src = src_ref[:]                                   # (R, 128) int32
    seps = jnp.broadcast_to(ftab_ref[0:1, :], src.shape)

    # branchless lower-bound over the 128 bucket separators:
    # first bucket g with max(bucket g) >= src
    b = jnp.zeros_like(src)
    for k in (64, 32, 16, 8, 4, 2, 1):
        cand = b + k
        sep = jnp.take_along_axis(seps, jnp.minimum(cand - 1, _LANES - 1),
                                  axis=1)
        b = jnp.where(sep < src, cand, b)
    b = jnp.minimum(b, _LANES - 1)

    # equality scan of the 32 entries of the selected bucket
    active = jnp.zeros_like(src)
    for j in range(32):
        lane = jnp.broadcast_to(ftab_ref[1 + j : 2 + j, :], src.shape)
        v = jnp.take_along_axis(lane, b, axis=1)
        active = jnp.bitwise_or(active, (v == src).astype(jnp.int32))

    prefix = _block_prefix(active) + carry_ref[0]
    out_ref[:] = prefix
    carry_ref[0] = prefix[prefix.shape[0] - 1, _LANES - 1]


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


@partial(jax.jit, static_argnames=("chunks",))
def active_prefix(words: jax.Array, src_pad: jax.Array, *,
                  chunks: int) -> jax.Array:
    """Inclusive prefix-count of frontier-active edges.

    words: (chunks*8, 128) int32 frontier bitmap (word w at [w>>7, w&127]).
    src_pad: int32[E_pad] (E_pad % EDGE_BLOCK == 0; padding points at an
    always-zero word). Returns int32[E_pad]; prefix[-1] is the active total.
    """
    e_pad = src_pad.shape[0]
    assert e_pad % EDGE_BLOCK == 0
    rows = e_pad // _LANES
    rblk = EDGE_BLOCK // _LANES
    src2 = src_pad.reshape(rows, _LANES)
    out = pl.pallas_call(
        partial(_prefix_kernel, chunks=chunks),
        grid=(rows // rblk,),
        in_specs=[
            pl.BlockSpec((chunks * 8, _LANES), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rblk, _LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((rblk, _LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows, _LANES), jnp.int32),
        scratch_shapes=[pltpu.SMEM((1,), jnp.int32)],
        interpret=_use_interpret(),
    )(words, src2)
    return out.reshape(e_pad)


@jax.jit
def active_prefix_sparse(ftab: jax.Array, src_pad: jax.Array) -> jax.Array:
    """Sparse-frontier inclusive prefix (ftab: (33,128) 2-level layout)."""
    e_pad = src_pad.shape[0]
    rows = e_pad // _LANES
    rblk = EDGE_BLOCK // _LANES
    src2 = src_pad.reshape(rows, _LANES)
    out = pl.pallas_call(
        _prefix_kernel_sparse,
        grid=(rows // rblk,),
        in_specs=[
            pl.BlockSpec((33, _LANES), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rblk, _LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((rblk, _LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows, _LANES), jnp.int32),
        scratch_shapes=[pltpu.SMEM((1,), jnp.int32)],
        interpret=_use_interpret(),
    )(ftab, src2)
    return out.reshape(e_pad)


def _frontier_table(frontier: jax.Array) -> jax.Array:
    """bool[num_nodes] (popcount <= FRONTIER_CAP) -> (33,128) search table."""
    imax = jnp.int32(np.iinfo(np.int32).max)
    flist = jnp.nonzero(frontier, size=FRONTIER_CAP, fill_value=imax)[0]
    flist = flist.astype(jnp.int32)        # sorted ascending, pads at end
    buckets = flist.reshape(_LANES, 32)
    seps = buckets[:, 31]                  # per-bucket max
    return jnp.concatenate([seps[None, :], buckets.T], axis=0)


class PullGraph(NamedTuple):
    """Device-resident pull-BFS layout of one predicate CSR."""

    in_src_pad: jax.Array       # int32[E_pad], sorted by destination
    in_indptr_dense: jax.Array  # int32[num_nodes+1] over ALL node ids
    num_nodes: int
    num_edges: int
    chunks: int


def prep_pull(subjects: np.ndarray, indptr: np.ndarray,
              indices: np.ndarray, num_nodes: int) -> PullGraph:
    """Host-side once-per-snapshot prep: transpose to dst-sorted in-edges
    with a DENSE per-node indptr (rows == node ids), pad the edge stream to
    the kernel block size pointing at an always-zero bitmap word."""
    E = len(indices)
    if E and int(np.max(indices)) >= num_nodes:
        raise ValueError(
            f"prep_pull: destination uid {int(np.max(indices))} >= "
            f"num_nodes={num_nodes}; pass num_nodes > max uid")
    if len(subjects) and int(np.max(subjects)) >= num_nodes:
        raise ValueError(
            f"prep_pull: subject uid {int(np.max(subjects))} >= "
            f"num_nodes={num_nodes}; pass num_nodes > max uid")
    src = np.repeat(subjects, np.diff(indptr)).astype(np.int64)
    order = np.argsort(indices, kind="stable")
    dst_sorted = np.asarray(indices)[order]
    src_sorted = src[order].astype(np.int32)
    counts = np.bincount(dst_sorted, minlength=num_nodes)
    iptr = np.zeros(num_nodes + 1, dtype=np.int32)
    np.cumsum(counts, out=iptr[1:])

    chunks = max(1, (num_nodes + NODES_PER_CHUNK - 1) // NODES_PER_CHUNK)
    if chunks * NODES_PER_CHUNK <= num_nodes:
        chunks += 1                  # pad node must be outside real uid space
    cap_nodes = chunks * NODES_PER_CHUNK
    pad_src = cap_nodes - 1          # beyond num_nodes: bit always 0
    e_pad = max(EDGE_BLOCK, -(-E // EDGE_BLOCK) * EDGE_BLOCK)
    src_pad = np.full(e_pad, pad_src, dtype=np.int32)
    src_pad[:E] = src_sorted
    return PullGraph(jnp.asarray(src_pad), jnp.asarray(iptr),
                     int(num_nodes), int(E), int(chunks))


def pack_words(mask: jax.Array, chunks: int) -> jax.Array:
    """bool[num_nodes] -> (chunks*8, 128) int32 bitmap (word w = nodes
    [32w, 32w+32), laid out row-major for the kernel's chunk windows)."""
    cap = chunks * NODES_PER_CHUNK
    m = jnp.zeros((cap,), jnp.int32).at[: mask.shape[0]].set(
        mask.astype(jnp.int32))
    m = m.reshape(chunks * WORDS_PER_CHUNK, 32)
    weights = jnp.left_shift(jnp.int32(1), jnp.arange(32, dtype=jnp.int32))
    return jnp.sum(m * weights, axis=1, dtype=jnp.int32).reshape(
        chunks * 8, _LANES)


class PullBFSResult(NamedTuple):
    visited: jax.Array       # bool[num_nodes]
    frontier: jax.Array      # bool[num_nodes]
    traversed: jax.Array     # int32


@partial(jax.jit, static_argnames=("hops", "chunks"))
def _k_hop_impl(in_src_pad: jax.Array, in_indptr_dense: jax.Array,
                seeds_mask: jax.Array, *, hops: int,
                chunks: int) -> PullBFSResult:
    def body(_i, carry):
        frontier, visited, traversed = carry
        fcount = jnp.sum(frontier, dtype=jnp.int32)

        def sparse_hop(f):
            return active_prefix_sparse(_frontier_table(f), in_src_pad)

        def dense_hop(f):
            return active_prefix(pack_words(f, chunks), in_src_pad,
                                 chunks=chunks)

        prefix = lax.cond(fcount <= FRONTIER_CAP, sparse_hop, dense_hop,
                          frontier)
        traversed = traversed + prefix[-1]
        bounds = jnp.take(prefix, in_indptr_dense - 1,
                          mode="clip")               # prefix[iptr-1], iptr>=0
        bounds = jnp.where(in_indptr_dense == 0, 0, bounds)
        reached = (bounds[1:] - bounds[:-1]) > 0     # [num_nodes]
        fresh = reached & ~visited
        return fresh, visited | fresh, traversed

    frontier, visited, traversed = lax.fori_loop(
        0, hops, body, (seeds_mask, seeds_mask, jnp.int32(0)))
    return PullBFSResult(visited, frontier, traversed)


def k_hop_pull_pallas(g: PullGraph, seeds_mask: jax.Array, *,
                      hops: int) -> PullBFSResult:
    """k-hop BFS with the Pallas active-prefix kernel per hop."""
    return _k_hop_impl(g.in_src_pad, g.in_indptr_dense, seeds_mask,
                       hops=hops, chunks=g.chunks)
