"""Pallas pull-BFS: the locality-blocked frontier-bit gather kernel.

This is the native kernel the reference implements as 146k lines of
generated SSE2 (bp128/unpack_amd64.s + worker/task.go:476-602 per-uid
posting iteration). PERF.md (round 1) measured XLA's element-granularity
gather at ~1000x below HBM bandwidth — every BFS formulation pays one
E-sized random gather per hop (frontier[in_src[e]]), so the pull kernel
topped out at ~36M edges/s. Here that gather runs inside a Pallas kernel
where it can't miss:

  - the frontier is a bit-packed bitmap: num_nodes bits = num_nodes/8
    bytes, VMEM-resident for the whole kernel (1M nodes = 128 KB). Zero
    HBM traffic for masks.
  - the bitmap is laid out as (CHUNKS, 1024) int32 words; 1024 words =
    one 8x128 int32 vreg, the unit Mosaic can gather from in one op. The
    kernel loops over chunks, gathering each edge's frontier word from
    the chunk that owns it (chunks = ceil(num_nodes / 32768); a scale-20
    graph needs 33 — ~5 VPU ops per edge per chunk).
  - the edge stream (in_src, sorted by destination) is the ONLY O(E) HBM
    traffic: 4 bytes in + 4 bytes out per edge, at streaming rate.
  - the kernel fuses the inclusive prefix-sum of the per-edge active
    flags (two-level lane/sublane scan + a sequential-grid carry in
    SMEM), so the XLA side needs no E-sized cumsum: per-node reachability
    is diff-of-prefix at the dense in-CSR row boundaries — node-sized.

Per hop:   active[e] = frontier_bit[in_src[e]]          (Pallas, streaming)
           prefix    = cumsum(active)                   (fused in kernel)
           reached_v = prefix[iptr[v+1]] - prefix[iptr[v]] > 0   (node-sized)
           frontier' = reached & ~visited               (node-sized)

Reference semantics preserved: `traversed` counts every out-edge of every
frontier node per hop (== active in-edges), and `visited` matches
traversal.k_hop_pull / the host BFS exactly (bench.py's equality gate).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dgraph_tpu.ops.csr import degrees as _csr_degrees
from dgraph_tpu.ops.csr import expand as _csr_expand

WORDS_PER_CHUNK = 1024          # one 8x128 int32 vreg
NODES_PER_CHUNK = WORDS_PER_CHUNK * 32
EDGE_BLOCK = 8192               # edges per grid step (64 x 128)
_LANES = 128


def _block_prefix(active: jax.Array) -> jax.Array:
    """Inclusive prefix sum of a (R, 128) int block in row-major order,
    computed as two triangular matmuls on the MXU (f32 is exact here:
    block totals are <= EDGE_BLOCK << 2^24). Mosaic lowers matmuls far
    better than narrow pad/concat scans."""
    R, L = active.shape
    af = active.astype(jnp.float32)
    kk = lax.broadcasted_iota(jnp.int32, (L, L), 0)
    jj = lax.broadcasted_iota(jnp.int32, (L, L), 1)
    upper = (kk <= jj).astype(jnp.float32)             # inclusive lane scan
    lane = lax.dot_general(af, upper, (((1,), (0,)), ((), ())),
                           preferred_element_type=jnp.float32)
    rr = lax.broadcasted_iota(jnp.int32, (R, R), 0)
    cc = lax.broadcasted_iota(jnp.int32, (R, R), 1)
    lower = (cc < rr).astype(jnp.float32)              # strictly-lower: rows before
    row_sums = jnp.sum(af, axis=1, keepdims=True)      # (R, 1)
    row_off = lax.dot_general(lower, row_sums, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    return (lane + row_off).astype(jnp.int32)


def _prefix_kernel(words_ref, src_ref, out_ref, carry_ref, *, chunks: int):
    """One grid step: EDGE_BLOCK edges -> inclusive active-prefix values.

    The frontier-word lookup runs as a chunk loop: each chunk is 1024 words
    laid out (8, 128); Mosaic's dynamic_gather handles the lane dimension
    (take_along_axis along axis=1, single-vreg form) and an 8-way masked
    select handles the sublane row (masks hoisted out of the chunk loop) —
    zero HBM traffic for the bitmap (VMEM-resident throughout)."""
    blk = pl.program_id(0)

    @pl.when(blk == 0)
    def _():
        carry_ref[0] = 0

    # bit-plane word layout (see pack_words): node n lives in chunk n>>15,
    # panel row (n>>12)&7, lane n&127, bit (n>>7)&31 — chosen so packing a
    # node mask into words is 32 lane-aligned shift-ors, not a 32-wide
    # cross-lane reduction
    src = src_ref[:]                                   # (R, 128) int32
    bit = jnp.bitwise_and(lax.shift_right_logical(src, 7), 31)
    cidx = lax.shift_right_logical(src, 15)            # owning chunk
    col = jnp.bitwise_and(src, _LANES - 1)
    row = jnp.bitwise_and(lax.shift_right_logical(src, 12), 7)
    row_masks = [row == r for r in range(8)]           # hoisted: 8 ops total

    def body(c, acc):
        cw = words_ref[pl.ds(c * 8, 8), :]             # (8,128): 1024 words
        cmask = cidx == c
        for r in range(8):
            row_r = jnp.broadcast_to(cw[r : r + 1, :], src.shape)
            g = jnp.take_along_axis(row_r, col, axis=1)    # in-vreg gather
            acc = jnp.where(row_masks[r] & cmask, g, acc)
        return acc

    wordv = lax.fori_loop(0, chunks, body, jnp.zeros_like(src))
    active = jnp.bitwise_and(lax.shift_right_logical(wordv, bit), 1)

    # inclusive scan in row-major (flattened-edge) order + sequential carry
    prefix = _block_prefix(active) + carry_ref[0]
    out_ref[:] = prefix
    carry_ref[0] = prefix[prefix.shape[0] - 1, _LANES - 1]


FRONTIER_CAP = 4096    # sparse-path capacity: 128 buckets x 32 entries


def _prefix_kernel_sparse(ftab_ref, src_ref, out_ref, carry_ref):
    """Sparse-frontier variant: membership test against a sorted frontier
    list (<= FRONTIER_CAP uids) in a 2-level 128-ary layout instead of the
    full-bitmap chunk loop — ~5x fewer VPU ops per edge, the win for the
    early BFS hops where the frontier is small.

    ftab layout (33, 128): row 0 = per-bucket max (bucket g = sorted
    frontier[32g:32g+32]); rows 1+j = element j of every bucket. Padding
    slots hold INT32_MAX (never equal to a real uid)."""
    blk = pl.program_id(0)

    @pl.when(blk == 0)
    def _():
        carry_ref[0] = 0

    src = src_ref[:]                                   # (R, 128) int32
    seps = jnp.broadcast_to(ftab_ref[0:1, :], src.shape)

    # branchless lower-bound over the 128 bucket separators:
    # first bucket g with max(bucket g) >= src
    b = jnp.zeros_like(src)
    for k in (64, 32, 16, 8, 4, 2, 1):
        cand = b + k
        sep = jnp.take_along_axis(seps, jnp.minimum(cand - 1, _LANES - 1),
                                  axis=1)
        b = jnp.where(sep < src, cand, b)
    b = jnp.minimum(b, _LANES - 1)

    # equality scan of the 32 entries of the selected bucket
    active = jnp.zeros_like(src)
    for j in range(32):
        lane = jnp.broadcast_to(ftab_ref[1 + j : 2 + j, :], src.shape)
        v = jnp.take_along_axis(lane, b, axis=1)
        active = jnp.bitwise_or(active, (v == src).astype(jnp.int32))

    prefix = _block_prefix(active) + carry_ref[0]
    out_ref[:] = prefix
    carry_ref[0] = prefix[prefix.shape[0] - 1, _LANES - 1]


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


@partial(jax.jit, static_argnames=("chunks",))
def active_prefix(words: jax.Array, src_pad: jax.Array, *,
                  chunks: int) -> jax.Array:
    """Inclusive prefix-count of frontier-active edges.

    words: (chunks*8, 128) int32 frontier bitmap (word w at [w>>7, w&127]).
    src_pad: int32[E_pad] (E_pad % EDGE_BLOCK == 0; padding points at an
    always-zero word). Returns int32[E_pad]; prefix[-1] is the active total.
    """
    e_pad = src_pad.shape[0]
    assert e_pad % EDGE_BLOCK == 0
    rows = e_pad // _LANES
    rblk = EDGE_BLOCK // _LANES
    src2 = src_pad.reshape(rows, _LANES)
    out = pl.pallas_call(
        partial(_prefix_kernel, chunks=chunks),
        grid=(rows // rblk,),
        in_specs=[
            pl.BlockSpec((chunks * 8, _LANES), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rblk, _LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((rblk, _LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows, _LANES), jnp.int32),
        scratch_shapes=[pltpu.SMEM((1,), jnp.int32)],
        interpret=_use_interpret(),
    )(words, src2)
    return out.reshape(e_pad)


@jax.jit
def active_prefix_sparse(ftab: jax.Array, src_pad: jax.Array) -> jax.Array:
    """Sparse-frontier inclusive prefix (ftab: (33,128) 2-level layout)."""
    e_pad = src_pad.shape[0]
    rows = e_pad // _LANES
    rblk = EDGE_BLOCK // _LANES
    src2 = src_pad.reshape(rows, _LANES)
    out = pl.pallas_call(
        _prefix_kernel_sparse,
        grid=(rows // rblk,),
        in_specs=[
            pl.BlockSpec((33, _LANES), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rblk, _LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((rblk, _LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows, _LANES), jnp.int32),
        scratch_shapes=[pltpu.SMEM((1,), jnp.int32)],
        interpret=_use_interpret(),
    )(ftab, src2)
    return out.reshape(e_pad)


def _frontier_table(frontier: jax.Array) -> jax.Array:
    """bool[num_nodes] (popcount <= FRONTIER_CAP) -> (33,128) search table."""
    imax = jnp.int32(np.iinfo(np.int32).max)
    flist = jnp.nonzero(frontier, size=FRONTIER_CAP, fill_value=imax)[0]
    flist = flist.astype(jnp.int32)        # sorted ascending, pads at end
    buckets = flist.reshape(_LANES, 32)
    seps = buckets[:, 31]                  # per-bucket max
    return jnp.concatenate([seps[None, :], buckets.T], axis=0)


class PullGraph(NamedTuple):
    """Device-resident pull-BFS layout of one predicate CSR.

    Both endpoint spaces are RANK-COMPRESSED: the kernel gathers frontier
    bits by source *rank* (position in the sorted out-degree>0 subject list)
    and reachability is computed per destination *rank* — power-law graphs
    leave ~half the uid space with no edges at all, so rank spaces halve the
    bitmap chunk loop (the kernel's per-edge cost), the frontier pack, and
    the node-phase bounds gather. One full-uid-space scatter at the very end
    restores the reference's visited/frontier semantics."""

    in_src_pad: jax.Array       # int32[E_pad] source SRC-RANKS, dst-sorted
    in_src_pad_d: jax.Array     # int32[E_pad] source DST-RANKS, dst-sorted
    in_iptr_rank: jax.Array     # int32[Nd+1] edge offsets per dst rank
    subjects: jax.Array         # int32[Ns] sorted uids with out-edges
    in_subjects: jax.Array      # int32[Nd] sorted uids with in-edges
    map_s2d: jax.Array          # int32[Ns] dst rank of subject j, or Nd
    fwd_indptr: jax.Array       # int32[Ns+1] forward CSR (push path)
    fwd_dst_rank: jax.Array     # int32[E] dst RANKS in forward edge order
    map_d2s: jax.Array          # int32[Nd] src rank of dst i, or SENTINEL
    num_nodes: int
    num_edges: int
    chunks: int                 # bitmap chunks over the SRC-RANK space
    chunks_d: int               # bitmap chunks over the DST-RANK space
    inv_order: np.ndarray | None = None  # HOST int32[E]: fwd edge position →
    # dst-sorted edge position (the kernel's per-edge flag space); used to
    # materialize per-source fresh-target lists lazily (recurse uidMatrix)
    host_in_iptr: np.ndarray | None = None     # HOST int32[Nd+1]
    host_in_src: np.ndarray | None = None      # HOST int32[E] src ranks,
    # dst-sorted — the in-adjacency the shortest-path backtrack walks
    host_map_s2d: np.ndarray | None = None     # HOST int32[Ns]
    host_in_subjects: np.ndarray | None = None  # HOST int64[Nd]
    host_subjects: np.ndarray | None = None     # HOST int64[Ns]


def prep_pull(subjects: np.ndarray, indptr: np.ndarray,
              indices: np.ndarray, num_nodes: int,
              with_host_arrays: bool = False) -> PullGraph:
    """Host-side once-per-snapshot prep: transpose to dst-sorted in-edges,
    remap both endpoints to rank spaces, pad the edge stream to the kernel
    block size pointing at an always-zero bitmap word."""
    E = len(indices)
    if E and int(np.max(indices)) >= num_nodes:
        raise ValueError(
            f"prep_pull: destination uid {int(np.max(indices))} >= "
            f"num_nodes={num_nodes}; pass num_nodes > max uid")
    if len(subjects) and int(np.max(subjects)) >= num_nodes:
        raise ValueError(
            f"prep_pull: subject uid {int(np.max(subjects))} >= "
            f"num_nodes={num_nodes}; pass num_nodes > max uid")
    subjects = np.asarray(subjects)
    src = np.repeat(np.arange(len(subjects), dtype=np.int64),
                    np.diff(indptr))                 # source RANK per edge
    order = np.argsort(np.asarray(indices), kind="stable")
    dst_sorted = np.asarray(indices)[order]
    src_sorted = src[order].astype(np.int32)
    in_subjects, counts = np.unique(dst_sorted, return_counts=True)
    nd = len(in_subjects)
    iptr = np.zeros(nd + 1, dtype=np.int32)
    np.cumsum(counts, out=iptr[1:])
    from dgraph_tpu.ops.uidset import host_rank_of

    # subject rank -> dst rank (Nd = "not a destination" sentinel slot)
    map_s2d = host_rank_of(in_subjects, subjects, nd).astype(np.int32)

    def _chunks_for(n):
        c = max(1, (n + NODES_PER_CHUNK - 1) // NODES_PER_CHUNK)
        if c * NODES_PER_CHUNK <= n:
            c += 1                   # pad rank must be outside real ranks
        return c

    ns = len(subjects)
    chunks = _chunks_for(ns)
    pad_src = chunks * NODES_PER_CHUNK - 1     # beyond Ns: bit always 0
    e_pad = max(EDGE_BLOCK, -(-E // EDGE_BLOCK) * EDGE_BLOCK)
    src_pad = np.full(e_pad, pad_src, dtype=np.int32)
    src_pad[:E] = src_sorted

    # dst-rank-space edge stream: after hop 1 the frontier is always a
    # subset of the destinations, so the kernel can gather bits straight
    # from the fresh dst-rank mask — no src<->dst remap gather per hop.
    # Sources that are never destinations can't be in a hop>=2 frontier;
    # their edges point at the always-zero pad word.
    chunks_d = _chunks_for(nd)
    pad_src_d = chunks_d * NODES_PER_CHUNK - 1
    src_d = map_s2d[src_sorted]                # Nd = "not a destination"
    src_d = np.where(src_d == nd, pad_src_d, src_d).astype(np.int32)
    src_pad_d = np.full(e_pad, pad_src_d, dtype=np.int32)
    src_pad_d[:E] = src_d

    # push-path (direction-optimizing) forward layout
    fwd_dst_rank = np.searchsorted(in_subjects, np.asarray(indices)).astype(
        np.int32)                    # every dst IS in in_subjects
    snt = np.int32(np.iinfo(np.int32).max)
    map_d2s = host_rank_of(subjects, in_subjects, snt).astype(np.int32)
    inv_order = hi_iptr = hi_src = hi_m = hi_subs = hi_fsubs = None
    if with_host_arrays:     # engine paths only (recurse materialization +
        # shortest backtrack); bench/BFS callers skip the host RAM
        inv_order = np.empty(E, dtype=np.int32)
        inv_order[order] = np.arange(E, dtype=np.int32)
        hi_iptr, hi_src = iptr, src_sorted
        hi_m, hi_subs = map_s2d, in_subjects.astype(np.int64)
        hi_fsubs = subjects.astype(np.int64)
    return PullGraph(jnp.asarray(src_pad), jnp.asarray(src_pad_d),
                     jnp.asarray(iptr),
                     jnp.asarray(subjects.astype(np.int32)),
                     jnp.asarray(in_subjects.astype(np.int32)),
                     jnp.asarray(map_s2d),
                     jnp.asarray(np.asarray(indptr).astype(np.int32)),
                     jnp.asarray(fwd_dst_rank),
                     jnp.asarray(map_d2s),
                     int(num_nodes), int(E), int(chunks), int(chunks_d),
                     inv_order, hi_iptr, hi_src, hi_m, hi_subs,
                     hi_fsubs)


def pack_words(mask: jax.Array, chunks: int) -> jax.Array:
    """bool[num_nodes] -> (chunks*8, 128) int32 bitmap, BIT-PLANE layout:
    word at [p, l] holds bit b for node p*4096 + b*128 + l. Packing is then
    32 lane-aligned shift-ors over (rows, 128) slices — the natural VPU
    shape — instead of a 32-wide cross-lane weighted reduction (~8x faster
    measured). The kernel's (chunk, row, lane, bit) decode matches."""
    cap = chunks * NODES_PER_CHUNK
    m = jnp.zeros((cap,), jnp.int32).at[: mask.shape[0]].set(
        mask.astype(jnp.int32))
    m3 = m.reshape(chunks * 8, 32, _LANES)
    words = m3[:, 0, :]
    for b in range(1, 32):
        words = jnp.bitwise_or(words, jnp.left_shift(m3[:, b, :], b))
    return words


class PullBFSResult(NamedTuple):
    visited: jax.Array       # bool[num_nodes]
    frontier: jax.Array      # bool[num_nodes]
    traversed: jax.Array     # int32


PUSH_CAP = 1 << 17     # push-path edge-gather capacity (targets buffer)
SPARSE_MAX = FRONTIER_CAP   # frontier popcount at/below which the sparse
                            # search-table kernel beats pack+dense (tunable)


@partial(jax.jit, static_argnames=("hops", "chunks", "chunks_d", "num_nodes",
                                   "have_seeds"))
def _k_hop_impl(in_src_pad: jax.Array, in_src_pad_d: jax.Array,
                in_iptr_rank: jax.Array,
                subjects: jax.Array, in_subjects: jax.Array,
                map_s2d: jax.Array, fwd_indptr: jax.Array,
                fwd_dst_rank: jax.Array, map_d2s: jax.Array,
                seeds_mask: jax.Array, seeds_ranks: jax.Array, *, hops: int,
                chunks: int, chunks_d: int, num_nodes: int,
                have_seeds: bool) -> PullBFSResult:
    """Direction-optimizing hop loop, entirely in rank spaces.

    Three regimes per hop (Beamer-style DOBFS, chosen at runtime):
      push   — frontier known as an explicit src-rank list (<= FRONTIER_CAP)
               with bounded degree sum: gather ONLY its out-edges through
               the forward CSR (work ∝ frontier, not E) and scatter the
               targets; the next list comes from the targets themselves.
      sparse — mask frontier, <= FRONTIER_CAP bits set: stream E against a
               2-level search table in the Pallas kernel.
      dense  — mask frontier: stream E against the packed VMEM bitmap.

    Carry: fresh set by DESTINATION rank (the only uids ever reachable),
    visited by dst rank, plus the push list + validity flag. The mask paths
    map fresh dst-ranks to src-rank bits lazily at the START of the next
    hop (so the final hop never pays it). Hop 1 is special in both paths: a
    seed with out-edges but no in-edges must still expand, so the mask path
    seeds src bits from the full-space seed mask and the push path takes
    pre-mapped seed src-ranks."""
    if hops == 0:
        # degenerate: no expansion — frontier IS the seed set (the old
        # fori_loop(0, 0) carry-through behavior, kept for callers that
        # treat frontier as "nodes at distance exactly k")
        return PullBFSResult(seeds_mask, seeds_mask, jnp.int32(0))

    nd = in_subjects.shape[0]
    snt = jnp.int32(np.iinfo(np.int32).max)

    def push_hop(args, build_next: bool):
        flist, _fresh_d, visited_d, traversed = args
        res = _csr_expand(fwd_indptr, fwd_dst_rank, flist, PUSH_CAP)
        traversed = traversed + res.total.astype(jnp.int32)
        tmask = jnp.zeros((nd,), bool).at[res.targets].set(
            True, mode="drop")                     # sentinel pads drop
        fresh = tmask & ~visited_d
        visited2 = visited_d | fresh
        if build_next:
            tsort = jnp.sort(res.targets)          # sentinels collect at end
            valid = tsort < nd
            dup = jnp.concatenate(
                [jnp.zeros((1,), bool), tsort[1:] == tsort[:-1]])
            was = jnp.take(visited_d, jnp.clip(tsort, 0, max(nd - 1, 0)),
                           mode="clip") & valid
            keep = valid & ~dup & ~was
            nfresh = jnp.sum(keep, dtype=jnp.int32)
            idxs = jnp.nonzero(keep, size=FRONTIER_CAP,
                               fill_value=PUSH_CAP)[0]
            cand_d = jnp.where(idxs < PUSH_CAP,
                               jnp.take(tsort, jnp.clip(idxs, 0, PUSH_CAP - 1),
                                        mode="clip"), nd)
            flist2 = jnp.where(cand_d < nd,
                               jnp.take(map_d2s, jnp.clip(cand_d, 0,
                                                          max(nd - 1, 0)),
                                        mode="clip"), snt)
            ok2 = nfresh <= FRONTIER_CAP
        else:
            flist2, ok2 = flist, jnp.bool_(False)
        return flist2, ok2, fresh, visited2, traversed

    def mask_hop(args, first: bool):
        flist, fresh_d, visited_d, traversed = args
        if first:
            # src-rank space: a seed with out-edges but no in-edges exists
            # only here
            frontier, stream, n_chunks = (
                jnp.take(seeds_mask, subjects), in_src_pad, chunks)
        else:
            # dst-rank space: a hop>=2 frontier is a subset of destinations,
            # so the fresh mask IS the kernel's bitmap — no remap gather
            frontier, stream, n_chunks = fresh_d, in_src_pad_d, chunks_d
        prefix = _prefix_for(frontier, stream, n_chunks)
        traversed = traversed + prefix[-1]
        bounds = jnp.take(prefix, in_iptr_rank - 1,
                          mode="clip")               # prefix[iptr-1], iptr>=0
        bounds = jnp.where(in_iptr_rank == 0, 0, bounds)
        reached = (bounds[1:] - bounds[:-1]) > 0     # [Nd]
        fresh = reached & ~visited_d
        return flist, jnp.bool_(False), fresh, visited_d | fresh, traversed

    visited_d = jnp.take(seeds_mask, in_subjects)    # seeds, dst-rank space
    fresh_d = jnp.zeros((nd,), dtype=bool)
    traversed = jnp.int32(0)
    flist = seeds_ranks if have_seeds else jnp.full(
        (FRONTIER_CAP,), snt, jnp.int32)
    flist_ok = jnp.bool_(bool(have_seeds))

    carry = (flist, flist_ok, fresh_d, visited_d, traversed)
    for h in range(hops):                            # hops is static + small
        flist, flist_ok, fresh_d, visited_d, traversed = carry
        deg_sum = jnp.sum(_csr_degrees(fwd_indptr, flist), dtype=jnp.int32)
        push_ok = flist_ok & (deg_sum <= PUSH_CAP)
        build_next = h + 1 < hops
        carry = lax.cond(
            push_ok,
            partial(push_hop, build_next=build_next),
            partial(mask_hop, first=(h == 0)),
            (flist, fresh_d, visited_d, traversed))
    _flist, _ok, fresh_d, visited_d, traversed = carry

    # restore full-uid-space semantics (once, not per hop): one combined
    # 2-bit scatter instead of two (scatter cost scales with index count)
    both = (visited_d.astype(jnp.int32)
            | (fresh_d.astype(jnp.int32) << 1))
    packed = jnp.zeros((num_nodes,), jnp.int32).at[in_subjects].set(
        both, mode="drop")
    visited = seeds_mask | ((packed & 1) > 0)
    frontier = (packed & 2) > 0
    return PullBFSResult(visited, frontier, traversed)


def k_hop_pull_pallas(g: PullGraph, seeds_mask: jax.Array, *, hops: int,
                      seed_uids: jax.Array | np.ndarray | None = None
                      ) -> PullBFSResult:
    """k-hop BFS with the Pallas active-prefix kernel per hop.

    seed_uids: optional explicit seed uid list (<= FRONTIER_CAP entries,
    must match seeds_mask) — enables the push fast path for hop 1 without
    paying a full-space compaction."""
    if seed_uids is not None:
        # dedup: a repeated seed would be expanded once per occurrence by
        # the push path, inflating traversed and the PUSH_CAP admission
        seed_uids = np.unique(np.asarray(seed_uids))
    if seed_uids is not None and len(seed_uids) <= FRONTIER_CAP:
        seeds = jnp.asarray(seed_uids, dtype=jnp.int32)
        pos = jnp.searchsorted(g.subjects, seeds)
        pos_c = jnp.clip(pos, 0, max(g.subjects.shape[0] - 1, 0))
        hit = (g.subjects.shape[0] > 0) & (
            jnp.take(g.subjects, pos_c, mode="clip") == seeds)
        ranks = jnp.where(hit, pos_c.astype(jnp.int32),
                          jnp.int32(np.iinfo(np.int32).max))
        pad = jnp.full((FRONTIER_CAP - seeds.shape[0],),
                       np.iinfo(np.int32).max, jnp.int32)
        seeds_ranks = jnp.concatenate([ranks, pad])
        have_seeds = True
    else:
        seeds_ranks = jnp.full((FRONTIER_CAP,), np.iinfo(np.int32).max,
                               jnp.int32)
        have_seeds = False
    return _k_hop_impl(g.in_src_pad, g.in_src_pad_d, g.in_iptr_rank,
                       g.subjects, g.in_subjects, g.map_s2d, g.fwd_indptr,
                       g.fwd_dst_rank, g.map_d2s, seeds_mask, seeds_ranks,
                       hops=hops, chunks=g.chunks, chunks_d=g.chunks_d,
                       num_nodes=g.num_nodes, have_seeds=have_seeds)


# ---------------------------------------------------------------------------
# edge-dedup traversal: the production @recurse path (reference
# query/recurse.go:31-177 expandRecurse). Unlike BFS (node-visited), recurse
# dedups EDGES: a node reached again over a never-traversed edge re-appears
# at the deeper level. The kernel's fused active-prefix provides exactly the
# per-edge active flags edge-dedup needs; "seen" is a bool vector over the
# dst-sorted edge stream carried on device across levels.
# ---------------------------------------------------------------------------


def pull_graph_for(csr) -> PullGraph:
    """Cached PullGraph for a storage PredCSR (one host prep per snapshot)."""
    g = getattr(csr, "_pull_graph", None)
    if g is None:
        subjects, indptr, indices = csr.host_arrays()
        hi = max(int(subjects[-1]) if len(subjects) else 0,
                 int(indices.max()) if len(indices) else 0)
        g = prep_pull(np.asarray(subjects), np.asarray(indptr),
                      np.asarray(indices), hi + 1, with_host_arrays=True)
        csr._pull_graph = g
    return g


@jax.jit
def pack_mask_rows(masks: jax.Array) -> jax.Array:
    """Row-wise pack_mask for a stacked [D, n] bool buffer — ONE dispatch
    and one fetch for every level's flags."""
    return jax.vmap(lambda m: pack_words(m, pack_chunks(masks.shape[1])))(
        masks)


def pack_chunks(n: int) -> int:
    """Minimal chunk count whose word capacity covers n bits (pure packing —
    no kernel pad-rank slot needed)."""
    return max(1, (n + NODES_PER_CHUNK - 1) // NODES_PER_CHUNK)


@jax.jit
def pack_mask(mask: jax.Array) -> jax.Array:
    """Bit-pack a bool vector for a host fetch (8x fewer relay bytes)."""
    return pack_words(mask, pack_chunks(mask.shape[0]))


def unpack_words(words: np.ndarray, n: int) -> np.ndarray:
    """Host inverse of pack_words' bit-plane layout: word [p, l] bit b holds
    node p*4096 + b*128 + l. Device→host results ride the relay bit-packed
    (~8x fewer bytes than bool; the relay moves ~6-8 MB/s — measured r5)."""
    w = np.asarray(words)
    bits = (w[:, None, :] >> np.arange(32, dtype=np.int32)[None, :, None]) & 1
    return bits.reshape(-1)[:n].astype(bool)


def _prefix_for(frontier_bits, stream, n_chunks: int):
    """Active-edge inclusive prefix for one frontier (sparse search-table
    kernel below SPARSE_MAX set bits, dense bitmap kernel above)."""
    fcount = jnp.sum(frontier_bits, dtype=jnp.int32)

    def sparse_hop(f):
        return active_prefix_sparse(_frontier_table(f), stream)

    def dense_hop(f):
        return active_prefix(pack_words(f, n_chunks), stream,
                             chunks=n_chunks)

    return lax.cond(fcount <= SPARSE_MAX, sparse_hop, dense_hop,
                    frontier_bits)


def _recurse_tail(prefix, in_iptr_rank, seen, allow_loop: bool):
    """Shared prefix→(reached_d, traversed, seen', fresh) tail: edge-dedup
    plus the bounds-diff reachability (the exactness-critical piece, kept
    in ONE place for the fused and stepped paths alike)."""
    traversed = prefix[-1]
    prev = jnp.concatenate([jnp.zeros((1,), jnp.int32), prefix[:-1]])
    active = (prefix - prev) > 0                           # bool[E_pad]
    if allow_loop:
        fresh, seen2 = active, seen
    else:
        fresh = active & ~seen
        seen2 = seen | active
    freshp = jnp.cumsum(fresh.astype(jnp.int32))
    bounds = jnp.take(freshp, in_iptr_rank - 1, mode="clip")
    bounds = jnp.where(in_iptr_rank == 0, 0, bounds)
    reached = (bounds[1:] - bounds[:-1]) > 0               # [Nd]
    return reached, traversed, seen2, fresh


def _recurse_level_core(fbits, stream, n_chunks: int, in_iptr_rank, seen,
                        allow_loop: bool):
    """One recurse level in RANK space: frontier bits (in the stream's
    source-ID space) → (reached_d [Nd], traversed, seen', fresh).
    traversed counts EVERY out-edge of every frontier node (the budget the
    reference charges, recurse.go:167); fresh marks first-traversal edges;
    reached_d = dst ranks with >= 1 fresh in-edge."""
    prefix = _prefix_for(fbits, stream, n_chunks)
    return _recurse_tail(prefix, in_iptr_rank, seen, allow_loop)


def _recurse_level(in_src_pad, in_iptr_rank, subjects, in_subjects,
                   frontier_mask, seen, *, chunks: int, num_nodes: int,
                   allow_loop: bool):
    """Full-uid-space recurse level (stepped path: multi-predicate
    frontiers are not confined to this predicate's destinations)."""
    fbits = jnp.take(frontier_mask, subjects)              # [Ns] rank space
    reached, traversed, seen2, fresh = _recurse_level_core(
        fbits, in_src_pad, chunks, in_iptr_rank, seen, allow_loop)
    dest_mask = jnp.zeros((num_nodes,), bool).at[in_subjects].set(
        reached, mode="drop")
    return dest_mask, traversed, seen2, fresh


@partial(jax.jit, static_argnames=("chunks", "num_nodes", "allow_loop"))
def recurse_step(in_src_pad, in_iptr_rank, subjects, in_subjects,
                 frontier_mask, seen, *, chunks: int, num_nodes: int,
                 allow_loop: bool):
    """Single stepped level (used when filters / multiple recurse children
    force host control between levels). Host-bound outputs (dest mask,
    fresh flags) come back BIT-PACKED — the relay fetch is the latency
    floor of a single query, not the kernel."""
    dest, trav, seen2, fresh = _recurse_level(
        in_src_pad, in_iptr_rank, subjects, in_subjects, frontier_mask, seen,
        chunks=chunks, num_nodes=num_nodes, allow_loop=allow_loop)
    dest_p = pack_words(dest, pack_chunks(num_nodes))
    return dest_p, trav, seen2, fresh


_DIST_BITS = 8          # BFS distance planes (max_hops clamped below 255)
DIST_UNREACHED = (1 << _DIST_BITS) - 1


@partial(jax.jit, static_argnames=("chunks", "chunks_d"))
def bfs_dist(in_src_pad, in_src_pad_d, in_iptr_rank, subjects, in_subjects,
             seeds_mask, dst_rank, max_hops, *, chunks: int, chunks_d: int):
    """Unweighted single-source BFS distances, early-exiting when dst is
    reached — the kernel behind `shortest` on large CSRs (replaces the
    Bellman-Ford E-gather of ops/traversal.sssp, which runs ~1000x below
    HBM bandwidth per PERF.md; here each hop is one Pallas E-stream).

    The whole hop loop runs in ONE dispatch (lax.while_loop); per-dst-rank
    distances return BIT-PACKED as 8 bit planes (value DIST_UNREACHED =
    never reached), so the host fetch is ~Nd bits, not Nd ints. The host
    walks the predecessor chain itself from the distance labels (each
    step scans one node's in-edge slice — microseconds)."""
    nd = in_subjects.shape[0]
    visited0 = jnp.take(seeds_mask, in_subjects)           # [Nd]
    dist0 = jnp.where(visited0, 0, DIST_UNREACHED).astype(jnp.int32)
    fresh0 = jnp.zeros((nd,), dtype=bool)

    def cond(c):
        h, fresh, _visited, _dist, found = c
        return (~found) & (h < max_hops) & ((h == 0) | fresh.any())

    def body(c):
        h, fresh, visited, dist, _found = c

        def first_hop(_):
            return _prefix_for(jnp.take(seeds_mask, subjects), in_src_pad,
                               chunks)

        def later_hop(_):
            # a hop>=2 frontier is a subset of destinations: gather bits
            # straight from the fresh dst-rank mask (no remap gather)
            return _prefix_for(fresh, in_src_pad_d, chunks_d)

        prefix = lax.cond(h == 0, first_hop, later_hop, None)
        bounds = jnp.take(prefix, in_iptr_rank - 1, mode="clip")
        bounds = jnp.where(in_iptr_rank == 0, 0, bounds)
        reached = (bounds[1:] - bounds[:-1]) > 0
        fresh2 = reached & ~visited
        visited2 = visited | fresh2
        dist2 = jnp.where(fresh2, h + 1, dist)
        found2 = jnp.take(visited2, dst_rank)
        return h + 1, fresh2, visited2, dist2, found2

    h, _f, _v, dist, found = lax.while_loop(
        cond, body, (jnp.int32(0), fresh0, visited0, dist0,
                     jnp.take(visited0, dst_rank)))
    planes = jnp.stack([
        pack_words(((dist >> b) & 1).astype(bool), pack_chunks(nd))
        for b in range(_DIST_BITS)])
    return planes, found, h


def shortest_bfs(g: PullGraph, src: int, dst: int, max_hops: int):
    """Host orchestration: run bfs_dist, fetch packed distances once, walk
    the predecessor chain on the host in-adjacency. Returns the uid path
    [src..dst] or None (unreachable within max_hops). Requires a PullGraph
    built with host arrays (pull_graph_for)."""
    nd = len(g.host_in_subjects)
    if nd == 0:
        return None
    dr = int(np.searchsorted(g.host_in_subjects, dst))
    if dr >= nd or g.host_in_subjects[dr] != dst:
        return None              # dst has no in-edges: unreachable
    max_hops = min(int(max_hops), DIST_UNREACHED - 1)
    seeds_mask = jnp.zeros((g.num_nodes,), dtype=bool)
    if src >= g.num_nodes:
        return None
    seeds_mask = seeds_mask.at[src].set(True)
    planes, found, _h = bfs_dist(
        g.in_src_pad, g.in_src_pad_d, g.in_iptr_rank, g.subjects,
        g.in_subjects, seeds_mask, jnp.int32(dr), jnp.int32(max_hops),
        chunks=g.chunks, chunks_d=g.chunks_d)
    planes_h, found_h = jax.device_get((planes, found))  # ONE round-trip
    if not bool(found_h):
        return None
    dist = np.zeros(nd, dtype=np.int32)
    for b in range(_DIST_BITS):
        dist |= unpack_words(planes_h[b], nd).astype(np.int32) << b

    iptr, in_src = g.host_in_iptr, g.host_in_src
    map_s2d = g.host_map_s2d
    sub_uids = g.host_subjects   # uid of a src rank

    path = [dst]
    v_rank = dr
    for d in range(int(dist[dr]), 0, -1):
        srcs = in_src[iptr[v_rank]: iptr[v_rank + 1]]     # src RANKS
        if d == 1:
            # predecessor must be the seed itself
            cand = srcs[sub_uids[srcs] == src]
            if len(cand) == 0:
                return None      # inconsistent labels (cannot happen)
            path.append(src)
            break
        m = map_s2d[srcs]
        ok = (m < nd)
        ok[ok] = dist[m[ok]] == d - 1
        cand = srcs[ok]
        if len(cand) == 0:
            return None          # inconsistent labels (cannot happen)
        u_rank = int(cand[0])
        path.append(int(sub_uids[u_rank]))
        v_rank = int(map_s2d[u_rank])
    return path[::-1]


def _recurse_fused_levels(in_src_pad, in_src_pad_d, in_iptr_rank, subjects,
                          in_subjects, seeds_mask, *, depth: int, chunks: int,
                          chunks_d: int, allow_loop: bool):
    """Traced body shared by recurse_fused (one seed mask) and
    recurse_fused_multi (a stacked batch of seed masks): all `depth`
    levels as one lax.scan over the SAME per-level kernel."""
    nd = in_subjects.shape[0]

    def body(carry, i):
        fresh_d, seen = carry
        # hop 1 reads seed bits in src-rank space; hops >= 2 read the
        # previous level's fresh dst-rank mask against the dst-rank stream
        prefix = lax.cond(
            i == 0,
            lambda _: _prefix_for(jnp.take(seeds_mask, subjects),
                                  in_src_pad, chunks),
            lambda _: _prefix_for(fresh_d, in_src_pad_d, chunks_d),
            None)
        reached, traversed, seen2, fresh = _recurse_tail(
            prefix, in_iptr_rank, seen, allow_loop)
        dest_p = pack_words(reached, pack_chunks(nd))
        return (reached, seen2), (dest_p, traversed, fresh)

    seen0 = jnp.zeros((in_src_pad.shape[0],), dtype=bool)  # device-side alloc
    fresh0 = jnp.zeros((nd,), dtype=bool)
    (_m, _s), (masks_p, trav, fresh) = lax.scan(
        body, (fresh0, seen0), jnp.arange(depth), length=depth)
    return masks_p, trav, fresh


@partial(jax.jit, static_argnames=("depth", "chunks", "chunks_d",
                                   "allow_loop"))
def recurse_fused(in_src_pad, in_src_pad_d, in_iptr_rank, subjects,
                  in_subjects, seeds_mask, *, depth: int, chunks: int,
                  chunks_d: int, allow_loop: bool):
    """All `depth` levels in ONE dispatch (lax.scan): no host round-trip —
    and no relay sync — between levels. Single-predicate shape, so levels
    >= 2 stay entirely in DST-RANK space (a recurse frontier is the
    previous level's fresh destinations): no full-uid scatter, no src-rank
    remap gather, and the bitmap pack runs over the compressed rank space
    (the same dual-space trick as the BFS kernel's mask_hop).

    Returns stacked per-level (dest_words [D,Cd*8,128] BIT-PACKED
    DST-RANK masks — the host fetches these every query and the relay
    moves ~6-8 MB/s, so packed-and-rank-compressed is the cheapest wire
    form; traversed [D]; fresh [D,E_pad] bools that STAY on device until
    a lazy uidMatrix materialization packs+fetches them). Only for the
    single-uid-child no-filter recurse shape (the common + benchmarked
    one); anything needing host logic between levels uses recurse_step."""
    return _recurse_fused_levels(
        in_src_pad, in_src_pad_d, in_iptr_rank, subjects, in_subjects,
        seeds_mask, depth=depth, chunks=chunks, chunks_d=chunks_d,
        allow_loop=allow_loop)


@partial(jax.jit, static_argnames=("depth", "chunks", "chunks_d",
                                   "allow_loop"))
def recurse_fused_multi(in_src_pad, in_src_pad_d, in_iptr_rank, subjects,
                        in_subjects, seeds_masks, *, depth: int, chunks: int,
                        chunks_d: int, allow_loop: bool):
    """Multi-source batched recurse: seeds_masks [B, num_nodes] stacks B
    concurrent queries' seed masks and the whole batch runs as ONE device
    dispatch — the one-extra-dimension extension of recurse_fused the
    batched-dispatch tier launches (query/batch.py). lax.map over the
    exact recurse_fused body, so slice b of the stacked outputs is
    bit-identical to a solo recurse_fused call with seeds_masks[b] (the
    per-level ops are integer/boolean — no float reassociation). Each
    query keeps its own seen-edge vector: batching never entangles
    traversals. Returns (masks_p [B, depth, ...], traversed [B, depth],
    fresh [B, depth, E_pad])."""
    return lax.map(
        lambda sm: _recurse_fused_levels(
            in_src_pad, in_src_pad_d, in_iptr_rank, subjects, in_subjects,
            sm, depth=depth, chunks=chunks, chunks_d=chunks_d,
            allow_loop=allow_loop),
        seeds_masks)


# device-runtime observatory (obs/devprof.py, ISSUE 19): jitted entry
# points by program family, probed for live jit-cache size on
# /debug/compiles (see ops/segments.py).
JIT_PROGRAMS = {
    "pb.active_prefix": active_prefix,
    "pb.active_prefix_sparse": active_prefix_sparse,
    "pb.k_hop": _k_hop_impl,
    "pb.pack_mask_rows": pack_mask_rows,
    "pb.pack_mask": pack_mask,
    "pb.recurse_step": recurse_step,
    "pb.bfs_dist": bfs_dist,
    "pb.recurse_fused": recurse_fused,
    "pb.recurse_fused_multi": recurse_fused_multi,
}
