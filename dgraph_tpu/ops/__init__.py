"""Device-side kernel substrate.

uidset:   sorted-uid set algebra (reference: algo/uidlist.go)
csr:      CSR frontier expansion / SpMSpV gather (reference: posting list iteration,
          worker/task.go handleUidPostings)
segments: segmented reductions for @groupby / aggregation
          (reference: query/groupby.go, query/aggregator.go)
"""

from dgraph_tpu.ops.uidset import (  # noqa: F401
    SENTINEL32,
    SENTINEL64,
    sentinel,
    make_set,
    to_numpy,
    size,
    compact,
    intersect,
    merge,
    difference,
    is_member,
    apply_filter,
    index_of,
    intersect_many,
    merge_many,
    paginate,
)
from dgraph_tpu.ops.csr import (  # noqa: F401
    expand,
    expand_dest,
    degrees,
)
from dgraph_tpu.ops.segments import (  # noqa: F401
    group_reduce,
    segment_reduce,
)
