"""CSR frontier expansion — the SpMSpV gather at the heart of traversal.

Reference semantics: worker/task.go handleUidPostings (:476-602) iterates, per uid in
the query frontier, the posting list of (predicate, uid) and emits one sorted uid list
per source uid (the "uidMatrix", intern.proto Result.uid_matrix). On TPU the posting
lists of one predicate live as a CSR adjacency (see storage/csr_build.py) and the whole
frontier is expanded in one gather:

    counts  = indptr[row+1] - indptr[row]          (per-frontier-slot degree)
    offsets = cumsum(counts)
    out[j]  = indices[ starts[seg(j)] + j - offsets[seg(j)-1] ]

where seg(j) = searchsorted(offsets, j) assigns each output slot to its source uid.
The result is the uidMatrix in CSR form: a flat target array plus per-source counts.
Output capacity is static; `total` reports the true edge count so the host can detect
overflow and re-issue with a larger capacity class (the analog of the reference's
1e6-edge query budget, x/init.go:53 QueryEdgeLimit).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from dgraph_tpu.ops.uidset import sentinel, _dedup_sorted


class ExpandResult(NamedTuple):
    """uidMatrix in CSR form.

    targets: [out_cap] flat neighbor uids, grouped by source slot, sorted within
             each group, sentinel-padded at the tail.
    seg:     [out_cap] frontier slot index of each target (-1 in padding).
    counts:  [frontier_cap] per-slot degree.
    total:   scalar true edge count (may exceed out_cap → truncated; host should
             re-issue with a larger capacity class).
    """

    targets: jax.Array
    seg: jax.Array
    counts: jax.Array
    total: jax.Array


def degrees(indptr: jax.Array, rows: jax.Array) -> jax.Array:
    """Per-slot out-degree for sentinel-padded row ids.

    Reference: posting/list.go Length(readTs, afterUid) — degree is the `count`
    index feature's base quantity (posting/index.go count mutations).
    """
    snt = sentinel(rows.dtype)
    valid = rows != snt
    r = jnp.where(valid, rows, 0).astype(jnp.int32)
    return jnp.where(valid, jnp.take(indptr, r + 1) - jnp.take(indptr, r), 0)


def expand(indptr: jax.Array, indices: jax.Array, rows: jax.Array, out_cap: int) -> ExpandResult:
    """Expand a frontier of CSR row ids into the concatenated neighbor lists.

    rows: sentinel-padded int32 row indices (NOT raw uids — map uids to rows with
    storage-side subjects lookup). out_cap: static output capacity.
    """
    if indices.shape[0] == 0 or rows.shape[0] == 0:
        # empty adjacency or empty frontier: all-sentinel result (jnp.take
        # rejects a non-empty gather from an empty array, so guard statically)
        return ExpandResult(
            jnp.full((out_cap,), sentinel(indices.dtype), dtype=indices.dtype),
            jnp.full((out_cap,), -1, dtype=jnp.int32),
            jnp.zeros((rows.shape[0],), dtype=indptr.dtype),
            jnp.zeros((), dtype=indptr.dtype),
        )
    snt = sentinel(rows.dtype)
    valid = rows != snt
    r = jnp.where(valid, rows, 0).astype(jnp.int32)
    starts = jnp.take(indptr, r)
    counts = jnp.where(valid, jnp.take(indptr, r + 1) - starts, 0)
    offsets = jnp.cumsum(counts)
    total = offsets[-1] if counts.shape[0] > 0 else jnp.int32(0)

    pos = jnp.arange(out_cap, dtype=offsets.dtype)
    seg = jnp.searchsorted(offsets, pos, side="right").astype(jnp.int32)
    seg_c = jnp.clip(seg, 0, rows.shape[0] - 1)
    prev = jnp.where(seg_c > 0, jnp.take(offsets, jnp.maximum(seg_c - 1, 0)), 0)
    src = jnp.take(starts, seg_c) + (pos - prev)
    ok = pos < total
    tgt_dtype = indices.dtype
    out = jnp.where(
        ok,
        jnp.take(indices, jnp.clip(src, 0, max(indices.shape[0] - 1, 0)).astype(jnp.int32)),
        sentinel(tgt_dtype),
    )
    seg_out = jnp.where(ok, seg_c, -1)
    return ExpandResult(out, seg_out, counts, total)


def expand_masked(
    indptr: jax.Array, indices: jax.Array, rows: jax.Array,
    patched: jax.Array, out_cap: int
) -> ExpandResult:
    """Base-side half of a delta-overlay merge-on-read (storage/delta.py
    OverlayCSR): expand the frontier over the UNCHANGED base arrays with the
    overlay-patched slots masked to sentinel — their rows come from the
    overlay's host-resident replacement rows, which the caller splices into
    the uidMatrix. The base device arrays are never rebuilt or re-uploaded;
    an overlay commit costs the delta, not the tablet."""
    snt = sentinel(rows.dtype)
    rows = jnp.where(jnp.asarray(patched), snt, rows)
    return expand(indptr, indices, rows, out_cap)


def expand_dest(
    indptr: jax.Array, indices: jax.Array, rows: jax.Array, out_cap: int
) -> tuple[jax.Array, jax.Array]:
    """Frontier expand returning (deduped sorted union of neighbors, true total).

    Reference: query/query.go:1928 DestUIDs = MergeSorted(uidMatrix) after a
    non-intersecting expand — the per-level BFS step of ProcessGraph. `total`
    must be checked against out_cap by the host: if total > out_cap the union
    is incomplete and the step should be re-issued at a larger capacity class.
    """
    res = expand(indptr, indices, rows, out_cap)
    return _dedup_sorted(jnp.sort(res.targets)), res.total
