"""On-device decode of block-packed uid lists (HBM-resident packed postings).

Counterpart of the reference's bp128 unpack kernels (bp128/unpack_amd64.s,
77k lines of generated SSE2 — one unrolled kernel per bit width). On TPU a
single branch-free jnp program covers every width: the packed delta of lane i
in block b sits at bit position i*w(b) in the block's word stream, so

    v = (words[k] >> s) | (words[k+1] << (32-s))   (two-word funnel shift)
    uid[b, i] = first[b] + cumsum_i(v & mask(w))

Shifts by data-dependent vector amounts and rowwise cumsum are native VPU ops.
The decoded layout is a [nb*128] sentinel-padded sorted uid-set — directly
consumable by ops.uidset algebra with no host round-trip.

Device lists use int32 uids (max uid < 2**31), so every delta fits in 31 bits
and the raw64 escape never appears on device; storage/packed.py retains full
uint64 fidelity on the host.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np
import jax
import jax.numpy as jnp

from dgraph_tpu.storage import packed as hostpacked
from dgraph_tpu.ops.uidset import sentinel

BLOCK = hostpacked.BLOCK


class DevicePackedList(NamedTuple):
    """Struct-of-arrays packed list, uploaded once and decoded in HBM."""

    block_first: jax.Array  # int32[nb]
    block_count: jax.Array  # int32[nb]
    block_width: jax.Array  # int32[nb]
    block_off: jax.Array    # int32[nb]
    words: jax.Array        # uint32[W+1] (one pad word for funnel reads)

    @property
    def capacity(self) -> int:
        return self.block_first.shape[0] * BLOCK


def to_device(pl: hostpacked.PackedUidList) -> DevicePackedList:
    if (pl.block_width == 64).any():
        raise ValueError("raw64 blocks imply uids >= 2**32; device lists are int32")
    if pl.count and int(pl.block_last[-1]) >= 2**31 - 1:
        raise ValueError("device uid space is int32; max uid must be < 2**31 - 1 "
                         "(2**31 - 1 is the padding sentinel)")
    return DevicePackedList(
        jnp.asarray(pl.block_first.astype(np.int32)),
        jnp.asarray(pl.block_count),
        jnp.asarray(pl.block_width),
        jnp.asarray(pl.block_off.astype(np.int32)),
        jnp.asarray(np.concatenate([pl.words, np.zeros(1, dtype=np.uint32)])),
    )


def unpack_device(pl: DevicePackedList) -> jax.Array:
    """Decode to a sentinel-padded sorted uid-set of shape [nb*BLOCK], int32."""
    nb = pl.block_first.shape[0]
    if nb == 0:
        return jnp.zeros((0,), dtype=jnp.int32)
    lane = jnp.arange(BLOCK, dtype=jnp.int32)[None, :]
    w = pl.block_width[:, None]
    bitpos = lane * w
    widx = pl.block_off[:, None] + (bitpos >> 5)
    shift = (bitpos & 31).astype(jnp.uint32)
    w0 = jnp.take(pl.words, widx)
    w1 = jnp.take(pl.words, widx + 1)
    # funnel shift; (w1 << (32-s)) is undefined at s==0, where w0 alone is exact
    hi = jnp.where(shift == 0, jnp.uint32(0), w1 << (jnp.uint32(32) - shift))
    v = (w0 >> shift) | hi
    mask = jnp.where(
        w >= 32,
        jnp.uint32(0xFFFFFFFF),
        (jnp.uint32(1) << jnp.clip(w, 0, 31).astype(jnp.uint32)) - jnp.uint32(1),
    )
    deltas = (v & mask).astype(jnp.int32)
    deltas = deltas.at[:, 0].set(0)
    uids = pl.block_first[:, None] + jnp.cumsum(deltas, axis=1)
    valid = lane < pl.block_count[:, None]
    return jnp.where(valid, uids, sentinel(jnp.int32)).reshape(-1)
