"""Dense-vector top-k kernels: the similarity probe of @index(vector).

The vector index's probe is the hardware's single best operation — a
segmented matmul + reduce (ROADMAP item 4): score = M @ q over the
predicate's row-aligned [R, D] HBM-resident embedding matrix, followed by
a running top-k merge. The kernels here follow the repo's device
conventions (ops/csr.py): static capacity classes (row space and k are
padded to pow2) so jit retraces are bounded, sentinel padding instead of
dynamic shapes, and one fused program per logical step.

Numerical contract (storage/vecindex.py owns the orchestration):

  * the DEVICE stage ranks by float32 *negated distance* — it only has to
    produce a candidate SUPERSET (k' >= k, with margin);
  * the HOST re-scores candidates in float64 and picks the final k by
    (distance, uid) — one exact, deterministic ranking rule shared by the
    host-scan, device, IVF, mesh-sharded, and fused-ANN paths, so every
    path returns byte-identical results.

Distances: cosine -> 1 - cos(x, q); l2 -> squared L2; dot -> -x.q.
Smaller is better everywhere; the device carries the negation so
lax.top_k's descending order applies.

`ann_expand` is the hybrid-pipeline kernel: top-k candidates -> uid
mapping -> CSR frontier expansion in ONE jitted program, so an ANN root
feeding a graph hop never round-trips through the host between stages
(the span tree shows a single device_kernel, tests/test_vector.py).
"""

from __future__ import annotations

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from dgraph_tpu.ops.csr import ExpandResult, expand
from dgraph_tpu.ops.uidset import sentinel

METRICS = ("cosine", "l2", "dot")

# default row-block size of the tiled scan (pow2; bumped to k when k is
# larger so the per-block top-k stays well-formed)
BLOCK_ROWS = 1 << 12


def row_capacity(n: int) -> int:
    """Pow2 row-capacity class for an n-row matrix (>= 8)."""
    return 1 << max(int(np.ceil(np.log2(max(n, 1) + 1))), 3)


def k_capacity(k: int, n_cap: int) -> int:
    """Pow2 candidate-capacity class for a final top-k of k: enough margin
    that the float64 re-rank's winners are inside the float32 candidate
    set for anything but adversarially tied corpora."""
    want = max(2 * k, k + 16)
    return min(1 << int(np.ceil(np.log2(max(want, 1)))), n_cap)


def host_distances(vecs64: np.ndarray, q64: np.ndarray, metric: str) -> np.ndarray:
    """Exact float64 distances of every row — the reference ranking every
    other path must reproduce (and the brute-force acceptance gate)."""
    s = vecs64 @ q64
    if metric == "cosine":
        nx = np.linalg.norm(vecs64, axis=1)
        nq = np.linalg.norm(q64)
        return 1.0 - s / np.maximum(nx * nq, 1e-300)
    if metric == "l2":
        nx2 = np.einsum("ij,ij->i", vecs64, vecs64)
        return nx2 - 2.0 * s + float(q64 @ q64)
    return -s                               # dot


def _block_neg_dist(blk, nrm, qv, qn, qn2, metric: str):
    """Negated distance of one row block (float32, MXU matmul)."""
    s = jnp.dot(blk, qv, preferred_element_type=jnp.float32)
    if metric == "cosine":
        return s / jnp.maximum(nrm * qn, 1e-30) - 1.0
    if metric == "l2":
        return -(nrm * nrm - 2.0 * s + qn2)
    return s                                # dot


def _topk_body(matrix, norms, valid, qv, k: int, metric: str, block: int):
    """Tiled scan: per block, score + mask + local top-k, merged into the
    running (neg_dist, row) top-k carry. Ties prefer earlier rows (rows are
    uid-sorted, so equal scores break toward the smaller uid — the same
    tie rule the host float64 ranking uses)."""
    R, D = matrix.shape
    qn2 = jnp.sum(qv * qv)
    qn = jnp.sqrt(qn2)
    nblocks = R // block

    def body(i, carry):
        bs, br = carry
        lo = i * block
        blk = lax.dynamic_slice(matrix, (lo, 0), (block, D))
        nrm = lax.dynamic_slice(norms, (lo,), (block,))
        vb = lax.dynamic_slice(valid, (lo,), (block,))
        nd = _block_neg_dist(blk, nrm, qv, qn, qn2, metric)
        nd = jnp.where(vb, nd, -jnp.inf)
        cs, ci = lax.top_k(nd, k)
        ms, mi = lax.top_k(jnp.concatenate([bs, cs]), k)
        rows = jnp.concatenate([br, (lo + ci).astype(jnp.int32)])
        return ms, jnp.take(rows, mi)

    init = (jnp.full((k,), -jnp.inf, jnp.float32),
            jnp.full((k,), R, jnp.int32))
    return lax.fori_loop(0, nblocks, body, init)


def _valid_mask(R: int, nrows, dead_rows):
    """Row-validity vector: real rows minus the overlay's dead rows
    (dead_rows is sentinel-padded with R -> dropped by the scatter)."""
    valid = jnp.arange(R, dtype=jnp.int32) < nrows
    return valid.at[dead_rows].set(False, mode="drop")


@partial(jax.jit, static_argnames=("k", "metric", "block"))
def topk_candidates(matrix, norms, qv, nrows, dead_rows, *,
                    k: int, metric: str, block: int):
    """Float32 candidate stage: (neg_dist f32[k], rows i32[k]); padding /
    masked rows surface as (-inf, R)."""
    valid = _valid_mask(matrix.shape[0], nrows, dead_rows)
    return _topk_body(matrix, norms, valid, qv, k, metric, block)


@partial(jax.jit, static_argnames=("k", "metric", "block"))
def topk_candidates_batch(matrix, norms, Q, nrows, dead_rows, *,
                          k: int, metric: str, block: int):
    """Stacked-query candidate stage: Q [B, D] query matrix -> per-query
    (neg_dist f32[B, k], rows i32[B, k]). The same tiled scan as
    topk_candidates, vmapped over the query dimension — B concurrent
    queries pay the fixed dispatch+sync ONCE (the batched-dispatch tier,
    query/batch.py), and the blockwise matmul runs [block, D] @ [D, B]
    instead of B matvecs. Per-query candidates obey the same contract as
    the solo kernel: a float32 superset the host re-ranks in float64, so
    batched results are byte-identical to solo execution."""
    valid = _valid_mask(matrix.shape[0], nrows, dead_rows)
    return jax.vmap(
        lambda qv: _topk_body(matrix, norms, valid, qv, k, metric, block))(Q)


@partial(jax.jit, static_argnames=("k", "metric"))
def ivf_topk(matrix, norms, qv, cand_rows, *, k: int, metric: str):
    """IVF fine stage: score ONLY the gathered candidate rows (cand_rows
    sentinel-padded with R) — the gather + matmul + top-k of the selected
    nprobe lists as one program."""
    R, _D = matrix.shape
    ok = cand_rows < R
    rc = jnp.clip(cand_rows, 0, R - 1).astype(jnp.int32)
    blk = jnp.take(matrix, rc, axis=0)
    nrm = jnp.take(norms, rc)
    qn2 = jnp.sum(qv * qv)
    qn = jnp.sqrt(qn2)
    nd = _block_neg_dist(blk, nrm, qv, qn, qn2, metric)
    nd = jnp.where(ok, nd, -jnp.inf)
    kk = min(k, int(cand_rows.shape[0]))
    cs, ci = lax.top_k(nd, kk)
    rows = jnp.where(cs > -jnp.inf, jnp.take(rc, ci), R)
    if kk < k:
        cs = jnp.concatenate([cs, jnp.full((k - kk,), -jnp.inf, jnp.float32)])
        rows = jnp.concatenate([rows, jnp.full((k - kk,), R, jnp.int32)])
    return cs, rows


@partial(jax.jit, static_argnames=("k", "metric", "block", "ecap"))
def ann_expand(matrix, norms, qv, nrows, dead_rows, vec_subjects,
               csr_subjects, indptr, indices, *,
               k: int, metric: str, block: int, ecap: int):
    """Fused ANN -> graph hop: top-k candidate rows, map rows -> uids ->
    CSR rows, expand the candidate frontier — ONE device dispatch, no host
    round trip between the ANN stage and the traversal stage.

    Returns (neg_dist f32[k], cand_uids i32[k] sentinel-padded,
    ExpandResult over the k candidate slots). The host slices the
    expansion rows of the float64-selected final k."""
    R = matrix.shape[0]
    valid = _valid_mask(R, nrows, dead_rows)
    nd, rows = _topk_body(matrix, norms, valid, qv, k, metric, block)
    snt = sentinel(csr_subjects.dtype) if csr_subjects.shape[0] else \
        sentinel(jnp.int32)
    ok = nd > -jnp.inf
    uids = jnp.where(ok, jnp.take(vec_subjects,
                                  jnp.clip(rows, 0, R - 1)), snt)
    if csr_subjects.shape[0]:
        pos = jnp.clip(jnp.searchsorted(csr_subjects, uids), 0,
                       csr_subjects.shape[0] - 1).astype(jnp.int32)
        hit = ok & (jnp.take(csr_subjects, pos) == uids)
        crows = jnp.where(hit, pos, snt)
    else:
        crows = jnp.full((k,), snt, dtype=jnp.int32)
    res = expand(indptr, indices, crows, ecap)
    return nd, uids, res


__all__ = ["METRICS", "BLOCK_ROWS", "ExpandResult", "row_capacity",
           "k_capacity", "host_distances", "topk_candidates",
           "topk_candidates_batch", "ivf_topk", "ann_expand"]


# device-runtime observatory (obs/devprof.py, ISSUE 19): jitted entry
# points by program family, probed for live jit-cache size on
# /debug/compiles (see ops/segments.py).
JIT_PROGRAMS = {
    "vector.topk": topk_candidates,
    "vector.topk_batch": topk_candidates_batch,
    "vector.ivf_topk": ivf_topk,
    "vector.ann_expand": ann_expand,
}
