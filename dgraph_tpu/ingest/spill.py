"""Sorted run spilling + k-way merge — the external-memory map/shuffle.

Reference semantics: dgraph/cmd/bulk/mapper.go:121-175 — map output
accumulates in a bounded in-RAM batch, and when the batch crosses the spill
budget it is sorted and written to a tmp file (one sorted "run"); the
shuffle/reduce then k-way-merges all runs of a shard (merge_shards.go:30,
reduce.go:36). Same shape here, with two run flavors:

  - uid PAIRS (subject→object edges, their reverses, degree→subject pairs):
    sorted by (a, b) and encoded with the storage/packed.py block codec in
    fixed-size chunks. The a-column is monotonic so it packs tightly; the
    b-column is only sorted within each a-group, so group boundaries fall
    back to the codec's raw64 escape — correctness is exact either way
    because pack/unpack round-trip deltas mod 2**64.
  - FRAMED records (typed values, facets, index tokens): (key bytes, seq,
    payload bytes) sorted by (key, seq). The global seq makes the k-way
    merge a total order, so per-key payload order is exactly input order —
    the determinism contract the in-RAM reduce path provides for free.

Merges are streaming: each run keeps one decoded chunk (pairs) or one io
buffer (framed) in RAM, so merge memory is O(fan-in × chunk), never O(run).
"""

from __future__ import annotations

import heapq
import itertools
import os
import struct
from array import array
from dataclasses import dataclass

import numpy as np

from dgraph_tpu.storage import packed

_CHDR = struct.Struct("<I")          # pairs in chunk
_PHDR = struct.Struct("<IIQ")        # packed list: count, nblocks, words len
_FHDR = struct.Struct("<IQI")        # frame: key len, seq, payload len

PAIR_CHUNK = 1 << 16                 # pairs per on-disk chunk (decode unit)
_PAIR_COST = 16                      # buffered bytes per (a, b) pair
_FRAME_COST = 48                     # framed-record overhead past key+payload
MERGE_FANIN_MAX = 64                 # open run files per merge pass; beyond
# this, runs cascade into intermediate runs first (bounds fds: a huge load
# with a small budget can produce thousands of runs per channel, and one
# flat heap over all of them would hit EMFILE exactly when out-of-core
# matters most — the reference shuffles map shards the same way,
# merge_shards.go smallest-into-smallest)


@dataclass
class SpillStats:
    """Ingest observability feed (satellite: /metrics ingest counters)."""

    spill_bytes: int = 0             # bytes written to run files
    spill_runs: int = 0              # run files written
    spill_flushes: int = 0           # whole-buffer flush events
    merge_fanin: int = 0             # max runs merged for one channel
    buffered_peak: int = 0           # max in-RAM buffer estimate seen

    def note_buffered(self, n: int) -> None:
        if n > self.buffered_peak:
            self.buffered_peak = n


# -- packed (de)serialization for pair runs ----------------------------------

def write_packed(f, pu: packed.PackedUidList) -> int:
    """Serialize one PackedUidList; returns bytes written."""
    parts = [_PHDR.pack(pu.count, pu.nblocks, len(pu.words)),
             pu.block_first.tobytes(), pu.block_last.tobytes(),
             pu.block_count.tobytes(), pu.block_width.tobytes(),
             pu.block_off.tobytes(), pu.words.tobytes()]
    n = 0
    for p in parts:
        f.write(p)
        n += len(p)
    return n


def read_packed(buf: bytes, off: int) -> tuple[packed.PackedUidList, int]:
    count, nb, wlen = _PHDR.unpack_from(buf, off)
    off += _PHDR.size

    def arr(dt, n):
        nonlocal off
        a = np.frombuffer(buf, dtype=dt, count=n, offset=off)
        off += a.nbytes
        return a

    return packed.PackedUidList(
        count, arr(np.uint64, nb), arr(np.uint64, nb), arr(np.int32, nb),
        arr(np.int32, nb), arr(np.int64, nb), arr(np.uint32, wlen)), off


# -- spillers ----------------------------------------------------------------

class SpillSet:
    """Shared budget over every channel of every registered spiller: when
    the combined in-RAM estimate crosses the budget, ALL channels flush
    (the reference flushes whole map batches, mapper.go:152 — a per-channel
    budget would let many small channels blow the global bound)."""

    def __init__(self, tmp_dir: str, budget_bytes: int,
                 stats: SpillStats | None = None) -> None:
        os.makedirs(tmp_dir, exist_ok=True)
        self.tmp_dir = tmp_dir
        self.budget = max(1, int(budget_bytes))
        self.stats = stats if stats is not None else SpillStats()
        self.bytes = 0
        self._spillers: list = []
        self._names = itertools.count()
        self.on_flush = None         # optional callback(stats) per flush

    def register(self, spiller) -> None:
        self._spillers.append(spiller)

    def charge(self, n: int) -> None:
        self.bytes += n
        self.stats.note_buffered(self.bytes)
        if self.bytes >= self.budget:
            self.flush()

    def flush(self) -> None:
        had = self.bytes > 0
        if had:
            self.stats.spill_flushes += 1
        for s in self._spillers:
            s.flush()
        self.bytes = 0
        if had and self.on_flush is not None:
            self.on_flush(self.stats)

    def new_run_path(self) -> str:
        from dgraph_tpu.utils import faults

        # disk fault seam: every spill-run write starts here, so a failing
        # or slow scratch disk surfaces as a typed error / stall at the
        # exact point a real ENOSPC/slow-NFS would
        faults.fire("disk.spill")
        return os.path.join(self.tmp_dir, f"run{next(self._names):06d}.spl")


class UidPairSpiller:
    """Channels of (a, b) uid pairs -> sorted chunked run files."""

    def __init__(self, pool: SpillSet) -> None:
        self.pool = pool
        self._bufs: dict = {}        # channel -> (array a, array b)
        self._runs: dict = {}        # channel -> [run path]
        pool.register(self)

    def add(self, channel, a: int, b: int) -> None:
        buf = self._bufs.get(channel)
        if buf is None:
            buf = self._bufs[channel] = (array("Q"), array("Q"))
        buf[0].append(a)
        buf[1].append(b)
        self.pool.charge(_PAIR_COST)

    def flush(self) -> None:
        for channel, (aa, bb) in self._bufs.items():
            if not len(aa):
                continue
            a = np.frombuffer(aa, dtype=np.uint64)
            b = np.frombuffer(bb, dtype=np.uint64)
            order = np.lexsort((b, a))
            a, b = a[order], b[order]
            path = self.pool.new_run_path()
            n = 0
            with open(path, "wb") as f:
                for i in range(0, len(a), PAIR_CHUNK):
                    ca, cb = a[i: i + PAIR_CHUNK], b[i: i + PAIR_CHUNK]
                    f.write(_CHDR.pack(len(ca)))
                    n += _CHDR.size
                    n += write_packed(f, packed.pack(ca))
                    n += write_packed(f, packed.pack(cb))
            self._runs.setdefault(channel, []).append(path)
            st = self.pool.stats
            st.spill_bytes += n
            st.spill_runs += 1
        self._bufs.clear()

    def channels(self):
        return sorted(set(self._runs) | set(self._bufs),
                      key=lambda c: str(c))

    def runs(self, channel) -> list[str]:
        return self._runs.get(channel, [])

    def discard(self, channel) -> None:
        """Delete a channel's consumed run files (frees tmp space as the
        reduce walks predicates — runs are single-use)."""
        for p in self._runs.pop(channel, []):
            try:
                os.unlink(p)
            except OSError:
                pass


class _PairRunReader:
    __slots__ = ("_f", "a", "b", "eof")

    def __init__(self, path: str) -> None:
        self._f = open(path, "rb")
        self.a = np.zeros(0, np.uint64)
        self.b = np.zeros(0, np.uint64)
        self.eof = False

    def fill(self) -> None:
        """Append the next chunk to the buffer (sets eof at end)."""
        hdr = self._f.read(_CHDR.size)
        if len(hdr) < _CHDR.size:
            self.eof = True
            self._f.close()
            return
        (n,) = _CHDR.unpack(hdr)
        ca = self._read_column()
        cb = self._read_column()
        assert len(ca) == n and len(cb) == n, "torn pair-run chunk"
        self.a = np.concatenate([self.a, ca]) if len(self.a) else ca
        self.b = np.concatenate([self.b, cb]) if len(self.b) else cb

    def _read_column(self) -> np.ndarray:
        head = self._f.read(_PHDR.size)
        _count, nb, wlen = _PHDR.unpack(head)
        body = self._f.read(nb * (8 + 8 + 4 + 4 + 8) + wlen * 4)
        pu, _ = read_packed(head + body, 0)
        return packed.unpack(pu)


def _write_pair_run(path: str, groups) -> None:
    """Materialize a merged (a, b-array) group stream back into a sorted
    chunked run file (the cascade step's intermediate)."""
    buf_a: list[np.ndarray] = []
    buf_b: list[np.ndarray] = []
    n = 0
    with open(path, "wb") as f:

        def emit(final: bool) -> None:
            nonlocal n, buf_a, buf_b
            while n >= PAIR_CHUNK or (final and n):
                a = np.concatenate(buf_a)
                b = np.concatenate(buf_b)
                ca, cb = a[:PAIR_CHUNK], b[:PAIR_CHUNK]
                buf_a, buf_b = [a[PAIR_CHUNK:]], [b[PAIR_CHUNK:]]
                n = len(buf_a[0])
                f.write(_CHDR.pack(len(ca)))
                write_packed(f, packed.pack(ca))
                write_packed(f, packed.pack(cb))

        for a, row in groups:
            buf_a.append(np.full(len(row), a, np.uint64))
            buf_b.append(row)
            n += len(row)
            emit(False)
        emit(True)


def merge_pairs(paths: list[str], stats: SpillStats | None = None,
                max_fanin: int = MERGE_FANIN_MAX):
    """K-way merge of sorted pair runs -> (a, sorted unique b array) per
    group, ascending a. More runs than `max_fanin` cascade into
    intermediate runs first, so open fds stay bounded regardless of how
    many flushes the spill budget forced."""
    if stats is not None:
        stats.merge_fanin = max(stats.merge_fanin,
                                min(len(paths), max_fanin))
    paths = list(paths)
    temps: list[str] = []
    try:
        while len(paths) > max_fanin:
            head, paths = paths[:max_fanin], paths[max_fanin:]
            t = f"{head[0]}.c{len(temps)}"
            _write_pair_run(t, _merge_pair_runs(head))
            temps.append(t)
            paths.append(t)
        yield from _merge_pair_runs(paths)
    finally:
        for t in temps:
            try:
                os.unlink(t)
            except OSError:
                pass


def _merge_pair_runs(paths: list[str]):
    """Single-pass streaming merge: each run buffers whole chunks; emission
    advances to the smallest last-buffered `a` across non-EOF runs, so a
    group is only ever emitted once all its pairs are in view. Duplicate
    pairs (within and across runs) collapse exactly like the in-RAM
    reduce's global dedupe (loader/bulk.py _group_rows)."""
    readers = [_PairRunReader(p) for p in paths]
    while True:
        for r in readers:
            # keep >= 2 distinct subjects buffered (or EOF): guarantees the
            # cut below always advances past r's first group
            while not r.eof and (len(r.a) == 0 or r.a[0] == r.a[-1]):
                r.fill()
        active = [r for r in readers if len(r.a)]
        if not active:
            return
        bounds = [int(r.a[-1]) for r in active if not r.eof]
        cut = min(bounds) if bounds else None      # None: all EOF, take all
        take_a, take_b = [], []
        for r in active:
            if cut is None:
                ta, tb = r.a, r.b
                r.a = np.zeros(0, np.uint64)
                r.b = np.zeros(0, np.uint64)
            else:
                k = int(np.searchsorted(r.a, np.uint64(cut), side="left"))
                ta, tb = r.a[:k], r.b[:k]
                r.a, r.b = r.a[k:], r.b[k:]
            if len(ta):
                take_a.append(ta)
                take_b.append(tb)
        if not take_a:
            continue
        a = np.concatenate(take_a)
        b = np.concatenate(take_b)
        order = np.lexsort((b, a))
        a, b = a[order], b[order]
        keep = np.ones(len(a), bool)
        keep[1:] = (a[1:] != a[:-1]) | (b[1:] != b[:-1])
        a, b = a[keep], b[keep]
        uq, starts = np.unique(a, return_index=True)
        ends = np.append(starts, len(a))
        for i in range(len(uq)):
            yield int(uq[i]), b[ends[i]: ends[i + 1]]


class FramedSpiller:
    """Channels of (key bytes, payload bytes) records; runs sorted by
    (key, seq) with a global monotone seq, so the merged per-key payload
    sequence is exactly input order (the determinism contract value rows
    and facets need)."""

    def __init__(self, pool: SpillSet) -> None:
        self.pool = pool
        self._bufs: dict = {}        # channel -> [(key, seq, payload)]
        self._runs: dict = {}
        self._seq = itertools.count()
        pool.register(self)

    def add(self, channel, key: bytes, payload: bytes) -> None:
        self._bufs.setdefault(channel, []).append(
            (key, next(self._seq), payload))
        self.pool.charge(len(key) + len(payload) + _FRAME_COST)

    def flush(self) -> None:
        for channel, recs in self._bufs.items():
            if not recs:
                continue
            recs.sort(key=lambda r: (r[0], r[1]))
            path = self.pool.new_run_path()
            n = 0
            with open(path, "wb") as f:
                for key, seq, payload in recs:
                    f.write(_FHDR.pack(len(key), seq, len(payload)))
                    f.write(key)
                    f.write(payload)
                    n += _FHDR.size + len(key) + len(payload)
            self._runs.setdefault(channel, []).append(path)
            st = self.pool.stats
            st.spill_bytes += n
            st.spill_runs += 1
        self._bufs.clear()

    def channels(self):
        return sorted(set(self._runs) | set(self._bufs),
                      key=lambda c: str(c))

    def runs(self, channel) -> list[str]:
        return self._runs.get(channel, [])

    def discard(self, channel) -> None:
        for p in self._runs.pop(channel, []):
            try:
                os.unlink(p)
            except OSError:
                pass


def _iter_frames(path: str):
    with open(path, "rb", buffering=1 << 20) as f:
        while True:
            hdr = f.read(_FHDR.size)
            if len(hdr) < _FHDR.size:
                return
            klen, seq, plen = _FHDR.unpack(hdr)
            yield f.read(klen), seq, f.read(plen)


def _write_framed_run(path: str, frames) -> None:
    with open(path, "wb", buffering=1 << 20) as f:
        for key, seq, payload in frames:
            f.write(_FHDR.pack(len(key), seq, len(payload)))
            f.write(key)
            f.write(payload)


def merge_framed(paths: list[str], stats: SpillStats | None = None,
                 max_fanin: int = MERGE_FANIN_MAX):
    """K-way merge of framed runs by (key, seq) — streaming heap merge,
    cascading through intermediate runs past `max_fanin` (fd bound)."""
    if stats is not None:
        stats.merge_fanin = max(stats.merge_fanin,
                                min(len(paths), max_fanin))
    paths = list(paths)
    temps: list[str] = []
    key_fn = lambda t: (t[0], t[1])   # noqa: E731
    try:
        while len(paths) > max_fanin:
            head, paths = paths[:max_fanin], paths[max_fanin:]
            t = f"{head[0]}.c{len(temps)}"
            _write_framed_run(t, heapq.merge(
                *[_iter_frames(p) for p in head], key=key_fn))
            temps.append(t)
            paths.append(t)
        yield from heapq.merge(*[_iter_frames(p) for p in paths],
                               key=key_fn)
    finally:
        for t in temps:
            try:
                os.unlink(t)
            except OSError:
                pass


def group_framed(frames):
    """(key, seq, payload) stream -> (key, [payloads in seq order]) groups.
    One group is buffered at a time."""
    key = None
    payloads: list[bytes] = []
    for k, _seq, p in frames:
        if k != key:
            if key is not None:
                yield key, payloads
            key, payloads = k, []
        payloads.append(p)
    if key is not None:
        yield key, payloads
