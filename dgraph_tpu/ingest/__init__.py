"""External-memory ingest primitives shared by bulk, live, and checkpoint.

Reference semantics: dgraph/cmd/bulk — the map stage spills sorted runs to
tmp files sharded by predicate (mapper.go:121-175), the shuffle/reduce
k-way-merges them into packed posting lists written straight to badger SSTs
(merge_shards.go:30, reduce.go:36-53). Here the same spill/merge/stream
shape feeds the repo's own columnar snapshot format:

  spill.py      bounded in-RAM buffers -> sorted per-channel run files
                (uid pairs ride the storage/packed.py block codec; typed
                values/facets/tokens ride framed byte-keyed records) plus
                streaming k-way merge iterators over the runs.
  snapwrite.py  streaming tablet-sectioned snapshot writer (DGTS3): rows
                stream in, columns spool to bounded buffers, peak transient
                memory is independent of total key count. Shared by
                Store.checkpoint and the bulk loader's spill reduce.
"""
