"""Streaming tablet-sectioned snapshot writer (DGTS3).

DGTS2 (storage/store.py) concatenates every list's packed metadata into
file-global columns — writing it requires every PostingList (and every
column) in RAM at once, so a checkpoint was a memory event proportional to
key count. DGTS3 keeps the same 14 columns but scopes them PER TABLET:

  b"DGTS3" | u64 upto_ts | u32 meta_len | meta json |
  sections until EOF, in globally sorted key order:
    u32 n_rows | 14 x (u64 byte_len | column bytes)

Tablet prefixes (kind byte + u32 attr len + attr) are never prefixes of one
another, so sorting sections by prefix keeps the concatenated key stream
globally sorted — every DGTS2 reader invariant (contiguous tablet runs,
sorted keys, searchsorted find) carries over per section.

Rows STREAM in: each section spools its columns to bounded buffers
(tempfile.SpooledTemporaryFile — RAM up to `spool_max` per column, disk
past it), so writer memory is O(open sections x spool_max), independent of
row count. A pristine mmap'd SegmentRun can be attached wholesale
(`add_run`): its columns are copied file-to-file in chunks with ZERO
per-row work — the checkpoint fast path for untouched tablets.

Shared by Store.checkpoint (storage/store.py) and the bulk loader's
out-of-core reduce (loader/bulk.py) — one writer is what makes spill-mode
bulk output byte-identical to the in-RAM path.
"""

from __future__ import annotations

import json
import struct
import tempfile

import numpy as np

from dgraph_tpu.storage import packed

_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")

SPOOL_MAX = 1 << 22          # per-column RAM before a section spools to disk
_COPY_CHUNK = 1 << 22        # file-to-file copy granularity (bytes)

# column order — MUST match storage/store.py's DGTS2 column order (the
# loader shares slicing code); dtypes noted for the derived columns
_NCOLS = 14


def tablet_prefix(kind: int, attr: str) -> bytes:
    """Sort key for sections == the shared prefix of every key in the
    tablet (storage/keys.py encoding: kind, u32 len, attr)."""
    a = attr.encode("utf-8")
    return bytes([kind]) + struct.pack(">I", len(a)) + a


def _write_arr(f, arr: np.ndarray) -> None:
    """Chunked array write: mmap-backed views stream through without one
    whole-column copy."""
    if arr.nbytes <= _COPY_CHUNK:
        f.write(arr.tobytes())
        return
    step = max(1, _COPY_CHUNK // max(1, arr.itemsize))
    for i in range(0, len(arr), step):
        f.write(arr[i: i + step].tobytes())


class _Section:
    """One tablet's columns, accumulated row-by-row into spooled buffers."""

    __slots__ = ("prefix", "n", "cols", "_writer")

    def __init__(self, prefix: bytes, spool_max: int, writer) -> None:
        self.prefix = prefix
        self.n = 0
        self.cols = [tempfile.SpooledTemporaryFile(max_size=spool_max)
                     for _ in range(_NCOLS)]
        self._writer = writer

    def add_row(self, kb: bytes, base_ts: int, pu: packed.PackedUidList,
                post: bytes = b"") -> None:
        c = self.cols
        c[0].write(_U32.pack(len(kb)))
        c[1].write(kb)
        c[2].write(_U64.pack(base_ts))
        c[3].write(_U32.pack(pu.count))
        c[4].write(_U32.pack(pu.nblocks))
        c[5].write(np.ascontiguousarray(pu.block_first, np.uint64).tobytes())
        c[6].write(np.ascontiguousarray(pu.block_last, np.uint64).tobytes())
        c[7].write(np.ascontiguousarray(pu.block_count, np.int32).tobytes())
        c[8].write(np.ascontiguousarray(pu.block_width, np.int32).tobytes())
        c[9].write(np.ascontiguousarray(pu.block_off, np.int64).tobytes())
        c[10].write(_U64.pack(len(pu.words)))
        c[11].write(np.ascontiguousarray(pu.words, np.uint32).tobytes())
        c[12].write(_U32.pack(len(post)))
        c[13].write(post)
        self.n += 1
        self._writer._note_row(
            len(kb) + len(post) + pu.nbytes + 8 * _NCOLS)

    def _emit(self, out) -> None:
        out.write(_U32.pack(self.n))
        for col in self.cols:
            blen = col.tell()
            out.write(_U64.pack(blen))
            col.seek(0)
            while True:
                chunk = col.read(_COPY_CHUNK)
                if not chunk:
                    break
                out.write(chunk)
            col.close()


class _RunSection:
    """A pristine SegmentRun attached wholesale: columns stream straight
    from the snapshot mmap — no spools, no per-row work."""

    __slots__ = ("prefix", "seg", "n")

    def __init__(self, prefix: bytes, seg) -> None:
        self.prefix = prefix
        self.seg = seg
        self.n = seg.n

    def _emit(self, out) -> None:
        seg = self.seg
        n = seg.n
        out.write(_U32.pack(n))
        kends = np.asarray(seg.kends, np.int64)
        wstarts = np.asarray(seg.wstarts, np.int64)
        pstarts = np.asarray(seg.pstarts, np.int64)
        key_lens = np.empty(n, np.int64)
        key_lens[0] = kends[0]
        np.subtract(kends[1:], kends[:-1], out=key_lens[1:])
        cols = [
            key_lens.astype(np.uint32),
            np.asarray(seg.keys_blob, np.uint8),
            np.asarray(seg.base_ts, np.uint64),
            np.asarray(seg.counts, np.uint32),
            np.asarray(seg.nbs, np.uint32),
            np.asarray(seg.bfirst, np.uint64),
            np.asarray(seg.blast, np.uint64),
            np.asarray(seg.bcount, np.int32),
            np.asarray(seg.bwidth, np.int32),
            np.asarray(seg.boff, np.int64),
            (wstarts[1:] - wstarts[:-1]).astype(np.uint64),
            np.asarray(seg.words, np.uint32),
            (pstarts[1:] - pstarts[:-1]).astype(np.uint32),
            np.asarray(seg.post_blob, np.uint8),
        ]
        for arr in cols:
            out.write(_U64.pack(arr.nbytes))
            _write_arr(out, arr)


class SnapshotWriter:
    """Assemble a DGTS3 snapshot from sections created in ANY order; they
    are emitted sorted by tablet prefix at finish(). Tracks the peak
    transient estimate (spooled-RAM ceiling + largest row) for the
    checkpoint metrics satellite."""

    def __init__(self, f, upto_ts: int, spool_max: int = SPOOL_MAX) -> None:
        self._f = f
        self.upto_ts = int(upto_ts)
        self.spool_max = spool_max
        self._sections: dict[bytes, object] = {}
        self._open_mem = 0           # sum of min(col bytes, spool_max)
        self.rows = 0
        self.peak_transient = 0

    def _note_row(self, nbytes: int) -> None:
        self.rows += 1
        # RAM estimate: spooled columns cap at spool_max each; count the
        # uncapped growth until then plus the row being appended
        self._open_mem = min(self._open_mem + nbytes,
                             len(self._sections) * _NCOLS * self.spool_max)
        self.peak_transient = max(self.peak_transient,
                                  self._open_mem + nbytes)

    def section(self, kind: int, attr: str) -> _Section:
        prefix = tablet_prefix(kind, attr)
        sec = self._sections.get(prefix)
        if sec is None:
            sec = self._sections[prefix] = _Section(
                prefix, self.spool_max, self)
        return sec

    def add_run(self, kind: int, attr: str, seg) -> None:
        prefix = tablet_prefix(kind, attr)
        assert prefix not in self._sections, "tablet emitted twice"
        self._sections[prefix] = _RunSection(prefix, seg)
        self.rows += seg.n

    def finish(self, meta: dict) -> None:
        f = self._f
        f.write(b"DGTS3")
        f.write(_U64.pack(self.upto_ts))
        mb = json.dumps(meta).encode()
        f.write(_U32.pack(len(mb)) + mb)
        for prefix in sorted(self._sections):
            sec = self._sections[prefix]
            if sec.n == 0:
                if isinstance(sec, _Section):
                    for col in sec.cols:
                        col.close()
                continue
            sec._emit(f)
        self._sections.clear()
