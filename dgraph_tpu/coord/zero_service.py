"""Zero as its own process: the coordinator's gRPC surface + client stub.

Reference semantics: `dgraph zero` is a separate Raft-backed service
(dgraph/cmd/zero/zero.go:328 Connect, oracle.go:276 commit, assign.go:65
leases, protos/internal.proto:370-379 service Zero). This exposes the
library Zero (coord/zero.py — oracle, uid lease, tablet map) over the
internal wire protocol so worker and client processes coordinate through
RPCs instead of shared memory. Single-instance (the library object IS the
replicated state machine's apply target; multi-zero Raft is out of scope —
the in-process quorum story lives in coord/replication.py).
"""

from __future__ import annotations

import json
import threading
import time
from concurrent import futures

try:
    import grpc
except ImportError:              # pragma: no cover
    grpc = None

from ..protos import internal_pb2 as ipb
from .zero import TxnConflict, TxnNotFound, Zero

SERVICE = "dgraph_tpu.internal.Zero"


class ZeroService:
    """gRPC handlers over one Zero instance."""

    def __init__(self, zero: Zero) -> None:
        self.zero = zero
        self._lock = threading.Lock()
        self._members: dict[int, list[str]] = {}   # group -> member addrs

    # -- membership ----------------------------------------------------------

    def connect(self, msg: ipb.ZeroConnectRequest, ctx) -> ipb.ZeroConnectResponse:
        """Assign a joining worker to a group (zero.go:328-434: fill groups
        round-robin; an explicit group joins as another replica of it)."""
        with self._lock:
            if msg.group >= 0:
                g = int(msg.group)
            else:
                sizes = {g: len(a) for g, a in self._members.items()}
                for g in range(self.zero.n_groups):
                    sizes.setdefault(g, 0)
                g = min(sizes, key=lambda k: (sizes[k], k))
            members = self._members.setdefault(g, [])
            if msg.addr and msg.addr not in members:
                members.append(msg.addr)
            rid = members.index(msg.addr) if msg.addr in members else 0
            return ipb.ZeroConnectResponse(group=g, replica_id=rid)

    # -- leases --------------------------------------------------------------

    def new_txn(self, msg: ipb.ZeroLeaseRequest, ctx) -> ipb.ZeroLeaseResponse:
        return ipb.ZeroLeaseResponse(
            first=self.zero.oracle.new_txn().start_ts)

    def timestamps(self, msg: ipb.ZeroLeaseRequest, ctx) -> ipb.ZeroLeaseResponse:
        return ipb.ZeroLeaseResponse(
            first=self.zero.oracle.timestamps(max(1, int(msg.n))))

    def assign_uids(self, msg: ipb.ZeroLeaseRequest, ctx) -> ipb.ZeroLeaseResponse:
        first, _last = self.zero.uids.assign(max(1, int(msg.n)))
        return ipb.ZeroLeaseResponse(first=first)

    # -- oracle --------------------------------------------------------------

    def commit_or_abort(self, msg: ipb.ZeroCommitRequest,
                        ctx) -> ipb.ZeroCommitResponse:
        """Track the txn's conflict keys then decide (oracle.go:276-320;
        the client sends keys collected from every group's Mutate reply)."""
        start_ts = int(msg.start_ts)
        if msg.abort:
            self.zero.oracle.abort(start_ts)
            return ipb.ZeroCommitResponse(commit_ts=0, aborted=True)
        try:
            self.zero.oracle.track(start_ts, list(msg.conflict_keys),
                                   list(msg.preds))
            commit_ts = self.zero.oracle.commit(start_ts)
            return ipb.ZeroCommitResponse(commit_ts=commit_ts, aborted=False)
        except TxnConflict:
            return ipb.ZeroCommitResponse(commit_ts=0, aborted=True)
        except TxnNotFound as e:
            ctx.abort(grpc.StatusCode.NOT_FOUND, str(e))

    # -- tablets -------------------------------------------------------------

    def should_serve(self, msg: ipb.ZeroTabletRequest,
                     ctx) -> ipb.ZeroTabletResponse:
        if msg.read_only:
            g = self.zero.tablets().get(msg.attr)
            return ipb.ZeroTabletResponse(group=-1 if g is None else g)
        return ipb.ZeroTabletResponse(group=self.zero.should_serve(msg.attr))

    def state(self, _msg: ipb.ZeroStateRequest, ctx) -> ipb.ZeroStateResponse:
        st = self.zero.state()
        with self._lock:
            for g, addrs in self._members.items():
                st["groups"].setdefault(str(g), {})["members"] = list(addrs)
        st["tabletMap"] = self.zero.tablets()
        return ipb.ZeroStateResponse(state_json=json.dumps(st))

    def handler(self):
        def u(fn, req_cls, resp_cls):
            return grpc.unary_unary_rpc_method_handler(
                fn, request_deserializer=req_cls.FromString,
                response_serializer=resp_cls.SerializeToString)
        return grpc.method_handlers_generic_handler(SERVICE, {
            "Connect": u(self.connect, ipb.ZeroConnectRequest,
                         ipb.ZeroConnectResponse),
            "NewTxn": u(self.new_txn, ipb.ZeroLeaseRequest,
                        ipb.ZeroLeaseResponse),
            "Timestamps": u(self.timestamps, ipb.ZeroLeaseRequest,
                            ipb.ZeroLeaseResponse),
            "AssignUids": u(self.assign_uids, ipb.ZeroLeaseRequest,
                            ipb.ZeroLeaseResponse),
            "CommitOrAbort": u(self.commit_or_abort, ipb.ZeroCommitRequest,
                               ipb.ZeroCommitResponse),
            "ShouldServe": u(self.should_serve, ipb.ZeroTabletRequest,
                             ipb.ZeroTabletResponse),
            "State": u(self.state, ipb.ZeroStateRequest,
                       ipb.ZeroStateResponse),
        })


class MoveError(Exception):
    pass


class ZeroOps:
    """Cluster operations driven FROM Zero: tablet moves over the wire and
    the automatic rebalance tick (dgraph/cmd/zero/tablet.go:60-74; move
    protocol worker/predicate_move.go:86-177)."""

    def __init__(self, svc: ZeroService) -> None:
        import os

        from ..parallel.remote import MOVE_CHUNK_BYTES

        self.svc = svc
        self.zero = svc.zero
        self._move_lock = threading.Lock()
        # env override so systests can force many small chunks through the
        # real wire path
        self.chunk_bytes = int(os.environ.get("DGRAPH_TPU_MOVE_CHUNK",
                                              MOVE_CHUNK_BYTES))

    def _leader_of(self, group: int):
        from ..parallel.remote import RemoteWorker

        with self.svc._lock:
            addrs = list(self.svc._members.get(group, ()))
        if not addrs:
            raise MoveError(f"group {group} has no members")
        if len(addrs) == 1:
            return RemoteWorker(addrs[0])
        for a in addrs:
            rw = RemoteWorker(a)
            try:
                if rw.status().leader:
                    return rw
            except Exception:
                pass
            rw.close()
        raise MoveError(f"group {group} has no live leader")

    def move_tablet(self, attr: str, dst_group: int) -> dict:
        """The 7-step move over the internal protocol: block writes → abort
        open txns touching the tablet → snapshot-stream its records to the
        destination leader → commit → flip the map → delete at the source.
        Buffered layers of aborted txns on workers are reaped by their own
        decide/abort paths; a mid-stream failure leaves the source
        authoritative (the copy rides an uncommitted txn)."""
        import base64

        with self._move_lock:
            src_group = self.zero.tablets().get(attr)
            if src_group is None:
                raise MoveError(f"tablet {attr!r} is not served")
            if src_group == dst_group:
                return {"moved_records": 0, "tablet": attr}
            src = self._leader_of(src_group)
            try:
                dst = self._leader_of(dst_group)
            except BaseException:
                src.close()
                raise
            self.zero.block_writes(attr)
            try:
                aborted = 0
                for ts in self.zero.oracle.pending_on(attr):
                    self.zero.oracle.abort(ts)
                    aborted += 1
                # a commit DECIDED at the oracle may still have its Decide
                # RPC in flight to the source leader; streaming before it
                # applies would silently drop committed postings (and the
                # source delete would destroy them). Wait for the source's
                # applied per-tablet watermark to reach the oracle's.
                target = self.zero.oracle.pred_commit.get(attr, 0)
                deadline = time.monotonic() + 5.0
                while target and time.monotonic() < deadline:
                    applied = json.loads(
                        src.membership().pred_commit_json or "{}")
                    if int(applied.get(attr, 0)) >= target:
                        break
                    time.sleep(0.05)
                else:
                    if target:
                        raise MoveError(
                            f"source never applied commits on {attr!r} up "
                            f"to ts {target} (lost Decide?); move aborted")
                read_ts = self.zero.oracle.read_ts()
                move_st = self.zero.oracle.new_txn()
                keys_b64 = []
                try:
                    # chunked stream: <=MOVE_CHUNK_BYTES per message
                    # (reference predicate_move.go:187), resumable cursor,
                    # count handshake before the map flips (:171-176)
                    sent = ingested = 0
                    cursor = b""
                    while True:
                        resp = src.predicate_data(
                            attr, read_ts, move_st.start_ts, after=cursor,
                            max_bytes=self.chunk_bytes)
                        keys_b64.extend(base64.b64encode(bytes(k)).decode()
                                        for k in resp.keys)
                        sent += len(resp.records)
                        if resp.records:
                            ingested += dst.ingest_records(
                                list(resp.records))
                        if resp.done:
                            break
                        cursor = bytes(resp.next)
                    if ingested != sent:
                        raise MoveError(
                            f"move count handshake failed: sent {sent} "
                            f"records, destination ingested {ingested}")
                    commit_ts = self.zero.oracle.commit(move_st.start_ts)
                    crec = json.dumps(
                        {"t": "c", "s": move_st.start_ts, "ts": commit_ts,
                         "k": keys_b64}, separators=(",", ":")).encode()
                    dst.ingest_records([crec])
                except BaseException:
                    # mid-stream failure (incl. a lost commit record): the
                    # map never flipped, so the source stays authoritative.
                    # Reap the partial copy buffered on dst — otherwise
                    # each retried move stacks another full tablet copy —
                    # and release the move txn at the oracle (a no-conflict
                    # txn, so a post-commit abort record is still safe: the
                    # tablet's data was never exposed under dst's map).
                    try:
                        arec = json.dumps(
                            {"t": "a", "s": move_st.start_ts,
                             "k": keys_b64},
                            separators=(",", ":")).encode()
                        dst.ingest_records([arec])
                    except Exception:
                        pass
                    self.zero.oracle.abort(move_st.start_ts)
                    raise
                self.zero.move_tablet(attr, dst_group)
                src.delete_predicate(attr)
                return {"moved_records": sent,
                        "aborted_txns": aborted, "tablet": attr,
                        "src": src_group, "dst": dst_group}
            finally:
                self.zero.unblock_writes(attr)
                src.close()
                dst.close()

    def rebalance_once(self) -> dict | None:
        """One tick: size reports from every group's leader feed the shared
        decision (coord/zero.choose_rebalance_move), then move_tablet."""
        from .zero import choose_rebalance_move

        sizes: dict[int, dict[str, int]] = {}
        with self.svc._lock:
            groups = list(self.svc._members)
        for g in groups:
            try:
                rw = self._leader_of(g)
            except MoveError:
                continue
            try:
                sizes[g] = {a: int(s) for a, s in json.loads(
                    rw.status().tablet_sizes_json or "{}").items()}
            finally:
                rw.close()
        pick = choose_rebalance_move(sizes,
                                     blocked=self.zero.moving_tablets())
        if pick is None:
            return None
        attr, _src, dst, sz = pick
        out = self.move_tablet(attr, dst)
        out["bytes"] = sz
        return out

    def remove_node(self, group: int, addr: str) -> bool:
        """Drop a member from the membership registry (zero /removeNode,
        http.go:38-128); its replicas stop being move/leader candidates."""
        with self.svc._lock:
            members = self.svc._members.get(group, [])
            if addr in members:
                members.remove(addr)
                return True
        return False


def serve_zero_http(svc: ZeroService, ops: ZeroOps, host: str = "127.0.0.1",
                    port: int = 0):
    """Zero's ops HTTP endpoints (dgraph/cmd/zero/http.go:38-130):
    GET /state, GET /moveTablet?tablet=X&group=N,
    GET /removeNode?group=N&addr=A. Returns (server, bound_port)."""
    import http.server
    import urllib.parse

    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):     # noqa: N802 — quiet
            pass

        def _reply(self, code: int, obj) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):              # noqa: N802 — http.server API
            u = urllib.parse.urlparse(self.path)
            q = urllib.parse.parse_qs(u.query)
            try:
                if u.path == "/state":
                    self._reply(200, json.loads(svc.state(
                        ipb.ZeroStateRequest(), None).state_json))
                elif u.path == "/moveTablet":
                    out = ops.move_tablet(q["tablet"][0],
                                          int(q["group"][0]))
                    self._reply(200, out)
                elif u.path == "/removeNode":
                    ok = ops.remove_node(int(q["group"][0]), q["addr"][0])
                    self._reply(200 if ok else 404, {"removed": ok})
                else:
                    self._reply(404, {"error": f"unknown path {u.path}"})
            except Exception as e:      # noqa: BLE001 — ops surface
                self._reply(500, {"error": str(e)})

    httpd = http.server.ThreadingHTTPServer((host, port), Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, httpd.server_address[1]


def serve_zero(zero: Zero, addr: str = "localhost:0", max_workers: int = 8):
    """Start the Zero gRPC server; returns (server, bound_port, service)."""
    svc = ZeroService(zero)
    from ..parallel.remote import GRPC_OPTIONS

    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers),
                         options=GRPC_OPTIONS)
    server.add_generic_rpc_handlers((svc.handler(),))
    port = server.add_insecure_port(addr)
    if port == 0:
        raise RuntimeError(f"could not bind zero listener on {addr}")
    server.start()
    return server, port, svc


class ZeroClient:
    """Client stub for a remote Zero — mirrors the library surface the
    dispatcher and write path consume (tablets/should_serve/oracle calls)."""

    def __init__(self, addr: str) -> None:
        self.addr = addr
        self.channel = grpc.insecure_channel(addr)

        def u(name, req_cls, resp_cls):
            return self.channel.unary_unary(
                f"/{SERVICE}/{name}",
                request_serializer=req_cls.SerializeToString,
                response_deserializer=resp_cls.FromString)
        self._connect = u("Connect", ipb.ZeroConnectRequest,
                          ipb.ZeroConnectResponse)
        self._new_txn = u("NewTxn", ipb.ZeroLeaseRequest, ipb.ZeroLeaseResponse)
        self._timestamps = u("Timestamps", ipb.ZeroLeaseRequest,
                             ipb.ZeroLeaseResponse)
        self._assign_uids = u("AssignUids", ipb.ZeroLeaseRequest,
                              ipb.ZeroLeaseResponse)
        self._commit = u("CommitOrAbort", ipb.ZeroCommitRequest,
                         ipb.ZeroCommitResponse)
        self._should_serve = u("ShouldServe", ipb.ZeroTabletRequest,
                               ipb.ZeroTabletResponse)
        self._state = u("State", ipb.ZeroStateRequest, ipb.ZeroStateResponse)

    def connect(self, addr: str, group: int = -1) -> tuple[int, int]:
        r = self._connect(ipb.ZeroConnectRequest(addr=addr, group=group))
        return r.group, r.replica_id

    def new_txn(self) -> int:
        return self._new_txn(ipb.ZeroLeaseRequest(n=1)).first

    def timestamps(self, n: int = 1) -> int:
        return self._timestamps(ipb.ZeroLeaseRequest(n=n)).first

    def assign_uids(self, n: int) -> int:
        return self._assign_uids(ipb.ZeroLeaseRequest(n=n)).first

    def commit(self, start_ts: int, conflict_keys, preds) -> int:
        """Returns commit_ts; raises TxnConflict on SSI abort."""
        r = self._commit(ipb.ZeroCommitRequest(
            start_ts=start_ts, conflict_keys=list(conflict_keys),
            preds=sorted(preds)))
        if r.aborted:
            raise TxnConflict(f"txn {start_ts} aborted by oracle")
        return r.commit_ts

    def abort(self, start_ts: int) -> None:
        self._commit(ipb.ZeroCommitRequest(start_ts=start_ts, abort=True))

    def should_serve(self, attr: str) -> int:
        return self._should_serve(ipb.ZeroTabletRequest(attr=attr)).group

    def tablets(self) -> dict[str, int]:
        return {a: g for a, g in json.loads(
            self._state(ipb.ZeroStateRequest()).state_json)
            .get("tabletMap", {}).items()}

    def state(self) -> dict:
        return json.loads(self._state(ipb.ZeroStateRequest()).state_json)

    # move fences are server-side in this topology
    def writes_blocked(self, _attr: str) -> bool:
        return False

    def moving_tablets(self) -> set:
        return set()

    def close(self) -> None:
        self.channel.close()
