"""Zero as its own process: the coordinator's gRPC surface + client stub.

Reference semantics: `dgraph zero` is a separate Raft-backed service
(dgraph/cmd/zero/zero.go:328 Connect, oracle.go:276 commit, assign.go:65
leases, protos/internal.proto:370-379 service Zero). This exposes the
library Zero (coord/zero.py — oracle, uid lease, tablet map) over the
internal wire protocol so worker and client processes coordinate through
RPCs instead of shared memory. Single-instance (the library object IS the
replicated state machine's apply target; multi-zero Raft is out of scope —
the in-process quorum story lives in coord/replication.py).
"""

from __future__ import annotations

import json
import threading
from concurrent import futures

try:
    import grpc
except ImportError:              # pragma: no cover
    grpc = None

from ..protos import internal_pb2 as ipb
from .zero import TxnConflict, TxnNotFound, Zero

SERVICE = "dgraph_tpu.internal.Zero"


class ZeroService:
    """gRPC handlers over one Zero instance."""

    def __init__(self, zero: Zero) -> None:
        self.zero = zero
        self._lock = threading.Lock()
        self._members: dict[int, list[str]] = {}   # group -> member addrs

    # -- membership ----------------------------------------------------------

    def connect(self, msg: ipb.ZeroConnectRequest, ctx) -> ipb.ZeroConnectResponse:
        """Assign a joining worker to a group (zero.go:328-434: fill groups
        round-robin; an explicit group joins as another replica of it)."""
        with self._lock:
            if msg.group >= 0:
                g = int(msg.group)
            else:
                sizes = {g: len(a) for g, a in self._members.items()}
                for g in range(self.zero.n_groups):
                    sizes.setdefault(g, 0)
                g = min(sizes, key=lambda k: (sizes[k], k))
            members = self._members.setdefault(g, [])
            if msg.addr and msg.addr not in members:
                members.append(msg.addr)
            rid = members.index(msg.addr) if msg.addr in members else 0
            return ipb.ZeroConnectResponse(group=g, replica_id=rid)

    # -- leases --------------------------------------------------------------

    def new_txn(self, msg: ipb.ZeroLeaseRequest, ctx) -> ipb.ZeroLeaseResponse:
        return ipb.ZeroLeaseResponse(
            first=self.zero.oracle.new_txn().start_ts)

    def timestamps(self, msg: ipb.ZeroLeaseRequest, ctx) -> ipb.ZeroLeaseResponse:
        return ipb.ZeroLeaseResponse(
            first=self.zero.oracle.timestamps(max(1, int(msg.n))))

    def assign_uids(self, msg: ipb.ZeroLeaseRequest, ctx) -> ipb.ZeroLeaseResponse:
        first, _last = self.zero.uids.assign(max(1, int(msg.n)))
        return ipb.ZeroLeaseResponse(first=first)

    # -- oracle --------------------------------------------------------------

    def commit_or_abort(self, msg: ipb.ZeroCommitRequest,
                        ctx) -> ipb.ZeroCommitResponse:
        """Track the txn's conflict keys then decide (oracle.go:276-320;
        the client sends keys collected from every group's Mutate reply)."""
        start_ts = int(msg.start_ts)
        if msg.abort:
            self.zero.oracle.abort(start_ts)
            return ipb.ZeroCommitResponse(commit_ts=0, aborted=True)
        try:
            self.zero.oracle.track(start_ts, list(msg.conflict_keys),
                                   list(msg.preds))
            commit_ts = self.zero.oracle.commit(start_ts)
            return ipb.ZeroCommitResponse(commit_ts=commit_ts, aborted=False)
        except TxnConflict:
            return ipb.ZeroCommitResponse(commit_ts=0, aborted=True)
        except TxnNotFound as e:
            ctx.abort(grpc.StatusCode.NOT_FOUND, str(e))

    # -- tablets -------------------------------------------------------------

    def should_serve(self, msg: ipb.ZeroTabletRequest,
                     ctx) -> ipb.ZeroTabletResponse:
        if msg.read_only:
            g = self.zero.tablets().get(msg.attr)
            return ipb.ZeroTabletResponse(group=-1 if g is None else g)
        return ipb.ZeroTabletResponse(group=self.zero.should_serve(msg.attr))

    def state(self, _msg: ipb.ZeroStateRequest, ctx) -> ipb.ZeroStateResponse:
        st = self.zero.state()
        with self._lock:
            for g, addrs in self._members.items():
                st["groups"].setdefault(str(g), {})["members"] = list(addrs)
        st["tabletMap"] = self.zero.tablets()
        return ipb.ZeroStateResponse(state_json=json.dumps(st))

    def handler(self):
        def u(fn, req_cls, resp_cls):
            return grpc.unary_unary_rpc_method_handler(
                fn, request_deserializer=req_cls.FromString,
                response_serializer=resp_cls.SerializeToString)
        return grpc.method_handlers_generic_handler(SERVICE, {
            "Connect": u(self.connect, ipb.ZeroConnectRequest,
                         ipb.ZeroConnectResponse),
            "NewTxn": u(self.new_txn, ipb.ZeroLeaseRequest,
                        ipb.ZeroLeaseResponse),
            "Timestamps": u(self.timestamps, ipb.ZeroLeaseRequest,
                            ipb.ZeroLeaseResponse),
            "AssignUids": u(self.assign_uids, ipb.ZeroLeaseRequest,
                            ipb.ZeroLeaseResponse),
            "CommitOrAbort": u(self.commit_or_abort, ipb.ZeroCommitRequest,
                               ipb.ZeroCommitResponse),
            "ShouldServe": u(self.should_serve, ipb.ZeroTabletRequest,
                             ipb.ZeroTabletResponse),
            "State": u(self.state, ipb.ZeroStateRequest,
                       ipb.ZeroStateResponse),
        })


def serve_zero(zero: Zero, addr: str = "localhost:0", max_workers: int = 8):
    """Start the Zero gRPC server; returns (server, bound_port)."""
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    server.add_generic_rpc_handlers((ZeroService(zero).handler(),))
    port = server.add_insecure_port(addr)
    if port == 0:
        raise RuntimeError(f"could not bind zero listener on {addr}")
    server.start()
    return server, port


class ZeroClient:
    """Client stub for a remote Zero — mirrors the library surface the
    dispatcher and write path consume (tablets/should_serve/oracle calls)."""

    def __init__(self, addr: str) -> None:
        self.addr = addr
        self.channel = grpc.insecure_channel(addr)

        def u(name, req_cls, resp_cls):
            return self.channel.unary_unary(
                f"/{SERVICE}/{name}",
                request_serializer=req_cls.SerializeToString,
                response_deserializer=resp_cls.FromString)
        self._connect = u("Connect", ipb.ZeroConnectRequest,
                          ipb.ZeroConnectResponse)
        self._new_txn = u("NewTxn", ipb.ZeroLeaseRequest, ipb.ZeroLeaseResponse)
        self._timestamps = u("Timestamps", ipb.ZeroLeaseRequest,
                             ipb.ZeroLeaseResponse)
        self._assign_uids = u("AssignUids", ipb.ZeroLeaseRequest,
                              ipb.ZeroLeaseResponse)
        self._commit = u("CommitOrAbort", ipb.ZeroCommitRequest,
                         ipb.ZeroCommitResponse)
        self._should_serve = u("ShouldServe", ipb.ZeroTabletRequest,
                               ipb.ZeroTabletResponse)
        self._state = u("State", ipb.ZeroStateRequest, ipb.ZeroStateResponse)

    def connect(self, addr: str, group: int = -1) -> tuple[int, int]:
        r = self._connect(ipb.ZeroConnectRequest(addr=addr, group=group))
        return r.group, r.replica_id

    def new_txn(self) -> int:
        return self._new_txn(ipb.ZeroLeaseRequest(n=1)).first

    def timestamps(self, n: int = 1) -> int:
        return self._timestamps(ipb.ZeroLeaseRequest(n=n)).first

    def assign_uids(self, n: int) -> int:
        return self._assign_uids(ipb.ZeroLeaseRequest(n=n)).first

    def commit(self, start_ts: int, conflict_keys, preds) -> int:
        """Returns commit_ts; raises TxnConflict on SSI abort."""
        r = self._commit(ipb.ZeroCommitRequest(
            start_ts=start_ts, conflict_keys=list(conflict_keys),
            preds=sorted(preds)))
        if r.aborted:
            raise TxnConflict(f"txn {start_ts} aborted by oracle")
        return r.commit_ts

    def abort(self, start_ts: int) -> None:
        self._commit(ipb.ZeroCommitRequest(start_ts=start_ts, abort=True))

    def should_serve(self, attr: str) -> int:
        return self._should_serve(ipb.ZeroTabletRequest(attr=attr)).group

    def tablets(self) -> dict[str, int]:
        return {a: g for a, g in json.loads(
            self._state(ipb.ZeroStateRequest()).state_json)
            .get("tabletMap", {}).items()}

    def state(self) -> dict:
        return json.loads(self._state(ipb.ZeroStateRequest()).state_json)

    # move fences are server-side in this topology
    def writes_blocked(self, _attr: str) -> bool:
        return False

    def moving_tablets(self) -> set:
        return set()

    def close(self) -> None:
        self.channel.close()
