"""Zero as its own process: the coordinator's gRPC surface + client stub.

Reference semantics: `dgraph zero` is a separate Raft-backed service
(dgraph/cmd/zero/zero.go:328 Connect, oracle.go:276 commit, assign.go:65
leases, protos/internal.proto:370-379 service Zero). This exposes the
library Zero (coord/zero.py — oracle, uid lease, tablet map) over the
internal wire protocol so worker and client processes coordinate through
RPCs instead of shared memory. Single-instance (the library object IS the
replicated state machine's apply target; multi-zero Raft is out of scope —
the in-process quorum story lives in coord/replication.py).
"""

from __future__ import annotations

import json
import threading
import time
from concurrent import futures

try:
    import grpc
except ImportError:              # pragma: no cover
    grpc = None

from ..obs import otrace
from ..protos import internal_pb2 as ipb
from ..utils import deadline as dl
from ..utils import faults
from ..utils.ballot import tally as _tally
from ..utils.deadline import DeadlineExceeded
from ..utils.errors import FailedPrecondition, Unavailable
from ..utils.retry import backoff_s
from .zero import TxnConflict, TxnNotFound, Zero

SERVICE = "dgraph_tpu.internal.Zero"


class ZeroService:
    """gRPC handlers over one Zero instance. With a ZeroReplica attached
    (multi-zero mode), coordination RPCs are served only by the leader —
    standbys reject with FAILED_PRECONDITION and clients rotate."""

    def __init__(self, zero: Zero) -> None:
        self.zero = zero
        self._lock = threading.Lock()
        self._members: dict[int, list[str]] = {}   # group -> member addrs
        self.replica: "ZeroReplica | None" = None  # multi-zero role
        # trace continuation for coordinator RPCs: a client-propagated span
        # context puts lease/commit/tablet calls in the query's trace
        self.tracer = otrace.Tracer(proc="zero")

    def _require_leader(self, ctx) -> None:
        if self.replica is not None and not self.replica.is_leader:
            if ctx is None:            # ops-HTTP path (no gRPC context)
                raise FailedPrecondition("not zero leader")
            ctx.abort(grpc.StatusCode.FAILED_PRECONDITION,
                      "not zero leader")

    # -- membership ----------------------------------------------------------

    def connect(self, msg: ipb.ZeroConnectRequest, ctx) -> ipb.ZeroConnectResponse:
        """Assign a joining worker to a group (zero.go:328-434: fill groups
        round-robin; an explicit group joins as another replica of it)."""
        self._require_leader(ctx)
        with self._lock:
            if msg.group >= 0:
                g = int(msg.group)
            else:
                sizes = {g: len(a) for g, a in self._members.items()}
                for g in range(self.zero.n_groups):
                    sizes.setdefault(g, 0)
                g = min(sizes, key=lambda k: (sizes[k], k))
            members = self._members.setdefault(g, [])
            if msg.addr and msg.addr not in members:
                members.append(msg.addr)
            rid = members.index(msg.addr) if msg.addr in members else 0
            return ipb.ZeroConnectResponse(group=g, replica_id=rid)

    # -- leases --------------------------------------------------------------

    def new_txn(self, msg: ipb.ZeroLeaseRequest, ctx) -> ipb.ZeroLeaseResponse:
        self._require_leader(ctx)
        return ipb.ZeroLeaseResponse(
            first=self.zero.oracle.new_txn().start_ts)

    def timestamps(self, msg: ipb.ZeroLeaseRequest, ctx) -> ipb.ZeroLeaseResponse:
        self._require_leader(ctx)
        return ipb.ZeroLeaseResponse(
            first=self.zero.oracle.timestamps(max(1, int(msg.n))))

    def assign_uids(self, msg: ipb.ZeroLeaseRequest, ctx) -> ipb.ZeroLeaseResponse:
        self._require_leader(ctx)
        first, _last = self.zero.uids.assign(max(1, int(msg.n)))
        return ipb.ZeroLeaseResponse(first=first)

    # -- oracle --------------------------------------------------------------

    def commit_or_abort(self, msg: ipb.ZeroCommitRequest,
                        ctx) -> ipb.ZeroCommitResponse:
        """Track the txn's conflict keys then decide (oracle.go:276-320;
        the client sends keys collected from every group's Mutate reply)."""
        self._require_leader(ctx)
        start_ts = int(msg.start_ts)
        if msg.abort:
            self.zero.oracle.abort(start_ts)
            return ipb.ZeroCommitResponse(commit_ts=0, aborted=True)
        try:
            self.zero.oracle.track(start_ts, list(msg.conflict_keys),
                                   list(msg.preds))
            commit_ts = self.zero.oracle.commit(start_ts)
            return ipb.ZeroCommitResponse(commit_ts=commit_ts, aborted=False)
        except TxnConflict:
            return ipb.ZeroCommitResponse(commit_ts=0, aborted=True)
        except TxnNotFound as e:
            ctx.abort(grpc.StatusCode.NOT_FOUND, str(e))

    # -- tablets -------------------------------------------------------------

    def should_serve(self, msg: ipb.ZeroTabletRequest,
                     ctx) -> ipb.ZeroTabletResponse:
        self._require_leader(ctx)
        if msg.read_only:
            g = self.zero.tablets().get(msg.attr)
            return ipb.ZeroTabletResponse(group=-1 if g is None else g)
        return ipb.ZeroTabletResponse(group=self.zero.should_serve(msg.attr))

    def state(self, _msg: ipb.ZeroStateRequest, ctx) -> ipb.ZeroStateResponse:
        self._require_leader(ctx)   # clients read floors/ts from the leader
        st = self.zero.state()
        with self._lock:
            for g, addrs in self._members.items():
                st["groups"].setdefault(str(g), {})["members"] = list(addrs)
        st["tabletMap"] = self.zero.tablets()
        return ipb.ZeroStateResponse(state_json=json.dumps(st))

    def _traced(self, fn, name: str):
        """Wrap one handler with trace continuation: join a propagated
        span context, ship the server span back in trailing metadata."""
        def handler(msg, ctx):
            wire = None
            if ctx is not None:
                for k, v in ctx.invocation_metadata() or ():
                    if k == otrace.WIRE_KEY:
                        wire = v
                        break
            if not wire:
                return fn(msg, ctx)
            sp = self.tracer.join(wire, f"zero:{name}")
            try:
                with sp:
                    return fn(msg, ctx)
            finally:
                spans = self.tracer.take(sp.trace_id)
                if spans:
                    try:
                        ctx.set_trailing_metadata(
                            ((otrace.SPANS_KEY,
                              otrace.encode_spans(spans)),))
                    # dgraph: allow(except-seam) aborted RPC: spans
                    # drop, buffer already drained
                    except Exception:
                        pass
        return handler

    def handler(self):
        def u(fn, req_cls, resp_cls, name=""):
            return grpc.unary_unary_rpc_method_handler(
                self._traced(fn, name) if name else fn,
                request_deserializer=req_cls.FromString,
                response_serializer=resp_cls.SerializeToString)
        methods = {
            "Connect": u(self.connect, ipb.ZeroConnectRequest,
                         ipb.ZeroConnectResponse, "Connect"),
            "NewTxn": u(self.new_txn, ipb.ZeroLeaseRequest,
                        ipb.ZeroLeaseResponse, "NewTxn"),
            "Timestamps": u(self.timestamps, ipb.ZeroLeaseRequest,
                            ipb.ZeroLeaseResponse, "Timestamps"),
            "AssignUids": u(self.assign_uids, ipb.ZeroLeaseRequest,
                            ipb.ZeroLeaseResponse, "AssignUids"),
            "CommitOrAbort": u(self.commit_or_abort, ipb.ZeroCommitRequest,
                               ipb.ZeroCommitResponse, "CommitOrAbort"),
            "ShouldServe": u(self.should_serve, ipb.ZeroTabletRequest,
                             ipb.ZeroTabletResponse, "ShouldServe"),
            "State": u(self.state, ipb.ZeroStateRequest,
                       ipb.ZeroStateResponse, "State"),
        }
        if self.replica is not None:
            r = self.replica
            methods.update({
                "ZeroShip": u(r.zero_ship, ipb.ZeroShipRequest,
                              ipb.ZeroShipResponse),
                "ZeroVote": u(r.zero_vote, ipb.ZeroVoteRequest,
                              ipb.ZeroVoteResponse),
                "ZeroPing": u(r.zero_ping, ipb.ZeroPingRequest,
                              ipb.ZeroPingResponse),
            })
        return grpc.method_handlers_generic_handler(SERVICE, methods)


class ZeroReplica:
    """Multi-zero replication + ballot election (VERDICT r4 #3; reference
    dgraph/cmd/zero/raft.go: Zero is its own Raft group).

    Redesign onto the quorum-shipping machinery: the leader ships its FULL
    durable state (zero_state.json — lease ceilings + tablet map, the exact
    payload a restarted Zero recovers from) plus the worker registry to
    standbys on every persist, quorum-acked. Standbys store it; a standby
    that misses pings campaigns (up-to-dateness = state sequence), and the
    winner re-initializes its Zero from the replicated state — the kill -9
    restart path — then serves. Crash semantics match the single-zero
    durability contract: at most one lease block burns; pending txns abort.
    """

    PING_S = 0.5
    ELECTION_TIMEOUT_S = (1.5, 3.0)

    def __init__(self, svc: ZeroService, zero_dir: str, advertise: str,
                 members: list[str], bootstrap_leader: bool) -> None:
        import os

        self.svc = svc
        self.dir = zero_dir
        self.advertise = advertise
        self.members = sorted(set(members) | {advertise})
        self.is_leader = False
        self.seq = 0
        self._meta_path = os.path.join(zero_dir, "zero_repl.json")
        self.term = 0
        if os.path.exists(self._meta_path):
            meta = json.loads(open(self._meta_path).read())
            self.term = int(meta.get("term", 0))
            self.seq = int(meta.get("seq", 0))
        self._lock = threading.RLock()
        self._leader_contact = time.monotonic()
        self._stop = threading.Event()
        self._bootstrap = bootstrap_leader
        self._peer_cache: dict[str, ZeroClient] = {}
        self._ping_fail_rounds = 0
        self._ship_pool = None       # parallel ship fan-out executor
        svc.replica = self

    # -- durable meta --------------------------------------------------------

    def _save_meta(self) -> None:
        import os

        tmp = self._meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"term": self.term, "seq": self.seq}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._meta_path)

    # -- leader side ---------------------------------------------------------

    def start(self) -> None:
        from ..utils.ballot import BallotLoop

        # bootstrap only a FRESH cluster: a restarted idx-0 zero with a
        # persisted term may rejoin a cluster that elected past it — it
        # must campaign like anyone else, not self-promote into a
        # split-brain at a colliding term
        if self._bootstrap and self.term == 0:
            self._become_leader(1)

        def touch():
            self._leader_contact = time.monotonic()

        self._ballot = BallotLoop(
            is_leader=lambda: self.is_leader,
            send_pings=self._ping_round,
            campaign=self._campaign,
            leader_contact=lambda: self._leader_contact,
            touch_contact=touch,
            ping_s=self.PING_S,
            timeout_range=self.ELECTION_TIMEOUT_S,
            stop_event=self._stop)
        self._ballot.start()

    def stop(self) -> None:
        self._stop.set()
        if self._ship_pool is not None:
            self._ship_pool.shutdown(wait=False)
        for c in self._peer_cache.values():
            try:
                c.close()
            # dgraph: allow(except-seam) shutdown path: close every peer
            # channel even when one is already torn down
            except Exception:
                pass
        self._peer_cache.clear()

    def _peer_clients(self):
        # persistent channels: pings run every PING_S and ships run under
        # Zero._plock — per-call channel setup would serialize lease
        # issuance behind TCP handshakes
        out = []
        for a in self.members:
            if a == self.advertise:
                continue
            c = self._peer_cache.get(a)
            if c is None:
                c = self._peer_cache[a] = ZeroClient(a)
            out.append(c)
        return out

    def _become_leader(self, term: int) -> None:
        with self._lock:
            self.term = term
            self._save_meta()
            # adopt the replicated state: re-init Zero from this dir (the
            # restart-recovery path: lease ceilings + tablets)
            old = self.svc.zero
            fresh = Zero(n_groups=old.n_groups, dirpath=self.dir)
            fresh.persist_sink = self._ship
            self.svc.zero = fresh
            # worker registry from the last ship received (if any)
            import os

            mp = os.path.join(self.dir, "zero_members.json")
            if os.path.exists(mp):
                try:
                    reg = json.loads(open(mp).read())
                    with self.svc._lock:
                        self.svc._members = {int(g): list(a)
                                             for g, a in reg.items()}
                except (ValueError, OSError):
                    pass    # torn legacy file: workers re-register anyway
            self._ping_fail_rounds = 0   # fresh leadership, fresh tolerance
            self.is_leader = True

    def _ship(self, state_json: str) -> None:
        """Called from Zero._persist (under its _plock): replicate to a
        quorum of zeros. Quorum counts self; on failure step down — a
        minority leader must not keep minting leases.

        The RPC fan-out runs in PARALLEL with the replica lock released:
        ships are full-state idempotent replaces ordered by seq (standbys
        reject anything below their seq), so ordering needs no lock — and
        one partitioned standby must cost one RPC timeout, not stall
        every lease persist behind a sequential walk while holding the
        lock the ping/vote handlers need."""
        with self._lock:
            if not self.is_leader:
                return
            self.seq += 1
            seq = self.seq
            term = self.term
            self._save_meta()
            with self.svc._lock:
                members_json = json.dumps(
                    {str(g): a for g, a in self.svc._members.items()})
            peers = self._peer_clients()
            members_n = len(self.members)
            if self._ship_pool is None and peers:
                self._ship_pool = futures.ThreadPoolExecutor(
                    max_workers=max(len(self.members), 2),
                    thread_name_prefix="dgt-zship")
            pool = self._ship_pool

        def one(c) -> int:
            try:
                r = c.zero_ship(term, seq, state_json, members_json)
                if r.ok:
                    return 1
                return -1 if r.term > term else 0
            except Exception:
                return 0

        try:
            results = list(pool.map(one, peers)) if peers else []
        except RuntimeError:
            # stop() shut the pool down mid-persist: count every peer as
            # un-acked — the quorum check below raises the same clean
            # quorum-lost error the sequential path produced
            results = [0] * len(peers)
        acks = 1 + sum(1 for r in results if r == 1)
        deposed = any(r == -1 for r in results)
        quorum = members_n // 2 + 1
        if deposed or acks < quorum:
            with self._lock:
                self.is_leader = False
            if acks < quorum:
                raise Unavailable(
                    f"zero quorum lost ({acks}/{members_n})")

    def _ping_round(self) -> None:
        """One leader ping fan-out with quorum tracking: a partitioned
        leader must stop deciding — two live oracles must never coexist
        (the worker path's NoQuorum step-down, applied to pings)."""
        acked = 1                    # self
        for c in self._peer_clients():
            try:
                r = c.zero_ping(self.term, self.advertise, self.members)
                if r.term <= self.term:
                    acked += 1
                else:                # deposed: a newer term exists
                    with self._lock:
                        self.term = int(r.term)
                        self.is_leader = False
                        self._save_meta()
                    self._ping_fail_rounds = 0
                    return
            # dgraph: allow(except-seam) ping fan-out: a dead peer is the
            # EXPECTED case; the tally below counts the silence
            except Exception:
                pass
        if not _tally(acked, len(self.members)):
            self._ping_fail_rounds += 1
            if self._ping_fail_rounds >= 3:
                with self._lock:
                    self.is_leader = False
        else:
            self._ping_fail_rounds = 0

    def _campaign(self) -> None:
        others = [a for a in self.members if a != self.advertise]
        if not others:
            return
        with self._lock:
            t = self.term + 1
            self.term = t
            self._save_meta()
            my_seq = self.seq
        votes = 1
        for c in self._peer_clients():
            try:
                r = c.zero_vote(t, my_seq, self.advertise)
                if r.granted:
                    votes += 1
                elif r.term > t:
                    with self._lock:
                        self.term = max(self.term, int(r.term))
                        self._save_meta()
                    return
            # dgraph: allow(except-seam) campaign fan-out: unreachable
            # voters are abstentions; the tally decides
            except Exception:
                pass
        if _tally(votes, len(self.members)):
            with self._lock:
                if self.term == t:
                    self._become_leader(t)

    # -- standby handlers ----------------------------------------------------

    def zero_ship(self, msg: ipb.ZeroShipRequest, ctx) -> ipb.ZeroShipResponse:
        import os

        with self._lock:
            if msg.term < self.term:
                return ipb.ZeroShipResponse(ok=False, term=self.term,
                                            seq=self.seq)
            newer_term = msg.term > self.term
            if newer_term or self.is_leader:
                self.term = int(msg.term)
                self.is_leader = False
            if not newer_term and int(msg.seq) < self.seq:
                # stale re-ship (e.g. a deposed leader's in-flight persist)
                # — but ONLY within the same term. A strictly newer term's
                # ship is a full-state replace and its seq is adopted: a
                # standby that alone received a quorum-failed ship would
                # otherwise reject every subsequent ship via this check
                # and later resurrect the unacked state by winning an
                # election on its inflated seq.
                return ipb.ZeroShipResponse(ok=False, term=self.term,
                                            seq=self.seq)
            self._leader_contact = time.monotonic()
            # store the full state durably (idempotent full replace)
            path = os.path.join(self.dir, "zero_state.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write(msg.state_json)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            if msg.members_json:
                mp = os.path.join(self.dir, "zero_members.json")
                with open(mp + ".tmp", "w") as f:
                    f.write(msg.members_json)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(mp + ".tmp", mp)
            self.seq = int(msg.seq)
            self._save_meta()
            return ipb.ZeroShipResponse(ok=True, term=self.term,
                                        seq=self.seq)

    def zero_vote(self, msg: ipb.ZeroVoteRequest, ctx) -> ipb.ZeroVoteResponse:
        with self._lock:
            if msg.term <= self.term:
                return ipb.ZeroVoteResponse(granted=False, term=self.term)
            self.term = int(msg.term)
            self.is_leader = False
            self._save_meta()
            if int(msg.seq) >= self.seq:      # up-to-dateness on state seq
                self._leader_contact = time.monotonic()
                return ipb.ZeroVoteResponse(granted=True, term=self.term)
            return ipb.ZeroVoteResponse(granted=False, term=self.term)

    def zero_ping(self, msg: ipb.ZeroPingRequest, ctx) -> ipb.ZeroPingResponse:
        with self._lock:
            if msg.term < self.term:
                return ipb.ZeroPingResponse(term=self.term, ok=False,
                                            leader=self.is_leader)
            if msg.term > self.term:
                self.term = int(msg.term)
                self.is_leader = False
                self._save_meta()
            self._leader_contact = time.monotonic()
            if msg.members:
                self.members = sorted(set(msg.members) | {self.advertise})
            return ipb.ZeroPingResponse(term=self.term, ok=True,
                                        leader=self.is_leader)


class MoveError(Exception):
    pass


class ZeroOps:
    """Cluster operations driven FROM Zero: tablet moves over the wire and
    the automatic rebalance tick (dgraph/cmd/zero/tablet.go:60-74; move
    protocol worker/predicate_move.go:86-177)."""

    def __init__(self, svc: ZeroService) -> None:
        import os

        from ..parallel.remote import MOVE_CHUNK_BYTES

        self.svc = svc
        self._move_lock = threading.Lock()
        # env override so systests can force many small chunks through the
        # real wire path
        self.chunk_bytes = int(os.environ.get("DGRAPH_TPU_MOVE_CHUNK",
                                              MOVE_CHUNK_BYTES))

    @property
    def zero(self):
        # dynamic: a ZeroReplica promotion swaps svc.zero for a fresh
        # instance recovered from the replicated state
        return self.svc.zero

    def _leader_of(self, group: int):
        from ..parallel.remote import RemoteWorker

        with self.svc._lock:
            addrs = list(self.svc._members.get(group, ()))
        if not addrs:
            raise MoveError(f"group {group} has no members")
        if len(addrs) == 1:
            return RemoteWorker(addrs[0])
        for a in addrs:
            rw = RemoteWorker(a)
            try:
                if rw.status().leader:
                    return rw
            # dgraph: allow(except-seam) leader probe: an unreachable
            # candidate simply is not the leader
            except Exception:
                pass
            rw.close()
        raise MoveError(f"group {group} has no live leader")

    def move_tablet(self, attr: str, dst_group: int) -> dict:
        """The 7-step move over the internal protocol: block writes → abort
        open txns touching the tablet → snapshot-stream its records to the
        destination leader → commit → flip the map → delete at the source.
        Buffered layers of aborted txns on workers are reaped by their own
        decide/abort paths; a mid-stream failure leaves the source
        authoritative (the copy rides an uncommitted txn)."""
        import base64

        with self._move_lock:
            # read replicas of a moving tablet are dropped FIRST — inside
            # _move_lock, so a concurrent install_replica (controller tick
            # or manual /addReplica) cannot re-install one between the
            # drop and the stream: the move streams into the destination
            # store, and a destination that already holds replica rows
            # would union two copies; holders on other groups would keep
            # pulling deltas from a deposed owner.
            for g in sorted(self.zero.replica_holders(attr)):
                try:
                    self.drop_replica(attr, g)
                # dgraph: allow(except-seam) routing already stopped;
                # orphaned replica data is reaped by a later install
                except Exception:
                    pass
            src_group = self.zero.tablets().get(attr)
            if src_group is None:
                raise MoveError(f"tablet {attr!r} is not served")
            if src_group == dst_group:
                return {"moved_records": 0, "tablet": attr}
            src = self._leader_of(src_group)
            try:
                dst = self._leader_of(dst_group)
            except BaseException:
                src.close()
                raise
            self.zero.block_writes(attr)
            try:
                aborted = 0
                for ts in self.zero.oracle.pending_on(attr):
                    self.zero.oracle.abort(ts)
                    aborted += 1
                # a commit DECIDED at the oracle may still have its Decide
                # RPC in flight to the source leader; streaming before it
                # applies would silently drop committed postings (and the
                # source delete would destroy them). Wait for the source's
                # applied per-tablet watermark to reach the oracle's.
                target = self.zero.oracle.pred_commit.get(attr, 0)
                deadline = time.monotonic() + 5.0
                while target and time.monotonic() < deadline:
                    applied = json.loads(
                        src.membership().pred_commit_json or "{}")
                    if int(applied.get(attr, 0)) >= target:
                        break
                    time.sleep(0.05)
                else:
                    if target:
                        raise MoveError(
                            f"source never applied commits on {attr!r} up "
                            f"to ts {target} (lost Decide?); move aborted")
                read_ts = self.zero.oracle.read_ts()
                move_st = self.zero.oracle.new_txn()
                keys_b64 = []
                try:
                    # chunked stream: <=MOVE_CHUNK_BYTES per message
                    # (reference predicate_move.go:187), resumable cursor,
                    # count handshake before the map flips (:171-176)
                    sent = ingested = 0
                    cursor = b""
                    while True:
                        faults.fire("move.chunk_ship")
                        resp = src.predicate_data(
                            attr, read_ts, move_st.start_ts, after=cursor,
                            max_bytes=self.chunk_bytes)
                        keys_b64.extend(base64.b64encode(bytes(k)).decode()
                                        for k in resp.keys)
                        sent += len(resp.records)
                        if resp.records:
                            ingested += dst.ingest_records(
                                list(resp.records))
                        if resp.done:
                            break
                        cursor = bytes(resp.next)
                    if ingested != sent:
                        raise MoveError(
                            f"move count handshake failed: sent {sent} "
                            f"records, destination ingested {ingested}")
                    commit_ts = self.zero.oracle.commit(move_st.start_ts)
                    crec = json.dumps(
                        {"t": "c", "s": move_st.start_ts, "ts": commit_ts,
                         "k": keys_b64}, separators=(",", ":")).encode()
                    dst.ingest_records([crec])
                except BaseException:
                    # mid-stream failure (incl. a lost commit record): the
                    # map never flipped, so the source stays authoritative.
                    # Reap the partial copy buffered on dst — otherwise
                    # each retried move stacks another full tablet copy —
                    # and release the move txn at the oracle (a no-conflict
                    # txn, so a post-commit abort record is still safe: the
                    # tablet's data was never exposed under dst's map).
                    try:
                        arec = json.dumps(
                            {"t": "a", "s": move_st.start_ts,
                             "k": keys_b64},
                            separators=(",", ":")).encode()
                        dst.ingest_records([arec])
                    # dgraph: allow(except-seam) best-effort abort record
                    # on the unwind path; the raise below carries the
                    # real failure
                    except Exception:
                        pass
                    self.zero.oracle.abort(move_st.start_ts)
                    raise
                self.zero.move_tablet(attr, dst_group)
                src.delete_predicate(attr)
                return {"moved_records": sent,
                        "aborted_txns": aborted, "tablet": attr,
                        "src": src_group, "dst": dst_group}
            finally:
                self.zero.unblock_writes(attr)
                src.close()
                dst.close()

    # -- read-only tablet replicas (coord/placement.py drives these) --------

    def install_replica(self, attr: str, dst_group: int) -> dict:
        """Install a read-only copy of a tablet on another group — the
        move protocol's streaming half with neither the map flip nor the
        source delete, and WITHOUT blocking writes (the copy is a snapshot
        cut; later commits reach the holder via delta ships).

        Coverage ordering makes the replica-read gate exact: read_ts is
        taken FIRST, so every commit <= read_ts was assigned before it and
        is <= the oracle's per-tablet floor read afterwards; waiting for
        the source to APPLY up to that floor guarantees the stream at
        read_ts contains them all. The holder commits the copy at read_ts
        — its gate watermark claims exactly what the cut holds."""
        with self._move_lock:
            src_group = self.zero.tablets().get(attr)
            if src_group is None:
                raise MoveError(f"tablet {attr!r} is not served")
            if src_group == dst_group:
                return {"installed_records": 0, "tablet": attr,
                        "noop": "owner"}
            if dst_group in self.zero.replica_holders(attr):
                return {"installed_records": 0, "tablet": attr,
                        "noop": "already a holder"}
            src = self._leader_of(src_group)
            try:
                dst = self._leader_of(dst_group)
            except BaseException:
                src.close()
                raise
            try:
                # clear any ORPHANED copy first: a prior drop_replica may
                # have unregistered the holder but failed the delete
                # (holder unreachable) — streaming over the stale copy
                # would union the two and resurrect deleted edges behind
                # a watermark that claims full freshness. Idempotent on a
                # clean destination.
                dst.delete_predicate(attr)
                read_ts = self.zero.oracle.read_ts()
                target = self.zero.oracle.pred_commit.get(attr, 0)
                deadline = time.monotonic() + 5.0
                while target and time.monotonic() < deadline:
                    applied = json.loads(
                        src.membership().pred_commit_json or "{}")
                    if int(applied.get(attr, 0)) >= target:
                        break
                    time.sleep(0.05)
                else:
                    if target:
                        raise MoveError(
                            f"source never applied commits on {attr!r} up "
                            f"to ts {target}; replica install aborted")
                start_ts = self.zero.oracle.timestamps(1)
                keys_b64: list[str] = []
                sent = ingested = 0
                cursor = b""
                try:
                    import base64

                    while True:
                        faults.fire("move.chunk_ship")
                        resp = src.predicate_data(
                            attr, read_ts, start_ts, after=cursor,
                            max_bytes=self.chunk_bytes)
                        keys_b64.extend(base64.b64encode(bytes(k)).decode()
                                        for k in resp.keys)
                        sent += len(resp.records)
                        if resp.records:
                            ingested += dst.ingest_records(
                                list(resp.records))
                        if resp.done:
                            break
                        cursor = bytes(resp.next)
                    if ingested != sent:
                        raise MoveError(
                            f"replica install handshake failed: sent "
                            f"{sent}, destination ingested {ingested}")
                    crec = json.dumps(
                        {"t": "c", "s": start_ts, "ts": read_ts,
                         "k": keys_b64}, separators=(",", ":")).encode()
                    dst.ingest_records([crec])
                except BaseException:
                    # reap the partial copy; the tablet was never routed
                    # to this holder, so aborting the buffered txn is safe
                    try:
                        arec = json.dumps(
                            {"t": "a", "s": start_ts, "k": keys_b64},
                            separators=(",", ":")).encode()
                        dst.ingest_records([arec])
                    # dgraph: allow(except-seam) best-effort abort record
                    # on the unwind path; the raise below carries the
                    # real failure
                    except Exception:
                        pass
                    raise
                # routing starts ONLY now, with the data fully installed
                self.zero.add_replica(attr, dst_group, read_ts)
                return {"installed_records": sent, "tablet": attr,
                        "src": src_group, "dst": dst_group,
                        "watermark": read_ts}
            finally:
                src.close()
                dst.close()

    def ship_replica_delta(self, attr: str, holder_group: int) -> dict:
        """Freshness ship: pull the owner's O(Δ) journal above the
        holder's watermark as DEL_ALL+rewrite records, apply them on the
        holder, commit at the owner's covered watermark. A journal that
        cannot prove completeness triggers a full re-install."""
        faults.fire("replica.delta_ship")
        holders = self.zero.replica_holders(attr)
        if holder_group not in holders:
            raise MoveError(f"group {holder_group} holds no replica of "
                            f"{attr!r}")
        since = int(holders[holder_group])
        src_group = self.zero.tablets().get(attr)
        if src_group is None or src_group == holder_group:
            raise MoveError(f"tablet {attr!r} has no distinct owner")
        src = self._leader_of(src_group)
        try:
            dst = self._leader_of(holder_group)
        except BaseException:
            src.close()
            raise
        try:
            read_ts = self.zero.oracle.read_ts()
            start_ts = self.zero.oracle.timestamps(1)
            resp = src.tablet_delta(attr, since, read_ts, start_ts)
            watermark = int(resp.watermark)
            if resp.full_resync:
                # journal overflow / bulk install: drop + re-install
                self.drop_replica(attr, holder_group)
                out = self.install_replica(attr, holder_group)
                out["resync"] = True
                return out
            if watermark <= since or not resp.records:
                self.zero.set_replica_watermark(attr, holder_group,
                                                watermark)
                return {"shipped_records": 0, "tablet": attr,
                        "watermark": max(watermark, since)}
            import base64

            keys_b64 = [base64.b64encode(bytes(k)).decode()
                        for k in resp.keys]
            try:
                dst.ingest_records(list(resp.records))
                crec = json.dumps(
                    {"t": "c", "s": start_ts, "ts": watermark,
                     "k": keys_b64}, separators=(",", ":")).encode()
                dst.ingest_records([crec])
            except BaseException:
                # reap the buffered rewrite txn: a failure between the
                # record ship and the commit record would otherwise leave
                # uncommitted layers at start_ts on the holder forever
                # (nothing else ever decides that ts)
                try:
                    arec = json.dumps(
                        {"t": "a", "s": start_ts, "k": keys_b64},
                        separators=(",", ":")).encode()
                    dst.ingest_records([arec])
                # dgraph: allow(except-seam) best-effort abort record on
                # the unwind path; the raise below carries the real one
                except Exception:
                    pass
                raise
            self.zero.set_replica_watermark(attr, holder_group, watermark)
            return {"shipped_records": len(resp.records), "tablet": attr,
                    "keys": len(resp.keys), "watermark": watermark}
        finally:
            src.close()
            dst.close()

    def drop_replica(self, attr: str, holder_group: int) -> bool:
        """Demote a replica: unregister from the map FIRST (routing stops;
        in-flight reads are covered by the holder's serve-time existence
        check), then delete the copy at the holder."""
        if not self.zero.drop_replica(attr, holder_group):
            return False
        try:
            rw = self._leader_of(holder_group)
            try:
                rw.delete_predicate(attr)
            finally:
                rw.close()
        # dgraph: allow(except-seam) holder unreachable: the data is
        # orphaned but unrouted; a later install starts from delete
        except Exception:
            pass
        return True

    def rebalance_once(self) -> dict | None:
        """One tick: size reports from every group's leader feed the shared
        decision (coord/zero.choose_rebalance_move), then move_tablet."""
        from .zero import choose_rebalance_move

        sizes: dict[int, dict[str, int]] = {}
        with self.svc._lock:
            groups = list(self.svc._members)
        for g in groups:
            try:
                rw = self._leader_of(g)
            except MoveError:
                continue
            try:
                sizes[g] = {a: int(s) for a, s in json.loads(
                    rw.status().tablet_sizes_json or "{}").items()}
            finally:
                rw.close()
        # replicated tablets are the load controller's responsibility —
        # their copies also inflate holder sizes, which would mislead the
        # size-only decision
        pick = choose_rebalance_move(
            sizes, blocked=self.zero.moving_tablets()
            | set(self.zero.replicas()))
        if pick is None:
            return None
        attr, _src, dst, sz = pick
        out = self.move_tablet(attr, dst)
        out["bytes"] = sz
        return out

    def remove_node(self, group: int, addr: str) -> bool:
        """Drop a member from the membership registry (zero /removeNode,
        http.go:38-128); its replicas stop being move/leader candidates."""
        with self.svc._lock:
            members = self.svc._members.get(group, [])
            if addr in members:
                members.remove(addr)
                return True
        return False


def fleet_scrape(svc: ZeroService) -> dict:
    """Poll every registered worker's Status for its shipped metric
    snapshot (StatusResponse.metrics_json — the same probe that carries
    the placement load reports) and return
    {"nodes": {addr: export}, "merged": merged, "unreachable": [...]}.
    Histograms merge exactly (fixed buckets, utils/metrics.merge_exports);
    counters and keyed gauges sum."""
    from concurrent import futures as _futures

    from ..parallel.remote import RemoteWorker
    from ..utils.metrics import merge_exports

    with svc._lock:
        addrs = sorted({a for addrs in svc._members.values()
                        for a in addrs})

    def poll(a: str):
        rw = RemoteWorker(a)
        try:
            st = rw.status(timeout=2.0)
            return a, json.loads(st.metrics_json or "{}")
        except Exception:
            return a, None               # RPC failed: truly unreachable
        finally:
            rw.close()

    nodes: dict[str, dict] = {}
    unreachable: list[str] = []
    if addrs:
        # concurrent polls: a partially-down fleet must not push the
        # scrape past Prometheus's timeout (serial 2s-per-dead-worker
        # would — and a down fleet is exactly when the view matters)
        with _futures.ThreadPoolExecutor(
                max_workers=min(len(addrs), 16)) as pool:
            for a, snap in pool.map(poll, addrs):
                if snap is None:
                    unreachable.append(a)
                elif snap:
                    nodes[a] = snap
                # else: reachable but no snapshot shipped (older binary
                # mid rolling upgrade) — NOT unreachable, just absent
    return {"nodes": nodes,
            "merged": merge_exports(list(nodes.values())),
            "unreachable": unreachable}


def serve_zero_http(svc: ZeroService, ops: ZeroOps, host: str = "127.0.0.1",
                    port: int = 0, controller=None):
    """Zero's ops HTTP endpoints (dgraph/cmd/zero/http.go:38-130):
    GET /state, GET /moveTablet?tablet=X&group=N,
    GET /removeNode?group=N&addr=A, plus the placement surface —
    GET /placement (controller decision log + load book + config),
    GET /addReplica?tablet=X&group=N, GET /dropReplica?tablet=X&group=N,
    GET /shipReplica?tablet=X&group=N — and the fleet metrics surface
    (ISSUE 13): GET /metrics/fleet (one Prometheus exposition summing/
    merging every worker's scrape — histograms merge exactly because
    buckets are fixed) and GET /debug/fleet (the per-node + merged JSON).
    Returns (server, bound_port)."""
    import http.server
    import urllib.parse

    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):     # noqa: N802 — quiet
            pass

        def _reply(self, code: int, obj) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):              # noqa: N802 — http.server API
            u = urllib.parse.urlparse(self.path)
            q = urllib.parse.parse_qs(u.query)
            try:
                if u.path == "/state":
                    self._reply(200, json.loads(svc.state(
                        ipb.ZeroStateRequest(), None).state_json))
                elif u.path == "/moveTablet":
                    out = ops.move_tablet(q["tablet"][0],
                                          int(q["group"][0]))
                    self._reply(200, out)
                elif u.path == "/removeNode":
                    ok = ops.remove_node(int(q["group"][0]), q["addr"][0])
                    self._reply(200 if ok else 404, {"removed": ok})
                elif u.path == "/addReplica":
                    self._reply(200, ops.install_replica(
                        q["tablet"][0], int(q["group"][0])))
                elif u.path == "/dropReplica":
                    ok = ops.drop_replica(q["tablet"][0],
                                          int(q["group"][0]))
                    self._reply(200 if ok else 404, {"dropped": ok})
                elif u.path == "/shipReplica":
                    self._reply(200, ops.ship_replica_delta(
                        q["tablet"][0], int(q["group"][0])))
                elif u.path == "/metrics/fleet":
                    from ..obs import prom as _prom

                    merged = fleet_scrape(svc)["merged"]
                    body, ctype = _prom.negotiated(
                        self.headers.get("Accept"),
                        lambda ex: _prom.render_export(merged,
                                                       exemplars=ex))
                    self.send_response(200)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif u.path == "/debug/fleet":
                    self._reply(200, fleet_scrape(svc))
                elif u.path == "/placement":
                    if controller is None:
                        self._reply(200, {"enabled": False,
                                          "replicaMap": {
                                              a: sorted(gs) for a, gs in
                                              ops.zero.replicas().items()}})
                    else:
                        self._reply(200, controller.snapshot())
                else:
                    self._reply(404, {"error": f"unknown path {u.path}"})
            except Exception as e:      # noqa: BLE001 — ops surface
                self._reply(500, {"error": str(e)})

    httpd = http.server.ThreadingHTTPServer((host, port), Handler)
    # dgraph: allow(ctxvar-copy) ops-HTTP accept loop: requests root
    # their own context at the handler
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, httpd.server_address[1]


def serve_zero(zero: Zero, addr: str = "localhost:0", max_workers: int = 8,
               svc: "ZeroService | None" = None):
    """Start the Zero gRPC server; returns (server, bound_port, service).
    Pass a pre-built svc when a ZeroReplica must be attached before the
    handler map is registered (multi-zero mode)."""
    svc = svc if svc is not None else ZeroService(zero)
    from ..parallel.remote import GRPC_OPTIONS

    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers),
                         options=GRPC_OPTIONS)
    server.add_generic_rpc_handlers((svc.handler(),))
    port = server.add_insecure_port(addr)
    if port == 0:
        raise Unavailable(f"could not bind zero listener on {addr}")
    server.start()
    return server, port, svc


class ZeroClient:
    """Client stub for a remote Zero — mirrors the library surface the
    dispatcher and write path consume (tablets/should_serve/oracle calls).

    Accepts a comma-separated list of zero addresses (multi-zero): a call
    that hits a dead zero or a standby (FAILED_PRECONDITION "not zero
    leader") rotates to the next address and retries, so failover is
    transparent to workers and clients."""

    _STUBS = {
        "_connect": ("Connect", ipb.ZeroConnectRequest,
                     ipb.ZeroConnectResponse),
        "_new_txn": ("NewTxn", ipb.ZeroLeaseRequest, ipb.ZeroLeaseResponse),
        "_timestamps": ("Timestamps", ipb.ZeroLeaseRequest,
                        ipb.ZeroLeaseResponse),
        "_assign_uids": ("AssignUids", ipb.ZeroLeaseRequest,
                         ipb.ZeroLeaseResponse),
        "_commit": ("CommitOrAbort", ipb.ZeroCommitRequest,
                    ipb.ZeroCommitResponse),
        "_should_serve": ("ShouldServe", ipb.ZeroTabletRequest,
                          ipb.ZeroTabletResponse),
        "_state": ("State", ipb.ZeroStateRequest, ipb.ZeroStateResponse),
        "_zero_ship": ("ZeroShip", ipb.ZeroShipRequest,
                       ipb.ZeroShipResponse),
        "_zero_vote": ("ZeroVote", ipb.ZeroVoteRequest,
                       ipb.ZeroVoteResponse),
        "_zero_ping": ("ZeroPing", ipb.ZeroPingRequest,
                       ipb.ZeroPingResponse),
    }

    def __init__(self, addr: str | list[str]) -> None:
        self.addrs = ([a.strip() for a in addr.split(",") if a.strip()]
                      if isinstance(addr, str) else list(addr))
        self._i = 0
        self.channel = None
        self._open(self.addrs[0])

    @property
    def addr(self) -> str:
        return self.addrs[self._i]

    def _open(self, addr: str) -> None:
        from ..parallel.remote import GRPC_OPTIONS

        if self.channel is not None:
            self.channel.close()
        self.channel = grpc.insecure_channel(addr, options=GRPC_OPTIONS)
        for attr, (name, req_cls, resp_cls) in self._STUBS.items():
            setattr(self, attr, self.channel.unary_unary(
                f"/{SERVICE}/{name}",
                request_serializer=req_cls.SerializeToString,
                response_deserializer=resp_cls.FromString))

    def _rotate(self) -> None:
        self._i = (self._i + 1) % len(self.addrs)
        self._open(self.addrs[self._i])

    def _rpc(self, stub_name: str, req, timeout: float = 10.0):
        """Issue an RPC with leader failover: dead zero / standby rejection
        rotates to the next address (2 passes over the ring). When a trace
        is active, the call runs under a client span and propagates the
        span context to Zero (its server span rides back in trailing
        metadata), so coordinator hops show in the query's trace."""
        sp = otrace.current()
        if sp is None:
            return self._rpc_raw(stub_name, req, timeout, None)
        with sp.tracer.start(f"zero:{self._STUBS[stub_name][0]}", parent=sp,
                             kind="client",
                             attrs={"addr": self.addr}) as rsp:
            return self._rpc_raw(stub_name, req, timeout, rsp)

    def _rpc_raw(self, stub_name: str, req, timeout: float, rsp):
        import random as _random

        last = None
        for attempt in range(max(2 * len(self.addrs), 1)):
            # budgeted callers never start an attempt past their deadline
            # — a pre-send check is unambiguous (nothing went out)
            dl.check(f"zero:{self._STUBS[stub_name][0]}")
            faults.fire("zero.rpc")
            try:
                stub = getattr(self, stub_name)
                call_timeout = dl.clamp(timeout)
                if call_timeout <= 0:
                    # budget hit zero between the check above and here:
                    # a pre-send raise is unambiguous (nothing went out),
                    # unlike falling back to the full unclamped timeout
                    raise DeadlineExceeded(
                        f"zero:{self._STUBS[stub_name][0]} budget "
                        "exhausted before send")
                md = []
                ddl = dl.to_metadata()
                if ddl is not None:
                    md.append(ddl)
                if rsp is None:
                    if not md:
                        return stub(req, timeout=call_timeout)
                    return stub(req, timeout=call_timeout,
                                metadata=tuple(md))
                md.append((otrace.WIRE_KEY,
                           f"{rsp.trace_id}:{rsp.span_id}"))
                resp, call = stub.with_call(
                    req, timeout=call_timeout, metadata=tuple(md))
                for k, v in call.trailing_metadata() or ():
                    if k == otrace.SPANS_KEY:
                        rsp.tracer.add_remote(otrace.decode_spans(v))
                return resp
            except grpc.RpcError as e:
                code = e.code()
                # explicit DEADLINE_EXCEEDED handling: an in-flight
                # timeout is ambiguous — re-firing a CommitOrAbort or
                # AssignUids that DID land would corrupt txn/lease state —
                # so it surfaces, typed, with NO rotation retry.
                if code == grpc.StatusCode.DEADLINE_EXCEEDED:
                    raise DeadlineExceeded(
                        f"zero:{self._STUBS[stub_name][0]} deadline "
                        f"exceeded at {self.addr}") from e
                # rotate only on signals that the call was NOT processed
                # (dead zero / standby rejection), with full-jitter
                # backoff between attempts so a thundering herd of
                # clients doesn't re-dogpile the surviving zero in step
                if len(self.addrs) > 1 and code in (
                        grpc.StatusCode.UNAVAILABLE,
                        grpc.StatusCode.FAILED_PRECONDITION):
                    last = e
                    self._rotate()
                    pause = backoff_s(attempt, base_s=0.05, cap_s=0.5,
                                      rng=_random)
                    rem = dl.remaining()
                    if rem is not None and pause >= rem:
                        raise      # sleeping would blow the budget
                    time.sleep(pause)
                    continue
                raise
        raise last

    def connect(self, addr: str, group: int = -1) -> tuple[int, int]:
        r = self._rpc("_connect", ipb.ZeroConnectRequest(addr=addr,
                                                         group=group))
        return r.group, r.replica_id

    def new_txn(self) -> int:
        return self._rpc("_new_txn", ipb.ZeroLeaseRequest(n=1)).first

    def timestamps(self, n: int = 1) -> int:
        return self._rpc("_timestamps", ipb.ZeroLeaseRequest(n=n)).first

    def assign_uids(self, n: int) -> int:
        return self._rpc("_assign_uids", ipb.ZeroLeaseRequest(n=n)).first

    def commit(self, start_ts: int, conflict_keys, preds) -> int:
        """Returns commit_ts; raises TxnConflict on SSI abort."""
        r = self._rpc("_commit", ipb.ZeroCommitRequest(
            start_ts=start_ts, conflict_keys=list(conflict_keys),
            preds=sorted(preds)))
        if r.aborted:
            raise TxnConflict(f"txn {start_ts} aborted by oracle")
        return r.commit_ts

    def abort(self, start_ts: int) -> None:
        self._rpc("_commit",
                  ipb.ZeroCommitRequest(start_ts=start_ts, abort=True))

    def should_serve(self, attr: str) -> int:
        return self._rpc("_should_serve",
                         ipb.ZeroTabletRequest(attr=attr)).group

    def tablets(self) -> dict[str, int]:
        return {a: g for a, g in self.state().get("tabletMap", {}).items()}

    def state(self) -> dict:
        return json.loads(
            self._rpc("_state", ipb.ZeroStateRequest()).state_json)

    # -- multi-zero replication RPCs (leader <-> standby, no rotation) -------

    def zero_ship(self, term: int, seq: int, state_json: str,
                  members_json: str = "") -> ipb.ZeroShipResponse:
        return self._zero_ship(ipb.ZeroShipRequest(
            term=term, seq=seq, state_json=state_json,
            members_json=members_json), timeout=3.0)

    def zero_vote(self, term: int, seq: int,
                  candidate: str) -> ipb.ZeroVoteResponse:
        return self._zero_vote(ipb.ZeroVoteRequest(
            term=term, seq=seq, candidate=candidate), timeout=1.5)

    def zero_ping(self, term: int, leader_addr: str,
                  members: list[str]) -> ipb.ZeroPingResponse:
        return self._zero_ping(ipb.ZeroPingRequest(
            term=term, leader_addr=leader_addr, members=members),
            timeout=1.5)

    # move fences are server-side in this topology
    def writes_blocked(self, _attr: str) -> bool:
        return False

    def moving_tablets(self) -> set:
        return set()

    def close(self) -> None:
        self.channel.close()
