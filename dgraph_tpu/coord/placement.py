"""Self-driving shard placement: a Zero-resident controller that scores
tablets by live load and heals skew with moves + hot-tablet read replicas.

The reference's Zero rebalances by SIZE alone (dgraph/cmd/zero/tablet.go:
60-74); under the Zipfian traffic the north star assumes, one hot
predicate pins one group while the others idle and sizes say nothing is
wrong. This controller closes the loop with the signals the system
already produces:

  inputs   per-tablet load reports — reads / writes / result bytes /
           serve seconds, counted at each worker's serve seam and shipped
           on the Status probe (tablet_load_json) — plus the same
           tablet_sizes the size-based rebalancer used.
  score    rate x log2(size): work per second weighted by how expensive
           the tablet is to serve (a hot 1 GB tablet outranks a hot 1 KB
           one; a cold tablet of any size scores ~0).
  actions  (a) tablet MOVES through the existing chunked resumable move
           path, to equalize group utilization;
           (b) read-only tablet REPLICAS on other groups for
           skew-dominant read-heavy tablets — moving those only moves
           the hotspot — kept fresh by shipping the owner's O(Δ)
           journal deltas (storage/store.delta_since, PR 2); the query
           router spreads reads across holders and collapses to the
           primary for anything a replica cannot prove fresh (the
           FAILED_PRECONDITION machinery from PR 7).
  guards   hysteresis (imbalance must persist `persist_ticks` polls),
           per-tablet cooldown, one action per tick, and a minimum
           cluster rate below which only demotions run — the controller
           must never thrash.

The decision core (`plan_action`) is pure: sizes + rates + maps in,
proposal out — unit-testable with no cluster at all. The controller
wraps it with collection, hysteresis state, the decision log, metrics,
and an executor adapter (wire mode: coord/zero_service.ZeroOps; embedded
mode: coord/cluster.Cluster).
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from dataclasses import dataclass

from ..utils import faults, locks

WRITE_WEIGHT = 2.0     # a write costs ~2 reads (apply + invalidation)


@dataclass
class TabletRate:
    """One tablet's measured load on one group, per second."""

    reads: float = 0.0
    writes: float = 0.0
    bytes: float = 0.0
    serve_s: float = 0.0

    def rate(self) -> float:
        return self.reads + WRITE_WEIGHT * self.writes

    def read_heavy(self, factor: float) -> bool:
        return self.reads >= factor * max(self.writes, 1e-9)


@dataclass
class PlacementConfig:
    threshold: float = 0.35      # act when utilization spread exceeds this
    persist_ticks: int = 2       # imbalance must hold this many polls
    cooldown_s: float = 30.0     # per-tablet quiet period after an action
    max_replicas: int = 2        # read-replica holders per tablet
    read_dominant: float = 3.0   # reads >= 3x writes => replica-eligible
    skew_frac: float = 0.5       # tablet >= 50% of its group => skew-dominant
    min_rate: float = 0.5        # below this cluster req/s, only demotions
    idle_drop_rate: float = 0.05  # tablet req/s under which replicas demote


@dataclass
class Action:
    kind: str                    # "move" | "add_replica" | "drop_replica"
    attr: str
    src: int                     # source / owner group
    dst: int                     # destination / holder group
    reason: str
    spread: float = 0.0


def tablet_score(size_bytes: float, rate: float) -> float:
    """size x measured load: work per second, weighted by how expensive
    the tablet is to serve. Pure rate would move a hot 1 KB tablet before
    a warm 1 GB one; pure size is the reference's blind spot."""
    return rate * max(1.0, math.log2(2.0 + max(float(size_bytes), 0.0)))


def utilization(sizes: dict[int, dict[str, float]],
                rates: dict[int, dict[str, TabletRate]]) -> tuple[
                    float, dict[int, float], dict[int, dict[str, float]]]:
    """(spread, per-group utilization, per-group per-tablet scores).
    Spread = (max - min) / max over group utilizations; 0 when idle."""
    groups = set(sizes) | set(rates)
    # a tablet's size is a property of the TABLET, not of each report:
    # replica holders serve the same data, and a holder whose (TTL-cached)
    # size report hasn't caught up yet must not score the same traffic
    # 14x lower than the owner
    attr_size: dict[str, float] = {}
    for g in groups:
        for attr, sz in sizes.get(g, {}).items():
            attr_size[attr] = max(attr_size.get(attr, 0.0), float(sz))
    per_tablet: dict[int, dict[str, float]] = {}
    per_group: dict[int, float] = {}
    for g in groups:
        grates = rates.get(g, {})
        scores = {attr: tablet_score(attr_size.get(attr, 0.0), tr.rate())
                  for attr, tr in grates.items()}
        per_tablet[g] = scores
        per_group[g] = sum(scores.values())
    if not per_group:
        return 0.0, {}, {}
    hi = max(per_group.values())
    lo = min(per_group.values())
    spread = (hi - lo) / hi if hi > 0 else 0.0
    return spread, per_group, per_tablet


def plan_action(sizes: dict[int, dict[str, float]],
                rates: dict[int, dict[str, TabletRate]],
                tablets: dict[str, int],
                replicas: dict[str, dict[int, int]],
                cfg: PlacementConfig,
                blocked: set[str] = frozenset()) -> tuple[
                    Action | None, dict]:
    """The pure decision: one proposed action (or None) + diagnostics.

    Healing order for an over-threshold spread, hottest group vs coldest:
      1. the hottest tablet is skew-DOMINANT and read-heavy -> replicate
         it onto the coldest group (moving it would only move the pin);
      2. otherwise move the largest-scoring tablet that fits half the
         utilization gap (the anti-ping-pong rule, load-weighted);
      3. a read-heavy hot tablet too big for the gap -> replicate anyway.
    Below threshold (or idle): demote replicas whose tablet went cold.
    """
    spread, per_group, per_tablet = utilization(sizes, rates)
    diag = {"spread": round(spread, 4),
            "utilization": {g: round(v, 3) for g, v in per_group.items()}}
    if len(per_group) < 2:
        return None, diag

    # tablet totals across every serving group (owner + replica holders)
    tablet_rate: dict[str, float] = {}
    for g, grates in rates.items():
        for attr, tr in grates.items():
            tablet_rate[attr] = tablet_rate.get(attr, 0.0) + tr.rate()
    total_rate = sum(tablet_rate.values())
    diag["total_rate"] = round(total_rate, 3)

    def demotion() -> Action | None:
        for attr in sorted(replicas):
            holders = replicas[attr]
            if not holders or attr in blocked:
                continue
            if tablet_rate.get(attr, 0.0) < cfg.idle_drop_rate:
                # relieve the busiest holder first
                dst = max(holders, key=lambda g: per_group.get(g, 0.0))
                return Action("drop_replica", attr, tablets.get(attr, -1),
                              dst, reason="tablet went cold", spread=spread)
        return None

    if total_rate < cfg.min_rate or spread <= cfg.threshold:
        return demotion(), diag

    hot = max(per_group, key=lambda g: per_group[g])
    cold = min(per_group, key=lambda g: per_group[g])
    if hot == cold:
        return None, diag
    gap = (per_group[hot] - per_group[cold]) / 2.0
    hot_tablets = sorted(per_tablet.get(hot, {}).items(),
                         key=lambda kv: -kv[1])
    hot_tablets = [(a, s) for a, s in hot_tablets
                   if a not in blocked and s > 0]
    if not hot_tablets:
        return None, diag
    top_attr, top_score = hot_tablets[0]
    top_tr = rates.get(hot, {}).get(top_attr, TabletRate())

    def replica_ok(attr: str) -> bool:
        h = replicas.get(attr, {})
        return (len(h) < cfg.max_replicas and cold not in h
                and tablets.get(attr) != cold)

    # the top tablet serving FROM a replica holder has no move story —
    # only owners move; holders shed load by demotion elsewhere
    top_owned_here = tablets.get(top_attr) == hot

    if (top_owned_here and top_score >= cfg.skew_frac * per_group[hot]
            and top_tr.read_heavy(cfg.read_dominant)
            and replica_ok(top_attr)):
        return Action("add_replica", top_attr, hot, cold,
                      reason=f"skew-dominant read-heavy tablet "
                             f"({top_score:.1f} of {per_group[hot]:.1f})",
                      spread=spread), diag
    for attr, sc in hot_tablets:
        if sc <= gap and tablets.get(attr) == hot:
            return Action("move", attr, hot, cold,
                          reason=f"fits half the gap "
                                 f"({sc:.1f} <= {gap:.1f})",
                          spread=spread), diag
    if (top_owned_here and top_tr.read_heavy(cfg.read_dominant)
            and replica_ok(top_attr)):
        return Action("add_replica", top_attr, hot, cold,
                      reason="hot tablet exceeds the move gap; "
                             "read-heavy -> replicate",
                      spread=spread), diag
    return None, diag


def diff_rates(prev: dict, cur: dict, dt: float) -> dict[str, TabletRate]:
    """Per-second rates from two cumulative {attr: {"r","w","b","d"}}
    polls. A counter that went backwards (worker restart) restarts from
    its current value instead of producing a negative rate."""
    out: dict[str, TabletRate] = {}
    dt = max(dt, 1e-6)
    for attr, c in cur.items():
        p = prev.get(attr, {})

        def d(k: str) -> float:
            dv = float(c.get(k, 0.0)) - float(p.get(k, 0.0))
            return (dv if dv >= 0 else float(c.get(k, 0.0))) / dt
        out[attr] = TabletRate(reads=d("r"), writes=d("w"),
                               bytes=d("b"), serve_s=d("d"))
    return out


class TabletLoadBook:
    """Cumulative per-tablet load counters with a labeled-gauge mirror:
    dgraph_tablet_load{pred,group,stat} on /metrics, the same {attr:
    {"r","w","b","d"}} snapshot shape workers ship on Status — so the
    controller's inputs are inspectable independently of its decisions."""

    def __init__(self, metrics=None, group: int = 0) -> None:
        self._lock = locks.Lock("placement.TabletLoadBook._lock")
        self._rows: dict[str, list[float]] = {}
        self.group = int(group)
        self._gauge = (metrics.keyed("dgraph_tablet_load",
                                     labels=("pred", "group", "stat"))
                       if metrics is not None else None)

    def _bump(self, attr: str, i: int, v: float) -> None:
        with self._lock:
            row = self._rows.get(attr)
            if row is None:
                row = self._rows[attr] = [0.0, 0.0, 0.0, 0.0]
            row[i] += v
            if self._gauge is not None:
                stat = ("reads", "writes", "bytes", "serve_ms")[i]
                scale = 1000.0 if i == 3 else 1.0
                self._gauge.set(f"{attr}|{self.group}|{stat}",
                                int(row[i] * scale))

    def record_read(self, attr: str, out_bytes: float = 0.0,
                    serve_s: float = 0.0) -> None:
        self._bump(attr, 0, 1.0)
        if out_bytes:
            self._bump(attr, 2, float(out_bytes))
        if serve_s:
            self._bump(attr, 3, float(serve_s))

    def record_write(self, attr: str, n: float = 1.0) -> None:
        self._bump(attr, 1, float(n))

    def snapshot(self) -> dict:
        with self._lock:
            return {a: {"r": r[0], "w": r[1], "b": r[2],
                        "d": round(r[3], 6)}
                    for a, r in self._rows.items()}


class PlacementController:
    """The Zero-resident control loop: poll load reports, keep replicas
    fresh, score, and heal — one guarded action per tick, every decision
    journaled.

    `collect` returns {group: (sizes {attr: bytes}, cumulative loads
    {attr: {"r","w","b","d"}})}. `executor` provides move(attr, dst),
    add_replica(attr, dst), drop_replica(attr, group) and optionally
    ship_deltas() for wire-mode freshness. `zero` is the tablet/replica
    map authority (coord/zero.Zero or a client with the same surface).
    """

    DECISION_LOG = 128

    def __init__(self, zero, collect, executor,
                 cfg: PlacementConfig | None = None,
                 metrics=None, logger=None,
                 clock=time.monotonic) -> None:
        from ..utils import metrics as metrics_mod

        self.zero = zero
        self.collect = collect
        self.executor = executor
        self.cfg = cfg or PlacementConfig()
        self.metrics = metrics if metrics is not None \
            else metrics_mod.Registry()
        self.log = logger
        self.clock = clock
        self._lock = locks.Lock("placement.PlacementController._lock")
        # journal lock is separate and tiny: GET /placement must stay
        # readable WHILE a tick streams a multi-second move under _lock —
        # the decision log matters most exactly then
        self._jlock = locks.Lock("placement.PlacementController._jlock")
        self._prev: dict[int, tuple[float, dict]] = {}  # g -> (t, cum loads)
        self._rates: dict[int, dict[str, TabletRate]] = {}
        self._streak = 0                    # consecutive over-threshold polls
        self._primed = False                # first poll only baselines
        self._last_action: dict[str, float] = {}        # attr -> clock()
        self._decisions: deque[dict] = deque(maxlen=self.DECISION_LOG)
        self._gauge = self.metrics.keyed(
            "dgraph_tablet_load", labels=("pred", "group", "stat"))
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.last_diag: dict = {}

    # -- collection ----------------------------------------------------------

    def _poll(self) -> tuple[dict, dict]:
        """One report round: (sizes, per-second rates) per group."""
        now = self.clock()
        reports = self.collect()
        sizes: dict[int, dict[str, float]] = {}
        rates: dict[int, dict[str, TabletRate]] = {}
        for g, (gsizes, cum) in reports.items():
            sizes[g] = dict(gsizes)
            pt, prev = self._prev.get(g, (now, {}))
            rates[g] = diff_rates(prev, cum, now - pt) if prev \
                else {a: TabletRate() for a in cum}
            self._prev[g] = (now, dict(cum))
            for attr, tr in rates[g].items():
                self._gauge.set(f"{attr}|{g}|reads",
                                int(cum.get(attr, {}).get("r", 0)))
                self._gauge.set(f"{attr}|{g}|writes",
                                int(cum.get(attr, {}).get("w", 0)))
                self._gauge.set(f"{attr}|{g}|bytes",
                                int(cum.get(attr, {}).get("b", 0)))
        self._rates = rates
        return sizes, rates

    # -- the tick ------------------------------------------------------------

    def tick(self) -> Action | None:
        """One controller pass. Returns the EXECUTED action, if any."""
        m = self.metrics
        m.counter("dgraph_placement_ticks_total").inc()
        with self._lock:
            # freshness first: replicas pull the owner's journal deltas
            # before any decision reads the cluster state
            ship = getattr(self.executor, "ship_deltas", None)
            if ship is not None:
                try:
                    shipped = ship()
                    if shipped:
                        m.counter(
                            "dgraph_placement_delta_ships_total").inc(
                                shipped)
                except Exception as e:
                    m.counter("dgraph_placement_errors_total").inc()
                    self._journal({"event": "delta_ship_error",
                                   "error": str(e)})
            try:
                faults.fire("zero.rebalance_decide", m=m)
                sizes, rates = self._poll()
            except Exception as e:
                m.counter("dgraph_placement_errors_total").inc()
                self._journal({"event": "collect_error", "error": str(e)})
                return None
            if not self._primed:
                # the first poll only baselines the cumulative counters —
                # acting on an all-zero rate window would demote every
                # replica the moment a restarted controller comes up
                self._primed = True
                self._journal({"event": "baseline"})
                return None
            proposal, diag = plan_action(
                sizes, rates, self.zero.tablets(), self.zero.replicas(),
                self.cfg, blocked=set(self.zero.moving_tablets()))
            self.last_diag = diag
            # hysteresis: imbalance must persist before a heal action;
            # demotions are the healthy-state path and skip the streak
            if diag.get("spread", 0.0) > self.cfg.threshold:
                self._streak += 1
            else:
                self._streak = 0
            if proposal is None:
                return None
            if proposal.kind != "drop_replica" \
                    and self._streak < self.cfg.persist_ticks:
                self._journal({"event": "defer", "streak": self._streak,
                               **self._act_dict(proposal)})
                return None
            last = self._last_action.get(proposal.attr)
            if last is not None and \
                    self.clock() - last < self.cfg.cooldown_s:
                m.counter("dgraph_placement_cooldown_skips_total").inc()
                self._journal({"event": "cooldown",
                               **self._act_dict(proposal)})
                return None
            return self._execute(proposal)

    def _execute(self, a: Action) -> Action | None:
        m = self.metrics
        try:
            if a.kind == "move":
                out = self.executor.move(a.attr, a.dst)
                m.counter("dgraph_placement_moves_total").inc()
            elif a.kind == "add_replica":
                out = self.executor.add_replica(a.attr, a.dst)
                m.counter("dgraph_placement_replicas_added_total").inc()
            else:
                out = self.executor.drop_replica(a.attr, a.dst)
                m.counter("dgraph_placement_replicas_dropped_total").inc()
        except Exception as e:
            m.counter("dgraph_placement_errors_total").inc()
            self._journal({"event": "action_error", "error": str(e),
                           **self._act_dict(a)})
            # errors still start the cooldown: retrying a failing move
            # every tick IS thrash
            self._last_action[a.attr] = self.clock()
            return None
        self._last_action[a.attr] = self.clock()
        self._streak = 0
        self._journal({"event": "action", "result": self._safe(out),
                       **self._act_dict(a)})
        if self.log is not None:
            self.log.info("placement action", kind=a.kind, tablet=a.attr,
                          src=a.src, dst=a.dst, reason=a.reason,
                          spread=round(a.spread, 3))
        return a

    @staticmethod
    def _safe(out):
        try:
            import json as _json

            _json.dumps(out)
            return out
        except (TypeError, ValueError):
            return str(out)

    @staticmethod
    def _act_dict(a: Action) -> dict:
        return {"kind": a.kind, "tablet": a.attr, "src": a.src,
                "dst": a.dst, "reason": a.reason,
                "spread": round(a.spread, 4)}

    def _journal(self, entry: dict) -> None:
        entry = {"at": round(time.time(), 3), **entry}
        with self._jlock:
            self._decisions.appendleft(entry)

    # -- surfaces ------------------------------------------------------------

    def decisions(self, n: int = 32) -> list[dict]:
        with self._jlock:
            return [d for i, d in enumerate(self._decisions) if i < n]

    def snapshot(self) -> dict:
        """The /placement payload: config, live diagnostics, maps, log.
        Deliberately does NOT take the tick lock — it must answer while a
        tick is mid-move; _rates/last_diag are replaced wholesale per
        poll, so a concurrent read sees a consistent previous view."""
        cfg = self.cfg
        rates = {str(g): {a: {"reads_s": round(tr.reads, 3),
                              "writes_s": round(tr.writes, 3),
                              "rate": round(tr.rate(), 3)}
                          for a, tr in gr.items()}
                 for g, gr in self._rates.items()}
        return {
            "enabled": True,
            "config": {"threshold": cfg.threshold,
                       "persist_ticks": cfg.persist_ticks,
                       "cooldown_s": cfg.cooldown_s,
                       "max_replicas": cfg.max_replicas,
                       "read_dominant": cfg.read_dominant,
                       "skew_frac": cfg.skew_frac,
                       "min_rate": cfg.min_rate},
            "diag": self.last_diag,
            "rates": rates,
            "tabletMap": self.zero.tablets(),
            "replicaMap": {a: {str(g): wm for g, wm in gs.items()}
                           for a, gs in self.zero.replicas().items()},
            "decisions": self.decisions(),
        }

    # -- background loop -----------------------------------------------------

    def start(self, interval_s: float) -> None:
        if self._thread is not None:
            return

        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.tick()
                except Exception:
                    self.metrics.counter(
                        "dgraph_placement_errors_total").inc()

        # dgraph: allow(ctxvar-copy) detached controller bg loop
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="dgt-placement")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()


class ZeroOpsExecutor:
    """Wire-mode executor adapter over coord/zero_service.ZeroOps."""

    def __init__(self, ops) -> None:
        self.ops = ops

    def move(self, attr: str, dst: int):
        return self.ops.move_tablet(attr, dst)

    def add_replica(self, attr: str, dst: int):
        return self.ops.install_replica(attr, dst)

    def drop_replica(self, attr: str, group: int):
        return self.ops.drop_replica(attr, group)

    def ship_deltas(self) -> int:
        """Pull owner journal deltas to every holder whose watermark is
        behind the oracle's per-tablet floor. Returns ships performed."""
        zero = self.ops.zero
        shipped = 0
        for attr, holders in sorted(zero.replicas().items()):
            floor = zero.oracle.pred_commit.get(attr, 0)
            for g, wm in sorted(holders.items()):
                if floor > wm:
                    self.ops.ship_replica_delta(attr, g)
                    shipped += 1
        return shipped


def wire_collect(ops):
    """collect() for wire mode: each group leader's Status probe carries
    tablet_sizes_json + tablet_load_json."""
    import json as _json

    def collect() -> dict:
        out: dict = {}
        with ops.svc._lock:
            groups = list(ops.svc._members)
        for g in groups:
            try:
                rw = ops._leader_of(g)
            except Exception:
                continue
            try:
                st = rw.status()
                out[g] = (
                    {a: float(s) for a, s in _json.loads(
                        st.tablet_sizes_json or "{}").items()},
                    _json.loads(st.tablet_load_json or "{}"))
            except Exception:
                continue
            finally:
                rw.close()
        return out
    return collect
