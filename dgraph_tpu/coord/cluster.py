"""Multi-group cluster: per-group stores, tablet routing, predicate moves.

Reference semantics:
- worker/groups.go:292 BelongsTo — every predicate ("tablet") is owned by
  exactly one group; mutations and task execution route to the owner.
- worker/mutation.go:470 populateMutationMap — a mutation's edges are split
  by owning group and applied on each.
- worker/predicate_move.go:86-177 — moving a tablet: block writes, abort
  open txns touching it, stream every key of the predicate to the target
  group at a snapshot ts, flip the tablet map in Zero, delete at the source.

Topology: one shared Zero (oracle + uid lease + tablet map) over N group
stores in one process — the same collapse the reference's own test harness
uses (embedded zero+workers). Queries assemble a federated snapshot by
building each predicate's device arrays from its OWNING group's store, so
the Executor is unchanged. Cross-group transactions work because conflict
detection is centralized in the shared oracle while buffered layers live in
each group's store.
"""

from __future__ import annotations

import threading

from dgraph_tpu.coord.zero import Zero
from dgraph_tpu.query import dql
from dgraph_tpu.query import mutation as mut
from dgraph_tpu.query.engine import Executor
from dgraph_tpu.storage import keys as K
from dgraph_tpu.storage.csr_build import GraphSnapshot, build_pred
from dgraph_tpu.storage.postings import Op
from dgraph_tpu.storage.store import Store
from dgraph_tpu.utils.schema import SchemaState, parse_schema


class MoveInProgress(Exception):
    pass


class Cluster:
    """N group stores behind one Zero (embedded multi-group topology)."""

    def __init__(self, n_groups: int = 2, dirs: list[str] | None = None) -> None:
        self.zero = Zero(n_groups)
        self.stores = [Store(dirs[g] if dirs else None)
                       for g in range(n_groups)]
        self._lock = threading.RLock()
        self._txn_keys: dict[int, dict[int, list[bytes]]] = {}  # ts -> g -> keys

    # -- routing -------------------------------------------------------------

    def group_of(self, attr: str) -> int:
        return self.zero.should_serve(attr)

    def store_of(self, attr: str) -> Store:
        return self.stores[self.group_of(attr)]

    @property
    def schema(self) -> SchemaState:
        """Cluster-wide schema view: alter replicates entries to every group,
        but mutation-time INFERRED entries land only on the owning group's
        store — merge them all (each predicate is owned by exactly one group,
        so there are no conflicting entries)."""
        merged = SchemaState()
        for s in self.stores:
            for attr in s.schema.predicates():
                merged.set(s.schema.get(attr))
        return merged

    # -- schema --------------------------------------------------------------

    def alter(self, schema_text: str) -> None:
        for e in parse_schema(schema_text):
            for s in self.stores:
                s.set_schema(e)
        for a in getattr(self, "_assemblers", ()):
            a.invalidate()   # schema is structural: cached folds may be wrong

    # -- mutate --------------------------------------------------------------

    def mutate(self, set_nquads: str = "", del_nquads: str = "",
               commit_now: bool = True) -> dict[str, int]:
        """Split edges by owning group, apply on each, commit via the shared
        oracle (populateMutationMap + MutateOverNetwork)."""
        from dgraph_tpu.query import rdf

        nq_set = rdf.parse(set_nquads) if set_nquads else []
        nq_del = rdf.parse(del_nquads) if del_nquads else []
        with self._lock:
            for e in nq_set + nq_del:
                if self.zero.writes_blocked(e.predicate) or (
                        e.predicate == "*" and self.zero.moving_tablets()):
                    raise MoveInProgress(
                        f"predicate {e.predicate!r} is moving; retry")
            st = self.zero.oracle.new_txn()
            keys_by_group: dict[int, list[bytes]] = {}
            try:
                uid_map = mut.assign_uids(nq_set + nq_del, self.zero.uids)
                edges = mut.to_edges(nq_set, uid_map, Op.SET) + \
                    mut.to_edges(nq_del, uid_map, Op.DEL)
                by_group = mut.split_edges_by_group(
                    edges, len(self.stores), self.group_of)
                conflicts: list[bytes] = []
                preds: set[str] = set()
                for g, ge in sorted(by_group.items()):
                    touched, conflict, p = mut.apply_mutations(
                        self.stores[g], ge, st.start_ts)
                    keys_by_group[g] = touched
                    conflicts += conflict
                    preds |= p
                self.zero.oracle.track(st.start_ts, conflicts, sorted(preds))
                self._txn_keys[st.start_ts] = keys_by_group
            except BaseException:
                # abort everything buffered so far: leaked pending txns pin
                # the oracle's purge watermark forever
                for g, kb in keys_by_group.items():
                    self.stores[g].abort(st.start_ts, kb)
                self.zero.oracle.abort(st.start_ts)
                raise
            if commit_now:
                self.commit(st.start_ts)
        return uid_map

    def commit(self, start_ts: int) -> int:
        with self._lock:
            keys_by_group = self._txn_keys.pop(start_ts, {})
            try:
                commit_ts = self.zero.oracle.commit(start_ts)
            except Exception:
                for g, kb in keys_by_group.items():
                    self.stores[g].abort(start_ts, kb)
                raise
            for g, kb in keys_by_group.items():
                self.stores[g].commit(start_ts, commit_ts, kb)
            return commit_ts

    # -- query ---------------------------------------------------------------

    def query(self, q: str, variables: dict | None = None) -> dict:
        """Federated read: each predicate's snapshot arrays come from its
        owning group's store (ProcessTaskOverNetwork routes the same way),
        through per-store incremental assemblers — a commit touching one
        predicate re-folds one predicate, not the world per query
        (VERDICT r3 weak#9; posting/lists.go:243 read-through)."""
        with self._lock:
            # read_ts under the lock: a move completing in between would make
            # the moved predicate invisible (streamed copy commits above our
            # ts, source copy already deleted)
            read_ts = self.zero.oracle.read_ts()
            if not hasattr(self, "_assemblers"):
                from dgraph_tpu.storage.csr_build import SnapshotAssembler

                self._assemblers = [SnapshotAssembler(s) for s in self.stores]
            per_group = [a.snapshot(read_ts) for a in self._assemblers]
            snap = GraphSnapshot(read_ts)
            for attr, g in sorted(self.zero.tablets().items()):
                pd = per_group[g].preds.get(attr)
                if pd is not None:
                    snap.preds[attr] = pd
        return Executor(snap, self.schema).execute(dql.parse(q, variables))

    # -- predicate move ------------------------------------------------------

    def move_predicate(self, attr: str, dst_group: int) -> dict:
        """The full move protocol (worker/predicate_move.go:86-177):
        1. block writes on the tablet (new mutations raise MoveInProgress);
        2. abort open txns that touched it (Zero TryAbort);
        3. snapshot-read every key of the predicate at ts and stream the
           effective postings into the destination store under one txn;
        4. flip the tablet map;
        5. delete the predicate at the source;
        6. unblock writes.
        """
        src_group = self.group_of(attr)
        if src_group == dst_group:
            return {"moved_keys": 0, "aborted_txns": 0}
        src, dst = self.stores[src_group], self.stores[dst_group]
        self.zero.block_writes(attr)
        try:
            with self._lock:
                aborted = 0
                for ts in self.zero.oracle.pending_on(attr):
                    self.zero.oracle.abort(ts)
                    kb = self._txn_keys.pop(ts, {})
                    for g, keys in kb.items():
                        self.stores[g].abort(ts, keys)
                    aborted += 1
                read_ts = self.zero.oracle.read_ts()
                move_st = self.zero.oracle.new_txn()
                moved_keys: list[bytes] = []
                try:
                    for kind in (K.KeyKind.DATA, K.KeyKind.REVERSE,
                                 K.KeyKind.INDEX, K.KeyKind.COUNT):
                        for kb in src.keys_of(kind, attr):
                            pl = src.lists.get(kb)
                            if pl is None:
                                continue
                            key = K.parse_key(kb)
                            for p in pl.postings(read_ts):
                                dst.add_mutation(move_st.start_ts, key, p)
                            moved_keys.append(kb)
                    entry = src.schema.get(attr)
                    if entry is not None:
                        dst.set_schema(entry)
                    # the move txn carries no conflict keys (writes on attr
                    # are blocked), so the oracle commit always succeeds
                    commit_ts = self.zero.oracle.commit(move_st.start_ts)
                except BaseException:
                    # mid-stream failure: drop the partial copy and the
                    # pending move txn; source stays authoritative
                    dst.abort(move_st.start_ts, moved_keys)
                    self.zero.oracle.abort(move_st.start_ts)
                    raise
                dst.commit(move_st.start_ts, commit_ts, moved_keys)
                self.zero.move_tablet(attr, dst_group)
                src.delete_predicate(attr)
                return {"moved_keys": len(moved_keys), "aborted_txns": aborted}
        finally:
            self.zero.unblock_writes(attr)

    # -- auto-rebalance (dgraph/cmd/zero/tablet.go:60-74) ---------------------

    def rebalance_once(self) -> dict | None:
        """One pass of the reference's rebalance tick (decision logic shared
        with the Zero process: coord/zero.choose_rebalance_move). Returns
        the move stats or None."""
        from dgraph_tpu.coord.zero import choose_rebalance_move

        sizes = {g: self.stores[g].tablet_sizes()
                 for g in range(len(self.stores))}
        pick = choose_rebalance_move(sizes,
                                     blocked=self.zero.moving_tablets())
        if pick is None:
            return None
        attr, src, dst, sz = pick
        stats = self.move_predicate(attr, dst)
        stats.update(tablet=attr, src=src, dst=dst, bytes=sz)
        return stats

    def start_rebalancer(self, interval_s: float = 8.0) -> None:
        """Background rebalance tick (the --rebalance_interval loop)."""
        import time as _time

        def loop():
            while not self._stop_rebalance.is_set():
                try:
                    self.rebalance_once()
                except Exception:
                    pass                   # next tick retries
                self._stop_rebalance.wait(interval_s)

        self._stop_rebalance = threading.Event()
        self._rebalance_thread = threading.Thread(target=loop, daemon=True)
        self._rebalance_thread.start()

    def close(self) -> None:
        ev = getattr(self, "_stop_rebalance", None)
        if ev is not None:
            ev.set()
        for s in self.stores:
            s.close()
