"""Multi-group cluster: per-group stores, tablet routing, predicate moves.

Reference semantics:
- worker/groups.go:292 BelongsTo — every predicate ("tablet") is owned by
  exactly one group; mutations and task execution route to the owner.
- worker/mutation.go:470 populateMutationMap — a mutation's edges are split
  by owning group and applied on each.
- worker/predicate_move.go:86-177 — moving a tablet: block writes, abort
  open txns touching it, stream every key of the predicate to the target
  group at a snapshot ts, flip the tablet map in Zero, delete at the source.

Topology: one shared Zero (oracle + uid lease + tablet map) over N group
stores in one process — the same collapse the reference's own test harness
uses (embedded zero+workers). Queries assemble a federated snapshot by
building each predicate's device arrays from its OWNING group's store, so
the Executor is unchanged. Cross-group transactions work because conflict
detection is centralized in the shared oracle while buffered layers live in
each group's store.
"""

from __future__ import annotations

import threading

from dgraph_tpu.coord.zero import Zero
from dgraph_tpu.query import dql
from dgraph_tpu.query import mutation as mut
from dgraph_tpu.query.engine import Executor
from dgraph_tpu.storage import keys as K
from dgraph_tpu.storage.csr_build import GraphSnapshot, build_pred
from dgraph_tpu.storage.postings import Op
from dgraph_tpu.storage.store import Store
from dgraph_tpu.utils.schema import SchemaState, parse_schema


class MoveInProgress(Exception):
    pass


class Cluster:
    """N group stores behind one Zero (embedded multi-group topology)."""

    def __init__(self, n_groups: int = 2, dirs: list[str] | None = None) -> None:
        self.zero = Zero(n_groups)
        self.stores = [Store(dirs[g] if dirs else None)
                       for g in range(n_groups)]
        self._lock = threading.RLock()
        self._txn_keys: dict[int, dict[int, list[bytes]]] = {}  # ts -> g -> keys
        # per-(group, attr) cumulative load counters [reads, writes,
        # bytes, serve_s] — the embedded analog of the workers'
        # tablet_load_json report, feeding the placement controller
        self._loads: dict[tuple[int, str], list[float]] = {}
        self._rr = 0     # replica read spread cursor

    # -- routing -------------------------------------------------------------

    def group_of(self, attr: str) -> int:
        return self.zero.should_serve(attr)

    def store_of(self, attr: str) -> Store:
        return self.stores[self.group_of(attr)]

    @property
    def schema(self) -> SchemaState:
        """Cluster-wide schema view: alter replicates entries to every group,
        but mutation-time INFERRED entries land only on the owning group's
        store — merge them all (each predicate is owned by exactly one group,
        so there are no conflicting entries)."""
        merged = SchemaState()
        for s in self.stores:
            for attr in s.schema.predicates():
                merged.set(s.schema.get(attr))
        return merged

    # -- schema --------------------------------------------------------------

    def alter(self, schema_text: str) -> None:
        for e in parse_schema(schema_text):
            for s in self.stores:
                s.set_schema(e)
        for a in getattr(self, "_assemblers", ()):
            a.invalidate()   # schema is structural: cached folds may be wrong

    # -- mutate --------------------------------------------------------------

    def mutate(self, set_nquads: str = "", del_nquads: str = "",
               commit_now: bool = True) -> dict[str, int]:
        """Split edges by owning group, apply on each, commit via the shared
        oracle (populateMutationMap + MutateOverNetwork)."""
        from dgraph_tpu.query import rdf

        nq_set = rdf.parse(set_nquads) if set_nquads else []
        nq_del = rdf.parse(del_nquads) if del_nquads else []
        with self._lock:
            for e in nq_set + nq_del:
                if self.zero.writes_blocked(e.predicate) or (
                        e.predicate == "*" and self.zero.moving_tablets()):
                    raise MoveInProgress(
                        f"predicate {e.predicate!r} is moving; retry")
            st = self.zero.oracle.new_txn()
            keys_by_group: dict[int, list[bytes]] = {}
            try:
                uid_map = mut.assign_uids(nq_set + nq_del, self.zero.uids)
                edges = mut.to_edges(nq_set, uid_map, Op.SET) + \
                    mut.to_edges(nq_del, uid_map, Op.DEL)
                by_group = mut.split_edges_by_group(
                    edges, len(self.stores), self.group_of)
                conflicts: list[bytes] = []
                preds: set[str] = set()
                for g, ge in sorted(by_group.items()):
                    touched, conflict, p = mut.apply_mutations(
                        self.stores[g], ge, st.start_ts)
                    keys_by_group[g] = touched
                    conflicts += conflict
                    preds |= p
                self.zero.oracle.track(st.start_ts, conflicts, sorted(preds))
                self._txn_keys[st.start_ts] = keys_by_group
                for e in edges:
                    if e.attr == "*":
                        continue
                    row = self._loads.setdefault(
                        (self.group_of(e.attr), e.attr),
                        [0.0, 0.0, 0.0, 0.0])
                    row[1] += 1.0
            except BaseException:
                # abort everything buffered so far: leaked pending txns pin
                # the oracle's purge watermark forever
                for g, kb in keys_by_group.items():
                    self.stores[g].abort(st.start_ts, kb)
                self.zero.oracle.abort(st.start_ts)
                raise
            if commit_now:
                self.commit(st.start_ts)
        return uid_map

    def commit(self, start_ts: int) -> int:
        with self._lock:
            keys_by_group = self._txn_keys.pop(start_ts, {})
            try:
                commit_ts = self.zero.oracle.commit(start_ts)
            except Exception:
                for g, kb in keys_by_group.items():
                    self.stores[g].abort(start_ts, kb)
                raise
            for g, kb in keys_by_group.items():
                self.stores[g].commit(start_ts, commit_ts, kb)
            self._ship_replica_deltas(start_ts, commit_ts, keys_by_group)
            # live-query wake (ISSUE 18): the wire-mode seam — workers
            # applied, the querying node's manager re-evaluates. Touched
            # predicates derive from the committed keys themselves, so
            # the wake filter sees exactly what the journal recorded.
            live = getattr(self, "_live", None)
            if live is not None and live.active:
                preds = {K.kind_attr_of(kb)[1]
                         for kbs in keys_by_group.values() for kb in kbs}
                live.notify_commit(commit_ts, preds)
            return commit_ts

    def _ship_replica_deltas(self, start_ts: int, commit_ts: int,
                             keys_by_group: dict) -> None:
        """Embedded-mode replica freshness: rewrite each touched key of a
        replicated tablet on every holder at the SAME commit_ts, under the
        cluster lock — in-process holders are therefore always exact, so
        replica-spread reads are byte-identical to owner reads at any
        read_ts the embedded query path can produce (the wire path's
        asynchronous analog is ZeroOps.ship_replica_delta)."""
        replicas = self.zero.replicas()
        if not replicas:
            return
        from dgraph_tpu.storage.postings import Op as _Op
        from dgraph_tpu.storage.postings import Posting as _Posting

        touched: dict[str, list[bytes]] = {}
        for _g, kbs in keys_by_group.items():
            for kb in kbs:
                attr = K.kind_attr_of(kb)[1]
                if attr in replicas:
                    touched.setdefault(attr, []).append(kb)
        for attr, kbs in touched.items():
            owner = self.stores[self.zero.tablets()[attr]]
            for holder in sorted(replicas[attr]):
                hstore = self.stores[holder]
                for kb in kbs:
                    key = K.parse_key(kb)
                    pl = owner.lists.get(kb)
                    hstore.add_mutation(start_ts, key,
                                        _Posting(0, _Op.DEL_ALL))
                    if pl is not None:
                        for p in pl.postings(commit_ts):
                            hstore.add_mutation(start_ts, key, p)
                hstore.commit(start_ts, commit_ts, kbs)
                self.zero.set_replica_watermark(attr, holder, commit_ts)

    # -- query ---------------------------------------------------------------

    def query(self, q: str, variables: dict | None = None,
              read_ts: int | None = None) -> dict:
        """Federated read: each predicate's snapshot arrays come from its
        owning group's store (ProcessTaskOverNetwork routes the same way),
        through per-store incremental assemblers — a commit touching one
        predicate re-folds one predicate, not the world per query
        (VERDICT r3 weak#9; posting/lists.go:243 read-through).

        read_ts pins the snapshot timestamp (live-query re-evaluation at
        a notification's carried watermark); None reads the newest."""
        serving: dict[str, int] = {}
        with self._lock:
            # read_ts under the lock: a move completing in between would make
            # the moved predicate invisible (streamed copy commits above our
            # ts, source copy already deleted)
            if read_ts is None:
                read_ts = self.zero.oracle.read_ts()
            if not hasattr(self, "_assemblers"):
                from dgraph_tpu.storage.csr_build import SnapshotAssembler

                self._assemblers = [SnapshotAssembler(s) for s in self.stores]
            per_group = [a.snapshot(read_ts) for a in self._assemblers]
            snap = GraphSnapshot(read_ts)
            from dgraph_tpu.storage.csr_build import DelegateThunk, LazyPreds

            # lazy federation (ISSUE 15): the per-group assemblers hand
            # out fold-thunks — routing only needs tablet PRESENCE, so
            # delegate per-attr reads to the owning group's map instead
            # of folding every tablet at assembly time
            lazy = LazyPreds()
            snap.preds = lazy
            replicas = self.zero.replicas()
            for attr, g in sorted(self.zero.tablets().items()):
                src_g = g
                holders = replicas.get(attr)
                if holders:
                    # spread reads round-robin across owner + holders:
                    # embedded holders are exact at every commit (see
                    # _ship_replica_deltas), so any source is correct
                    cands = [g] + sorted(h for h in holders if h != g)
                    src_g = cands[self._rr % len(cands)]
                    self._rr += 1
                src = per_group[src_g].preds
                if attr not in src:
                    continue
                if getattr(src, "is_pending", lambda _a: False)(attr):
                    lazy.register(attr, DelegateThunk(src, attr))
                else:
                    pd = src.get(attr)
                    if pd is None:
                        continue
                    lazy[attr] = pd
                serving[attr] = src_g

        def on_task(tq, res, dt):
            attr = tq.attr[1:] if tq.attr.startswith("~") else tq.attr
            g = serving.get(attr)
            if g is None:
                return
            with self._lock:
                row = self._loads.setdefault((g, attr),
                                             [0.0, 0.0, 0.0, 0.0])
                row[0] += 1.0
                if res.dest_uids is not None:
                    row[2] += 8.0 * len(res.dest_uids)
                row[3] += dt
        return Executor(snap, self.schema,
                        on_task=on_task).execute(dql.parse(q, variables))

    # -- live queries (ISSUE 18) --------------------------------------------

    def subscribe(self, q: str, variables: dict | None = None, *,
                  cursor: int | None = None, queue_max: int | None = None):
        """Wire-mode standing query: each group's store applies its
        tablets' writes, the querying node's manager re-evaluates the
        federated read at the commit watermark and streams diffs — the
        same fan-out seam as query(). Lazy: the manager (and its notifier
        thread) exists only once something subscribes."""
        live = getattr(self, "_live", None)
        if live is None:
            from dgraph_tpu.live import LiveManager

            live = LiveManager(
                eval_fn=lambda qq, vv, ts: self.query(qq, vv, read_ts=ts),
                watermark_fn=lambda: max(
                    (s.max_seen_commit_ts for s in self.stores), default=0),
                parse_fn=dql.parse,
                stores=self.stores)
            self._live = live
            for s in self.stores:
                s.on_delta_overflow = live.on_journal_overflow
        return live.subscribe(q, variables, cursor=cursor,
                              queue_max=queue_max)

    # -- predicate move ------------------------------------------------------

    def move_predicate(self, attr: str, dst_group: int) -> dict:
        """The full move protocol (worker/predicate_move.go:86-177):
        1. block writes on the tablet (new mutations raise MoveInProgress);
        2. abort open txns that touched it (Zero TryAbort);
        3. snapshot-read every key of the predicate at ts and stream the
           effective postings into the destination store under one txn;
        4. flip the tablet map;
        5. delete the predicate at the source;
        6. unblock writes.
        """
        src_group = self.group_of(attr)
        if src_group == dst_group:
            return {"moved_keys": 0, "aborted_txns": 0}
        # replicas of a moving tablet drop first: the destination may BE a
        # holder (its copy would union with the streamed one), and holders
        # must not outlive their owner's location
        for holder in sorted(self.zero.replica_holders(attr)):
            self.drop_replica(attr, holder)
        src, dst = self.stores[src_group], self.stores[dst_group]
        self.zero.block_writes(attr)
        try:
            with self._lock:
                aborted = 0
                for ts in self.zero.oracle.pending_on(attr):
                    self.zero.oracle.abort(ts)
                    kb = self._txn_keys.pop(ts, {})
                    for g, keys in kb.items():
                        self.stores[g].abort(ts, keys)
                    aborted += 1
                read_ts = self.zero.oracle.read_ts()
                move_st = self.zero.oracle.new_txn()
                moved_keys: list[bytes] = []
                try:
                    for kind in (K.KeyKind.DATA, K.KeyKind.REVERSE,
                                 K.KeyKind.INDEX, K.KeyKind.COUNT):
                        for kb in src.keys_of(kind, attr):
                            pl = src.lists.get(kb)
                            if pl is None:
                                continue
                            key = K.parse_key(kb)
                            for p in pl.postings(read_ts):
                                dst.add_mutation(move_st.start_ts, key, p)
                            moved_keys.append(kb)
                    entry = src.schema.get(attr)
                    if entry is not None:
                        dst.set_schema(entry)
                    # the move txn carries no conflict keys (writes on attr
                    # are blocked), so the oracle commit always succeeds
                    commit_ts = self.zero.oracle.commit(move_st.start_ts)
                except BaseException:
                    # mid-stream failure: drop the partial copy and the
                    # pending move txn; source stays authoritative
                    dst.abort(move_st.start_ts, moved_keys)
                    self.zero.oracle.abort(move_st.start_ts)
                    raise
                dst.commit(move_st.start_ts, commit_ts, moved_keys)
                self.zero.move_tablet(attr, dst_group)
                src.delete_predicate(attr)
                return {"moved_keys": len(moved_keys), "aborted_txns": aborted}
        finally:
            self.zero.unblock_writes(attr)

    # -- read-only tablet replicas (coord/placement.py, embedded mode) -------

    def add_replica(self, attr: str, group: int) -> dict:
        """Install a read-only copy of `attr` on `group`'s store: stream
        every key's effective postings at a snapshot cut under one txn,
        then register the holder — routing starts only with the copy
        complete. Freshness afterwards is synchronous (commit-time
        rewrite, _ship_replica_deltas), so embedded holders never lag."""
        with self._lock:
            src_group = self.group_of(attr)
            if src_group == group:
                return {"installed_keys": 0, "noop": "owner"}
            if group in self.zero.replica_holders(attr):
                return {"installed_keys": 0, "noop": "already a holder"}
            src, dst = self.stores[src_group], self.stores[group]
            read_ts = self.zero.oracle.read_ts()
            st = self.zero.oracle.new_txn()
            copied: list[bytes] = []
            try:
                for kind in (K.KeyKind.DATA, K.KeyKind.REVERSE,
                             K.KeyKind.INDEX, K.KeyKind.COUNT):
                    for kb in src.keys_of(kind, attr):
                        pl = src.lists.get(kb)
                        if pl is None:
                            continue
                        key = K.parse_key(kb)
                        for p in pl.postings(read_ts):
                            dst.add_mutation(st.start_ts, key, p)
                        copied.append(kb)
                entry = src.schema.get(attr)
                if entry is not None:
                    dst.set_schema(entry)
                commit_ts = self.zero.oracle.commit(st.start_ts)
            except BaseException:
                dst.abort(st.start_ts, copied)
                self.zero.oracle.abort(st.start_ts)
                raise
            dst.commit(st.start_ts, commit_ts, copied)
            self.zero.add_replica(attr, group, commit_ts)
            return {"installed_keys": len(copied), "tablet": attr,
                    "src": src_group, "dst": group,
                    "watermark": commit_ts}

    def drop_replica(self, attr: str, group: int) -> bool:
        """Demote: unregister first (routing stops under the cluster
        lock), then delete the copy."""
        with self._lock:
            if not self.zero.drop_replica(attr, group):
                return False
            self.stores[group].delete_predicate(attr)
            self._loads.pop((group, attr), None)
            return True

    def tablet_loads(self) -> dict[int, dict[str, dict]]:
        """Cumulative per-group per-tablet load counters, the embedded
        analog of the wire Status tablet_load_json report."""
        with self._lock:
            out: dict[int, dict[str, dict]] = {
                g: {} for g in range(len(self.stores))}
            for (g, attr), r in self._loads.items():
                out[g][attr] = {"r": r[0], "w": r[1], "b": r[2],
                                "d": round(r[3], 6)}
            return out

    def placement_controller(self, cfg=None, metrics=None,
                             clock=None):
        """A PlacementController wired to this embedded cluster: sizes +
        load counters in, move/add_replica/drop_replica out. The caller
        drives tick() (tests) or start(interval_s)."""
        import time as _time

        from dgraph_tpu.coord.placement import PlacementController

        cluster = self

        class _Exec:
            def move(self, attr, dst):
                return cluster.move_predicate(attr, dst)

            def add_replica(self, attr, dst):
                return cluster.add_replica(attr, dst)

            def drop_replica(self, attr, group):
                return cluster.drop_replica(attr, group)

            # freshness is synchronous in-process: nothing to ship

        def collect():
            loads = cluster.tablet_loads()
            return {g: (cluster.stores[g].tablet_sizes(), loads.get(g, {}))
                    for g in range(len(cluster.stores))}

        return PlacementController(
            self.zero, collect, _Exec(), cfg=cfg, metrics=metrics,
            clock=clock if clock is not None else _time.monotonic)

    # -- auto-rebalance (dgraph/cmd/zero/tablet.go:60-74) ---------------------

    def rebalance_once(self) -> dict | None:
        """One pass of the reference's rebalance tick (decision logic shared
        with the Zero process: coord/zero.choose_rebalance_move). Returns
        the move stats or None."""
        from dgraph_tpu.coord.zero import choose_rebalance_move

        sizes = {g: self.stores[g].tablet_sizes()
                 for g in range(len(self.stores))}
        # replicated tablets are the load controller's responsibility —
        # their copies also inflate holder sizes, which would mislead the
        # size-only decision
        pick = choose_rebalance_move(
            sizes, blocked=self.zero.moving_tablets()
            | set(self.zero.replicas()))
        if pick is None:
            return None
        attr, src, dst, sz = pick
        stats = self.move_predicate(attr, dst)
        stats.update(tablet=attr, src=src, dst=dst, bytes=sz)
        return stats

    def start_rebalancer(self, interval_s: float = 8.0) -> None:
        """Background rebalance tick (the --rebalance_interval loop)."""
        import time as _time

        def loop():
            while not self._stop_rebalance.is_set():
                try:
                    self.rebalance_once()
                except Exception:
                    pass                   # next tick retries
                self._stop_rebalance.wait(interval_s)

        self._stop_rebalance = threading.Event()
        # dgraph: allow(ctxvar-copy) detached rebalance bg loop
        self._rebalance_thread = threading.Thread(target=loop, daemon=True)
        self._rebalance_thread.start()

    def close(self) -> None:
        live = getattr(self, "_live", None)
        if live is not None:
            live.close()
        ev = getattr(self, "_stop_rebalance", None)
        if ev is not None:
            ev.set()
        for s in self.stores:
            s.close()
