"""Zero-analog cluster coordinator: timestamps, UID leases, txn oracle, tablets.

Reference semantics (dgraph/cmd/zero/):
  - oracle.go:71-83 hasConflict — SSI write-conflict detection: a txn aborts
    if any of its conflict-key fingerprints was committed by a txn with
    commit_ts > this txn's start_ts.
  - oracle.go:276-320 commit — assign commitTs, update per-key max-commit-ts,
    stream the decision to groups.
  - assign.go:65-125 — UID and timestamp block leases (10k chunks), handed to
    servers/loaders on demand.
  - zero.go:436 ShouldServe / tablet.go — predicate → group ("tablet")
    assignment.

Redesign: the reference runs this as a separate Raft-replicated process
reached over gRPC. Here it is an in-process object (the embedded
single-process cluster mode the reference's own tests use, SURVEY.md §4);
the distribution layer (parallel/) consults the same tablet map to place
predicates on mesh device groups. All logic is host-side and device-free.
"""

from __future__ import annotations

import hashlib
import threading
from dataclasses import dataclass, field


class TxnConflict(Exception):
    """SSI write-conflict: another txn committed one of our keys after our
    start_ts (reference oracle.go:71 hasConflict → Code aborted)."""


class TxnNotFound(Exception):
    pass


def fingerprint(key_bytes: bytes) -> int:
    """Conflict-key fingerprint (reference x.Fingerprint / farmhash)."""
    return int.from_bytes(
        hashlib.blake2b(key_bytes, digest_size=8).digest(), "big")


@dataclass
class TxnState:
    start_ts: int
    keys: set[int] = field(default_factory=set)   # conflict fingerprints
    preds: set[str] = field(default_factory=set)  # touched predicates


LEASE_BLOCK = 10_000  # reference assign.go leaseBankSize


class Oracle:
    """SSI transaction oracle (reference dgraph/cmd/zero/oracle.go).

    Timestamps are a single monotonic sequence shared by reads and commits;
    max_commit_ts per conflict key implements first-committer-wins snapshot
    isolation. `max_applied` tracks the highest ts whose commit decision has
    been applied to the store — reads wait below it (the WaitForTs analog;
    in-process application is synchronous so it equals max_assigned here).
    """

    PURGE_EVERY = 256  # commit/abort decisions between watermark purges

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # durability hook: called UNDER _lock whenever max_assigned crosses
        # the current lease ceiling, BEFORE the triggering ts is returned —
        # a caller never receives a timestamp the new ceiling doesn't
        # durably cover (assign.go lease-block semantics). Covers every
        # mutator (timestamps/new_txn/commit) by construction.
        self.on_lease = None
        self._ceiling = 0
        self._next_ts = 1
        self._key_commit: dict[int, int] = {}     # fingerprint -> max commit_ts
        self._pending: dict[int, TxnState] = {}   # start_ts -> state
        self._aborted: set[int] = set()
        # attr -> highest commit_ts ASSIGNED to a txn touching it: tablet
        # moves wait for the owner to APPLY up to this before streaming
        # (a Decide RPC still in flight must not be left behind)
        self.pred_commit: dict[str, int] = {}
        self.max_assigned = 0
        self._decisions = 0                       # purge cadence counter

    def _bump_ceiling_locked(self) -> None:
        if self.on_lease is not None and self.max_assigned >= self._ceiling:
            self._ceiling = self.max_assigned + LEASE_BLOCK
            self.on_lease(self._ceiling)

    def _purge_below_locked(self) -> None:
        """Drop conflict/abort state no live or future txn can observe
        (reference oracle.go purgeBelow at the MinTs watermark :112-160).

        A _key_commit entry with ts <= every pending txn's start_ts can never
        trigger _has_conflict (future txns get start_ts > max_assigned >= ts).
        """
        watermark = min(self._pending, default=self.max_assigned + 1)
        self._key_commit = {fp: ts for fp, ts in self._key_commit.items()
                            if ts > watermark}
        self._aborted = {ts for ts in self._aborted if ts >= watermark}

    # -- timestamps ----------------------------------------------------------

    def timestamps(self, n: int = 1) -> int:
        """Lease n timestamps; returns the first (reference assign.go:127)."""
        with self._lock:
            ts = self._next_ts
            self._next_ts += n
            self.max_assigned = self._next_ts - 1
            self._bump_ceiling_locked()
            return ts

    def new_txn(self) -> TxnState:
        with self._lock:
            ts = self._next_ts
            self._next_ts += 1
            self.max_assigned = self._next_ts - 1
            self._bump_ceiling_locked()
            st = TxnState(ts)
            self._pending[ts] = st
            return st

    def pending_on(self, attr: str) -> list[int]:
        """start_ts of open txns that touched a predicate (the TryAbort
        candidates when that tablet moves; zero.go:436 + predicate_move)."""
        with self._lock:
            return [ts for ts, st in self._pending.items()
                    if attr in st.preds]

    def min_pending(self) -> int | None:
        """Smallest open txn start_ts (the MinTs watermark feeding rollup and
        conflict GC; reference oracle.go MinTs)."""
        with self._lock:
            return min(self._pending) if self._pending else None

    def read_ts(self) -> int:
        """Snapshot ts for a fresh read-only query: everything committed so
        far is visible (max assigned; application is synchronous here)."""
        with self._lock:
            return self.max_assigned

    # -- conflict tracking ---------------------------------------------------

    def track(self, start_ts: int, key_bytes_list: list[bytes],
              preds: list[str] = ()) -> None:
        """Record conflict keys touched by a txn (TxnContext.Keys, mvcc.go:222)."""
        with self._lock:
            st = self._pending.get(start_ts)
            if st is None:
                # decided (committed/aborted/purged) or never-issued ts:
                # recreating it would resurrect a finished txn, or register
                # one whose start_ts the sequence hasn't reached
                if start_ts in self._aborted:
                    raise TxnNotFound(f"txn {start_ts} was aborted")
                raise TxnNotFound(f"txn {start_ts} is not pending")
            st.keys.update(fingerprint(kb) for kb in key_bytes_list)
            st.preds.update(preds)

    def _has_conflict(self, st: TxnState) -> bool:
        return any(self._key_commit.get(fp, 0) > st.start_ts for fp in st.keys)

    # -- commit / abort ------------------------------------------------------

    def commit(self, start_ts: int) -> int:
        """Assign a commit ts if conflict-free, else abort (oracle.go:276).

        Returns commit_ts. Raises TxnConflict (txn is aborted server-side,
        like the reference's ABORTED TxnContext) on an SSI conflict.
        """
        with self._lock:
            st = self._pending.get(start_ts)
            if st is None:
                if start_ts in self._aborted:
                    raise TxnConflict(f"txn {start_ts} already aborted")
                raise TxnNotFound(f"unknown txn {start_ts}")
            if self._has_conflict(st):
                del self._pending[start_ts]
                self._aborted.add(start_ts)
                raise TxnConflict(
                    f"txn {start_ts} conflicts on a key committed after it")
            commit_ts = self._next_ts
            self._next_ts += 1
            self.max_assigned = self._next_ts - 1
            self._bump_ceiling_locked()
            for fp in st.keys:
                prev = self._key_commit.get(fp, 0)
                if commit_ts > prev:
                    self._key_commit[fp] = commit_ts
            for pred in st.preds:
                if commit_ts > self.pred_commit.get(pred, 0):
                    self.pred_commit[pred] = commit_ts
            del self._pending[start_ts]
            self._decisions += 1
            if self._decisions % self.PURGE_EVERY == 0:
                self._purge_below_locked()
            return commit_ts

    def commit_batch(self, start_ts_list: list[int]) -> list:
        """One commit window's decisions under ONE lock hold (the group-
        commit conflict pass, ISSUE 16): per member, exactly commit()'s
        logic — conflict check against _key_commit, commit_ts assignment,
        key/pred watermark updates. Returns a per-member list of either the
        assigned commit_ts (int) or the exception INSTANCE (TxnConflict /
        TxnNotFound) that member's solo commit() would have raised; the
        caller demuxes. Intra-window conflicts resolve first-committer-wins
        naturally: an earlier member's _key_commit update aborts a later
        member of the same window that shares a key."""
        out: list = []
        with self._lock:
            d0 = self._decisions
            for start_ts in start_ts_list:
                st = self._pending.get(start_ts)
                if st is None:
                    if start_ts in self._aborted:
                        out.append(TxnConflict(
                            f"txn {start_ts} already aborted"))
                    else:
                        out.append(TxnNotFound(f"unknown txn {start_ts}"))
                    continue
                if self._has_conflict(st):
                    del self._pending[start_ts]
                    self._aborted.add(start_ts)
                    self._decisions += 1
                    out.append(TxnConflict(
                        f"txn {start_ts} conflicts on a key committed "
                        f"after it"))
                    continue
                commit_ts = self._next_ts
                self._next_ts += 1
                self.max_assigned = self._next_ts - 1
                self._bump_ceiling_locked()
                for fp in st.keys:
                    if commit_ts > self._key_commit.get(fp, 0):
                        self._key_commit[fp] = commit_ts
                for pred in st.preds:
                    if commit_ts > self.pred_commit.get(pred, 0):
                        self.pred_commit[pred] = commit_ts
                del self._pending[start_ts]
                self._decisions += 1
                out.append(commit_ts)
            if self._decisions // self.PURGE_EVERY > d0 // self.PURGE_EVERY:
                self._purge_below_locked()
        return out

    def abort(self, start_ts: int) -> None:
        with self._lock:
            self._pending.pop(start_ts, None)
            self._aborted.add(start_ts)
            self._decisions += 1
            if self._decisions % self.PURGE_EVERY == 0:
                self._purge_below_locked()

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)


class UidLease:
    """Monotonic UID allocator handing out blocks (reference assign.go:65)."""

    def __init__(self, start: int = 1) -> None:
        self._lock = threading.Lock()
        self.on_lease = None       # same contract as Oracle.on_lease
        self._ceiling = 0
        self._next = start

    def _bump_ceiling_locked(self) -> None:
        if self.on_lease is not None and self._next - 1 >= self._ceiling:
            self._ceiling = self._next - 1 + LEASE_BLOCK
            self.on_lease(self._ceiling)

    def assign(self, n: int) -> tuple[int, int]:
        """Lease n uids; returns [start, end] inclusive."""
        if n <= 0:
            raise ValueError("need n >= 1")
        with self._lock:
            s = self._next
            self._next += n
            self._bump_ceiling_locked()
            return s, self._next - 1

    def bump_to(self, uid: int) -> None:
        """Advance the lease past an externally-seen uid (xidmap/restart)."""
        with self._lock:
            self._next = max(self._next, uid + 1)
            self._bump_ceiling_locked()

    @property
    def max_leased(self) -> int:
        with self._lock:
            return self._next - 1


REBALANCE_RATIO = 0.85   # tablet.go:60-74: move only while the smallest
                         # group serves < 85% of the largest (anti-ping-pong)


def choose_rebalance_move(sizes: dict[int, dict[str, int]],
                          ratio: float = REBALANCE_RATIO,
                          blocked: set | frozenset = frozenset()):
    """One rebalance decision (dgraph/cmd/zero/tablet.go:60-74 + :156
    chooseTablet): compare the largest- and smallest-serving groups; if
    imbalanced past `ratio`, pick the largest source tablet that fits half
    the gap. Returns (attr, src_group, dst_group, size) or None. Shared by
    the in-process Cluster and the Zero-process rebalancer so the two
    planes cannot drift."""
    totals = {g: sum(t.values()) for g, t in sizes.items()}
    if len(totals) < 2:
        return None
    src = max(totals, key=lambda g: totals[g])
    dst = min(totals, key=lambda g: totals[g])
    if src == dst or totals[dst] >= ratio * totals[src]:
        return None
    gap = (totals[src] - totals[dst]) / 2
    for attr, sz in sorted(sizes[src].items(), key=lambda kv: -kv[1]):
        if sz <= gap and attr not in blocked:
            return attr, src, dst, sz
    return None


class Zero:
    """The coordinator facade: oracle + uid lease + tablet map.

    Reference: the `dgraph zero` process. Tablets map predicates to groups
    (zero.go:436 ShouldServe); in the TPU design a "group" is a set of mesh
    devices serving that predicate's sharded CSR (parallel/mesh.py).

    Durability (`dirpath`): the reference Raft-persists leases and the
    tablet map (assign.go:65-125 proposes lease BLOCKS so a crash skips at
    most one block; zero.go tablet proposals). Here a state file records
    lease CEILINGS (bumped a block ahead of issuance) plus the tablet map:
    a restarted Zero resumes past every ts/uid it could have handed out —
    it may burn up to one block, exactly the reference's crash semantics.
    Pending (undecided) txns are lost on restart = aborted, also matching
    the reference (their Decide would fail at the new oracle).
    """

    def __init__(self, n_groups: int = 1, dirpath: str | None = None) -> None:
        self.oracle = Oracle()
        self.uids = UidLease()
        self.n_groups = max(1, n_groups)
        self._tablets: dict[str, int] = {}
        # read-only tablet replicas (coord/placement.py): attr -> {holder
        # group: applied watermark}. A holder serves reads of a tablet it
        # does NOT own, kept fresh by delta ships; the watermark is the
        # owner commit ts its copy provably covers (the replica-read gate
        # bound). Owners never appear as their own holders.
        self._replicas: dict[str, dict[int, int]] = {}
        self._moving: set[str] = set()     # tablets mid-move: writes blocked
        # multi-tenant QoS (ISSUE 20): the serving node installs its
        # TenantRegistry here so /state exposes the cluster's tenant
        # table (specs + totals + sheds) next to the tablet map
        self.tenants = None
        self._tlock = threading.Lock()
        self._dir = dirpath
        self._ts_ceiling = 0
        self._uid_ceiling = 0
        self._plock = threading.Lock()
        if dirpath:
            import json as _json
            import os as _os

            _os.makedirs(dirpath, exist_ok=True)
            path = _os.path.join(dirpath, "zero_state.json")
            if _os.path.exists(path):
                with open(path) as f:
                    st = _json.load(f)
                # restore the CEILINGS too: a restart that issues nothing
                # before the next crash must not write them back as 0
                self._ts_ceiling = int(st.get("ts_ceiling", 0))
                self._uid_ceiling = int(st.get("uid_ceiling", 0))
                self.oracle.timestamps(max(self._ts_ceiling, 0))
                if self._uid_ceiling > 0:
                    self.uids.bump_to(self._uid_ceiling)
                self._tablets = {a: int(g)
                                 for a, g in st.get("tablets", {}).items()}
                self._replicas = {
                    a: {int(g): int(wm) for g, wm in gs.items()}
                    for a, gs in st.get("replicas", {}).items()}
                self.n_groups = max(self.n_groups,
                                    int(st.get("n_groups", self.n_groups)))
            # lease-source callbacks run UNDER the issuing lock, so a ts
            # or uid is never returned before the ceiling covering it is
            # durable (assign.go: a crash burns at most one block)
            self.oracle.on_lease = self._on_ts_lease
            self.uids.on_lease = self._on_uid_lease
            self._persist()

    def _on_ts_lease(self, ceiling: int) -> None:
        self._ts_ceiling = ceiling
        self._persist()

    def _on_uid_lease(self, ceiling: int) -> None:
        self._uid_ceiling = ceiling
        self._persist()

    # multi-zero hook: called with the persisted state JSON after every
    # durable write — the leader's ZeroReplica ships it to standby zeros
    persist_sink = None

    def _persist(self, tablets: dict | None = None,
                 replicas: dict | None = None) -> None:
        import json as _json
        import os as _os

        # take the tablet/replica snapshots BEFORE _plock (callers inside
        # _tlock pass them; taking _tlock under _plock would deadlock
        # against the _tlock -> _plock order of the claim paths)
        snap = tablets if tablets is not None else self.tablets()
        rsnap = replicas if replicas is not None else self.replicas()
        path = _os.path.join(self._dir, "zero_state.json")
        tmp = path + ".tmp"
        with self._plock:   # ts/uid/tablet persists may race each other
            payload = _json.dumps({"ts_ceiling": self._ts_ceiling,
                                   "uid_ceiling": self._uid_ceiling,
                                   "tablets": snap,
                                   "replicas": {a: {str(g): wm
                                                    for g, wm in gs.items()}
                                                for a, gs in rsnap.items()},
                                   "n_groups": self.n_groups})
            with open(tmp, "w") as f:
                f.write(payload)
                f.flush()
                _os.fsync(f.fileno())
            _os.replace(tmp, path)
            sink = self.persist_sink
            if sink is not None:
                # under _plock: standbys receive states in persist order
                sink(payload)

    def block_writes(self, attr: str) -> None:
        """Mark a tablet read-only for the duration of a move (the reference
        aborts/rejects mutations on a moving predicate,
        predicate_move.go:86 + worker/mutation.go tablet checks)."""
        with self._tlock:
            self._moving.add(attr)

    def unblock_writes(self, attr: str) -> None:
        with self._tlock:
            self._moving.discard(attr)

    def writes_blocked(self, attr: str) -> bool:
        with self._tlock:
            return attr in self._moving

    def moving_tablets(self) -> set[str]:
        with self._tlock:
            return set(self._moving)

    def should_serve(self, attr: str) -> int:
        """Group owning a predicate; first-asker claims it, balanced by
        tablet count (reference zero.go:436 + tablet.go chooseTablet)."""
        with self._tlock:
            g = self._tablets.get(attr)
            if g is None:
                loads = [0] * self.n_groups
                for gg in self._tablets.values():
                    loads[gg] += 1
                g = loads.index(min(loads))
                self._tablets[attr] = g
                if self._dir:
                    # durable BEFORE any caller can act on the claim — a
                    # crash must not re-balance a tablet that data already
                    # landed on (the reference Raft-proposes the claim)
                    self._persist(tablets=dict(self._tablets),
                                  replicas=self._replicas_locked())
        return g

    def tablets(self) -> dict[str, int]:
        with self._tlock:
            return dict(self._tablets)

    def move_tablet(self, attr: str, group: int) -> None:
        with self._tlock:
            self._tablets[attr] = group
            # the new owner must not also be listed as a read replica of
            # itself (a move to a holder group collapses that replica)
            holders = self._replicas.get(attr)
            if holders is not None:
                holders.pop(group, None)
                if not holders:
                    del self._replicas[attr]
            if self._dir:
                self._persist(tablets=dict(self._tablets),
                              replicas=self._replicas_locked())

    # -- read-only tablet replicas (coord/placement.py) ----------------------

    def _replicas_locked(self) -> dict:
        return {a: dict(gs) for a, gs in self._replicas.items()}

    def replicas(self) -> dict[str, dict[int, int]]:
        """attr -> {holder group: covered watermark} for every tablet with
        read replicas."""
        with self._tlock:
            return self._replicas_locked()

    def replica_holders(self, attr: str) -> dict[int, int]:
        with self._tlock:
            return dict(self._replicas.get(attr, {}))

    def add_replica(self, attr: str, group: int, watermark: int) -> None:
        """Register a read replica AFTER its data is installed (routing
        starts the moment the map carries it — never before the copy is
        complete)."""
        with self._tlock:
            if self._tablets.get(attr) == group:
                return                 # the owner is not a replica
            self._replicas.setdefault(attr, {})[group] = int(watermark)
            if self._dir:
                self._persist(tablets=dict(self._tablets),
                              replicas=self._replicas_locked())

    def set_replica_watermark(self, attr: str, group: int,
                              watermark: int) -> None:
        with self._tlock:
            holders = self._replicas.get(attr)
            if holders is not None and group in holders:
                holders[group] = max(holders[group], int(watermark))
                if self._dir:
                    self._persist(tablets=dict(self._tablets),
                                  replicas=self._replicas_locked())

    def drop_replica(self, attr: str, group: int) -> bool:
        """Unregister a replica BEFORE its data is deleted (routing stops
        first; in-flight reads are covered by the holder-side existence
        check in serve_task)."""
        with self._tlock:
            holders = self._replicas.get(attr)
            if holders is None or group not in holders:
                return False
            del holders[group]
            if not holders:
                del self._replicas[attr]
            if self._dir:
                self._persist(tablets=dict(self._tablets),
                              replicas=self._replicas_locked())
            return True

    def state(self) -> dict:
        """Membership dump (reference /state, dgraph/cmd/zero/http.go:130)."""
        out = {
            "maxTxnTs": self.oracle.max_assigned,
            "maxLeaseId": self.uids.max_leased,
            # per-tablet last commit ts: the replica-read floor hedged
            # reads carry (TaskRequest.min_applied)
            "predCommit": dict(self.oracle.pred_commit),
            # read-replica holders per tablet (the query router spreads
            # reads across owner + holders; coord/placement.py maintains)
            "replicaMap": {a: sorted(gs)
                           for a, gs in self.replicas().items()},
            "groups": {str(g): {"tablets": sorted(
                a for a, gg in self.tablets().items() if gg == g)}
                for g in range(self.n_groups)},
        }
        if self.tenants is not None and self.tenants.configured:
            out["tenants"] = self.tenants.table()
        return out
