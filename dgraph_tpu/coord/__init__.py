"""Control plane: the Zero-analog coordinator (timestamps, UID leases,
SSI transaction oracle, tablet map). Device-independent host logic."""

from dgraph_tpu.coord.zero import Oracle, TxnConflict, TxnNotFound, Zero

__all__ = ["Oracle", "TxnConflict", "TxnNotFound", "Zero"]
