"""Replication: WAL shipping to a replica quorum with leader failover.

Reference semantics (the contract, not the transport):
  - worker/draft.go:190 proposeAndWait — a mutation is acked only after the
    Raft quorum has the entry; :485-624 Run loop stores entries before
    applying them.
  - conn/node.go:47-105 — replica membership and health; CheckQuorum.
  - raftwal/wal.go:31 — the per-replica durable log replayed on restart.
  - worker/draft.go:452 retrieveSnapshot — a lagging follower catches up by
    full snapshot + log tail from the leader.

TPU-era redesign: replicas are posting-store directories; the data plane
needing consensus is ONLY the WAL byte stream (device snapshots rebuild from
it deterministically), so replication is synchronous record shipping — every
WAL record fsyncs on a majority of live replicas before the leader's own
append proceeds. Failover promotes the live replica with the longest log
(Raft's up-to-date rule) by opening a Node on its directory — the normal
crash-recovery path — and fences the old term via a per-replica term file.

In-process today (one ReplicaGroup object owns the member dirs — the
embedded single-process cluster mode of SURVEY.md §4); the record stream is
already the wire format a gRPC/DCN transport would carry.
"""

from __future__ import annotations

import contextvars
import os
import struct
import threading

from dgraph_tpu.api.server import Node
from dgraph_tpu.utils import deadline as dl
from dgraph_tpu.query import dql
from dgraph_tpu.query.engine import Executor
from dgraph_tpu.storage.csr_build import build_snapshot
from dgraph_tpu.storage.store import Store, decode_record
from dgraph_tpu.utils.watermark import WaterMark

_U32 = struct.Struct("<I")


class NoQuorum(Exception):
    """Fewer than a majority of replicas are alive and acking."""


class StaleLeader(Exception):
    """A deposed leader tried to ship records (term fencing)."""


class _Member:
    """One replica: a directory with wal.log (+ snapshot) and a term file."""

    def __init__(self, member_id: int, dirpath: str) -> None:
        self.id = member_id
        self.dir = dirpath
        os.makedirs(dirpath, exist_ok=True)
        self.alive = True
        self.reader: "FollowerReader | None" = None
        self._wal = None

    # -- term fencing --------------------------------------------------------

    @property
    def term(self) -> int:
        try:
            with open(os.path.join(self.dir, "TERM")) as f:
                return int(f.read().strip() or 0)
        except FileNotFoundError:
            return 0

    def set_term(self, term: int) -> None:
        with open(os.path.join(self.dir, "TERM"), "w") as f:
            f.write(str(term))

    # -- log append (the follower side of the ship) --------------------------

    def append(self, data: bytes, sync: bool) -> None:
        if self._wal is None:
            self._wal = open(os.path.join(self.dir, "wal.log"), "ab")
        self._wal.write(_U32.pack(len(data)) + data)
        if sync:
            self._wal.flush()
            os.fsync(self._wal.fileno())

    def close(self) -> None:
        if self._wal is not None:
            self._wal.flush()
            self._wal.close()
            self._wal = None

    def wal_len(self) -> int:
        try:
            return os.path.getsize(os.path.join(self.dir, "wal.log"))
        except FileNotFoundError:
            return 0


class FollowerReader:
    """A read replica: an in-memory Store that live-applies shipped WAL
    records, serving (slightly stale) snapshot reads for hedging
    (worker/draft.go applies committed entries to follower state the same
    way; worker/task.go:75-132 reads from it on backup requests)."""

    def __init__(self, dirpath: str | None = None) -> None:
        # memory-only going forward: the member's file WAL is the durability
        # story, the reader just mirrors state. An existing replica dir seeds
        # the mirror (rejoin / restart), then the file handles detach so the
        # member's own appends stay the only writer.
        if dirpath and (os.path.exists(os.path.join(dirpath, "snapshot.bin"))
                        or os.path.exists(os.path.join(dirpath, "wal.log"))):
            s = Store(dirpath)
            if s._wal is not None:
                s._wal.close()
                s._wal = None
            s.dir = None
            self.store = s
        else:
            self.store = Store()
        self._lock = threading.Lock()
        # incremental per-predicate snapshot reuse (VERDICT r3 #6): a commit
        # touching one predicate re-folds one predicate on this follower
        from dgraph_tpu.storage.csr_build import (STRUCTURAL_RECORDS,
                                                  SnapshotAssembler)

        self._assembler = SnapshotAssembler(self.store)
        self._structural = STRUCTURAL_RECORDS
        self._read_lock = threading.Lock()
        self._version = 0
        # applied watermark: record index n is done once apply(n) returns;
        # wait_for_mark(n) = "this reader reflects the first n records"
        # (x/watermark.go applied-watermark contract)
        self.applied = WaterMark("applied")

    def apply(self, data: bytes) -> None:
        with self._lock:
            idx = self._version + 1
            self.applied.begin(idx)
            try:
                rec = decode_record(data)
                self.store.apply_record(rec)
                if rec.get("t") in self._structural:
                    # schema/drop records change structure beyond the
                    # per-predicate commit watermark the assembler keys on.
                    # Serialize with in-flight assembly (_read_lock): an
                    # invalidate landing mid-assemble would otherwise be
                    # overwritten by the pre-drop entries being cached.
                    with self._read_lock:
                        self._assembler.invalidate()
            finally:
                self._version = idx
                self.applied.done(idx)

    def query(self, q: str, variables: dict | None = None) -> dict:
        # capture ts under the apply lock, assemble OUTSIDE it: the leader's
        # synchronous ship path blocks on that lock, so holding it across a
        # fold would stall every commit. Assembly at read_ts = ts is torn-
        # proof (visibility is commit_ts <= read_ts, and a concurrent apply
        # lands at a ts the fold excludes); per-predicate reuse means only
        # predicates committed since the last read are re-folded.
        with self._lock:
            ts = self.store.max_seen_commit_ts
        with self._read_lock:
            snap = self._assembler.snapshot(ts)
        return Executor(snap, self.store.schema).execute(
            dql.parse(q, variables))


class ReplicaGroup:
    """A leader Node plus follower replicas with synchronous quorum shipping."""

    def __init__(self, base_dir: str, n: int = 3,
                 serve_reads: bool = False) -> None:
        if n < 1:
            raise ValueError("need n >= 1 replicas")
        self.n = n
        self.term = 1
        self.serve_reads = serve_reads
        self.members = [_Member(i, os.path.join(base_dir, f"replica{i}"))
                        for i in range(n)]
        for m in self.members:
            m.set_term(self.term)
        self.leader_id = 0
        self.hedged_reads = 0
        # fault injection hook (test/ops surface): called per (member,
        # record) before a follower append; raising simulates a transport
        # fault for that member — it stops counting toward the quorum.
        # Reference analog: conn/pool Echo health failures.
        self.fault_hook = None
        if serve_reads:
            for m in self._followers_of(0):
                m.reader = FollowerReader(m.dir)
        self.node: Node = self._open_leader()

    # -- leadership ----------------------------------------------------------

    @property
    def quorum(self) -> int:
        return self.n // 2 + 1

    def _followers(self) -> list[_Member]:
        return self._followers_of(self.leader_id)

    def _followers_of(self, leader_id: int) -> list[_Member]:
        return [m for m in self.members if m.id != leader_id]

    def _open_leader(self) -> Node:
        node = Node(self.members[self.leader_id].dir)
        node.store.wal_sink = self._ship
        return node

    def _ship(self, data: bytes, sync: bool) -> None:
        """Deliver one WAL record to followers; ack needs a quorum counting
        the leader itself (proposeAndWait's commit wait).

        Quorum feasibility and term fencing are checked for EVERY live
        follower before any append, so a rejected ship leaves no follower
        holding a record the leader never wrote. A member whose transport
        faults (fault_hook raising) is marked dead — the failure-detection
        path — and the quorum re-checked before anything is appended. Term
        fencing runs FIRST, over every live member: a higher-term member
        deposes this leader even if its transport is currently faulty."""
        live = [m for m in self._followers() if m.alive]
        for m in live:
            if m.term > self.term:
                raise StaleLeader(
                    f"member {m.id} is at term {m.term} > {self.term}")
        if self.fault_hook is not None:
            for m in list(live):
                try:
                    self.fault_hook(m, data)
                except Exception:
                    m.alive = False      # detected failure: stop counting it
                    m.close()
                    live.remove(m)
        if len(live) + 1 < self.quorum:
            raise NoQuorum(
                f"{len(live) + 1}/{self.n} acks < quorum {self.quorum}")
        for m in live:
            m.append(data, sync)
            if m.reader is not None:
                m.reader.apply(data)

    # -- hedged reads --------------------------------------------------------

    def read(self, q: str, variables: dict | None = None,
             hedge_after: float = 0.05) -> tuple[str, dict]:
        """Backup-request read (worker/task.go:75-132): ask the leader; when
        it hasn't answered within hedge_after seconds — or is dead — race a
        live follower reader; the first answer wins. Returns
        ("leader" | "followerN", result). Follower answers reflect the
        quorum-acked prefix (read-your-quorum, possibly a beat behind the
        leader's unacked tail — the same staleness contract as the
        reference's best-effort backup reads)."""
        result: list[tuple[str, dict]] = []
        errs: list[Exception] = []
        done = threading.Event()
        leader = self.members[self.leader_id]
        leader_asked = leader.alive
        if leader.alive:
            def from_leader():
                try:
                    out, _ = self.node.query(q, variables)
                    result.append(("leader", out))
                except Exception as e:   # noqa: BLE001 — raced result decides
                    errs.append(e)
                finally:
                    done.set()
            # copy context so the leader read carries the caller's
            # deadline/trace/cost contextvars across the thread seam
            ctx = contextvars.copy_context()
            threading.Thread(target=ctx.run, args=(from_leader,),
                             daemon=True).start()
            done.wait(dl.clamp(hedge_after))
            if result:
                return result[0]
        self.hedged_reads += 1
        for m in self._followers():
            if m.alive and m.reader is not None:
                out = m.reader.query(q, variables)
                return result[0] if result else (f"follower{m.id}", out)
        if not leader_asked:
            # dead leader AND no follower reader: nothing will ever answer
            raise NoQuorum("no live member can serve reads")
        # no follower reader available: block on the leader after all —
        # clamped to the caller's budget (typed, never a hang)
        if not done.wait(dl.clamp(None)):
            dl.check("quorum read: leader reply")
        if result:
            return result[0]
        raise errs[0] if errs else NoQuorum("no live member can serve reads")

    # -- failures ------------------------------------------------------------

    def kill(self, member_id: int) -> None:
        """Crash a member. Killing the leader triggers failover to the live
        member with the longest log (Raft's up-to-date election rule)."""
        m = self.members[member_id]
        m.alive = False
        m.close()
        if member_id != self.leader_id:
            return
        self.node.close()
        live = [x for x in self.members if x.alive]
        if len(live) < self.quorum:
            raise NoQuorum(
                f"{len(live)} live members cannot form quorum {self.quorum}")
        new_leader = max(live, key=lambda x: (x.wal_len(), -x.id))
        self.term += 1
        for x in live:
            x.set_term(self.term)
        self.leader_id = new_leader.id
        new_leader.close()
        new_leader.reader = None      # leaders serve reads directly
        self.node = self._open_leader()

    def rejoin(self, member_id: int) -> None:
        """Bring a dead member back via snapshot + WAL tail from the leader
        (retrieveSnapshot / populateShard analog)."""
        m = self.members[member_id]
        if member_id == self.leader_id:
            raise ValueError("leader cannot rejoin itself")
        # fold the leader's log so the copy is compact, then clone state
        # (clone_to flushes + copies under the store lock, so no concurrent
        # commit can land half-shipped in the copy window)
        self.node.store.checkpoint(self.node.store.max_seen_commit_ts)
        m.close()
        self.node.store.clone_to(m.dir)
        m.set_term(self.term)
        m.alive = True
        if self.serve_reads:
            m.reader = FollowerReader(m.dir)   # reseed from the fresh clone

    def close(self) -> None:
        self.node.close()
        for m in self.members:
            m.close()
