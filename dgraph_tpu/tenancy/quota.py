"""Per-tenant cost-metered quotas: token buckets in cost-ledger units.

Each tenant carries up to three buckets — device-ms, traversed edges,
and transfer bytes per second — refilled continuously with a burst
allowance of `burst_s` seconds of rate. Costs are only known AFTER a
request runs (the CostLedger record), so buckets debit post-execution
and may go into debt (floored at one extra burst window); admission at
the API edge then sheds the tenant typed — the PR 7 ResourceExhausted
shape, never a queue slot — until refill clears the debt. That is the
standard cost-metered quota discipline: a burst is served, the debt is
repaid in shed time.

The registry also owns the per-tenant attribution surface: exact float
totals for /debug/metrics and the dgraph_tenant_{device_ms,edges,bytes,
shed}_total{tenant=} labeled gauges (integer floors of the floats).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from dgraph_tpu.utils.deadline import ResourceExhausted

# spec key applying to any tenant without its own entry
DEFAULT_SPEC_KEY = "*"


@dataclass
class TenantSpec:
    """One tenant's QoS contract. None = unlimited for that unit."""

    name: str
    weight: float = 1.0                  # fair-share weight (sched.py)
    device_ms_per_s: float | None = None
    edges_per_s: float | None = None
    bytes_per_s: float | None = None
    burst_s: float = 5.0                 # burst allowance, seconds of rate
    max_subs: int | None = None          # standing live subscriptions
    sub_queue_max: int | None = None     # per-subscription notify bound

    @classmethod
    def from_dict(cls, name: str, d: dict) -> "TenantSpec":
        known = {"weight", "device_ms_per_s", "edges_per_s", "bytes_per_s",
                 "burst_s", "max_subs", "sub_queue_max"}
        bad = set(d) - known
        if bad:
            raise ValueError(
                f"tenant {name!r}: unknown quota keys {sorted(bad)}")
        kw = {}
        for k in known & set(d):
            v = d[k]
            kw[k] = None if v is None else (
                int(v) if k in ("max_subs", "sub_queue_max") else float(v))
        return cls(name=name, **kw)

    def to_dict(self) -> dict:
        return {"weight": self.weight,
                "device_ms_per_s": self.device_ms_per_s,
                "edges_per_s": self.edges_per_s,
                "bytes_per_s": self.bytes_per_s,
                "burst_s": self.burst_s,
                "max_subs": self.max_subs,
                "sub_queue_max": self.sub_queue_max}


@dataclass
class _Bucket:
    """Continuous-refill token bucket with bounded debt."""

    rate: float                  # units per second
    burst: float                 # capacity (units)
    level: float = 0.0
    last: float = field(default_factory=time.monotonic)

    def __post_init__(self) -> None:
        self.level = self.burst

    def _refill(self, now: float) -> None:
        self.level = min(self.burst,
                         self.level + (now - self.last) * self.rate)
        self.last = now

    def debit(self, cost: float, now: float) -> None:
        self._refill(now)
        # debt floored at one extra burst window: a single runaway query
        # costs at most 2*burst_s of shed time, not unbounded lockout
        self.level = max(-self.burst, self.level - cost)

    def ok(self, now: float) -> bool:
        self._refill(now)
        return self.level > 0.0


class TenantRegistry:
    """Tenant table: specs (hot-reloadable), quota buckets, and the exact
    per-tenant cost accumulators behind the labeled gauge series."""

    _UNITS = ("device_ms", "edges", "bytes")
    _GAUGES = {"device_ms": "dgraph_tenant_device_ms_total",
               "edges": "dgraph_tenant_edges_total",
               "bytes": "dgraph_tenant_bytes_total"}

    def __init__(self, metrics=None) -> None:
        self.metrics = metrics
        self._lock = threading.Lock()
        self._specs: dict[str, TenantSpec] = {}
        self._buckets: dict[str, dict[str, _Bucket]] = {}
        self._totals: dict[str, dict[str, float]] = {}
        self._sheds: dict[str, int] = {}

    # -- configuration (serve flag + POST /admin/tenant hot reload) -----------

    def configure(self, cfg: dict, replace: bool = False) -> dict:
        """Install/merge tenant specs from {"tenants": {name: {...}}} (or
        the bare name->spec map). Returns the resulting table. Reconfig
        resets only the reconfigured tenants' buckets — a hot reload must
        not hand every tenant a fresh burst."""
        tenants = cfg.get("tenants", cfg)
        if not isinstance(tenants, dict):
            raise ValueError("tenants config must be a JSON object")
        specs = {}
        for name, d in tenants.items():
            if name != DEFAULT_SPEC_KEY:
                from dgraph_tpu import tenancy

                tenancy.validate(name)
            specs[name] = TenantSpec.from_dict(name, dict(d or {}))
        with self._lock:
            if replace:
                self._specs = specs
                self._buckets.clear()
            else:
                self._specs.update(specs)
                for name in specs:
                    self._buckets.pop(name, None)
        return self.table()

    @property
    def configured(self) -> bool:
        return bool(self._specs)

    def spec(self, tenant: str) -> TenantSpec | None:
        with self._lock:
            return self._specs.get(tenant) or \
                self._specs.get(DEFAULT_SPEC_KEY)

    def weight(self, tenant: str) -> float:
        sp = self.spec(tenant)
        return sp.weight if sp is not None and sp.weight > 0 else 1.0

    def window_share(self, tenant: str, slots: int) -> int:
        """Weight-proportional share of `slots` group-window slots (floor
        1): the WriteBatcher's per-tenant cap, so one heavy writer cannot
        fill the shared commit window."""
        with self._lock:
            total = sum(max(sp.weight, 0.0)
                        for name, sp in self._specs.items()
                        if name != DEFAULT_SPEC_KEY)
        w = self.weight(tenant)
        return max(1, int(slots * w / max(total, w, 1.0)))

    # -- quota enforcement ----------------------------------------------------

    def _buckets_for(self, tenant: str, sp: TenantSpec) -> dict:
        b = self._buckets.get(tenant)
        if b is None:
            b = {}
            for unit, rate in (("device_ms", sp.device_ms_per_s),
                               ("edges", sp.edges_per_s),
                               ("bytes", sp.bytes_per_s)):
                if rate is not None and rate > 0:
                    b[unit] = _Bucket(rate=rate,
                                      burst=rate * max(sp.burst_s, 0.001))
            self._buckets[tenant] = b
        return b

    def note_shed(self, tenant: str) -> None:
        """Book one per-tenant shed (quota debt, subscription cap, ...)
        into the shed counter + the labeled tenant series."""
        with self._lock:
            self._sheds[tenant] = self._sheds.get(tenant, 0) + 1
        if self.metrics is not None:
            self.metrics.counter("dgraph_shed_total").inc()
            self.metrics.keyed("dgraph_tenant_shed_total",
                               labels=("tenant",)).inc(tenant or "default")

    def admit(self, tenant: str) -> None:
        """Shed typed when any of the tenant's buckets is in debt. Never
        queues — over-quota work is rejected while it is still cheap."""
        sp = self.spec(tenant)
        if sp is None:
            return
        now = time.monotonic()
        with self._lock:
            for unit, b in self._buckets_for(tenant, sp).items():
                if not b.ok(now):
                    deficit = -b.level
                    break
            else:
                return
        self.note_shed(tenant)
        raise ResourceExhausted(
            f"tenant {tenant or 'default'!r} over {unit} quota "
            f"({deficit:.0f} {unit} in debt; refills at "
            f"{getattr(sp, unit + '_per_s', 0)}/s)")

    def debit(self, tenant: str, device_ms: float = 0.0,
              edges: float = 0.0, bytes_: float = 0.0) -> None:
        """Attribute one request's ledger totals: debit quota buckets and
        advance the exact accumulators + labeled gauges."""
        sp = self.spec(tenant)
        now = time.monotonic()
        vals = {"device_ms": float(device_ms), "edges": float(edges),
                "bytes": float(bytes_)}
        with self._lock:
            if sp is not None:
                for unit, b in self._buckets_for(tenant, sp).items():
                    b.debit(vals[unit], now)
            tot = self._totals.setdefault(
                tenant, dict.fromkeys(self._UNITS, 0.0))
            for unit in self._UNITS:
                tot[unit] += vals[unit]
            snap = dict(tot)
        if self.metrics is not None:
            key = tenant or "default"
            self.metrics.keyed("dgraph_tenant_device_ms_total",
                               labels=("tenant",)).set(
                                   key, int(snap["device_ms"]))
            self.metrics.keyed("dgraph_tenant_edges_total",
                               labels=("tenant",)).set(
                                   key, int(snap["edges"]))
            self.metrics.keyed("dgraph_tenant_bytes_total",
                               labels=("tenant",)).set(
                                   key, int(snap["bytes"]))

    # -- live-query caps ------------------------------------------------------

    def max_subs(self, tenant: str) -> int | None:
        sp = self.spec(tenant)
        return sp.max_subs if sp is not None else None

    def sub_queue_max(self, tenant: str) -> int | None:
        sp = self.spec(tenant)
        return sp.sub_queue_max if sp is not None else None

    # -- inspection (Zero state / /admin/tenant / /debug/metrics) -------------

    def table(self) -> dict:
        with self._lock:
            now = time.monotonic()
            out = {}
            names = set(self._specs) | set(self._totals)
            for name in sorted(names):
                sp = self._specs.get(name)
                row = {"spec": sp.to_dict() if sp is not None else None,
                       "totals": dict(self._totals.get(
                           name, dict.fromkeys(self._UNITS, 0.0))),
                       "sheds": self._sheds.get(name, 0)}
                b = self._buckets.get(name)
                if b:
                    row["buckets"] = {
                        u: {"level": round(bk.level, 3),
                            "rate": bk.rate, "burst": bk.burst,
                            "ok": bk.ok(now)}
                        for u, bk in b.items()}
                out[name] = row
            return out
