"""Namespace views: tenant-scoped predicates at the snapshot/schema seam.

A tenant's predicate "name" lives in storage as "<tenant>/name" — a
distinct attr with its own posting lists, PredData/CSR identity, journal
rows, and schema entry. Queries execute against a NamespacedSnapshot that
translates attr names both ways, so the executor, planner, caches, and
batcher all run unmodified on the tenant's unprefixed vocabulary while
reading only the tenant's tablets. The translation is name-level only:
PredData objects pass through untouched, so qcache per-predicate tokens
(object identity) and DeviceBatcher same-CSR-object compatibility keys
stay exactly as sound as in the single-tenant server.

Cross-namespace references are structurally impossible — any attr
containing the separator raises the typed NamespaceError before touching
storage.
"""

from __future__ import annotations

from dataclasses import replace

SEP = "/"


class NamespaceError(ValueError):
    """Typed cross-namespace access / invalid tenant reference."""


def _check(attr: str) -> str:
    if SEP in attr:
        raise NamespaceError(
            f"cross-namespace predicate reference {attr!r}: the "
            f"namespace separator {SEP!r} is reserved")
    return attr


def prefix(tenant: str, attr: str) -> str:
    """Tenant attr -> storage attr. Handles the reverse marker; '*' (the
    wildcard delete / expand-all token) passes through — callers decide
    its scope."""
    if not tenant or not attr or attr == "*":
        return attr
    if attr.startswith("~"):
        return "~" + tenant + SEP + _check(attr[1:])
    return tenant + SEP + _check(attr)


def strip(tenant: str, attr: str) -> str:
    """Storage attr -> tenant attr (inverse of prefix; attr must belong)."""
    if not tenant:
        return attr
    if attr.startswith("~"):
        return "~" + strip(tenant, attr[1:])
    pre = tenant + SEP
    return attr[len(pre):] if attr.startswith(pre) else attr


def owns(tenant: str, attr: str) -> bool:
    a = attr[1:] if attr.startswith("~") else attr
    if not tenant:
        return SEP not in a
    return a.startswith(tenant + SEP)


def split(attr: str) -> tuple[str, str]:
    """Storage attr -> (tenant, bare attr); default-namespace attrs map to
    ("", attr). The per-tenant journal/overlay accounting groups on this."""
    a = attr[1:] if attr.startswith("~") else attr
    if SEP not in a:
        return "", attr
    tenant, _, bare = a.partition(SEP)
    return tenant, ("~" + bare if attr.startswith("~") else bare)


def prefix_attrs(tenant: str, attrs) -> frozenset:
    return frozenset(prefix(tenant, a) for a in attrs)


class NamespacedPreds:
    """Read-only dict-protocol view over a snapshot's preds map (plain
    dict or LazyPreds), translating tenant attrs <-> storage attrs.
    Iteration surfaces ONLY the tenant's predicates, stripped — so
    expand(_all_), known-uid validation, and planner stats see exactly
    the tenant's universe. Lazy-fold views (is_pending / resolve /
    materialize_all / pending hints) delegate per-attr so the demand-
    driven fold seam works identically through the view."""

    __slots__ = ("_base", "_tenant", "_pre")

    def __init__(self, base, tenant: str) -> None:
        self._base = base
        self._tenant = tenant
        self._pre = tenant + SEP

    # -- name translation -----------------------------------------------------

    def _s(self, attr: str) -> str:          # tenant -> storage
        return prefix(self._tenant, attr)

    def _mine(self, attr: str) -> bool:
        return attr.startswith(self._pre)

    def _keys(self) -> list[str]:
        n = len(self._pre)
        return sorted(a[n:] for a in self._base.keys() if self._mine(a))

    # -- mapping protocol -----------------------------------------------------

    def get(self, attr, default=None):
        return self._base.get(self._s(attr), default)

    def __getitem__(self, attr):
        try:
            return self._base[self._s(attr)]
        except KeyError:
            raise KeyError(attr) from None

    def __contains__(self, attr) -> bool:
        return self._s(attr) in self._base

    def __len__(self) -> int:
        return len(self._keys())

    def __iter__(self):
        return iter(self._keys())

    def keys(self):
        return self._keys()

    def values(self):
        return [self._base[self._s(a)] for a in self._keys()]

    def items(self):
        return [(a, self._base[self._s(a)]) for a in self._keys()]

    # -- lazy-aware views (planner / stats / residency / prefetch) ------------

    def folded_get(self, attr, default=None):
        fg = getattr(self._base, "folded_get", None)
        if fg is None:
            return self._base.get(self._s(attr), default)
        return fg(self._s(attr), default)

    def folded_items(self):
        fi = getattr(self._base, "folded_items", None)
        items = fi() if fi is not None else self._base.items()
        n = len(self._pre)
        return [(a[n:], pd) for a, pd in items if self._mine(a)]

    def folded_values(self):
        return [pd for _a, pd in self.folded_items()]

    def pending_attrs(self) -> list[str]:
        pa = getattr(self._base, "pending_attrs", None)
        if pa is None:
            return []
        n = len(self._pre)
        return [a[n:] for a in pa() if self._mine(a)]

    def is_pending(self, attr: str) -> bool:
        ip = getattr(self._base, "is_pending", None)
        return bool(ip is not None and ip(self._s(attr)))

    def pending_card(self, attr: str) -> int:
        pc = getattr(self._base, "pending_card", None)
        return int(pc(self._s(attr))) if pc is not None else 0

    def resolve(self, attr: str, trigger: str = "lazy"):
        rs = getattr(self._base, "resolve", None)
        if rs is None:
            return self._base.get(self._s(attr))
        return rs(self._s(attr), trigger)

    def materialize_all(self, trigger: str = "eager") -> int:
        # fold only THIS tenant's pending tablets, not the whole world
        n = 0
        for a in self.pending_attrs():
            if self.resolve(a, trigger) is not None:
                n += 1
        return n

    @property
    def hint_fn(self):
        fn = getattr(self._base, "hint_fn", None)
        if fn is None:
            return None
        return lambda attr: fn(self._s(attr))


class NamespacedSnapshot:
    """Tenant view of one GraphSnapshot. PredData objects pass through by
    identity (qcache tokens stay per-storage-tablet); only names
    translate. The cache token derives from the base snapshot's token
    plus the tenant, so every view of one base snapshot — this request's
    or the next's — keys caches identically, and a new base snapshot
    (commit/alter/drop) rotates every tenant's keys at once."""

    __slots__ = ("_base", "tenant", "preds", "metrics")

    def __init__(self, base, tenant: str) -> None:
        self._base = base
        self.tenant = tenant
        self.preds = NamespacedPreds(base.preds, tenant)
        self.metrics = getattr(base, "metrics", None)

    @property
    def base(self):
        return self._base

    @property
    def read_ts(self) -> int:
        return self._base.read_ts

    @property
    def cache_token(self):
        from dgraph_tpu.query import qcache

        return ("ns", self.tenant, qcache.snapshot_token(self._base))

    def pred(self, attr: str):
        return self.preds.get(attr)

    @property
    def nbytes(self) -> int:
        return self._base.nbytes


class NamespacedSchema:
    """Tenant view of the store's SchemaState: lookups prefix, listings
    filter + strip. Returned SchemaEntry objects are copies carrying the
    tenant's unprefixed predicate name (schema{} responses and error
    messages must never leak the storage prefix)."""

    __slots__ = ("_base", "_tenant", "_pre")

    def __init__(self, base, tenant: str) -> None:
        self._base = base
        self._tenant = tenant
        self._pre = tenant + SEP

    def _s(self, pred: str) -> str:
        return prefix(self._tenant, pred)

    def _out(self, e):
        if e is None:
            return None
        return replace(e, predicate=strip(self._tenant, e.predicate),
                       tokenizers=list(e.tokenizers))

    def set(self, e) -> None:
        self._base.set(replace(e, predicate=self._s(e.predicate),
                               tokenizers=list(e.tokenizers)))

    def get(self, pred: str):
        return self._out(self._base.get(self._s(pred)))

    def ensure(self, pred: str, tid, is_list: bool = False):
        return self._out(self._base.ensure(self._s(pred), tid,
                                           is_list=is_list))

    def delete(self, pred: str) -> None:
        self._base.delete(self._s(pred))

    def predicates(self) -> list[str]:
        n = len(self._pre)
        return sorted(p[n:] for p in self._base.predicates()
                      if p.startswith(self._pre))

    def entries(self) -> list:
        return [self.get(p) for p in self.predicates()]

    def type_of(self, pred: str):
        return self._base.type_of(self._s(pred))

    def is_indexed(self, pred: str) -> bool:
        return self._base.is_indexed(self._s(pred))

    def is_reversed(self, pred: str) -> bool:
        return self._base.is_reversed(self._s(pred))

    def has_count(self, pred: str) -> bool:
        return self._base.has_count(self._s(pred))

    def is_list(self, pred: str) -> bool:
        return self._base.is_list(self._s(pred))

    def tokenizer_names(self, pred: str) -> list[str]:
        return self._base.tokenizer_names(self._s(pred))

    def vector_spec(self, pred: str):
        return self._base.vector_spec(self._s(pred))

    def to_text(self) -> str:
        return "\n".join(str(e) for e in self.entries())
