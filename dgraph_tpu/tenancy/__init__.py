"""Multi-tenant QoS (ISSUE 20): namespaces, cost-metered quotas, and
weighted-fair device scheduling.

Reference semantics: the reference's namespace seam (edgraph/ access
checks, SURVEY §API) scopes every predicate to the caller's namespace by
prefixing attr names at the server boundary — the tenant's own DQL never
sees the prefix. This port does the same at the snapshot/schema seam
(namespace.py): tenant attrs are DISTINCT storage attrs
("<tenant>/<attr>"), so MVCC, the delta journal, qcache per-predicate
tokens, and DeviceBatcher same-CSR batching are all tenant-isolated by
construction, and the default tenant ("") takes no wrapper at all —
byte-identical to the pre-tenancy server.

Quotas (quota.py) meter in cost-ledger units — device-ms, traversed
edges, transfer bytes per refill window with a burst allowance — debited
from each request's CostLedger record and enforced at the API edge via
the PR 7 shed path: an over-quota tenant gets typed ResourceExhausted
before any device work, never a queue slot.

Fair scheduling (sched.py) orders contended DispatchGate admissions by
per-tenant weighted virtual time fed by the gate's measured device-ms,
so one tenant at 100x fair share cannot monopolize the device.

The tenant rides a contextvar: the HTTP handler (X-Dgraph-Tenant
header) and the gRPC worker (dgt-tenant metadata) install it at the
edge; Node.query/mutate/alter/subscribe read it.
"""

from __future__ import annotations

import contextvars
import re
from contextlib import contextmanager

from dgraph_tpu.tenancy.namespace import (SEP, NamespacedPreds,
                                          NamespacedSchema,
                                          NamespacedSnapshot,
                                          NamespaceError, prefix,
                                          prefix_attrs, split, strip)
from dgraph_tpu.tenancy.quota import TenantRegistry, TenantSpec
from dgraph_tpu.tenancy.sched import FairScheduler

__all__ = [
    "SEP", "DEFAULT", "HTTP_HEADER", "WIRE_KEY",
    "NamespaceError", "NamespacedPreds", "NamespacedSchema",
    "NamespacedSnapshot", "prefix", "prefix_attrs", "split", "strip",
    "TenantRegistry", "TenantSpec", "FairScheduler",
    "current", "scope", "validate",
]

# the default (admin) namespace: no prefixing, no wrapping — the
# pre-tenancy single-tenant server, byte for byte
DEFAULT = ""

# request-context carriers: HTTP header at the api/http.py edge, metadata
# key on the gRPC wire (parallel/remote.py — same pattern as the cost
# ledger's dgt-cost-bin sidecar)
HTTP_HEADER = "X-Dgraph-Tenant"
WIRE_KEY = "dgt-tenant"

_NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_.-]{0,63}$")

_current: contextvars.ContextVar[str] = contextvars.ContextVar(
    "dgt-tenant", default=DEFAULT)


def validate(tenant: str) -> str:
    """Tenant names are path-safe identifiers; the namespace separator is
    structurally impossible in one, so a prefixed storage attr always
    splits unambiguously."""
    if tenant == DEFAULT:
        return tenant
    if not isinstance(tenant, str) or not _NAME_RE.match(tenant):
        raise NamespaceError(
            f"invalid tenant name {tenant!r} (want [A-Za-z0-9][A-Za-z0-9"
            f"_.-]{{0,63}})")
    return tenant


def current() -> str:
    """The requesting tenant ("" = default namespace)."""
    return _current.get()


@contextmanager
def scope(tenant: str):
    """Install the tenant for one request's dynamic extent."""
    tok = _current.set(validate(tenant))
    try:
        yield
    finally:
        _current.reset(tok)
