"""Weighted-fair device scheduling: tenant-ordered DispatchGate admission.

Start-time fair queueing over per-tenant virtual time: every measured
device dispatch charges its wall-ms / weight to the submitting tenant's
virtual clock, and when the gate is CONTENDED (the non-blocking acquire
failed), waiters are admitted lowest-virtual-time-first across tenants —
deficit-weighted round-robin in the limit, since a tenant that just ran
has the highest clock and a starved tenant the lowest. One tenant at
100x fair share therefore queues behind every lighter tenant's next
dispatch instead of monopolizing the device, while the uncontended path
(and the whole scheduler when disarmed) costs exactly one attribute
load at the gate.

The scheduler also keeps the per-tenant device-ms EWMA — the deficit
signal the ISSUE names — surfaced in snapshot() for /debug/metrics and
used by the WriteBatcher's per-tenant window slot caps.
"""

from __future__ import annotations

import threading

from dgraph_tpu.utils import deadline as dl

_EWMA_ALPHA = 0.2
# renormalize virtual clocks when the floor passes this (keeps floats
# bounded over weeks of uptime without changing any ordering)
_VTIME_NORM = 1e9


class FairScheduler:
    """Per-tenant fair admission for the DispatchGate (+ the EWMA/weight
    oracle for the write window). weight_fn maps tenant -> fair-share
    weight (TenantRegistry.weight)."""

    def __init__(self, weight_fn=None, metrics=None) -> None:
        self._weight_fn = weight_fn or (lambda _t: 1.0)
        self.metrics = metrics
        self._cv = threading.Condition()
        self._waiting: dict[str, int] = {}
        self._vtime: dict[str, float] = {}
        self._ewma_ms: dict[str, float] = {}

    # -- admission (called by DispatchGate._acquire on contention) ------------

    def _floor_locked(self) -> float:
        return min(self._vtime.values(), default=0.0)

    def _turn_locked(self) -> str | None:
        floor = self._floor_locked()
        best, bv = None, None
        for t in self._waiting:
            v = self._vtime.get(t, floor)
            if bv is None or v < bv or (v == bv and t < best):
                best, bv = t, v
        return best

    def admit(self, tenant: str) -> None:
        """Block until it is this tenant's turn to contend for a slot.
        Budgeted callers wait at most their remaining deadline (typed
        DeadlineExceeded past it) — the fair queue must never out-hang
        the lifeline contract."""
        with self._cv:
            # a long-idle tenant re-enters at the current floor: history
            # neither punishes it nor banks an unbounded burst credit
            self._vtime[tenant] = max(self._vtime.get(tenant, 0.0),
                                      self._floor_locked())
            self._waiting[tenant] = self._waiting.get(tenant, 0) + 1
            try:
                while self._turn_locked() != tenant:
                    if not self._cv.wait(dl.clamp(None)):
                        dl.check("tenant fair queue")
            finally:
                n = self._waiting[tenant] - 1
                if n:
                    self._waiting[tenant] = n
                else:
                    del self._waiting[tenant]
                self._cv.notify_all()

    def acquire(self, tenant: str, sem) -> bool:
        """Admission and slot acquisition in ONE wait: block until this
        tenant holds the lowest virtual clock among waiters AND the gate
        semaphore yields a slot, then take the slot before returning.

        Folding the two waits closes the barging window admit() alone
        leaves open: a hot thread that just released the slot re-grabs it
        through a non-blocking fast path before any parked waiter wakes,
        and under saturation that hands one tenant the whole device (the
        waiters sit invisible inside the semaphore, so the fair queue
        never even sees contention). Waiters instead park HERE, and every
        release (charge() notifies under the same condition) re-opens the
        contest in virtual-time order. Budgeted callers wait at most
        their remaining deadline (typed DeadlineExceeded past it); the
        bounded re-poll covers a scheduler disarmed mid-wait (--no_qos
        hot toggle), after which charges stop notifying.

        Returns True when it had to wait for the slot."""
        waited = False
        with self._cv:
            self._vtime[tenant] = max(self._vtime.get(tenant, 0.0),
                                      self._floor_locked())
            self._waiting[tenant] = self._waiting.get(tenant, 0) + 1
            try:
                while not (self._turn_locked() == tenant
                           and sem.acquire(blocking=False)):
                    waited = True
                    if not self._cv.wait(dl.clamp(0.05)):
                        dl.check("tenant fair queue")
                return waited
            finally:
                n = self._waiting[tenant] - 1
                if n:
                    self._waiting[tenant] = n
                else:
                    del self._waiting[tenant]
                self._cv.notify_all()

    def depth(self) -> int:
        """Waiters currently parked in the fair queue (the armed gate's
        max_queue shed input)."""
        with self._cv:
            return sum(self._waiting.values())

    # -- charging (DispatchGate.run, after the measured dispatch) -------------

    def charge(self, tenant: str, ms: float) -> None:
        if ms < 0:
            return
        w = self._weight_fn(tenant)
        w = w if w and w > 0 else 1.0
        with self._cv:
            prev = self._ewma_ms.get(tenant, 0.0)
            self._ewma_ms[tenant] = ms if not prev else (
                (1 - _EWMA_ALPHA) * prev + _EWMA_ALPHA * ms)
            self._vtime[tenant] = self._vtime.get(
                tenant, self._floor_locked()) + ms / w
            if self._vtime and min(self._vtime.values()) > _VTIME_NORM:
                base = min(self._vtime.values())
                for t in self._vtime:
                    self._vtime[t] -= base
            self._cv.notify_all()

    def ewma_ms(self, tenant: str) -> float:
        with self._cv:
            return self._ewma_ms.get(tenant, 0.0)

    def snapshot(self) -> dict:
        with self._cv:
            return {"waiting": dict(self._waiting),
                    "vtime_ms": {t: round(v, 3)
                                 for t, v in self._vtime.items()},
                    "ewma_ms": {t: round(v, 3)
                                for t, v in self._ewma_ms.items()}}
