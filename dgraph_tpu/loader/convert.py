"""Dataset → RDF converters.

- GeoJSON (reference: dgraph/cmd/dgraph-converter/main.go — one blank node
  per feature, geometry as a geo:geojson literal, properties as value
  triples).
- LDBC-SNB interactive CSV dumps (ROADMAP item 5 groundwork): the
  persons/knows/posts subset of the DATAGEN "social_network" layout mapped
  to N-Quads, so `bulk -f <out>` ingests a social-network benchmark graph.
  The SF10/SF100 ingest itself rides the out-of-core bulk pipeline (PR 5);
  this is only the format bridge.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
from dataclasses import dataclass


@dataclass
class ConvertStats:
    features: int = 0
    triples: int = 0


@dataclass
class LdbcStats:
    persons: int = 0
    knows: int = 0
    posts: int = 0
    comments: int = 0
    reply_of: int = 0
    triples: int = 0


def _esc(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def convert_geojson(geo_path: str, out_path: str,
                    geopred: str = "loc") -> ConvertStats:
    op = gzip.open if geo_path.endswith(".gz") else open
    with op(geo_path, "rt", encoding="utf-8") as f:
        doc = json.load(f)
    feats = doc.get("features", []) if doc.get("type") == "FeatureCollection" \
        else [doc]
    stats = ConvertStats()
    with gzip.open(out_path, "wt", encoding="utf-8") as out:
        for i, feat in enumerate(feats):
            geom = feat.get("geometry")
            if not geom:
                continue
            node = f"_:f{i}"
            out.write(f'{node} <{geopred}> '
                      f'"{_esc(json.dumps(geom, separators=(",", ":")))}"'
                      f'^^<geo:geojson> .\n')
            stats.triples += 1
            for k, v in (feat.get("properties") or {}).items():
                if v is None:
                    continue
                if isinstance(v, bool):
                    lit = f'"{str(v).lower()}"^^<xs:boolean>'
                elif isinstance(v, int):
                    lit = f'"{v}"^^<xs:int>'
                elif isinstance(v, float):
                    lit = f'"{v}"^^<xs:float>'
                else:
                    lit = f'"{_esc(str(v))}"'
                out.write(f"{node} <{k}> {lit} .\n")
                stats.triples += 1
            stats.features += 1
    return stats


# -- LDBC-SNB interactive (persons / knows / posts subset) -------------------
#
# DATAGEN CSV layout: pipe-separated with one header row; entity files
# carry `id|...` columns, relation files carry `<Type>.id|<Type>.id|...`.
# Blank-node ids are namespaced per entity type (person ids and post ids
# overlap numerically in the dumps).

# entity value columns kept, in header name -> (predicate, xsd type) form
_PERSON_COLS = {"firstName": ("firstName", None),
                "lastName": ("lastName", None),
                "gender": ("gender", None),
                "birthday": ("birthday", None),
                "creationDate": ("creationDate", None)}
_POST_COLS = {"content": ("content", None),
              "imageFile": ("imageFile", None),
              "language": ("language", None),
              "creationDate": ("creationDate", None),
              "length": ("length", "xs:int")}
_COMMENT_COLS = {"content": ("content", None),
                 "creationDate": ("creationDate", None),
                 "length": ("length", "xs:int")}


def _ldbc_file(dirpath: str, stem: str) -> str | None:
    """Find `<stem>_0_0.csv(.gz)` / `<stem>.csv(.gz)` under the dump dir
    (DATAGEN shards entity files; the fixture uses the bare name)."""
    for pat in (f"{stem}_0_0.csv", f"{stem}_0_0.csv.gz",
                f"{stem}.csv", f"{stem}.csv.gz"):
        hits = sorted(glob.glob(os.path.join(dirpath, pat)))
        if hits:
            return hits[0]
    return None


def _ldbc_rows(path: str):
    """(header list, row iterator) over one pipe-separated CSV."""
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rt", encoding="utf-8") as f:
        header = None
        for line in f:
            line = line.rstrip("\n\r")
            if not line:
                continue
            if header is None:
                header = line.split("|")
                continue
            yield header, line.split("|")


def _emit_entity(out, path: str | None, prefix: str, id_pred: str,
                 cols: dict, stats: LdbcStats, count_attr: str) -> None:
    if path is None:
        return
    n = triples = 0
    for header, row in _ldbc_rows(path):
        vals = dict(zip(header, row))
        ident = vals.get("id")
        if ident is None:
            continue
        node = f"_:{prefix}{ident}"
        out.write(f'{node} <{id_pred}> "{ident}"^^<xs:int> .\n')
        triples += 1
        for col, (pred, typ) in cols.items():
            v = vals.get(col, "")
            if not v:
                continue
            lit = f'"{v}"^^<{typ}>' if typ else f'"{_esc(v)}"'
            out.write(f"{node} <{pred}> {lit} .\n")
            triples += 1
        n += 1
    setattr(stats, count_attr, getattr(stats, count_attr) + n)
    stats.triples += triples


def _emit_relation(out, path: str | None, src_prefix: str, pred: str,
                   dst_prefix: str, stats: LdbcStats,
                   count_attr: str | None) -> None:
    if path is None:
        return
    n = 0
    for header, row in _ldbc_rows(path):
        if len(row) < 2:
            continue
        out.write(f"_:{src_prefix}{row[0]} <{pred}> "
                  f"_:{dst_prefix}{row[1]} .\n")
        n += 1
    if count_attr is not None:
        setattr(stats, count_attr, getattr(stats, count_attr) + n)
    stats.triples += n


LDBC_SCHEMA = """\
person.id: int @index(int) @upsert .
firstName: string @index(exact) .
lastName: string @index(exact) .
gender: string .
birthday: string .
creationDate: string .
knows: [uid] @reverse @count .
post.id: int @index(int) @upsert .
content: string .
imageFile: string .
language: string .
length: int .
hasCreator: [uid] @reverse @count .
comment.id: int @index(int) @upsert .
replyOf: [uid] @reverse @count .
"""


def convert_ldbc(dirpath: str, out_path: str) -> LdbcStats:
    """Map an LDBC-SNB interactive CSV dump (persons/knows/posts/comments
    subset) to gzipped N-Quads for `bulk -f`. Also writes `<out>.schema`
    with the matching schema text. Blank-node identity is `_:p<id>` /
    `_:post<id>` / `_:c<id>` so relation files join without an id map.

    Comment entities carry the `replyOf` chains (comment→post and
    comment→comment, ISSUE 15) so depth-3 traversals over
    replyOf/hasCreator have realistic fan-out, not just person.knows."""
    stats = LdbcStats()
    with gzip.open(out_path, "wt", encoding="utf-8") as out:
        _emit_entity(out, _ldbc_file(dirpath, "person"), "p", "person.id",
                     _PERSON_COLS, stats, "persons")
        _emit_relation(out, _ldbc_file(dirpath, "person_knows_person"),
                       "p", "knows", "p", stats, "knows")
        _emit_entity(out, _ldbc_file(dirpath, "post"), "post", "post.id",
                     _POST_COLS, stats, "posts")
        _emit_relation(out, _ldbc_file(dirpath, "post_hasCreator_person"),
                       "post", "hasCreator", "p", stats, None)
        _emit_entity(out, _ldbc_file(dirpath, "comment"), "c", "comment.id",
                     _COMMENT_COLS, stats, "comments")
        _emit_relation(out, _ldbc_file(dirpath, "comment_replyOf_post"),
                       "c", "replyOf", "post", stats, "reply_of")
        _emit_relation(out, _ldbc_file(dirpath, "comment_replyOf_comment"),
                       "c", "replyOf", "c", stats, "reply_of")
        _emit_relation(out, _ldbc_file(dirpath, "comment_hasCreator_person"),
                       "c", "hasCreator", "p", stats, None)
    with open(out_path + ".schema", "w", encoding="utf-8") as f:
        f.write(LDBC_SCHEMA)
    return stats
