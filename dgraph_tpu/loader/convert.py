"""GeoJSON → RDF converter (reference: dgraph/cmd/dgraph-converter/main.go
— reads a GeoJSON FeatureCollection, emits one blank node per feature with
the geometry as a geo:geojson literal plus each property as a value triple).
"""

from __future__ import annotations

import gzip
import json
from dataclasses import dataclass


@dataclass
class ConvertStats:
    features: int = 0
    triples: int = 0


def _esc(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def convert_geojson(geo_path: str, out_path: str,
                    geopred: str = "loc") -> ConvertStats:
    op = gzip.open if geo_path.endswith(".gz") else open
    with op(geo_path, "rt", encoding="utf-8") as f:
        doc = json.load(f)
    feats = doc.get("features", []) if doc.get("type") == "FeatureCollection" \
        else [doc]
    stats = ConvertStats()
    with gzip.open(out_path, "wt", encoding="utf-8") as out:
        for i, feat in enumerate(feats):
            geom = feat.get("geometry")
            if not geom:
                continue
            node = f"_:f{i}"
            out.write(f'{node} <{geopred}> '
                      f'"{_esc(json.dumps(geom, separators=(",", ":")))}"'
                      f'^^<geo:geojson> .\n')
            stats.triples += 1
            for k, v in (feat.get("properties") or {}).items():
                if v is None:
                    continue
                if isinstance(v, bool):
                    lit = f'"{str(v).lower()}"^^<xs:boolean>'
                elif isinstance(v, int):
                    lit = f'"{v}"^^<xs:int>'
                elif isinstance(v, float):
                    lit = f'"{v}"^^<xs:float>'
                else:
                    lit = f'"{_esc(str(v))}"'
                out.write(f"{node} <{k}> {lit} .\n")
                stats.triples += 1
            stats.features += 1
    return stats
