"""Loaders & export: bulk (offline map/reduce), live (txn batches), xidmap,
RDF export. Reference: dgraph/cmd/bulk, dgraph/cmd/live, xidmap/,
worker/export.go."""

from dgraph_tpu.loader.bulk import BulkStats, bulk_load
from dgraph_tpu.loader.export import ExportStats, export_rdf
from dgraph_tpu.loader.live import LiveStats, live_load
from dgraph_tpu.loader.xidmap import XidMap

__all__ = ["BulkStats", "bulk_load", "ExportStats", "export_rdf",
           "LiveStats", "live_load", "XidMap"]
