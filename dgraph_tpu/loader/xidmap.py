"""External-id → uid assignment for loaders.

Reference semantics: xidmap/xidmap.go:30 — loaders map RDF node names
(blank nodes, IRIs) to uids, leasing uid ranges from Zero; names that parse
as uids ("0x2a", "123") pass through and advance the lease so later leased
blocks can never collide. The reference shards an LRU over badger; here the
map is an in-memory dict with TWO durability modes:

  - JSON save/load (bulk outputs persist it next to the posting snapshot
    so a follow-up live load keeps identities), and
  - an append-only assignment LOG (`wal_path`): every NEW mapping appends
    one record, fsynced per live-load batch (`sync()`), and `open()`
    replays it — a crashed live load RESUMES with every identity it had
    already assigned (the reference's badger-persisted map, in log form).
"""

from __future__ import annotations

import json
import os

from dgraph_tpu.coord.zero import LEASE_BLOCK, UidLease


def parse_uid_literal(xid: str) -> int | None:
    """'0x2a' / '123' → uid, else None (a name to map)."""
    try:
        u = int(xid, 0)
    except ValueError:
        return None
    return u if u > 0 else None


class XidMap:
    def __init__(self, lease: UidLease, block: int = LEASE_BLOCK) -> None:
        self._lease = lease
        self._block = block
        self._map: dict[str, int] = {}
        self._taken: set[int] = set()   # explicit uids seen (never hand out)
        self._next = 0
        self._end = -1   # exhausted
        self._wal = None   # set ONLY by open(): appending to an existing
        # log without replaying it would mint divergent duplicate uids

    @classmethod
    def open(cls, wal_path: str, lease: UidLease,
             block: int = LEASE_BLOCK) -> "XidMap":
        """Crash-resumable map: replay the assignment log, then append.
        A torn trailing record (crash mid-write) is dropped — its xid was
        never acked, so the loader re-assigns it."""
        xm = cls(lease, block)
        if os.path.exists(wal_path):
            with open(wal_path, "rb") as f:
                raw = f.read()
            # a record is durable only when newline-terminated: ANY
            # unterminated tail is torn (a truncated uid still parses as
            # a valid shorter number — parseability cannot detect it) and
            # must be truncated away so the next append cannot fuse onto it
            keep_upto = raw.rfind(b"\n") + 1
            for line in raw[:keep_upto].split(b"\n"):
                if not line:
                    continue
                try:
                    xid_b, uid_b = line.rsplit(b"\t", 1)
                    xm._map[xid_b.decode("utf-8")] = int(uid_b)
                except (ValueError, UnicodeDecodeError):
                    continue         # unparseable complete line: skip
            if keep_upto < len(raw):
                with open(wal_path, "r+b") as f:
                    f.truncate(keep_upto)
            if xm._map:
                lease.bump_to(max(xm._map.values()))
        xm._wal = open(wal_path, "ab")
        return xm

    def _log(self, xid: str, uid: int) -> None:
        if self._wal is not None:
            self._wal.write(xid.encode("utf-8") + b"\t" +
                            str(uid).encode() + b"\n")

    def sync(self) -> None:
        """Make all assignments so far durable (call per committed batch:
        an identity must never be re-assigned after its txn was acked)."""
        if self._wal is not None:
            self._wal.flush()
            os.fsync(self._wal.fileno())

    def close(self) -> None:
        if self._wal is not None:
            self.sync()
            self._wal.close()
            self._wal = None

    def uid(self, xid: str) -> int:
        u = self._map.get(xid)
        if u is not None:
            return u
        explicit = parse_uid_literal(xid)
        if explicit is not None:
            # reserve: the uid may fall inside an already-leased block.
            # Memoize like named nodes — graph data repeats each uid ~degree
            # times, and re-parsing + re-locking the lease per occurrence
            # was the bulk loader's hottest line
            self._taken.add(explicit)
            self._lease.bump_to(explicit)
            self._map[xid] = explicit
            return explicit          # literal uids need no log (stateless)
        while True:
            if self._next > self._end:
                self._next, self._end = self._lease.assign(self._block)
            u = self._next
            self._next += 1
            if u not in self._taken:
                break
        self._map[xid] = u
        self._log(xid, u)
        return u

    def __len__(self) -> int:
        return len(self._map)

    def save(self, path: str) -> None:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self._map, f)
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str, lease: UidLease,
             block: int = LEASE_BLOCK) -> "XidMap":
        xm = cls(lease, block)
        with open(path) as f:
            xm._map = {k: int(v) for k, v in json.load(f).items()}
        if xm._map:
            lease.bump_to(max(xm._map.values()))
        return xm
