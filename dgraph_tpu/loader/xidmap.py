"""External-id → uid assignment for loaders.

Reference semantics: xidmap/xidmap.go:30 — loaders map RDF node names
(blank nodes, IRIs) to uids, leasing uid ranges from Zero; names that parse
as uids ("0x2a", "123") pass through and advance the lease so later leased
blocks can never collide. The reference shards an LRU over badger; here the
map is an in-memory dict with JSON save/load (bulk outputs persist it next
to the posting snapshot so a follow-up live load keeps identities).
"""

from __future__ import annotations

import json
import os

from dgraph_tpu.coord.zero import LEASE_BLOCK, UidLease


def parse_uid_literal(xid: str) -> int | None:
    """'0x2a' / '123' → uid, else None (a name to map)."""
    try:
        u = int(xid, 0)
    except ValueError:
        return None
    return u if u > 0 else None


class XidMap:
    def __init__(self, lease: UidLease, block: int = LEASE_BLOCK) -> None:
        self._lease = lease
        self._block = block
        self._map: dict[str, int] = {}
        self._taken: set[int] = set()   # explicit uids seen (never hand out)
        self._next = 0
        self._end = -1   # exhausted

    def uid(self, xid: str) -> int:
        u = self._map.get(xid)
        if u is not None:
            return u
        explicit = parse_uid_literal(xid)
        if explicit is not None:
            # reserve: the uid may fall inside an already-leased block.
            # Memoize like named nodes — graph data repeats each uid ~degree
            # times, and re-parsing + re-locking the lease per occurrence
            # was the bulk loader's hottest line
            self._taken.add(explicit)
            self._lease.bump_to(explicit)
            self._map[xid] = explicit
            return explicit
        while True:
            if self._next > self._end:
                self._next, self._end = self._lease.assign(self._block)
            u = self._next
            self._next += 1
            if u not in self._taken:
                break
        self._map[xid] = u
        return u

    def __len__(self) -> int:
        return len(self._map)

    def save(self, path: str) -> None:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self._map, f)
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str, lease: UidLease,
             block: int = LEASE_BLOCK) -> "XidMap":
        xm = cls(lease, block)
        with open(path) as f:
            xm._map = {k: int(v) for k, v in json.load(f).items()}
        if xm._map:
            lease.bump_to(max(xm._map.values()))
        return xm
